"""Loopback RPC for the fleet: actors/learner ⇄ replay/serving host.

The Podracer decomposition (PAPERS.md, "Podracer architectures for
scalable RL") puts the environment loops, the inference server, the
replay service, and the learner in separate PROCESSES; what connects
them is a small request/response protocol. This module is that
protocol's transport, built on `multiprocessing.connection` (stdlib
pickle framing over a loopback TCP socket — no new dependency, and the
same `Listener`/`Client` pair a real multi-host deployment would swap
for its RPC system of choice):

  * `RpcServer` — accept loop + one handler thread per connection.
    The handler callable sees `(method, payload, ctx)` where `ctx` is
    a per-connection dict that SURVIVES until disconnect: the host
    stores each connection's replay-session ids there, and the
    synthetic `__disconnect__` call on EOF is how a crashed actor's
    staged half-episode gets aborted server-side (the session-abort
    crash contract of `replay.service`, extended across the process
    boundary).
  * `RpcClient` — blocking request/response. NOT thread-safe by
    design: one owner thread per client. A process that needs RPC
    from two threads (the learner's train loop + its prefetch thread)
    opens two clients — loopback connections are cheap, and two
    sockets beat a lock that would serialize a param publish behind a
    slow sample (and trip the CON301 blocking-under-lock rule this
    package is linted with).

This module must stay importable WITHOUT jax: actor processes import
it at spawn and never touch a device (tests/test_fleet.py pins the
jax-free actor import).
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from multiprocessing.connection import Client, Listener
from typing import Any, Callable, List, Optional, Tuple

from tensor2robot_tpu import telemetry

log = logging.getLogger(__name__)

# The shared secret for connection auth. Loopback-only transport; the
# orchestrator generates a per-fleet key so two fleets on one machine
# cannot cross-connect even if they guess each other's port.
DEFAULT_AUTHKEY = b"t2r-fleet"

DISCONNECT_METHOD = "__disconnect__"


class RpcError(RuntimeError):
  """A handler raised on the server side; carries the remote traceback."""


class RpcServer:
  """Threaded request/response server over a loopback Listener."""

  def __init__(self,
               handler: Callable[[str, Any, dict], Any],
               host: str = "127.0.0.1",
               authkey: bytes = DEFAULT_AUTHKEY):
    """`handler(method, payload, ctx) -> result` runs on a
    per-connection thread; exceptions it raises are serialized back to
    the caller as `RpcError` (the connection stays up). On EOF the
    synthetic `(DISCONNECT_METHOD, None, ctx)` call runs once."""
    self._handler = handler
    self._listener = Listener((host, 0), authkey=authkey)
    self.address: Tuple[str, int] = self._listener.address
    self._stop = threading.Event()
    self._lock = threading.Lock()
    self._conns: List[Any] = []
    self._threads: List[threading.Thread] = []
    self._accept_thread = threading.Thread(
        target=self._accept_loop, name="fleet-rpc-accept", daemon=True)
    self._accept_thread.start()

  def _accept_loop(self) -> None:
    while not self._stop.is_set():
      try:
        conn = self._listener.accept()
      except (OSError, EOFError):
        # close() closed the listener under us (the only way to
        # unblock accept); anything else on a closed socket is the
        # same shutdown signal.
        return
      except Exception:  # auth failure from a stray connector
        log.warning("fleet rpc: rejected connection", exc_info=True)
        continue
      thread = threading.Thread(
          target=self._serve, args=(conn,),
          name="fleet-rpc-conn", daemon=True)
      with self._lock:
        self._conns.append(conn)
        self._threads.append(thread)
      thread.start()

  def _serve(self, conn) -> None:
    ctx: dict = {}
    try:
      while not self._stop.is_set():
        try:
          method, payload = conn.recv()
        except (EOFError, OSError):
          break
        try:
          # Every RPC method gets a server-side span for free: the
          # merged timeline shows act/commit/sample handler time per
          # connection thread (no-op until telemetry is configured).
          with telemetry.span(f"rpc.{method}"):
            result = self._handler(method, payload, ctx)
          reply = ("ok", result)
        except BaseException:  # serialized back, connection stays up
          reply = ("err", traceback.format_exc())
        try:
          conn.send(reply)
        except (EOFError, OSError):
          break
    finally:
      try:
        self._handler(DISCONNECT_METHOD, None, ctx)
      except Exception:
        log.exception("fleet rpc: disconnect handler failed")
      try:
        conn.close()
      except OSError:
        pass
      with self._lock:
        if conn in self._conns:
          self._conns.remove(conn)

  def close(self, timeout_secs: float = 5.0) -> None:
    """Stops intake: closes the listener (unblocks accept) and every
    live connection (unblocks recv), then joins the handler threads."""
    self._stop.set()
    try:
      self._listener.close()
    except OSError:
      pass
    with self._lock:
      conns = list(self._conns)
      threads = list(self._threads)
    for conn in conns:
      try:
        conn.close()
      except OSError:
        pass
    deadline = time.monotonic() + timeout_secs
    for thread in threads + [self._accept_thread]:
      thread.join(timeout=max(0.0, deadline - time.monotonic()))

  def __enter__(self) -> "RpcServer":
    return self

  def __exit__(self, *exc) -> bool:
    self.close()
    return False


class RpcClient:
  """Blocking request/response client. One owner thread per instance
  (see module docstring) — open a second client for a second thread."""

  def __init__(self,
               address: Tuple[str, int],
               authkey: bytes = DEFAULT_AUTHKEY,
               connect_timeout_secs: float = 20.0):
    deadline = time.monotonic() + connect_timeout_secs
    last_error: Optional[BaseException] = None
    self._conn = None
    while True:
      try:
        self._conn = Client(tuple(address), authkey=authkey)
        break
      except (ConnectionRefusedError, FileNotFoundError) as e:
        # The host process may still be warming up its engine; retry
        # until the connect window closes.
        last_error = e
        if time.monotonic() > deadline:
          raise TimeoutError(
              f"fleet rpc: no server at {address} after "
              f"{connect_timeout_secs:.0f}s") from last_error
        time.sleep(0.05)

  def call(self, method: str, payload: Any = None,
           timeout_secs: Optional[float] = None) -> Any:
    """One request/response round trip; raises `RpcError` when the
    server-side handler raised (its traceback is the message).

    `timeout_secs` bounds the wait for the REPLY (the orchestrator's
    shutdown path must not hang on a wedged host); on expiry the
    client raises `TimeoutError` and the connection should be
    considered poisoned (an in-flight reply may still arrive).
    """
    try:
      # Client-side span: the caller's view of the same RPC (queueing
      # + transport + handler), so actor-vs-host wait decomposes in
      # the merged timeline.
      with telemetry.span(f"rpc_call.{method}"):
        self._conn.send((method, payload))
        if timeout_secs is not None and not self._conn.poll(
            timeout_secs):
          raise TimeoutError(
              f"fleet rpc: no reply to {method!r} in "
              f"{timeout_secs:.0f}s")
        status, value = self._conn.recv()
    except (EOFError, OSError) as e:
      raise ConnectionError(
          f"fleet rpc: server dropped during {method!r}") from e
    if status == "err":
      raise RpcError(f"remote {method!r} failed:\n{value}")
    return value

  def close(self) -> None:
    if self._conn is not None:
      try:
        self._conn.close()
      except OSError:
        pass
      self._conn = None

  def __enter__(self) -> "RpcClient":
    return self

  def __exit__(self, *exc) -> bool:
    self.close()
    return False
