"""Fleet orchestrator: the organs run together as one topology.

PRs 1–6 built every organ of the scalable QT-Opt stack — bucketed AOT
serving with lock-free hot-swap, the sharded replay service with
measured staleness, the shm-ring host data plane, gloo-backed
distributed init. This module is the composition layer: the Sebulba
decomposition from "Podracer architectures for scalable RL"
(PAPERS.md) as a process-supervising orchestrator on one host —

    actor 0..N-1 ──act──▶ ┌───────────────────────┐
        │                 │ host: CEMPolicyServer │
        │ commit          │  + ReplayWriteService │ ◀─publish─ learner
        └────────────────▶│  + ReplayStore        │ ──sample─▶ (train_qtopt)
                          └───────────────────────┘

Lifecycle contract (docs/FLEET.md):

  * LAUNCH GATE — when gin configs are given, `run_t2r_trainer
    --validate_only` runs as a pre-spawn subprocess; a typo'd binding
    fails the launch in seconds instead of minutes into a fleet run.
  * HEARTBEAT + EXIT-CODE SUPERVISION — the hard-death latching
    pattern from `data/plane.py`: child exit codes are polled and the
    first failure is LATCHED (later teardown noise never masks it);
    each child additionally stamps a shared monotonic heartbeat so a
    silently hung process is detected, not just a dead one.
  * ACTOR-CRASH POLICY — `restart` (default): the actor process is
    respawned under the same actor id, which re-opens its replay
    session — the service aborts whatever the dead incarnation staged
    (restart-with-session-abort), so partial episodes never land.
    `abort`: any actor death takes the fleet down.
  * LEARNER/HOST DEATH — always fatal: actors are stopped, everything
    is torn down, and the latched error is raised.
  * SHUTDOWN BARRIER — stop event → actors drain and exit → final
    metrics are read → host flushes replay and exits → every child is
    joined (escalating terminate→kill on timeout). `shutdown` proves
    zero leaked processes; the fleet allocates no shm segments
    (tests/test_fleet.py pins both).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import multiprocessing as mp
import os
import re
import secrets
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import control as control_lib
from tensor2robot_tpu.fleet import actor as actor_lib
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import front as front_lib
from tensor2robot_tpu.fleet import host as host_lib
from tensor2robot_tpu.fleet import learner as learner_lib
from tensor2robot_tpu.fleet import pod as pod_lib
from tensor2robot_tpu.fleet.rpc import RpcClient, TRANSPORTS
from tensor2robot_tpu.telemetry import core as tcore
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import perf as perf_lib
from tensor2robot_tpu.telemetry import records as trecords
from tensor2robot_tpu.telemetry import sentinel as sentinel_lib

log = logging.getLogger(__name__)

_ENVS = ("toy_grasp", "pose", "mujoco_pose")
_CRASH_POLICIES = ("restart", "abort")
_LEARNER_CRASH_POLICIES = ("fatal", "resume")
_CRASH_MODES = ("raise", "hard", "mid_episode")
_OVERFLOW = ("drop", "block")


class FleetError(RuntimeError):
  """A latched fleet failure (child death, hang, launch-gate reject)."""


# ---- broadcast tree shape (ISSUE 16) ----
#
# Learner publications fan over a complete d-ary tree in HEAP LAYOUT
# over the serving-host list: host 0 is the root (the learner's only
# publish target) and host i forwards to serving[i*d+1 : i*d+1+d].
# Pure functions so the mapping is unit-testable without processes.


def broadcast_children(index: int, num_hosts: int,
                       degree: int) -> List[int]:
  """Serving-host indices `index` forwards publications to."""
  first = index * degree + 1
  return list(range(first, min(first + degree, num_hosts)))


def broadcast_depths(num_hosts: int, degree: int) -> List[int]:
  """Per-host hop count from the root (root = 0)."""
  depths = [0] * num_hosts
  for i in range(1, num_hosts):
    depths[i] = depths[(i - 1) // degree] + 1
  return depths


@gin.configurable
@dataclasses.dataclass
class FleetConfig:
  """One fleet's topology + model + lifecycle knobs (picklable: the
  same instance is shipped to every child process)."""

  # Topology.
  num_actors: int = 2
  env: str = "mujoco_pose"
  # Model (mirrors GraspingQModel/QTOptLearner constructor args so the
  # host's serving tree and the learner's training tree match).
  image_size: int = 32
  action_dim: int = 2
  torso_filters: Tuple[int, ...] = (16, 32)
  head_filters: Tuple[int, ...] = (32, 32)
  dense_sizes: Tuple[int, ...] = (32, 32)
  cem_population: int = 64
  cem_iterations: int = 2
  cem_elites: int = 6
  cem_inference: str = "bf16"
  # Learner loop.
  batch_size: int = 64
  max_train_steps: int = 200
  min_replay_size: Optional[int] = None
  publish_every_steps: int = 25  # checkpoint == param-refresh cadence
  log_every_steps: int = 25
  # Actors.
  batch_episodes: int = 16
  epsilon: float = 0.1
  # Replay plane.
  replay_capacity: int = 4096
  replay_shards: int = 2
  queue_batches: int = 16
  overflow: str = "drop"
  # Serving plane.
  serve_max_batch: int = 8
  serve_max_wait_us: int = 200
  # Cross-host topology (ISSUE 16). transport="tcp" moves every fleet
  # RPC onto fleet/transport.py's length-prefixed socket framing with
  # out-of-band buffer serialization (loopback stays the stdlib
  # multiprocessing.connection default, bitwise-identical behavior).
  # serving_hosts > 1 spawns engine-only serving replicas; actors
  # spread act traffic round-robin and learner publications fan over a
  # `broadcast_degree`-ary tree rooted at host 0. replay_hosts > 0
  # moves the replay plane onto dedicated shard processes (one shard
  # per host); actors commit to their rendezvous-hashed home shard and
  # the learner's sampler fans across shards shard-major. Replicas own
  # no replay store, so serving_hosts > 1 requires replay_hosts >= 1.
  transport: str = "loopback"
  tcp_sndbuf: int = 0  # 0 = kernel default (SO_SNDBUF untouched)
  tcp_rcvbuf: int = 0
  serving_hosts: int = 1
  replay_hosts: int = 0
  broadcast_degree: int = 2
  # Hybrid Podracer (ISSUE 19). learner_hosts > 1 spawns a LEARNER
  # GROUP: every rank adopts ONE ephemeral gloo coordinator
  # (`parallel.distributed`), the `parallel/` mesh spans all ranks'
  # devices, and the jitted train step runs as one cross-process
  # GSPMD program — gradients all-reduce over the mesh with no
  # train-loop changes. Each rank samples its own batch_size/N
  # shard-fanout batch from the replay plane; ONLY rank 0 publishes
  # params and writes checkpoints (`train_qtopt` gates every side
  # effect on `jax.process_index() == 0`). N=1 is bitwise the
  # single-learner path; any group member's death is fatal (the
  # collective is torn), so learner_hosts > 1 requires
  # learner_crash_policy="fatal". pod_hosts > 0 spawns Anakin PODS
  # (`fleet.pod`): vectorized on-device collectors — envs_per_pod
  # functional envs vmapped inside pmap roll pod_rollout_length steps
  # per segment, acting params refreshed from the pod's assigned
  # serving replica ("acting_state"), whole segments committed
  # atomically to the pod's rendezvous-hashed home shard. Pods
  # coexist with (or, with num_actors=0, replace) process actors in
  # the same supervised lifecycle and share the actor restart budget.
  learner_hosts: int = 1
  pod_hosts: int = 0
  envs_per_pod: int = 64
  pod_rollout_length: int = 4
  # Replicated serving-front tier (ISSUE 17). front_hosts > 0 spawns
  # that many `fleet.front.front_main` replicas — each a complete
  # multi-tenant ServingFront (arena + admission + continuous
  # batching) behind the fleet RPC transport. They join the SAME
  # broadcast tree as the serving hosts (one learner uplink fans to
  # both kinds), callers place tenants over them with
  # `serving.router.ServingRouter` (rendezvous hashing,
  # `front_spread`-wide hot-tenant spread), and — unlike serving
  # replicas/shards — a front replica death is SURVIVABLE: the router
  # sheds its tenants to HRW survivors and the orchestrator records
  # the membership change instead of latching a fleet error.
  front_hosts: int = 0
  front_tenants: Tuple[str, ...] = ("policy",)
  front_spread: int = 1
  front_slo_ms: float = 100.0
  # speculative_cem: each front tenant serves the 1-iteration CEM
  # program inline and refines with the full program in the
  # background (serving.speculative — refined actions are
  # version-stamped, never served across a param hot-swap).
  speculative_cem: bool = False
  # Router-side observation-dedup cache entries (0 disables);
  # identical quantized frames short-circuit at the caller.
  dedup_capacity: int = 0
  # Lifecycle. The restart budget is RATE-based (ISSUE 14): a crashed
  # actor may be respawned up to `max_actor_restarts` times per
  # `restart_window_secs` sliding window — a crash-loop trips the
  # budget in minutes while a long-lived fleet absorbs unbounded
  # occasional churn (restart_window_secs=0 restores the lifetime cap).
  actor_crash_policy: str = "restart"
  max_actor_restarts: int = 3
  restart_window_secs: float = 600.0
  # "fatal" (default): learner death takes the fleet down. "resume":
  # the learner is respawned and `train_qtopt` resumes from the latest
  # checkpoint in model_dir while the HOST keeps the replay store and
  # serving engine alive — at most one publish cadence of training
  # progress is lost, and no collected experience at all.
  learner_crash_policy: str = "fatal"
  max_learner_restarts: int = 2
  heartbeat_timeout_secs: float = 300.0  # 0 disables hang detection
  # Actor hang detection cadence (actors beat per collect batch, so a
  # much tighter bound than the learner's compile-warmup-tolerant
  # global timeout is safe). 0 = use heartbeat_timeout_secs.
  actor_heartbeat_timeout_secs: float = 0.0
  launch_timeout_secs: float = 240.0
  run_timeout_secs: float = 1800.0
  distributed_learner: bool = False
  seed: int = 0
  authkey: bytes = b""  # per-fleet key generated at Fleet construction
  # RPC deadline/retry envelope for the DATA-PLANE clients (actor +
  # both learner clients, rpc.RpcClient): per-call reply deadline +
  # reconnect-and-retry. The orchestrator's control channel takes the
  # deadline but stays single-shot (retry would stall supervision).
  rpc_call_timeout_secs: float = 120.0
  rpc_max_retries: int = 2
  # Telemetry plane (docs/OBSERVABILITY.md). Empty = derived from the
  # fleet's model_dir at launch (<model_dir>/telemetry, /flightrec);
  # telemetry_dir="off" disables cross-process tracing entirely.
  telemetry_dir: str = ""
  flightrec_dir: str = ""
  telemetry_poll_secs: float = 10.0  # 0 disables the aggregated poll
  # Alert sentinel over the aggregated fleet view (ISSUE 15): watch
  # rules (telemetry.sentinel.fleet_watches, gin-tunable) evaluated at
  # every poll; a page-severity breach dumps flight records naming the
  # offending role, exactly like the hang path. Needs the telemetry
  # plane (poll cadence > 0).
  sentinel: bool = True
  # Closed-loop control plane (ISSUE 18, docs/CONTROL.md): when on,
  # a jax-free `control.Controller` evaluates the gin-tunable rule
  # table (`control.policies.fleet_rules`) over every aggregated
  # telemetry poll and drives the fleet's own levers — actor/front
  # scaling, targeted kill-and-respawn, admission retunes, the
  # degradation ladder — under a global rate-based actuation budget.
  # `control_dry_run` evaluates + records would-act decisions without
  # touching an actuator (the rollout workflow). Paging stays the
  # FALLBACK tier: the sentinel's `on_act` hook routes page-severity
  # alerts through the controller first, and only an unremediated
  # breach pages.
  control: bool = False
  control_dry_run: bool = False
  control_cadence_secs: float = 0.0  # 0 = every telemetry poll
  control_max_actions: int = 4
  control_budget_window_secs: float = 300.0
  # Graceful degradation: tenants in SHED ORDER (lowest priority
  # first); the `shed_tenant` actuator clamps the next one's admission
  # rate to `control_shed_rate_rps` ("serve the flagship slowly
  # rather than everyone badly"), `restore_tenants` undoes all sheds.
  control_shed_priorities: Tuple[str, ...] = ()
  control_shed_rate_rps: float = 1.0
  # Front replica recovery (ISSUE 18): a lost front replica is
  # RESPAWNED at its index under its own rate budget
  # (`max_front_restarts` per `restart_window_secs`), rejoining the
  # broadcast tree and — via the front observer seam — the routers
  # (`ServingRouter.mark_alive`). Budget exhausted or respawn off:
  # the ISSUE-17 survivable membership shrink, unchanged.
  front_respawn: bool = True
  max_front_restarts: int = 2
  # Fault injection (tests / bench failure-path rehearsal). The
  # legacy single-fault knobs remain; `fault_plan` is the ISSUE-14
  # deterministic schedule (faults.FaultPlan — picklable, shipped to
  # every child, each role injects its own events through the
  # rpc/actor/learner seams).
  actor_crash_after_episodes: Optional[int] = None
  actor_crash_mode: str = "raise"
  crash_actor_index: int = 0
  learner_crash_after_steps: Optional[int] = None
  fault_plan: Optional[Any] = None

  def __post_init__(self):
    if not self.authkey:
      # Per-fleet secret, generated at construction and shipped (via
      # pickle) to every child: two fleets on one machine can never
      # cross-connect. Never b"" — a falsy authkey makes the stdlib
      # Listener SKIP the auth challenge the Client then waits for
      # (a handshake deadlock, found the hard way).
      self.authkey = secrets.token_bytes(16)
    if self.num_actors < 0:
      raise ValueError(f"num_actors must be >= 0, got {self.num_actors}")
    if self.num_actors < 1 and self.pod_hosts < 1:
      raise ValueError(
          "the fleet needs at least one collector: num_actors >= 1 or "
          "pod_hosts >= 1")
    if self.learner_hosts < 1:
      raise ValueError(
          f"learner_hosts must be >= 1, got {self.learner_hosts}")
    if self.batch_size % self.learner_hosts != 0:
      raise ValueError(
          f"batch_size ({self.batch_size}) must divide evenly across "
          f"the learner group (learner_hosts={self.learner_hosts}): "
          "each rank samples and feeds batch_size/learner_hosts rows")
    if self.learner_hosts > 1 and self.learner_crash_policy != "fatal":
      raise ValueError(
          "learner_hosts > 1 requires learner_crash_policy='fatal': a "
          "group member's death tears the gloo collective, so the only "
          "sound recovery is a full-group teardown")
    if self.pod_hosts < 0:
      raise ValueError(f"pod_hosts must be >= 0, got {self.pod_hosts}")
    if self.envs_per_pod < 1:
      raise ValueError(
          f"envs_per_pod must be >= 1, got {self.envs_per_pod}")
    if self.pod_rollout_length < 1:
      raise ValueError(
          f"pod_rollout_length must be >= 1, got "
          f"{self.pod_rollout_length}")
    if self.pod_hosts and self.env == "toy_grasp":
      raise ValueError(
          "pod_hosts requires a functional env family (pose/"
          "mujoco_pose/procgen): Anakin pods vmap the env inside pmap, "
          "which toy_grasp's stateful host env cannot do")
    if self.env not in _ENVS:
      raise ValueError(f"env must be one of {_ENVS}, got {self.env!r}")
    if self.actor_crash_policy not in _CRASH_POLICIES:
      raise ValueError(
          f"actor_crash_policy must be one of {_CRASH_POLICIES}, got "
          f"{self.actor_crash_policy!r}")
    if self.actor_crash_mode not in _CRASH_MODES:
      raise ValueError(
          f"actor_crash_mode must be one of {_CRASH_MODES}, got "
          f"{self.actor_crash_mode!r}")
    if self.learner_crash_policy not in _LEARNER_CRASH_POLICIES:
      raise ValueError(
          f"learner_crash_policy must be one of "
          f"{_LEARNER_CRASH_POLICIES}, got "
          f"{self.learner_crash_policy!r}")
    if self.overflow not in _OVERFLOW:
      raise ValueError(
          f"overflow must be one of {_OVERFLOW}, got {self.overflow!r}")
    if self.transport not in TRANSPORTS:
      raise ValueError(
          f"transport must be one of {TRANSPORTS}, got "
          f"{self.transport!r}")
    if self.serving_hosts < 1:
      raise ValueError(
          f"serving_hosts must be >= 1, got {self.serving_hosts}")
    if self.replay_hosts < 0:
      raise ValueError(
          f"replay_hosts must be >= 0, got {self.replay_hosts}")
    if self.broadcast_degree < 1:
      raise ValueError(
          f"broadcast_degree must be >= 1, got {self.broadcast_degree}")
    if self.serving_hosts > 1 and self.replay_hosts < 1:
      raise ValueError(
          "serving_hosts > 1 requires replay_hosts >= 1: serving "
          "replicas are engine-only (no replay store), so the replay "
          "plane must live on dedicated shard hosts")
    if self.front_hosts < 0:
      raise ValueError(
          f"front_hosts must be >= 0, got {self.front_hosts}")
    if self.front_spread < 1:
      raise ValueError(
          f"front_spread must be >= 1, got {self.front_spread}")
    if self.front_hosts and self.front_spread > self.front_hosts:
      raise ValueError(
          f"front_spread ({self.front_spread}) cannot exceed "
          f"front_hosts ({self.front_hosts})")
    if not self.front_tenants:
      raise ValueError("front_tenants must name at least one tenant")
    if self.dedup_capacity < 0:
      raise ValueError(
          f"dedup_capacity must be >= 0, got {self.dedup_capacity}")
    if self.max_front_restarts < 0:
      raise ValueError(
          f"max_front_restarts must be >= 0, got "
          f"{self.max_front_restarts}")
    if self.control_max_actions < 1:
      raise ValueError(
          f"control_max_actions must be >= 1, got "
          f"{self.control_max_actions}")
    if self.control_cadence_secs < 0 or self.control_budget_window_secs < 0:
      raise ValueError(
          "control_cadence_secs and control_budget_window_secs must "
          "be >= 0")
    if self.control_shed_rate_rps <= 0:
      raise ValueError(
          f"control_shed_rate_rps must be positive, got "
          f"{self.control_shed_rate_rps}")
    if self.fault_plan is not None and not isinstance(
        self.fault_plan, faults_lib.FaultPlan):
      raise ValueError(
          f"fault_plan must be a faults.FaultPlan, got "
          f"{type(self.fault_plan).__name__}")


@dataclasses.dataclass
class FleetResult:
  """What a completed fleet run measured (the bench `fleet` axis)."""

  env_steps_per_sec: float
  learner_steps_per_sec: float
  param_refresh_lag: Dict[str, Any]
  replay_staleness: Dict[str, Any]
  publishes: int
  params_version: int
  actor_restarts: int
  wall_secs: float
  clean_shutdown: bool
  metrics: Dict[str, Any]
  # Recovery accounting (ISSUE 14): one record per supervised fault
  # the orchestrator detected AND recovered from ({fault, target,
  # mttr_ms, ...}); learner respawns under the resume policy; elastic
  # membership changes ({action, index, t}).
  recoveries: List[Dict[str, Any]] = dataclasses.field(
      default_factory=list)
  learner_restarts: int = 0
  scale_events: List[Dict[str, Any]] = dataclasses.field(
      default_factory=list)


class Fleet:
  """Launches, supervises, and tears down one learner/actor fleet."""

  def __init__(self, config: FleetConfig, model_dir: str,
               gin_configs: Sequence[str] = ()):
    self.config = config
    # The per-run resolved copy (telemetry/flight-record dirs filled
    # in) is built at launch(); until then fall back to the caller's.
    self._run_config = config
    self.model_dir = model_dir
    self.gin_configs = tuple(gin_configs)
    self._ctx = mp.get_context("spawn")
    # Stop signals: the host has its own (it must outlive the
    # actor/learner drain so the final metrics read has someone to
    # talk to), and every actor gets a PER-ACTOR event so elastic
    # scale-down can drain one actor without touching the rest
    # (`scale_to`); the shutdown barrier drains the whole fleet by
    # setting every per-actor event under `_scale_lock`.
    self._host_stop = self._ctx.Event()
    self._host: Optional[mp.Process] = None
    # Cross-host topology (ISSUE 16): serving replicas (host_index>0)
    # and replay shard hosts, all sharing `_host_stop` — every
    # host-class process must outlive the actor/learner drain so the
    # shutdown barrier can read final metrics from each.
    self._serving: Dict[int, mp.Process] = {}
    self._shards: Dict[int, mp.Process] = {}
    # Replicated front tier (ISSUE 17): front replica death is the
    # one SURVIVABLE host-class failure — lost replicas move to
    # `front_failures` and the membership shrinks.
    self._fronts: Dict[int, mp.Process] = {}
    self.front_failures: List[Dict[str, Any]] = []
    # One persistent control entry per extra host: {name, address,
    # client} — client opened lazily, dropped on poisoning like the
    # root control channel.
    self._aux_hosts: List[Dict[str, Any]] = []
    self._addresses: Optional[Dict[str, Any]] = None
    self._learner: Optional[mp.Process] = None
    # Learner group (ISSUE 19): ranks 1..N-1 of the multi-process
    # learner. Rank 0 stays `self._learner` so every existing
    # supervision/restart path sees the group through its chief; any
    # peer's death is fatal (the collective is torn).
    self._learner_peers: Dict[int, mp.Process] = {}
    self._actors: Dict[int, mp.Process] = {}
    self._actor_stops: Dict[int, Any] = {}
    # Anakin pods (ISSUE 19): vectorized collectors supervised like
    # actors (same crash policy + restart budget), drained like actors
    # at shutdown so their final commits land before the metrics read.
    self._pods: Dict[int, mp.Process] = {}
    self._pod_stops: Dict[int, Any] = {}
    self._pod_restarts: Dict[int, int] = {}
    self._draining: List[Tuple[int, mp.Process]] = []
    self._heartbeats: Dict[str, Any] = {}
    self._spawned_at: Dict[str, float] = {}
    self._restarts: Dict[int, int] = {}
    # Sliding-window restart stamps per target name — the RATE-based
    # budget (restarts per restart_window_secs, not per lifetime).
    self._restart_times: Dict[str, Any] = {}
    self._learner_restarts = 0
    # In-flight recoveries: detected faults whose respawned process
    # has not yet stamped a heartbeat. Completed ones move to
    # `recoveries` with their measured MTTR.
    self._pending_recoveries: List[Dict[str, Any]] = []
    self.recoveries: List[Dict[str, Any]] = []
    self.scale_events: List[Dict[str, Any]] = []
    # Guards actor-membership mutations: scale_to() may be called
    # from another thread while wait() supervises.
    self._scale_lock = threading.RLock()
    self._next_actor_index = config.num_actors
    self._next_pod_index = config.pod_hosts
    self._control: Optional[RpcClient] = None
    self._address: Optional[Tuple[str, int]] = None
    self._error: Optional[BaseException] = None
    self._launched = False
    self._closed = False
    self._t_launched: Optional[float] = None
    self._tracer: Optional[tcore.Tracer] = None
    self._telemetry_file: Optional[Any] = None
    self._t_last_poll = 0.0
    self._sentinel: Optional[sentinel_lib.Sentinel] = None
    # Closed-loop control plane (ISSUE 18): built at launch when
    # `config.control` is on; stepped after every telemetry poll.
    self._controller: Optional[control_lib.Controller] = None
    self._degradation: Optional[control_lib.DegradationLadder] = None
    # Front membership callbacks `(event, index, address)` with event
    # in {"respawned", "lost", "added", "removed"} — a ServingRouter
    # owner calls `mark_alive`/`mark_dead` from them so a respawned
    # replica rejoins placement with NO manual step.
    self._front_observers: List[Callable[[str, int, Any], None]] = []
    self._front_restarts: Dict[int, int] = {}
    self._next_front_index = config.front_hosts

  # ---- launch ----

  def _run_launch_gate(self) -> None:
    """`run_t2r_trainer --validate_only` as the pre-spawn gate."""
    for config_path in self.gin_configs:
      result = subprocess.run(
          [sys.executable, "-m",
           "tensor2robot_tpu.bin.run_t2r_trainer",
           "--validate_only", "--gin_configs", config_path],
          capture_output=True, text=True, timeout=300)
      if result.returncode != 0:
        raise FleetError(
            f"launch gate rejected {config_path!r} "
            f"(validate_only exit {result.returncode}):\n"
            f"{result.stdout}\n{result.stderr}")

  def _heartbeat(self, name: str):
    value = self._ctx.Value("d", time.monotonic())
    self._heartbeats[name] = value
    self._spawned_at[name] = time.monotonic()
    return value

  def _spawn_actor(self, index: int, incarnation: int) -> None:
    name = f"t2r-fleet-actor-{index}"
    heartbeat = self._heartbeat(name)
    stop = self._actor_stops.get(index)
    if stop is None:
      stop = self._actor_stops[index] = self._ctx.Event()
    process = self._ctx.Process(
        target=actor_lib.actor_main,
        args=(self._run_config, index, self._addresses or self._address,
              stop, heartbeat, incarnation),
        name=name, daemon=True)
    process.start()
    self._actors[index] = process

  def _spawn_pod(self, index: int, incarnation: int) -> None:
    name = f"t2r-fleet-pod-{index}"
    heartbeat = self._heartbeat(name)
    stop = self._pod_stops.get(index)
    if stop is None:
      stop = self._pod_stops[index] = self._ctx.Event()
    process = self._ctx.Process(
        target=pod_lib.pod_main,
        args=(self._run_config, index, self._addresses or self._address,
              stop, heartbeat, incarnation),
        name=name, daemon=True)
    process.start()
    self._pods[index] = process

  def _spawn_learner(self, incarnation: int = 0) -> None:
    config = self._run_config
    world = int(getattr(config, "learner_hosts", 1))
    coordinator_address = None
    if config.distributed_learner or world > 1:
      from tensor2robot_tpu.parallel.distributed import (
          ephemeral_coordinator_address,
      )
      coordinator_address = ephemeral_coordinator_address()
    self._learner = self._ctx.Process(
        target=learner_lib.learner_main,
        args=(config, self.model_dir,
              self._addresses or self._address,
              self._heartbeat("t2r-fleet-learner"), coordinator_address,
              incarnation, world, 0),
        name="t2r-fleet-learner", daemon=True)
    self._learner.start()
    for rank in range(1, world):
      name = f"t2r-fleet-learner-r{rank}"
      process = self._ctx.Process(
          target=learner_lib.learner_main,
          args=(config, self.model_dir,
                self._addresses or self._address,
                self._heartbeat(name), coordinator_address,
                incarnation, world, rank),
          name=name, daemon=True)
      process.start()
      self._learner_peers[rank] = process

  def _await_ready(self, parent_conn: Any, process: mp.Process,
                   what: str, timeout_secs: float) -> Tuple[str, int]:
    """One ready-handshake: blocks for the child's address report."""
    if not parent_conn.poll(timeout_secs):
      raise FleetError(
          f"{what} did not report ready within {timeout_secs:.0f}s "
          f"(exitcode={process.exitcode})")
    try:
      info = parent_conn.recv()
    except (EOFError, OSError):
      # poll() also returns True on EOF: a child that died DURING
      # construction (bad config, import failure) lands here, not in
      # the timeout branch — same latch/abort treatment.
      process.join(timeout=10.0)
      raise FleetError(
          f"{what} died before reporting ready "
          f"(exitcode={process.exitcode})") from None
    parent_conn.close()
    return tuple(info["address"])

  def _spawn_extra_hosts(self, config: FleetConfig) -> None:
    """Serving replicas + replay shard hosts: spawn all, then await
    every ready-handshake under ONE shared launch deadline."""
    pending: List[Tuple[Dict[str, Any], Any, mp.Process, str]] = []
    for i in range(1, config.serving_hosts):
      name = f"t2r-fleet-host-{i}"
      parent_conn, child_conn = self._ctx.Pipe()
      process = self._ctx.Process(
          target=host_lib.host_main,
          args=(config, child_conn, self._host_stop,
                self._heartbeat(name), i, self._address),
          name=name, daemon=True)
      process.start()
      child_conn.close()
      self._serving[i] = process
      entry = {"kind": "serving", "index": i, "name": f"host{i}",
               "address": None, "client": None}
      self._aux_hosts.append(entry)
      pending.append((entry, parent_conn, process, f"serving host {i}"))
    for i in range(config.replay_hosts):
      name = f"t2r-fleet-shard-{i}"
      parent_conn, child_conn = self._ctx.Pipe()
      process = self._ctx.Process(
          target=host_lib.replay_shard_main,
          args=(config, i, self._address, child_conn, self._host_stop,
                self._heartbeat(name)),
          name=name, daemon=True)
      process.start()
      child_conn.close()
      self._shards[i] = process
      entry = {"kind": "shard", "index": i, "name": f"shard{i}",
               "address": None, "client": None}
      self._aux_hosts.append(entry)
      pending.append((entry, parent_conn, process, f"replay shard {i}"))
    for i in range(getattr(config, "front_hosts", 0)):
      pending.append(self._spawn_front(config, i))
    deadline = time.monotonic() + config.launch_timeout_secs
    for entry, parent_conn, process, what in pending:
      remaining = max(0.0, deadline - time.monotonic())
      entry["address"] = self._await_ready(
          parent_conn, process, what, remaining)

  def _spawn_front(self, config: FleetConfig, index: int):
    """Forks one front replica and registers its bookkeeping; returns
    the `(entry, parent_conn, process, what)` pending-handshake tuple
    (launch, respawn, and front scale-up all await it the same way)."""
    name = f"t2r-fleet-front-{index}"
    parent_conn, child_conn = self._ctx.Pipe()
    process = self._ctx.Process(
        target=front_lib.front_main,
        args=(config, index, self._address, child_conn,
              self._host_stop, self._heartbeat(name)),
        name=name, daemon=True)
    process.start()
    child_conn.close()
    self._fronts[index] = process
    entry = {"kind": "front", "index": index, "name": f"front{index}",
             "address": None, "client": None}
    self._aux_hosts.append(entry)
    return entry, parent_conn, process, f"front host {index}"

  def _aux_client(self, entry: Dict[str, Any]) -> Optional[RpcClient]:
    """The entry's control client, (re)connected on demand. Same
    single-shot envelope as the root control channel."""
    if entry["client"] is None:
      config = self._run_config
      try:
        entry["client"] = RpcClient(
            entry["address"], authkey=config.authkey,
            connect_timeout_secs=10.0,
            call_timeout_secs=config.rpc_call_timeout_secs,
            max_retries=0, transport=config.transport,
            sndbuf=config.tcp_sndbuf, rcvbuf=config.tcp_rcvbuf)
      except Exception:  # noqa: BLE001
        log.warning("control reconnect to %s failed", entry["name"],
                    exc_info=True)
        return None
    return entry["client"]

  def _aux_call(self, entry: Dict[str, Any], method: str,
                payload: Any = None,
                timeout_secs: Optional[float] = None) -> Any:
    """One control call to an extra host; poisoned-on-timeout clients
    are dropped so the next call reconnects (rpc.py contract)."""
    client = self._aux_client(entry)
    if client is None:
      raise FleetError(f"no control channel to {entry['name']}")
    try:
      return client.call(method, payload, timeout_secs=timeout_secs)
    except Exception:
      client.close()
      entry["client"] = None
      raise

  def _configure_broadcast(self, config: FleetConfig) -> None:
    """Wires the d-ary publication tree over the serving hosts AND
    the front replicas: one combined heap layout (serving hosts
    first, fronts after), so the learner's single uplink fans to
    every engine AND every front arena. Each host learns its forward
    set and its depth (stamped into act replies as `params_hop` for
    per-hop lag attribution)."""
    serving = list(self._addresses["serving"])
    front_entries = [entry for entry in self._aux_hosts
                     if entry["kind"] == "front"]
    combined = serving + [entry["address"] for entry in front_entries]
    if len(combined) < 2:
      return  # single serving host: root defaults (no children, hop 0)
    depths = broadcast_depths(len(combined), config.broadcast_degree)
    replicas = [entry for entry in self._aux_hosts
                if entry["kind"] == "serving"]
    for i in range(len(combined)):
      children = [list(combined[c]) for c in broadcast_children(
          i, len(combined), config.broadcast_degree)]
      payload = {"children": children, "depth": depths[i]}
      if i == 0:
        self._control.call("configure_broadcast", payload,
                           timeout_secs=30.0)
      elif i < len(serving):
        self._aux_call(replicas[i - 1], "configure_broadcast", payload,
                       timeout_secs=30.0)
      else:
        self._aux_call(front_entries[i - len(serving)],
                       "configure_broadcast", payload,
                       timeout_secs=30.0)
    if self._tracer is not None:
      self._tracer.event("fleet.broadcast_configured",
                         hosts=len(combined),
                         degree=config.broadcast_degree,
                         max_depth=max(depths))

  def launch(self) -> None:
    """Gate → hosts (handshakes) → broadcast wiring → actors →
    learner."""
    if self._launched:
      return
    self._run_launch_gate()
    # Resolve the telemetry plane BEFORE spawn into a per-RUN copy:
    # the copy ships (via pickle) to every child, so this is the one
    # place the trace/flight-record directories are decided — and the
    # caller's FleetConfig is never mutated (a reused config must not
    # inherit run 1's dirs, nor lose an explicit "off" opt-out).
    telemetry_dir = self.config.telemetry_dir
    if telemetry_dir == "off":
      telemetry_dir = ""  # tracing off; flight dumps keep working
    elif not telemetry_dir:
      telemetry_dir = os.path.join(self.model_dir, "telemetry")
    config = dataclasses.replace(
        self.config,
        telemetry_dir=telemetry_dir,
        flightrec_dir=(self.config.flightrec_dir
                       or flightrec.flightrec_dir(self.model_dir)))
    self._run_config = config
    if config.telemetry_dir:
      # The orchestrator's own timeline: a PRIVATE tracer (never the
      # process-global one — the supervising process may be a trainer
      # or a test with its own telemetry identity).
      self._tracer = tcore.Tracer().configure(
          "orchestrator", trace_dir=config.telemetry_dir)
    if (config.control and config.telemetry_dir
        and config.telemetry_poll_secs):
      # The closed-loop control plane (ISSUE 18): the gin-tunable
      # rule table over the standard actuator set, stepped after
      # every aggregated poll. Built BEFORE the sentinel so the
      # sentinel's act tier can route alerts through it.
      if config.control_shed_priorities:
        self._degradation = control_lib.DegradationLadder(
            config.control_shed_priorities,
            retune=self._shed_retune,
            shed_rate_rps=config.control_shed_rate_rps)
      self._controller = control_lib.Controller(
          control_lib.fleet_rules(),
          control_lib.fleet_actuators(
              self, on_page=self._control_page,
              degradation=self._degradation),
          cadence_secs=config.control_cadence_secs,
          dry_run=config.control_dry_run,
          max_actions=config.control_max_actions,
          budget_window_secs=config.control_budget_window_secs,
          decisions_path=os.path.join(
              config.telemetry_dir, control_lib.DECISIONS_FILENAME),
          tracer=self._tracer)
    if (config.telemetry_dir and config.sentinel
        and config.telemetry_poll_secs and perf_lib.plane_enabled()):
      # The fleet sentinel (ISSUE 15): gin-tunable rules evaluated
      # over every aggregated poll; a page-severity breach first
      # offers itself to the controller's act tier (ISSUE 18 — a
      # successful remediation demotes the page), and only an
      # unremediated breach triggers the flight-recorder path below,
      # role-named like the hang path.
      self._sentinel = sentinel_lib.Sentinel(
          sentinel_lib.fleet_watches(),
          alerts_path=os.path.join(config.telemetry_dir,
                                   sentinel_lib.ALERTS_FILENAME),
          on_act=(self._controller.handle_alert
                  if self._controller is not None else None),
          on_page=self._sentinel_page,
          tracer=self._tracer)
    parent_conn, child_conn = self._ctx.Pipe()
    self._host = self._ctx.Process(
        target=host_lib.host_main,
        args=(config, child_conn, self._host_stop,
              self._heartbeat("t2r-fleet-host")),
        name="t2r-fleet-host", daemon=True)
    self._host.start()
    child_conn.close()
    try:
      # Handshake: the host reports its bound RPC address once its
      # engine is warm; a host that died compiling surfaces here with
      # its exit code instead of a silent hang.
      self._address = self._await_ready(
          parent_conn, self._host, "host", config.launch_timeout_secs)
      # Extra hosts (ISSUE 16): serving replicas + replay shards, all
      # handshaking against the ROOT's clock. Spawned after the root
      # is warm (they need its address), awaited in parallel — the
      # launch timeout covers the whole topology, not each host.
      self._spawn_extra_hosts(config)
    except FleetError as e:
      self._latch(e)
      self._abort()
      raise self._error from None
    self._addresses = {
        "serving": [self._address] + [
            entry["address"] for entry in self._aux_hosts
            if entry["kind"] == "serving"],
        "shards": [entry["address"] for entry in self._aux_hosts
                   if entry["kind"] == "shard"],
        # Front replicas are NOT act-traffic targets (actors
        # round-robin over "serving" only); routers read this map.
        "fronts": {entry["index"]: entry["address"]
                   for entry in self._aux_hosts
                   if entry["kind"] == "front"},
    }
    # The control channel rides the DEADLINE half of the envelope
    # only: every control call sits on a latency-bounded path (the
    # supervision loop, the shutdown barrier, forensics) with its own
    # poisoned-connection recovery, and a transparent
    # reconnect-and-retry would multiply a wedged host's stall by
    # (retries+1) — freezing hang detection for exactly the window
    # the chaos MTTR gates measure. Data-plane clients keep retries.
    self._control = RpcClient(
        self._address, authkey=config.authkey,
        call_timeout_secs=config.rpc_call_timeout_secs,
        max_retries=0, transport=config.transport,
        sndbuf=config.tcp_sndbuf, rcvbuf=config.tcp_rcvbuf)
    try:
      self._configure_broadcast(config)
    except Exception as e:  # noqa: BLE001 — any wiring failure is fatal
      self._latch(FleetError(f"broadcast-tree configuration failed: "
                             f"{e!r}"))
      self._abort()
      raise self._error from None
    for index in range(config.num_actors):
      self._restarts[index] = 0
      self._spawn_actor(index, incarnation=0)
    for index in range(config.pod_hosts):
      self._pod_restarts[index] = 0
      self._spawn_pod(index, incarnation=0)
    self._spawn_learner(incarnation=0)
    self._launched = True
    self._t_launched = time.monotonic()
    if self._tracer is not None:
      self._tracer.event("orchestrator.launched",
                         actors=config.num_actors,
                         pods=config.pod_hosts,
                         learner_hosts=config.learner_hosts)

  # ---- supervision ----

  def _latch(self, error: BaseException) -> None:
    """First failure wins — the data/plane.py latch pattern: teardown
    noise after the latch never replaces the root cause."""
    if self._error is None:
      self._error = error

  # ---- the rate-based restart budget ----

  def _budget_ok(self, target: str) -> bool:
    """True while `target` has budget left in the SLIDING restart
    window (restarts per `restart_window_secs`, not per lifetime —
    window 0 restores the lifetime cap). Expired stamps are pruned
    here, so a long-lived fleet absorbs occasional churn forever
    while a crash-loop trips the budget within one window."""
    window = self.config.restart_window_secs
    if target == "learner":
      limit = self.config.max_learner_restarts
    elif target.startswith("front-"):
      limit = self.config.max_front_restarts
    else:
      limit = self.config.max_actor_restarts
    stamps = self._restart_times.setdefault(
        target, collections.deque())
    if window:
      now = time.monotonic()
      while stamps and now - stamps[0] > window:
        stamps.popleft()
    return len(stamps) < limit

  def _charge_restart(self, target: str) -> None:
    self._restart_times.setdefault(
        target, collections.deque()).append(time.monotonic())

  # ---- fault recovery ----

  def _begin_recovery(self, fault: str, target: str, name: str,
                      **detail: Any) -> None:
    """Registers an in-flight recovery: the respawned process named
    `name` completes it by stamping its heartbeat (its first unit of
    real work — an actor's first collect batch, the learner's first
    resumed train step), which is when MTTR honestly ends."""
    if self._tracer is not None:
      self._tracer.event("fleet.fault_detected", fault=fault,
                         target=target, **detail)
    self._pending_recoveries.append({
        "fault": fault, "target": target,
        "t_detected": detail.pop("t_detected"),
        "t_respawned": time.monotonic(),
        "heartbeat": self._heartbeats[name],
        "detail": detail})

  def _complete_recoveries(self) -> None:
    still: List[Dict[str, Any]] = []
    for pending in self._pending_recoveries:
      stamped = pending["heartbeat"].value
      if stamped <= pending["t_respawned"]:
        still.append(pending)
        continue
      mttr_ms = (stamped - pending["t_detected"]) * 1e3
      entry = {"fault": pending["fault"], "target": pending["target"],
               "mttr_ms": round(mttr_ms, 1)}
      entry.update(pending["detail"])
      self.recoveries.append(entry)
      # The recovery histogram every chaos dashboard keys on
      # (docs/OBSERVABILITY.md); RPC-level recoveries observe the
      # same name from their own processes.
      faults_lib.recovery_histogram().observe(mttr_ms)
      if self._tracer is not None:
        self._tracer.event("fleet.recovered", **entry)
      log.warning("fleet recovered from %s (%s): MTTR %.0f ms",
                  pending["fault"], pending["target"], mttr_ms)
    self._pending_recoveries = still

  def _handle_actor_failure(self, index: int, fault: str,
                            t_detected: Optional[float] = None,
                            **detail: Any) -> None:
    """One dead/hung actor: respawn under the rate budget, or raise.

    ``t_detected`` is when the fault was DETECTED — callers whose
    handling itself takes time (the hang path's terminate/join
    escalation) pass the stamp they took at detection so MTTR never
    excludes the kill latency; None = detection is now (the exit-code
    poll path, where detection and handling coincide)."""
    target = f"actor-{index}"
    if (self.config.actor_crash_policy == "restart"
        and self._budget_ok(target)):
      self._restarts[index] += 1
      self._charge_restart(target)
      log.warning(
          "actor %d failed (%s %s); restart %d (budget %d per "
          "%.0fs window) — session will reopen with "
          "abort-of-staged-rows", index, fault, detail,
          self._restarts[index], self.config.max_actor_restarts,
          self.config.restart_window_secs)
      if t_detected is None:
        t_detected = time.monotonic()
      self._spawn_actor(index, incarnation=self._restarts[index])
      self._begin_recovery(fault, target, f"t2r-fleet-actor-{index}",
                           t_detected=t_detected, **detail)
      return
    raise FleetError(
        f"actor {index} died ({fault}, {detail}) under "
        f"policy={self.config.actor_crash_policy!r} after "
        f"{self._restarts[index]} restart(s) — restart budget "
        f"({self.config.max_actor_restarts} per "
        f"{self.config.restart_window_secs:.0f}s window) exhausted"
        if self.config.actor_crash_policy == "restart" else
        f"actor {index} died ({fault}, {detail}) under "
        f"policy={self.config.actor_crash_policy!r}")

  def _handle_pod_failure(self, index: int, fault: str,
                          t_detected: Optional[float] = None,
                          **detail: Any) -> None:
    """One dead/hung Anakin pod: same contract as an actor failure —
    the pod's staged rows were begin/commit-atomic on the shard host,
    so a respawn reopens a fresh session and no partial segment ever
    lands (`adds_total % (envs_per_pod * pod_rollout_length) == 0`
    is the pin)."""
    target = f"pod-{index}"
    if (self.config.actor_crash_policy == "restart"
        and self._budget_ok(target)):
      self._pod_restarts[index] += 1
      self._charge_restart(target)
      log.warning(
          "pod %d failed (%s %s); restart %d (budget %d per %.0fs "
          "window) — segments are committed atomically so no partial "
          "rows survive", index, fault, detail,
          self._pod_restarts[index], self.config.max_actor_restarts,
          self.config.restart_window_secs)
      if t_detected is None:
        t_detected = time.monotonic()
      self._spawn_pod(index, incarnation=self._pod_restarts[index])
      self._begin_recovery(fault, target, f"t2r-fleet-pod-{index}",
                           t_detected=t_detected, **detail)
      return
    raise FleetError(
        f"pod {index} died ({fault}, {detail}) under "
        f"policy={self.config.actor_crash_policy!r} after "
        f"{self._pod_restarts[index]} restart(s) — restart budget "
        f"({self.config.max_actor_restarts} per "
        f"{self.config.restart_window_secs:.0f}s window) exhausted"
        if self.config.actor_crash_policy == "restart" else
        f"pod {index} died ({fault}, {detail}) under "
        f"policy={self.config.actor_crash_policy!r}")

  def _handle_front_failure(self, index: int, fault: str,
                            t_detected: Optional[float] = None,
                            **detail: Any) -> None:
    """One lost front replica: RESPAWN under the front rate budget
    (ISSUE 18), membership SHRINK as the fallback (ISSUE 17).

    Fronts only serve — they hold no replay rows, no training lease,
    and no actor act-traffic — so a death is never fatal. With
    `front_respawn` on and budget left, the replica is respawned at
    its ORIGINAL index; the fresh address replaces the old one in the
    broadcast tree and the front observers are told "respawned" so a
    router owner re-admits it via `mark_alive(index, address)` — no
    manual step. Respawn off / budget spent / mid-shutdown: the
    survivable shrink — routers fail the replica's tenants over to
    HRW survivors on their side within one client deadline (the
    placement remap touches ONLY the lost replica's tenants), and
    the orchestrator prunes the broadcast tree so the next publish
    fans over the survivors instead of erroring at the dead child.
    """
    if t_detected is None:
      t_detected = time.monotonic()
    # The dead incarnation's bookkeeping goes either way.
    self._fronts.pop(index, None)
    name = f"t2r-fleet-front-{index}"
    self._heartbeats.pop(name, None)
    self._spawned_at.pop(name, None)
    entry = next(
        (e for e in self._aux_hosts
         if e["kind"] == "front" and e["index"] == index), None)
    if entry is not None:
      if entry["client"] is not None:
        entry["client"].close()
        entry["client"] = None
      self._aux_hosts.remove(entry)
    if self._addresses is not None:
      self._addresses.get("fronts", {}).pop(index, None)
    target = f"front-{index}"
    if (self.config.front_respawn and not self._closed
        and self._budget_ok(target)):
      try:
        address = self._respawn_front(index, fault, t_detected, detail)
      except FleetError:
        log.warning("front %d respawn failed; falling back to "
                    "membership shrink", index, exc_info=True)
      else:
        self._notify_front_observers("respawned", index, address)
        return
    event = {"fault": fault, "target": target,
             "t_detected": t_detected}
    event.update(detail)
    self.front_failures.append(event)
    if self._tracer is not None:
      self._tracer.event("fleet.front_replica_lost", **event)
    log.warning("front replica %d lost (%s %s); %d replica(s) "
                "remain — routers reshed its tenants to survivors",
                index, fault, detail, len(self._fronts))
    try:
      self._configure_broadcast(self._run_config)
    except Exception:  # noqa: BLE001 — best-effort rewire
      log.warning("broadcast rewire after front loss failed",
                  exc_info=True)
    self._notify_front_observers("lost", index, None)

  def _respawn_front(self, index: int, fault: str, t_detected: float,
                     detail: Dict[str, Any]) -> Tuple[str, int]:
    """Respawns one front replica at its original index; returns the
    NEW address. A failed respawn unwinds its half-spawn bookkeeping
    and raises `FleetError` (the caller falls back to the shrink)."""
    self._front_restarts[index] = self._front_restarts.get(index, 0) + 1
    self._charge_restart(f"front-{index}")
    log.warning(
        "front %d failed (%s %s); respawn %d (budget %d per %.0fs "
        "window)", index, fault, detail, self._front_restarts[index],
        self.config.max_front_restarts,
        self.config.restart_window_secs)
    entry, parent_conn, process, what = self._spawn_front(
        self._run_config, index)
    try:
      entry["address"] = self._await_ready(
          parent_conn, process, what,
          self._run_config.launch_timeout_secs)
    except FleetError:
      self._fronts.pop(index, None)
      self._heartbeats.pop(f"t2r-fleet-front-{index}", None)
      self._spawned_at.pop(f"t2r-fleet-front-{index}", None)
      if entry in self._aux_hosts:
        self._aux_hosts.remove(entry)
      if process.is_alive():
        process.kill()
        process.join(timeout=5.0)
      raise
    if self._addresses is not None:
      self._addresses.setdefault("fronts", {})[index] = entry["address"]
    self._begin_recovery(fault, f"front-{index}",
                         f"t2r-fleet-front-{index}",
                         t_detected=t_detected, **detail)
    try:
      self._configure_broadcast(self._run_config)
    except Exception:  # noqa: BLE001 — best-effort rewire
      log.warning("broadcast rewire after front respawn failed",
                  exc_info=True)
    return entry["address"]

  def add_front_observer(
      self, fn: Callable[[str, int, Any], None]) -> None:
    """Registers a front-membership callback `(event, index,
    address)`, event in {"respawned", "lost", "added", "removed"} —
    the seam a `ServingRouter` owner uses to call
    `mark_alive(index, address)` / `mark_dead(index)` so placement
    tracks supervision with no manual step (ISSUE 18)."""
    self._front_observers.append(fn)

  def _notify_front_observers(self, event: str, index: int,
                              address: Any) -> None:
    for fn in list(self._front_observers):
      try:
        fn(event, index, address)
      except Exception:  # noqa: BLE001 — an observer must never
        # break supervision (it runs on the supervision thread).
        log.warning("front observer failed on %s front %d", event,
                    index, exc_info=True)

  def _check_heartbeats(self) -> None:
    """Hang detection. A stale ACTOR heartbeat is a recoverable fault
    under the restart policy (kill-and-respawn, the `actor_hang`
    class); a stale learner/host heartbeat stays fatal — a hung
    learner holds the training lease and a hung host IS the fleet."""
    global_timeout = self.config.heartbeat_timeout_secs
    actor_timeout = (self.config.actor_heartbeat_timeout_secs
                     or global_timeout)
    now = time.monotonic()
    for name, value in list(self._heartbeats.items()):
      is_actor = name.startswith("t2r-fleet-actor-")
      # Pods stamp per-segment like actors stamp per-batch, so they
      # share the collector timeout AND the kill-and-respawn policy.
      is_pod = name.startswith("t2r-fleet-pod-")
      timeout = (actor_timeout if (is_actor or is_pod)
                 else global_timeout)
      if not timeout:
        continue
      last = max(value.value, self._spawned_at.get(name, 0.0))
      stale = now - last
      if stale <= timeout:
        continue
      if name.startswith("t2r-fleet-front-"):
        # A hung front replica is handled like a dead one: kill it
        # and shrink the membership (survivable — see
        # `_handle_front_failure`).
        index = int(name.rsplit("-", 1)[1])
        process = self._fronts.get(index)
        if process is None:
          continue
        log.warning("front %d heartbeat stale for %.0fs; killing the "
                    "hung replica", index, stale)
        # MTTR starts at detection, like the actor hang path: the
        # kill latency below is part of the outage.
        t_detected = time.monotonic()
        process.terminate()
        process.join(timeout=5.0)
        if process.is_alive():
          process.kill()
          process.join(timeout=5.0)
        self._handle_front_failure(
            index, faults_lib.SERVING_REPLICA_CRASH,
            t_detected=t_detected, stale_secs=round(stale, 1))
        continue
      if is_pod and self.config.actor_crash_policy == "restart":
        index = int(name.rsplit("-", 1)[1])
        process = self._pods.get(index)
        if process is None:
          continue  # drained by a concurrent scale_pods_to
        log.warning("pod %d heartbeat stale for %.0fs; killing the "
                    "hung process for respawn", index, stale)
        t_detected = time.monotonic()
        process.terminate()
        process.join(timeout=5.0)
        if process.is_alive():
          process.kill()
          process.join(timeout=5.0)
        self._handle_pod_failure(index, faults_lib.ACTOR_HANG,
                                 t_detected=t_detected,
                                 stale_secs=round(stale, 1))
        continue
      if is_actor and self.config.actor_crash_policy == "restart":
        index = int(name.rsplit("-", 1)[1])
        process = self._actors.get(index)
        if process is None:
          continue  # drained by a concurrent scale_down
        log.warning("actor %d heartbeat stale for %.0fs; killing the "
                    "hung process for respawn", index, stale)
        # MTTR starts HERE, at detection: a SIGTERM-masking hang pays
        # up to two 5s joins below, and that kill latency is part of
        # the outage the fleet experienced.
        t_detected = time.monotonic()
        process.terminate()
        process.join(timeout=5.0)
        if process.is_alive():
          process.kill()
          process.join(timeout=5.0)
        self._handle_actor_failure(index, faults_lib.ACTOR_HANG,
                                   t_detected=t_detected,
                                   stale_secs=round(stale, 1))
        continue
      raise FleetError(
          f"{name} heartbeat stale for {stale:.0f}s "
          f"(> {timeout:.0f}s): process hung")

  def _fresh_control(self) -> Optional[RpcClient]:
    """A new control-channel client (a timed-out call poisons the old
    one — rpc.py contract); None when the host is unreachable.
    Single-shot like the launch-time client: control calls must stay
    latency-bounded (see the `max_retries=0` rationale at launch)."""
    if self._address is None:
      return None
    try:
      return RpcClient(
          self._address, authkey=self._run_config.authkey,
          connect_timeout_secs=10.0,
          call_timeout_secs=self._run_config.rpc_call_timeout_secs,
          max_retries=0)
    except Exception:  # noqa: BLE001
      log.warning("control-channel reconnect failed", exc_info=True)
      return None

  def _poll_telemetry(self, force: bool = False) -> None:
    """One aggregated fleet-wide metrics read at the poll cadence:
    the host's registry (replay/serving/lag live at that choke point)
    plus every snapshot the other roles pushed, flattened per-role and
    appended to `<telemetry_dir>/fleet_metrics.jsonl` as one envelope
    record. `force` bypasses the cadence gate (the end-of-run view
    must land even when the learner finishes mid-interval)."""
    cadence = self._run_config.telemetry_poll_secs
    if (not cadence or self._control is None
        or not self._run_config.telemetry_dir):
      return
    now = time.monotonic()
    if not force and now - self._t_last_poll < cadence:
      return
    self._t_last_poll = now
    try:
      view = self._control.call("telemetry", timeout_secs=30.0)
    except Exception:  # noqa: BLE001 — instrumentation only
      # A timed-out call POISONS the client (rpc.py contract: the
      # late reply may still arrive and would be read as the answer
      # to the next control call — e.g. the final `metrics`).
      # Instrumentation must not corrupt the control channel: drop
      # the connection and open a fresh one; on failure, leave the
      # orchestrator without a control client (shutdown handles None).
      log.warning("fleet telemetry poll failed; reconnecting the "
                  "control channel", exc_info=True)
      self._control.close()
      self._control = self._fresh_control()
      return
    payload = tmetrics.scalars_from_snapshot(view.get("host") or {})
    for role, pushed in (view.get("pushed") or {}).items():
      payload.update(tmetrics.scalars_from_snapshot(
          pushed.get("snapshot") or {}, prefix=f"{role}/"))
    # Extra hosts fold into the SAME envelope, namespaced per host
    # (host1/..., shard0/...); pushed snapshots keep their role keys
    # (actor ids are fleet-unique, whichever host they report to).
    for entry in self._aux_hosts:
      try:
        aux_view = self._aux_call(entry, "telemetry", timeout_secs=30.0)
      except Exception:  # noqa: BLE001 — instrumentation only
        log.warning("telemetry poll of %s failed", entry["name"],
                    exc_info=True)
        continue
      payload.update(tmetrics.scalars_from_snapshot(
          aux_view.get("host") or {}, prefix=f"{entry['name']}/"))
      for role, pushed in (aux_view.get("pushed") or {}).items():
        payload.update(tmetrics.scalars_from_snapshot(
            pushed.get("snapshot") or {}, prefix=f"{role}/"))
    record = trecords.make_record(
        int(payload.get("replay.learner_step", 0)), payload,
        role="orchestrator")
    if self._telemetry_file is None:
      self._telemetry_file = open(
          os.path.join(self._run_config.telemetry_dir,
                       "fleet_metrics.jsonl"), "a")
    self._telemetry_file.write(json.dumps(record) + "\n")
    self._telemetry_file.flush()
    if self._tracer is not None:
      self._tracer.event("orchestrator.telemetry_poll",
                         metrics=len(payload))
    if self._sentinel is not None:
      # Watch rules over the SAME aggregated view that just landed in
      # fleet_metrics.jsonl — the sentinel sees exactly what the
      # operator's dashboard would. Page-severity breaches route
      # through the controller's act tier (on_act) synchronously
      # here, BEFORE the regular rule pass below.
      self._sentinel.evaluate(payload)
    if self._controller is not None:
      try:
        self._controller.maybe_step(
            payload, step=int(payload.get("replay.learner_step", 0)))
      except Exception:  # noqa: BLE001 — the policy plane must never
        # take down the supervision loop it advises.
        log.warning("control step failed", exc_info=True)

  def _sentinel_page(self, alert: Dict[str, Any]) -> None:
    """Page-severity alert → the flight-recorder path: the
    orchestrator dumps its own view (heartbeat ages, restart counts)
    with the OFFENDING ROLE in the reason — exactly the artifact the
    hang path produces — and asks a still-live host to dump its ring.
    Non-fatal: the fleet keeps running; the regression is documented.
    """
    if not self._run_config.flightrec_dir:
      return
    reason = (f"sentinel page: alert.{alert['rule']} on "
              f"{alert['metric']} = {alert.get('value'):.6g} "
              f"(role {alert['role']})")
    now = time.monotonic()
    ages = {
        name: round(now - max(value.value,
                              self._spawned_at.get(name, 0.0)), 3)
        for name, value in self._heartbeats.items()}
    extra: Dict[str, Any] = {"alert": alert,
                             "heartbeat_ages_secs": ages,
                             "actor_restarts": dict(self._restarts),
                             "pod_restarts": dict(self._pod_restarts)}
    if self._controller is not None:
      # An escalated page means the act tier did NOT remediate; the
      # decision tail shows why (cooldown, budget, actuator error).
      extra["control"] = self._controller.flight_extra()
    flightrec.dump(
        self._run_config.flightrec_dir, reason, extra=extra,
        role="orchestrator")
    if (self._control is not None and self._host is not None
        and self._host.is_alive()):
      try:
        self._control.call("flight_record", {
            "out_dir": self._run_config.flightrec_dir,
            "reason": reason}, timeout_secs=15.0)
      except Exception:  # noqa: BLE001 — forensics must not mask
        log.warning("host flight-record request failed", exc_info=True)
        # Poisoned-on-timeout contract (rpc.py): never let a later
        # control call read this call's late reply.
        self._control.close()
        self._control = self._fresh_control()

  def _control_page(self, decision: Dict[str, Any]) -> None:
    """The control plane's terminal lever (the `page` actuator): a
    rule ran out of cheaper actions, so this decision escalates to a
    human with the same flight-record artifact a sentinel page
    produces — plus the controller's own recent-decision tail, so the
    post-mortem shows every lever that was tried first."""
    if not self._run_config.flightrec_dir:
      return
    reason = (f"control page: rule {decision.get('rule')} on "
              f"{decision.get('metric')} (role {decision.get('role')})")
    now = time.monotonic()
    ages = {
        name: round(now - max(value.value,
                              self._spawned_at.get(name, 0.0)), 3)
        for name, value in self._heartbeats.items()}
    extra = {"decision": {k: v for k, v in decision.items()
                          if k != "detail"},
             "heartbeat_ages_secs": ages,
             "actor_restarts": dict(self._restarts),
             "pod_restarts": dict(self._pod_restarts)}
    if self._controller is not None:
      extra["control"] = self._controller.flight_extra()
    flightrec.dump(self._run_config.flightrec_dir, reason,
                   extra=extra, role="orchestrator")

  def _flight_record(self, error: BaseException) -> None:
    """The latched-error / hang-detection flight-recorder trigger:
    dump the orchestrator's view (heartbeat ages name a HUNG process —
    one that cannot dump itself) and ask a still-live host to dump its
    own ring; learner/actor dumps happen in their processes' except
    paths."""
    if not self._run_config.flightrec_dir:
      return
    now = time.monotonic()
    ages = {
        name: round(now - max(value.value,
                              self._spawned_at.get(name, 0.0)), 3)
        for name, value in self._heartbeats.items()}
    extra: Dict[str, Any] = {"heartbeat_ages_secs": ages,
                             "actor_restarts": dict(self._restarts),
                             "pod_restarts": dict(self._pod_restarts)}
    if self._controller is not None:
      # What the control plane saw and did before the latch — the
      # first question a post-mortem of a self-driving fleet asks.
      extra["control"] = self._controller.flight_extra()
    flightrec.dump(
        self._run_config.flightrec_dir, f"fleet latched: {error!r}",
        extra=extra, role="orchestrator")
    if (self._control is not None and self._host is not None
        and self._host.is_alive()):
      try:
        self._control.call("flight_record", {
            "out_dir": self._run_config.flightrec_dir,
            "reason": f"fleet latched: {error!r}"}, timeout_secs=15.0)
      except Exception:  # noqa: BLE001 — forensics must not mask
        log.warning("host flight-record request failed", exc_info=True)
        # Poisoned on timeout (rpc.py contract) and we are aborting:
        # drop it rather than let shutdown read a stale reply.
        self._control.close()
        self._control = None

  def _reap_draining(self) -> None:
    """Scale-down drains finish asynchronously; a drained actor's exit
    (any code — it was leaving) must never read as a crash."""
    still: List[Tuple[int, mp.Process]] = []
    for index, process in self._draining:
      if process.exitcode is None:
        still.append((index, process))
      elif process.exitcode != 0:
        log.warning("drained actor %d exited %s", index,
                    process.exitcode)
    self._draining = still

  def _supervise_once(self) -> bool:
    """One poll; returns True when the learner finished cleanly."""
    with self._scale_lock:
      # Learner-group peers first: a dead rank tears the gloo
      # collective, so rank 0 is (or soon will be) wedged inside an
      # all-reduce — the peer's exit code is the honest root cause.
      for rank, process in self._learner_peers.items():
        if process.exitcode is not None and process.exitcode != 0:
          raise FleetError(
              f"learner group rank {rank} died (exit "
              f"{process.exitcode}): the collective is torn, so the "
              "whole group is lost (learner_crash_policy='fatal' is "
              "the only sound policy for learner_hosts > 1)")
      learner = self._learner
      if learner.exitcode is not None:
        if learner.exitcode == 0:
          return True
        if (self.config.learner_crash_policy == "resume"
            and self._budget_ok("learner")):
          # The resume policy (ISSUE 14): respawn the learner — the
          # HOST stays up with the replay store and serving engine
          # intact, and `train_qtopt` restores from the latest
          # checkpoint in model_dir, so at most one publish cadence
          # of training progress is lost and no experience at all.
          self._learner_restarts += 1
          self._charge_restart("learner")
          log.warning(
              "learner died (exit %s); resume %d (budget %d per "
              "%.0fs window) from the latest checkpoint",
              learner.exitcode, self._learner_restarts,
              self.config.max_learner_restarts,
              self.config.restart_window_secs)
          t_detected = time.monotonic()
          self._spawn_learner(incarnation=self._learner_restarts)
          self._begin_recovery(
              faults_lib.LEARNER_CRASH, "learner",
              "t2r-fleet-learner", t_detected=t_detected,
              exitcode=learner.exitcode)
        else:
          raise FleetError(
              f"learner died (exit {learner.exitcode}) under "
              f"policy={self.config.learner_crash_policy!r} after "
              f"{self._learner_restarts} resume(s); stopping actors")
      if self._host.exitcode is not None:
        raise FleetError(
            f"replay/serving host died (exit {self._host.exitcode})")
      # Every host-class process is load-bearing topology: a dead
      # serving replica strands its actors' act traffic and its
      # broadcast subtree; a dead shard strands committed experience.
      # Both stay fatal (actors are the only elastic tier).
      for index, process in self._serving.items():
        if process.exitcode is not None:
          raise FleetError(
              f"serving host {index} died (exit {process.exitcode})")
      for index, process in self._shards.items():
        if process.exitcode is not None:
          raise FleetError(
              f"replay shard {index} died (exit {process.exitcode})")
      # Front replicas are the exception: serving-only, so a death is
      # a survivable membership shrink, not a fleet error (ISSUE 17).
      for index, process in list(self._fronts.items()):
        if process.exitcode is not None:
          self._handle_front_failure(
              index, faults_lib.SERVING_REPLICA_CRASH,
              exitcode=process.exitcode)
      for index, process in list(self._actors.items()):
        if process.exitcode is None:
          continue
        # Any exit while the fleet is running is a crash (clean actor
        # exits only happen after a stop event: shutdown or a
        # scale-down drain, both of which remove the actor first).
        self._handle_actor_failure(index, faults_lib.ACTOR_CRASH,
                                   exitcode=process.exitcode)
      for index, process in list(self._pods.items()):
        if process.exitcode is None:
          continue
        self._handle_pod_failure(index, faults_lib.ACTOR_CRASH,
                                 exitcode=process.exitcode)
      self._reap_draining()
      self._check_heartbeats()
      self._complete_recoveries()
    return False

  # ---- elastic membership ----

  def scale_to(self, num_actors: int) -> None:
    """Elastic actor membership: grow or shrink the fleet MID-RUN.

    Scale-up spawns fresh actors under new indices (each with its own
    stop event, heartbeat, and restart budget); scale-down sets the
    highest-indexed actors' PER-ACTOR stop events — each finishes its
    current collect batch (commits are atomic episodes, so no partial
    rows can land) and exits, joined asynchronously by supervision.
    Safe to call from another thread while `wait()` supervises.
    """
    if num_actors < 1:
      raise ValueError(f"num_actors must be >= 1, got {num_actors}")
    with self._scale_lock:
      # Checked under the lock shutdown() closes the fleet under: a
      # scale-up can never slip between the `_closed` flip and the
      # stop-event broadcast and spawn an actor nothing would stop.
      if not self._launched or self._closed:
        raise FleetError("scale_to() needs a launched, open fleet")
      current = sorted(self._actors)
      delta = num_actors - len(current)
      if delta == 0:
        return
      now = time.monotonic()
      if delta > 0:
        for _ in range(delta):
          index = self._next_actor_index
          self._next_actor_index += 1
          self._restarts[index] = 0
          self._spawn_actor(index, incarnation=0)
          self.scale_events.append(
              {"action": "add", "index": index, "t": now})
      else:
        for index in current[delta:]:
          process = self._actors.pop(index)
          self._actor_stops.pop(index).set()
          name = f"t2r-fleet-actor-{index}"
          self._heartbeats.pop(name, None)
          self._spawned_at.pop(name, None)
          self._draining.append((index, process))
          self.scale_events.append(
              {"action": "remove", "index": index, "t": now})
      tmetrics.gauge("fleet.actors").set(len(self._actors))
      if self._tracer is not None:
        self._tracer.event("fleet.scaled", actors=len(self._actors))
      log.info("fleet scaled to %d actors", len(self._actors))

  @property
  def num_actors(self) -> int:
    return len(self._actors)

  @property
  def num_pods(self) -> int:
    return len(self._pods)

  def scale_pods_to(self, num_pods: int) -> None:
    """Elastic POD membership (ISSUE 19), mirroring `scale_to`:
    grow under fresh indices, shrink by setting the highest-indexed
    pods' per-pod stop events — each finishes (and commits) its
    current segment and exits, joined by the supervision drain.
    0 is allowed when process actors remain: pods and actors are
    interchangeable collectors and the fleet needs only one of the
    two tiers to stay non-empty."""
    if num_pods < 0:
      raise ValueError(f"num_pods must be >= 0, got {num_pods}")
    with self._scale_lock:
      if not self._launched or self._closed:
        raise FleetError("scale_pods_to() needs a launched, open "
                         "fleet")
      if num_pods == 0 and not self._actors:
        raise FleetError(
            "scale_pods_to(0) would leave the fleet with no "
            "collectors (no process actors remain)")
      current = sorted(self._pods)
      delta = num_pods - len(current)
      if delta == 0:
        return
      now = time.monotonic()
      if delta > 0:
        for _ in range(delta):
          index = self._next_pod_index
          self._next_pod_index += 1
          self._pod_restarts[index] = 0
          self._spawn_pod(index, incarnation=0)
          self.scale_events.append(
              {"action": "add_pod", "index": index, "t": now})
      else:
        for index in current[delta:]:
          process = self._pods.pop(index)
          self._pod_stops.pop(index).set()
          name = f"t2r-fleet-pod-{index}"
          self._heartbeats.pop(name, None)
          self._spawned_at.pop(name, None)
          self._draining.append((index, process))
          self.scale_events.append(
              {"action": "remove_pod", "index": index, "t": now})
      tmetrics.gauge("fleet.pods").set(len(self._pods))
      if self._tracer is not None:
        self._tracer.event("fleet.scaled_pods", pods=len(self._pods))
      log.info("fleet scaled to %d pods", len(self._pods))

  @property
  def num_fronts(self) -> int:
    return len(self._fronts)

  def scale_fronts_to(self, num_fronts: int) -> None:
    """Elastic FRONT-tier membership (ISSUE 18): grow under fresh
    indices (observers told "added" for router admission), shrink by
    draining the highest-indexed replicas via their RPC `shutdown`
    (observers told "removed" first, so routers stop placing tenants
    on a replica that is about to leave). Either way the broadcast
    tree is rewired over the result. Safe from another thread while
    `wait()` supervises, exactly like `scale_to`."""
    if num_fronts < 1:
      raise ValueError(f"num_fronts must be >= 1, got {num_fronts}")
    with self._scale_lock:
      if not self._launched or self._closed:
        raise FleetError("scale_fronts_to() needs a launched, open "
                         "fleet")
      current = sorted(self._fronts)
      delta = num_fronts - len(current)
      if delta == 0:
        return
      now = time.monotonic()
      if delta > 0:
        pending = []
        for _ in range(delta):
          index = self._next_front_index
          self._next_front_index += 1
          pending.append(self._spawn_front(self._run_config, index))
        deadline = (time.monotonic()
                    + self._run_config.launch_timeout_secs)
        for entry, parent_conn, process, what in pending:
          entry["address"] = self._await_ready(
              parent_conn, process, what,
              max(0.0, deadline - time.monotonic()))
          if self._addresses is not None:
            self._addresses.setdefault(
                "fronts", {})[entry["index"]] = entry["address"]
          self.scale_events.append(
              {"action": "add_front", "index": entry["index"],
               "t": now})
          self._notify_front_observers("added", entry["index"],
                                       entry["address"])
      else:
        for index in current[delta:]:
          self._notify_front_observers("removed", index, None)
          process = self._fronts.pop(index)
          entry = next(
              (e for e in self._aux_hosts
               if e["kind"] == "front" and e["index"] == index), None)
          if entry is not None:
            try:
              self._aux_call(entry, "shutdown", timeout_secs=10.0)
            except Exception:  # noqa: BLE001 — join/kill below wins
              log.warning("front %d shutdown rpc failed", index,
                          exc_info=True)
            if entry["client"] is not None:
              entry["client"].close()
              entry["client"] = None
            self._aux_hosts.remove(entry)
          if self._addresses is not None:
            self._addresses.get("fronts", {}).pop(index, None)
          self._heartbeats.pop(f"t2r-fleet-front-{index}", None)
          self._spawned_at.pop(f"t2r-fleet-front-{index}", None)
          self._join_or_kill(process, 30.0, f"front host {index}")
          self.scale_events.append(
              {"action": "remove_front", "index": index, "t": now})
      try:
        self._configure_broadcast(self._run_config)
      except Exception:  # noqa: BLE001 — best-effort rewire
        log.warning("broadcast rewire after front scale failed",
                    exc_info=True)
      tmetrics.gauge("fleet.fronts").set(len(self._fronts))
      if self._tracer is not None:
        self._tracer.event("fleet.fronts_scaled",
                           fronts=len(self._fronts))
      log.info("fleet scaled to %d fronts", len(self._fronts))

  def kick(self, role: str) -> None:
    """Targeted kill-and-respawn of one RECOVERABLE role (ISSUE 18 —
    the `respawn_role` actuator's seam): the process is terminated
    and the EXISTING failure paths take over, so an actor respawns
    under the actor budget and a front under the front budget, with
    the same MTTR accounting as an organic crash. Accepts telemetry
    role names (`actor-3`, `front1`); anything else — learner, host,
    shard, "fleet" — raises (those roles are load-bearing: kicking
    them IS an outage, not a remediation)."""
    match = re.fullmatch(r"(actor|front|pod)-?(\d+)", role)
    if match is None:
      raise FleetError(
          f"role {role!r} is not kickable (only actor-N / front-N / "
          f"pod-N are recoverable by respawn)")
    kind, index = match.group(1), int(match.group(2))
    with self._scale_lock:
      if not self._launched or self._closed:
        raise FleetError("kick() needs a launched, open fleet")
      processes = {"actor": self._actors, "front": self._fronts,
                   "pod": self._pods}[kind]
      process = processes.get(index)
      if process is None or process.exitcode is not None:
        raise FleetError(f"{role} is not running (already respawned "
                         f"or scaled away?)")
      target = f"{kind}-{index}"
      if not self._budget_ok(target):
        # Check BEFORE the kill: a kick with no respawn budget would
        # turn a remediation into an outage.
        raise FleetError(
            f"no restart budget left for {target}; refusing to kick")
      t_detected = time.monotonic()
      log.warning("control plane kicking %s (slow-host remediation)",
                  target)
      process.terminate()
      process.join(timeout=5.0)
      if process.is_alive():
        process.kill()
        process.join(timeout=5.0)
      if kind == "actor":
        self._handle_actor_failure(index, faults_lib.ACTOR_HANG,
                                   t_detected=t_detected, kicked=True)
      elif kind == "pod":
        self._handle_pod_failure(index, faults_lib.ACTOR_HANG,
                                 t_detected=t_detected, kicked=True)
      else:
        self._handle_front_failure(
            index, faults_lib.SERVING_REPLICA_CRASH,
            t_detected=t_detected, kicked=True)

  def retune_admission(self, tenant: str,
                       rate_rps: Optional[float] = None,
                       factor: Optional[float] = None,
                       min_rate_rps: float = 1.0,
                       max_rate_rps: Optional[float] = None,
                       ) -> Dict[str, Any]:
    """Fans one admission retune to EVERY front replica (each owns
    its own `AdmissionController`; a tenant's budget is per replica,
    matching how the router spreads a tenant). `factor` scales the
    current rate; otherwise `rate_rps` is absolute (None = restore to
    unlimited). Returns per-front replies; a failed front reports its
    error instead of aborting the fan-out (the controller's decision
    record carries both)."""
    payload: Dict[str, Any] = {"tenant": str(tenant),
                               "min_rate_rps": float(min_rate_rps)}
    if factor is not None:
      payload["factor"] = float(factor)
    else:
      payload["rate_rps"] = rate_rps
    if max_rate_rps is not None:
      payload["max_rate_rps"] = float(max_rate_rps)
    replies: Dict[str, Any] = {}
    for entry in [e for e in self._aux_hosts if e["kind"] == "front"]:
      try:
        replies[entry["name"]] = self._aux_call(
            entry, "admission_retune", payload, timeout_secs=15.0)
      except Exception as e:  # noqa: BLE001 — partial fan-out reported
        log.warning("admission retune on %s failed", entry["name"],
                    exc_info=True)
        replies[entry["name"]] = {"error": repr(e)}
    if self._tracer is not None:
      self._tracer.event("fleet.admission_retuned", tenant=tenant,
                         fronts=len(replies))
    return replies

  def _shed_retune(self, tenant: str,
                   rate_rps: Optional[float] = None) -> None:
    """The degradation ladder's retune callable: clamp (or restore,
    rate None = unlimited) one tenant on every front."""
    self.retune_admission(tenant, rate_rps=rate_rps)

  def admission_slo_reports(self) -> Dict[str, Any]:
    """Per-front SLO scorecards (`AdmissionController.slo_report`),
    keyed by front name — the controller's retune rules and the
    bench's goodput gates read these."""
    reports: Dict[str, Any] = {}
    for entry in [e for e in self._aux_hosts if e["kind"] == "front"]:
      try:
        reports[entry["name"]] = self._aux_call(
            entry, "slo_report", timeout_secs=15.0)
      except Exception:  # noqa: BLE001 — instrumentation only
        log.warning("slo report from %s failed", entry["name"],
                    exc_info=True)
    return reports

  def wait(self) -> None:
    """Blocks until the learner exits cleanly; on any latched failure
    the fleet is aborted (all children stopped) and the error raised."""
    deadline = self._t_launched + self.config.run_timeout_secs
    try:
      while True:
        if self._supervise_once():
          # Final aggregated view of the run, cadence bypassed.
          self._poll_telemetry(force=True)
          return
        self._poll_telemetry()
        if time.monotonic() > deadline:
          raise FleetError(
              f"fleet exceeded run_timeout_secs="
              f"{self.config.run_timeout_secs:.0f}")
        time.sleep(0.05)
    except BaseException as e:
      self._latch(e)
      self._flight_record(e)
      self._abort()
      raise self._error from None

  # ---- shutdown ----

  def _join_or_kill(self, process: mp.Process, timeout_secs: float,
                    what: str) -> None:
    process.join(timeout=timeout_secs)
    if process.is_alive():
      log.warning("%s did not exit within %.0fs; terminating",
                  what, timeout_secs)
      process.terminate()
      process.join(timeout=5.0)
    if process.is_alive():
      process.kill()
      process.join(timeout=5.0)

  def _all_processes(self) -> List[mp.Process]:
    procs = list(self._actors.values())
    procs.extend(self._pods.values())
    procs.extend(process for _, process in self._draining)
    if self._learner is not None:
      procs.append(self._learner)
    procs.extend(self._learner_peers.values())
    if self._host is not None:
      procs.append(self._host)
    procs.extend(self._serving.values())
    procs.extend(self._shards.values())
    procs.extend(self._fronts.values())
    return [p for p in procs if p is not None]

  def shutdown(self, timeout_secs: float = 60.0,
               collect_metrics: bool = True) -> Optional[Dict[str, Any]]:
    """The shutdown barrier: actors → final metrics → host → joined.

    Returns the host's final metrics (None when `collect_metrics` is
    off or the host is already gone). Raises `FleetError` if any child
    survives the barrier — the zero-leak contract is checked, not
    assumed.
    """
    with self._scale_lock:
      # `_closed` flips and every stop event is set under the SAME
      # lock `scale_to` holds while it checks `_closed` and spawns:
      # a racing scale-up either completes first (its fresh actor's
      # stop event exists here and gets set) or observes `_closed`
      # and refuses — no actor can be spawned without a stop signal.
      if self._closed:
        return None
      self._closed = True
      for stop in self._actor_stops.values():
        stop.set()
      for stop in self._pod_stops.values():
        stop.set()
      actors = list(self._actors.items())
      pods = list(self._pods.items())
      draining = list(self._draining)
    for index, process in actors + draining:
      self._join_or_kill(process, timeout_secs / 2,
                         f"actor {index}")
    # Pods drain BEFORE the final metrics read, like actors: their
    # last segment commit and telemetry push must land on the hosts
    # the reads below aggregate.
    for index, process in pods:
      self._join_or_kill(process, timeout_secs / 2,
                         f"pod {index}")
    metrics = None
    if (collect_metrics and self._host is not None
        and self._host.is_alive()):
      # The control client may have been dropped by a failed telemetry
      # poll (its poisoning contract); a telemetry hiccup must not
      # cost a clean run its final metrics — reconnect for the read.
      if self._control is None:
        self._control = self._fresh_control()
      if self._control is not None:
        try:
          metrics = self._control.call("metrics", timeout_secs=30.0)
        except Exception:
          log.warning("final metrics read failed", exc_info=True)
        else:
          # The chaos bench's RPC-recovery gates read the
          # actor/learner registry snapshots (retry/recovery
          # counters live in THOSE processes); actors push a final
          # snapshot as they drain, so this read sees them all.
          try:
            view = self._control.call("telemetry", timeout_secs=15.0)
            metrics["pushed_telemetry"] = view.get("pushed")
            metrics["host_telemetry"] = view.get("host")
          except Exception:
            # Poisoned-on-timeout contract: the `shutdown` call below
            # must not read this call's late reply.
            log.warning("final telemetry read failed", exc_info=True)
            self._control.close()
            self._control = self._fresh_control()
    if metrics is not None and self._aux_hosts:
      # Cross-host final view: every extra host reports before the
      # stop event lands, and the per-host reads merge into ONE
      # `_result_from_metrics`-shaped dict (service counters summed
      # across shards, commit window spanning min-first→max-last,
      # lag histograms merged with weighted means) so the result
      # math is topology-blind.
      replica_metrics: List[Dict[str, Any]] = []
      shard_metrics: List[Dict[str, Any]] = []
      front_metrics: List[Dict[str, Any]] = []
      for entry in self._aux_hosts:
        try:
          aux = self._aux_call(entry, "metrics", timeout_secs=30.0)
        except Exception:  # noqa: BLE001
          log.warning("final metrics read from %s failed",
                      entry["name"], exc_info=True)
          continue
        if entry["kind"] == "serving":
          replica_metrics.append(aux)
        elif entry["kind"] == "front":
          front_metrics.append(aux)
        else:
          shard_metrics.append(aux)
      metrics = _merge_fleet_metrics(
          metrics, replica_metrics, shard_metrics)
      if front_metrics:
        # Front replicas report beside the training-plane merge (the
        # replica/shard merge math is topology math for the TRAINING
        # result; fronts are a serving-only tier).
        metrics["front_hosts"] = front_metrics
      if self.front_failures:
        metrics["front_failures"] = list(self.front_failures)
    self._host_stop.set()
    if self._control is not None:
      if self._host is not None and self._host.is_alive():
        try:
          self._control.call("shutdown", timeout_secs=10.0)
        except Exception:
          log.warning("host shutdown rpc failed (will join/terminate)",
                      exc_info=True)
      self._control.close()
      self._control = None
    if self._learner is not None:
      self._join_or_kill(self._learner, timeout_secs / 2, "learner")
    for rank, process in self._learner_peers.items():
      self._join_or_kill(process, timeout_secs / 2,
                         f"learner rank {rank}")
    if self._host is not None:
      self._join_or_kill(self._host, timeout_secs / 2, "host")
    for index, process in self._serving.items():
      self._join_or_kill(process, timeout_secs / 2,
                         f"serving host {index}")
    for index, process in self._shards.items():
      self._join_or_kill(process, timeout_secs / 2,
                         f"replay shard {index}")
    for index, process in self._fronts.items():
      self._join_or_kill(process, timeout_secs / 2,
                         f"front host {index}")
    for entry in self._aux_hosts:
      if entry["client"] is not None:
        entry["client"].close()
        entry["client"] = None
    if metrics is not None and self._controller is not None:
      metrics["control"] = self._controller.stats()
    if self._telemetry_file is not None:
      self._telemetry_file.close()
      self._telemetry_file = None
    if self._controller is not None:
      self._controller.close()
    if self._sentinel is not None:
      self._sentinel.close()
    if self._tracer is not None:
      self._tracer.close()
    leaked = [p.name for p in self._all_processes() if p.is_alive()]
    if leaked:
      raise FleetError(f"shutdown leaked processes: {leaked}")
    return metrics

  def _abort(self) -> None:
    """Failure-path teardown: no metrics, everything force-stopped."""
    try:
      self.shutdown(timeout_secs=20.0, collect_metrics=False)
    except FleetError:
      log.exception("abort teardown incomplete")

  # ---- the whole run ----

  def run(self) -> FleetResult:
    """launch → wait → metrics → shutdown, as one supervised unit."""
    t0 = time.monotonic()
    self.launch()
    self.wait()
    metrics = self.shutdown()
    wall = time.monotonic() - t0
    if metrics is None:
      raise FleetError("fleet completed but final metrics were lost")
    result = _result_from_metrics(metrics, wall, sum(
        self._restarts.values()) + sum(self._pod_restarts.values()))
    result.recoveries = list(self.recoveries)
    result.learner_restarts = self._learner_restarts
    result.scale_events = list(self.scale_events)
    return result


def _merge_lag_snapshots(
    snaps: Sequence[Optional[Dict[str, Any]]]) -> Optional[Dict[str, Any]]:
  """Row-weighted merge of `LagStats.snapshot()` dicts across hosts."""
  snaps = [s for s in snaps if s]
  if not snaps:
    return None
  rows = sum(int(s.get("rows", 0)) for s in snaps)
  histogram: Dict[str, int] = {}
  for s in snaps:
    for label, count in (s.get("histogram") or {}).items():
      histogram[label] = histogram.get(label, 0) + int(count)
  by_hop: Dict[str, List[float]] = {}
  for s in snaps:
    for hop, h in (s.get("by_hop") or {}).items():
      acc = by_hop.setdefault(str(hop), [0, 0.0, 0])
      n = int(h.get("rows", 0))
      acc[0] += n
      acc[1] += float(h.get("mean", 0.0)) * n
      acc[2] = max(acc[2], int(h.get("max", 0)))
  out: Dict[str, Any] = {
      "rows": rows,
      "mean": (sum(float(s.get("mean", 0.0)) * int(s.get("rows", 0))
                   for s in snaps) / rows) if rows else 0.0,
      "max": max(int(s.get("max", 0)) for s in snaps),
      "histogram": histogram,
  }
  if by_hop:
    out["by_hop"] = {
        hop: {"rows": n, "mean": (total / n) if n else 0.0, "max": m}
        for hop, (n, total, m) in sorted(
            by_hop.items(), key=lambda kv: int(kv[0]))}
  return out


def _merge_fleet_metrics(
    root: Dict[str, Any],
    replicas: Sequence[Dict[str, Any]],
    shards: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
  """One `_result_from_metrics`-shaped dict for a multi-host fleet.

  Shard replay planes merge into the top-level replay keys — service
  counters summed, the commit window spanning the earliest first to
  the latest last (time.monotonic is one system-wide clock, so stamps
  compare across processes on one machine), lag histograms merged
  row-weighted, staleness namespaced per shard. Control-plane keys
  (learner_window, publishes, params_version) stay the root's: the
  root is the learner's control host and the broadcast origin. The
  raw per-host dicts ride along for forensics.
  """
  merged = dict(root)
  if shards:
    store_sum: Dict[str, float] = {}
    service_sum: Dict[str, float] = {}
    staleness: Dict[str, Any] = {}
    windows = []
    for i, shard in enumerate(shards):
      index = shard.get("shard_index", i)
      for key, value in (shard.get("store") or {}).items():
        if key == "learner_step":
          store_sum[key] = max(store_sum.get(key, 0.0), float(value))
        elif key != "fill":
          store_sum[key] = store_sum.get(key, 0.0) + float(value)
      for key, value in (shard.get("service") or {}).items():
        service_sum[key] = service_sum.get(key, 0.0) + float(value)
      for batch_size, snap in (shard.get("staleness") or {}).items():
        staleness[f"shard{index}:{batch_size}"] = snap
      if shard.get("commit_window"):
        windows.append(shard["commit_window"])
    if store_sum.get("capacity"):
      store_sum["fill"] = store_sum.get("size", 0.0) / store_sum[
          "capacity"]
    merged["store"] = store_sum or None
    merged["service"] = service_sum or None
    merged["staleness"] = staleness
    merged["param_refresh_lag"] = _merge_lag_snapshots(
        [shard.get("param_refresh_lag") for shard in shards])
    merged["commit_window"] = (None if not windows else {
        "first_time": min(float(w["first_time"]) for w in windows),
        "last_time": max(float(w["last_time"]) for w in windows),
    })
    merged["replay_shards"] = list(shards)
  if replicas:
    merged["serving_replicas"] = list(replicas)
  return merged


def _result_from_metrics(metrics: Dict[str, Any], wall_secs: float,
                         actor_restarts: int) -> FleetResult:
  service = metrics.get("service") or {}
  committed = float(service.get("replay_committed_transitions", 0.0))
  commit_window = metrics.get("commit_window") or {}
  commit_span = max(
      float(commit_window.get("last_time", 0.0))
      - float(commit_window.get("first_time", 0.0)), 1e-9)
  learner_window = metrics.get("learner_window") or {}
  step_span = (float(learner_window.get("last_step", 0))
               - float(learner_window.get("first_step", 0)))
  time_span = max(float(learner_window.get("last_time", 0.0))
                  - float(learner_window.get("first_time", 0.0)), 1e-9)
  return FleetResult(
      env_steps_per_sec=committed / commit_span,
      learner_steps_per_sec=step_span / time_span,
      param_refresh_lag=metrics.get("param_refresh_lag") or {},
      replay_staleness=metrics.get("staleness") or {},
      publishes=int(metrics.get("publishes", 0)),
      params_version=int(metrics.get("params_version", 0)),
      actor_restarts=actor_restarts,
      wall_secs=wall_secs,
      clean_shutdown=True,
      metrics=metrics,
  )


@gin.configurable
def run_fleet(model_dir: str = gin.REQUIRED,
              config: Optional[FleetConfig] = None,
              gin_configs: Sequence[str] = ()) -> FleetResult:
  """Gin entry point (`run_t2r_trainer --trainer=fleet`): runs one
  fleet to completion and returns its measured result."""
  config = config or FleetConfig()
  os.makedirs(model_dir, exist_ok=True)
  fleet = Fleet(config, model_dir, gin_configs=gin_configs)
  result = fleet.run()
  log.info(
      "fleet complete: %.1f env steps/s, %.1f learner steps/s, "
      "param_refresh_lag mean %.1f steps, %d publishes, %d restarts",
      result.env_steps_per_sec, result.learner_steps_per_sec,
      result.param_refresh_lag.get("mean", 0.0), result.publishes,
      result.actor_restarts)
  return result
