"""Fleet orchestrator: the organs run together as one topology.

PRs 1–6 built every organ of the scalable QT-Opt stack — bucketed AOT
serving with lock-free hot-swap, the sharded replay service with
measured staleness, the shm-ring host data plane, gloo-backed
distributed init. This module is the composition layer: the Sebulba
decomposition from "Podracer architectures for scalable RL"
(PAPERS.md) as a process-supervising orchestrator on one host —

    actor 0..N-1 ──act──▶ ┌───────────────────────┐
        │                 │ host: CEMPolicyServer │
        │ commit          │  + ReplayWriteService │ ◀─publish─ learner
        └────────────────▶│  + ReplayStore        │ ──sample─▶ (train_qtopt)
                          └───────────────────────┘

Lifecycle contract (docs/FLEET.md):

  * LAUNCH GATE — when gin configs are given, `run_t2r_trainer
    --validate_only` runs as a pre-spawn subprocess; a typo'd binding
    fails the launch in seconds instead of minutes into a fleet run.
  * HEARTBEAT + EXIT-CODE SUPERVISION — the hard-death latching
    pattern from `data/plane.py`: child exit codes are polled and the
    first failure is LATCHED (later teardown noise never masks it);
    each child additionally stamps a shared monotonic heartbeat so a
    silently hung process is detected, not just a dead one.
  * ACTOR-CRASH POLICY — `restart` (default): the actor process is
    respawned under the same actor id, which re-opens its replay
    session — the service aborts whatever the dead incarnation staged
    (restart-with-session-abort), so partial episodes never land.
    `abort`: any actor death takes the fleet down.
  * LEARNER/HOST DEATH — always fatal: actors are stopped, everything
    is torn down, and the latched error is raised.
  * SHUTDOWN BARRIER — stop event → actors drain and exit → final
    metrics are read → host flushes replay and exits → every child is
    joined (escalating terminate→kill on timeout). `shutdown` proves
    zero leaked processes; the fleet allocates no shm segments
    (tests/test_fleet.py pins both).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import multiprocessing as mp
import os
import secrets
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.fleet import actor as actor_lib
from tensor2robot_tpu.fleet import host as host_lib
from tensor2robot_tpu.fleet import learner as learner_lib
from tensor2robot_tpu.fleet.rpc import RpcClient
from tensor2robot_tpu.telemetry import core as tcore
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import records as trecords

log = logging.getLogger(__name__)

_ENVS = ("toy_grasp", "pose", "mujoco_pose")
_CRASH_POLICIES = ("restart", "abort")
_CRASH_MODES = ("raise", "hard", "mid_episode")
_OVERFLOW = ("drop", "block")


class FleetError(RuntimeError):
  """A latched fleet failure (child death, hang, launch-gate reject)."""


@gin.configurable
@dataclasses.dataclass
class FleetConfig:
  """One fleet's topology + model + lifecycle knobs (picklable: the
  same instance is shipped to every child process)."""

  # Topology.
  num_actors: int = 2
  env: str = "mujoco_pose"
  # Model (mirrors GraspingQModel/QTOptLearner constructor args so the
  # host's serving tree and the learner's training tree match).
  image_size: int = 32
  action_dim: int = 2
  torso_filters: Tuple[int, ...] = (16, 32)
  head_filters: Tuple[int, ...] = (32, 32)
  dense_sizes: Tuple[int, ...] = (32, 32)
  cem_population: int = 64
  cem_iterations: int = 2
  cem_elites: int = 6
  cem_inference: str = "bf16"
  # Learner loop.
  batch_size: int = 64
  max_train_steps: int = 200
  min_replay_size: Optional[int] = None
  publish_every_steps: int = 25  # checkpoint == param-refresh cadence
  log_every_steps: int = 25
  # Actors.
  batch_episodes: int = 16
  epsilon: float = 0.1
  # Replay plane.
  replay_capacity: int = 4096
  replay_shards: int = 2
  queue_batches: int = 16
  overflow: str = "drop"
  # Serving plane.
  serve_max_batch: int = 8
  serve_max_wait_us: int = 200
  # Lifecycle.
  actor_crash_policy: str = "restart"
  max_actor_restarts: int = 3
  heartbeat_timeout_secs: float = 300.0  # 0 disables hang detection
  launch_timeout_secs: float = 240.0
  run_timeout_secs: float = 1800.0
  distributed_learner: bool = False
  seed: int = 0
  authkey: bytes = b""  # per-fleet key generated at Fleet construction
  # Telemetry plane (docs/OBSERVABILITY.md). Empty = derived from the
  # fleet's model_dir at launch (<model_dir>/telemetry, /flightrec);
  # telemetry_dir="off" disables cross-process tracing entirely.
  telemetry_dir: str = ""
  flightrec_dir: str = ""
  telemetry_poll_secs: float = 10.0  # 0 disables the aggregated poll
  # Fault injection (tests / bench failure-path rehearsal).
  actor_crash_after_episodes: Optional[int] = None
  actor_crash_mode: str = "raise"
  crash_actor_index: int = 0
  learner_crash_after_steps: Optional[int] = None

  def __post_init__(self):
    if not self.authkey:
      # Per-fleet secret, generated at construction and shipped (via
      # pickle) to every child: two fleets on one machine can never
      # cross-connect. Never b"" — a falsy authkey makes the stdlib
      # Listener SKIP the auth challenge the Client then waits for
      # (a handshake deadlock, found the hard way).
      self.authkey = secrets.token_bytes(16)
    if self.num_actors < 1:
      raise ValueError(f"num_actors must be >= 1, got {self.num_actors}")
    if self.env not in _ENVS:
      raise ValueError(f"env must be one of {_ENVS}, got {self.env!r}")
    if self.actor_crash_policy not in _CRASH_POLICIES:
      raise ValueError(
          f"actor_crash_policy must be one of {_CRASH_POLICIES}, got "
          f"{self.actor_crash_policy!r}")
    if self.actor_crash_mode not in _CRASH_MODES:
      raise ValueError(
          f"actor_crash_mode must be one of {_CRASH_MODES}, got "
          f"{self.actor_crash_mode!r}")
    if self.overflow not in _OVERFLOW:
      raise ValueError(
          f"overflow must be one of {_OVERFLOW}, got {self.overflow!r}")


@dataclasses.dataclass
class FleetResult:
  """What a completed fleet run measured (the bench `fleet` axis)."""

  env_steps_per_sec: float
  learner_steps_per_sec: float
  param_refresh_lag: Dict[str, Any]
  replay_staleness: Dict[str, Any]
  publishes: int
  params_version: int
  actor_restarts: int
  wall_secs: float
  clean_shutdown: bool
  metrics: Dict[str, Any]


class Fleet:
  """Launches, supervises, and tears down one learner/actor fleet."""

  def __init__(self, config: FleetConfig, model_dir: str,
               gin_configs: Sequence[str] = ()):
    self.config = config
    # The per-run resolved copy (telemetry/flight-record dirs filled
    # in) is built at launch(); until then fall back to the caller's.
    self._run_config = config
    self.model_dir = model_dir
    self.gin_configs = tuple(gin_configs)
    self._ctx = mp.get_context("spawn")
    # Two stop signals on purpose: `_stop` drains the ACTORS, while
    # the host has its own — it must outlive the actor/learner drain
    # so the final metrics read has someone to talk to.
    self._stop = self._ctx.Event()
    self._host_stop = self._ctx.Event()
    self._host: Optional[mp.Process] = None
    self._learner: Optional[mp.Process] = None
    self._actors: Dict[int, mp.Process] = {}
    self._heartbeats: Dict[str, Any] = {}
    self._spawned_at: Dict[str, float] = {}
    self._restarts: Dict[int, int] = {}
    self._control: Optional[RpcClient] = None
    self._address: Optional[Tuple[str, int]] = None
    self._error: Optional[BaseException] = None
    self._launched = False
    self._closed = False
    self._t_launched: Optional[float] = None
    self._tracer: Optional[tcore.Tracer] = None
    self._telemetry_file: Optional[Any] = None
    self._t_last_poll = 0.0

  # ---- launch ----

  def _run_launch_gate(self) -> None:
    """`run_t2r_trainer --validate_only` as the pre-spawn gate."""
    for config_path in self.gin_configs:
      result = subprocess.run(
          [sys.executable, "-m",
           "tensor2robot_tpu.bin.run_t2r_trainer",
           "--validate_only", "--gin_configs", config_path],
          capture_output=True, text=True, timeout=300)
      if result.returncode != 0:
        raise FleetError(
            f"launch gate rejected {config_path!r} "
            f"(validate_only exit {result.returncode}):\n"
            f"{result.stdout}\n{result.stderr}")

  def _heartbeat(self, name: str):
    value = self._ctx.Value("d", time.monotonic())
    self._heartbeats[name] = value
    self._spawned_at[name] = time.monotonic()
    return value

  def _spawn_actor(self, index: int, incarnation: int) -> None:
    name = f"t2r-fleet-actor-{index}"
    heartbeat = self._heartbeat(name)
    process = self._ctx.Process(
        target=actor_lib.actor_main,
        args=(self._run_config, index, self._address, self._stop,
              heartbeat, incarnation),
        name=name, daemon=True)
    process.start()
    self._actors[index] = process

  def launch(self) -> None:
    """Gate → host (handshake) → actors → learner."""
    if self._launched:
      return
    self._run_launch_gate()
    # Resolve the telemetry plane BEFORE spawn into a per-RUN copy:
    # the copy ships (via pickle) to every child, so this is the one
    # place the trace/flight-record directories are decided — and the
    # caller's FleetConfig is never mutated (a reused config must not
    # inherit run 1's dirs, nor lose an explicit "off" opt-out).
    telemetry_dir = self.config.telemetry_dir
    if telemetry_dir == "off":
      telemetry_dir = ""  # tracing off; flight dumps keep working
    elif not telemetry_dir:
      telemetry_dir = os.path.join(self.model_dir, "telemetry")
    config = dataclasses.replace(
        self.config,
        telemetry_dir=telemetry_dir,
        flightrec_dir=(self.config.flightrec_dir
                       or flightrec.flightrec_dir(self.model_dir)))
    self._run_config = config
    if config.telemetry_dir:
      # The orchestrator's own timeline: a PRIVATE tracer (never the
      # process-global one — the supervising process may be a trainer
      # or a test with its own telemetry identity).
      self._tracer = tcore.Tracer().configure(
          "orchestrator", trace_dir=config.telemetry_dir)
    parent_conn, child_conn = self._ctx.Pipe()
    self._host = self._ctx.Process(
        target=host_lib.host_main,
        args=(config, child_conn, self._host_stop,
              self._heartbeat("t2r-fleet-host")),
        name="t2r-fleet-host", daemon=True)
    self._host.start()
    child_conn.close()
    # Handshake: the host reports its bound RPC address once its
    # engine is warm; a host that died compiling surfaces here with
    # its exit code instead of a silent hang.
    if not parent_conn.poll(config.launch_timeout_secs):
      self._latch(FleetError(
          f"host did not report ready within "
          f"{config.launch_timeout_secs:.0f}s "
          f"(exitcode={self._host.exitcode})"))
      self._abort()
      raise self._error
    try:
      info = parent_conn.recv()
    except (EOFError, OSError):
      # poll() also returns True on EOF: a host that died DURING
      # construction (bad config, import failure) lands here, not in
      # the timeout branch — same latch/abort treatment.
      self._host.join(timeout=10.0)
      self._latch(FleetError(
          "host died before reporting ready "
          f"(exitcode={self._host.exitcode})"))
      self._abort()
      raise self._error from None
    parent_conn.close()
    self._address = tuple(info["address"])
    self._control = RpcClient(self._address, authkey=config.authkey)
    for index in range(config.num_actors):
      self._restarts[index] = 0
      self._spawn_actor(index, incarnation=0)
    coordinator_address = None
    if config.distributed_learner:
      from tensor2robot_tpu.parallel.distributed import (
          ephemeral_coordinator_address,
      )
      coordinator_address = ephemeral_coordinator_address()
    self._learner = self._ctx.Process(
        target=learner_lib.learner_main,
        args=(config, self.model_dir, self._address,
              self._heartbeat("t2r-fleet-learner"), coordinator_address),
        name="t2r-fleet-learner", daemon=True)
    self._learner.start()
    self._launched = True
    self._t_launched = time.monotonic()
    if self._tracer is not None:
      self._tracer.event("orchestrator.launched",
                         actors=config.num_actors)

  # ---- supervision ----

  def _latch(self, error: BaseException) -> None:
    """First failure wins — the data/plane.py latch pattern: teardown
    noise after the latch never replaces the root cause."""
    if self._error is None:
      self._error = error

  def _check_heartbeats(self) -> None:
    timeout = self.config.heartbeat_timeout_secs
    if not timeout:
      return
    now = time.monotonic()
    for name, value in self._heartbeats.items():
      last = max(value.value, self._spawned_at.get(name, 0.0))
      if now - last > timeout:
        raise FleetError(
            f"{name} heartbeat stale for {now - last:.0f}s "
            f"(> {timeout:.0f}s): process hung")

  def _fresh_control(self) -> Optional[RpcClient]:
    """A new control-channel client (a timed-out call poisons the old
    one — rpc.py contract); None when the host is unreachable."""
    if self._address is None:
      return None
    try:
      return RpcClient(self._address,
                       authkey=self._run_config.authkey,
                       connect_timeout_secs=10.0)
    except Exception:  # noqa: BLE001
      log.warning("control-channel reconnect failed", exc_info=True)
      return None

  def _poll_telemetry(self, force: bool = False) -> None:
    """One aggregated fleet-wide metrics read at the poll cadence:
    the host's registry (replay/serving/lag live at that choke point)
    plus every snapshot the other roles pushed, flattened per-role and
    appended to `<telemetry_dir>/fleet_metrics.jsonl` as one envelope
    record. `force` bypasses the cadence gate (the end-of-run view
    must land even when the learner finishes mid-interval)."""
    cadence = self._run_config.telemetry_poll_secs
    if (not cadence or self._control is None
        or not self._run_config.telemetry_dir):
      return
    now = time.monotonic()
    if not force and now - self._t_last_poll < cadence:
      return
    self._t_last_poll = now
    try:
      view = self._control.call("telemetry", timeout_secs=30.0)
    except Exception:  # noqa: BLE001 — instrumentation only
      # A timed-out call POISONS the client (rpc.py contract: the
      # late reply may still arrive and would be read as the answer
      # to the next control call — e.g. the final `metrics`).
      # Instrumentation must not corrupt the control channel: drop
      # the connection and open a fresh one; on failure, leave the
      # orchestrator without a control client (shutdown handles None).
      log.warning("fleet telemetry poll failed; reconnecting the "
                  "control channel", exc_info=True)
      self._control.close()
      self._control = self._fresh_control()
      return
    payload = tmetrics.scalars_from_snapshot(view.get("host") or {})
    for role, pushed in (view.get("pushed") or {}).items():
      payload.update(tmetrics.scalars_from_snapshot(
          pushed.get("snapshot") or {}, prefix=f"{role}/"))
    record = trecords.make_record(
        int(payload.get("replay.learner_step", 0)), payload,
        role="orchestrator")
    if self._telemetry_file is None:
      self._telemetry_file = open(
          os.path.join(self._run_config.telemetry_dir,
                       "fleet_metrics.jsonl"), "a")
    self._telemetry_file.write(json.dumps(record) + "\n")
    self._telemetry_file.flush()
    if self._tracer is not None:
      self._tracer.event("orchestrator.telemetry_poll",
                         metrics=len(payload))

  def _flight_record(self, error: BaseException) -> None:
    """The latched-error / hang-detection flight-recorder trigger:
    dump the orchestrator's view (heartbeat ages name a HUNG process —
    one that cannot dump itself) and ask a still-live host to dump its
    own ring; learner/actor dumps happen in their processes' except
    paths."""
    if not self._run_config.flightrec_dir:
      return
    now = time.monotonic()
    ages = {
        name: round(now - max(value.value,
                              self._spawned_at.get(name, 0.0)), 3)
        for name, value in self._heartbeats.items()}
    flightrec.dump(
        self._run_config.flightrec_dir, f"fleet latched: {error!r}",
        extra={"heartbeat_ages_secs": ages,
               "actor_restarts": dict(self._restarts)},
        role="orchestrator")
    if (self._control is not None and self._host is not None
        and self._host.is_alive()):
      try:
        self._control.call("flight_record", {
            "out_dir": self._run_config.flightrec_dir,
            "reason": f"fleet latched: {error!r}"}, timeout_secs=15.0)
      except Exception:  # noqa: BLE001 — forensics must not mask
        log.warning("host flight-record request failed", exc_info=True)
        # Poisoned on timeout (rpc.py contract) and we are aborting:
        # drop it rather than let shutdown read a stale reply.
        self._control.close()
        self._control = None

  def _supervise_once(self) -> bool:
    """One poll; returns True when the learner finished cleanly."""
    learner = self._learner
    if learner.exitcode is not None:
      if learner.exitcode == 0:
        return True
      raise FleetError(
          f"learner died (exit {learner.exitcode}); stopping actors")
    if self._host.exitcode is not None:
      raise FleetError(
          f"replay/serving host died (exit {self._host.exitcode})")
    for index, process in list(self._actors.items()):
      if process.exitcode is None:
        continue
      # Any exit while the fleet is running is a crash (clean actor
      # exits only happen after the stop event in shutdown).
      if (self.config.actor_crash_policy == "restart"
          and self._restarts[index] < self.config.max_actor_restarts):
        self._restarts[index] += 1
        log.warning(
            "actor %d died (exit %s); restart %d/%d — session will "
            "reopen with abort-of-staged-rows", index, process.exitcode,
            self._restarts[index], self.config.max_actor_restarts)
        self._spawn_actor(index, incarnation=self._restarts[index])
      else:
        raise FleetError(
            f"actor {index} died (exit {process.exitcode}) under "
            f"policy={self.config.actor_crash_policy!r} after "
            f"{self._restarts[index]} restart(s)")
    self._check_heartbeats()
    return False

  def wait(self) -> None:
    """Blocks until the learner exits cleanly; on any latched failure
    the fleet is aborted (all children stopped) and the error raised."""
    deadline = self._t_launched + self.config.run_timeout_secs
    try:
      while True:
        if self._supervise_once():
          # Final aggregated view of the run, cadence bypassed.
          self._poll_telemetry(force=True)
          return
        self._poll_telemetry()
        if time.monotonic() > deadline:
          raise FleetError(
              f"fleet exceeded run_timeout_secs="
              f"{self.config.run_timeout_secs:.0f}")
        time.sleep(0.05)
    except BaseException as e:
      self._latch(e)
      self._flight_record(e)
      self._abort()
      raise self._error from None

  # ---- shutdown ----

  def _join_or_kill(self, process: mp.Process, timeout_secs: float,
                    what: str) -> None:
    process.join(timeout=timeout_secs)
    if process.is_alive():
      log.warning("%s did not exit within %.0fs; terminating",
                  what, timeout_secs)
      process.terminate()
      process.join(timeout=5.0)
    if process.is_alive():
      process.kill()
      process.join(timeout=5.0)

  def _all_processes(self) -> List[mp.Process]:
    procs = list(self._actors.values())
    if self._learner is not None:
      procs.append(self._learner)
    if self._host is not None:
      procs.append(self._host)
    return [p for p in procs if p is not None]

  def shutdown(self, timeout_secs: float = 60.0,
               collect_metrics: bool = True) -> Optional[Dict[str, Any]]:
    """The shutdown barrier: actors → final metrics → host → joined.

    Returns the host's final metrics (None when `collect_metrics` is
    off or the host is already gone). Raises `FleetError` if any child
    survives the barrier — the zero-leak contract is checked, not
    assumed.
    """
    if self._closed:
      return None
    self._closed = True
    self._stop.set()
    for index, process in self._actors.items():
      self._join_or_kill(process, timeout_secs / 2,
                         f"actor {index}")
    metrics = None
    if (collect_metrics and self._host is not None
        and self._host.is_alive()):
      # The control client may have been dropped by a failed telemetry
      # poll (its poisoning contract); a telemetry hiccup must not
      # cost a clean run its final metrics — reconnect for the read.
      if self._control is None:
        self._control = self._fresh_control()
      if self._control is not None:
        try:
          metrics = self._control.call("metrics", timeout_secs=30.0)
        except Exception:
          log.warning("final metrics read failed", exc_info=True)
    self._host_stop.set()
    if self._control is not None:
      if self._host is not None and self._host.is_alive():
        try:
          self._control.call("shutdown", timeout_secs=10.0)
        except Exception:
          log.warning("host shutdown rpc failed (will join/terminate)",
                      exc_info=True)
      self._control.close()
      self._control = None
    if self._learner is not None:
      self._join_or_kill(self._learner, timeout_secs / 2, "learner")
    if self._host is not None:
      self._join_or_kill(self._host, timeout_secs / 2, "host")
    if self._telemetry_file is not None:
      self._telemetry_file.close()
      self._telemetry_file = None
    if self._tracer is not None:
      self._tracer.close()
    leaked = [p.name for p in self._all_processes() if p.is_alive()]
    if leaked:
      raise FleetError(f"shutdown leaked processes: {leaked}")
    return metrics

  def _abort(self) -> None:
    """Failure-path teardown: no metrics, everything force-stopped."""
    try:
      self.shutdown(timeout_secs=20.0, collect_metrics=False)
    except FleetError:
      log.exception("abort teardown incomplete")

  # ---- the whole run ----

  def run(self) -> FleetResult:
    """launch → wait → metrics → shutdown, as one supervised unit."""
    t0 = time.monotonic()
    self.launch()
    self.wait()
    metrics = self.shutdown()
    wall = time.monotonic() - t0
    if metrics is None:
      raise FleetError("fleet completed but final metrics were lost")
    return _result_from_metrics(metrics, wall, sum(
        self._restarts.values()))


def _result_from_metrics(metrics: Dict[str, Any], wall_secs: float,
                         actor_restarts: int) -> FleetResult:
  service = metrics.get("service", {})
  committed = float(service.get("replay_committed_transitions", 0.0))
  commit_window = metrics.get("commit_window") or {}
  commit_span = max(
      float(commit_window.get("last_time", 0.0))
      - float(commit_window.get("first_time", 0.0)), 1e-9)
  learner_window = metrics.get("learner_window") or {}
  step_span = (float(learner_window.get("last_step", 0))
               - float(learner_window.get("first_step", 0)))
  time_span = max(float(learner_window.get("last_time", 0.0))
                  - float(learner_window.get("first_time", 0.0)), 1e-9)
  return FleetResult(
      env_steps_per_sec=committed / commit_span,
      learner_steps_per_sec=step_span / time_span,
      param_refresh_lag=metrics.get("param_refresh_lag", {}),
      replay_staleness=metrics.get("staleness", {}),
      publishes=int(metrics.get("publishes", 0)),
      params_version=int(metrics.get("params_version", 0)),
      actor_restarts=actor_restarts,
      wall_secs=wall_secs,
      clean_shutdown=True,
      metrics=metrics,
  )


@gin.configurable
def run_fleet(model_dir: str = gin.REQUIRED,
              config: Optional[FleetConfig] = None,
              gin_configs: Sequence[str] = ()) -> FleetResult:
  """Gin entry point (`run_t2r_trainer --trainer=fleet`): runs one
  fleet to completion and returns its measured result."""
  config = config or FleetConfig()
  os.makedirs(model_dir, exist_ok=True)
  fleet = Fleet(config, model_dir, gin_configs=gin_configs)
  result = fleet.run()
  log.info(
      "fleet complete: %.1f env steps/s, %.1f learner steps/s, "
      "param_refresh_lag mean %.1f steps, %d publishes, %d restarts",
      result.env_steps_per_sec, result.learner_steps_per_sec,
      result.param_refresh_lag.get("mean", 0.0), result.publishes,
      result.actor_restarts)
  return result
