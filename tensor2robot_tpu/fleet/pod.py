"""Fleet Anakin pod: a whole vectorized collector as ONE fleet unit.

The hybrid Podracer topology (ISSUE 19, PAPERS.md): where a process
actor steps one env and pays an `act` RPC per decision, a pod runs
`make_anakin_collect_fn` — `envs_per_pod` functional envs vmapped
INSIDE pmap over its local devices — so acting and env stepping are
one device program and the wire carries whole rollout SEGMENTS, not
per-step traffic. The pod is a pure collector: it never trains.

Three seams tie it into the existing fleet contracts:

  * Params come from the pod's assigned serving replica via the
    `acting_state` RPC (host.py): the broadcast tree already pushed
    the publication there, so the pod polls its replica — version
    stamp only when unchanged, full acting `TrainState` when it moved
    — and acts with device-resident params until the next refresh.
    `param_refresh_lag` attribution rides the same version/step/hop
    stamp process actors use.
  * Experience lands on the pod's rendezvous-hashed home shard through
    the SAME `FleetReplaySession.add` one-commit-per-call contract:
    each segment ([T·N] rows after `flatten_devices`) is one atomic
    episode-batch commit, so a pod death can never leave partial rows
    (`adds_total % (envs_per_pod * pod_rollout_length) == 0` is the
    pin).
  * Supervision: pods share the actor crash policy, restart budget,
    chaos schedule, heartbeat cadence (one beat per segment), and
    telemetry merge — the orchestrator treats `pod-N` exactly like a
    (much louder) `actor-N`.

Unlike `fleet.actor`, this module's MAIN does import jax (the whole
point is on-device collection) — but only inside `pod_main`, after
the scrub/telemetry/RPC bring-up, so importing the module stays cheap
and worker-safe (the orchestrator imports it to spawn).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import proc
from tensor2robot_tpu.fleet.actor import (
    CRASH_EXIT_CODE,
    FleetReplaySession,
    _push_telemetry,
    address_book,
    home_shard,
)
from tensor2robot_tpu.fleet.rpc import RpcClient
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)


def pod_env_family(env: str) -> str:
  """Maps a FleetConfig env name onto a FUNCTIONAL env family.

  Pods compile the env into the rollout program, so only pure
  `envs/core.FunctionalEnv` families qualify. `mujoco_pose` process
  actors drive real physics on the host; a pod in the same fleet
  collects from the functional `pose` renderer instead — same wire
  spec, same reward rule, no host stepping.
  """
  if env in ("pose", "mujoco_pose"):
    return "pose"
  if env == "procgen":
    return "procgen"
  raise ValueError(
      f"env {env!r} has no functional family for Anakin pods "
      "(pose/mujoco_pose/procgen)")


def trim_devices(devices, num_envs: int):
  """The largest device prefix that divides `num_envs` evenly.

  `make_anakin_collect_fn` requires `num_envs % num_devices == 0`;
  rather than force every config to know the host's device count, the
  pod shrinks its pmap axis until the batch divides (worst case one
  device — always valid). Pure so tests pin it.
  """
  devices = list(devices)
  num_devices = max(1, len(devices))
  while num_envs % num_devices:
    num_devices -= 1
  return devices[:num_devices]


class PodParamClient:
  """Acting-params cache refreshed over the `acting_state` RPC.

  Duck-types the `FleetPolicyClient` stamp surface
  (`params_version` / `params_learner_step` / `params_hop`) so the
  shared `FleetReplaySession` attributes committed segments to the
  publication that produced them, exactly like process actors.
  """

  def __init__(self, client: RpcClient):
    self._client = client
    self.state = None
    self.params_version = -1
    self.params_learner_step = 0
    self.params_hop = 0

  def refresh(self) -> bool:
    """One poll; True when a NEW publication replaced the cache."""
    reply = self._client.call(
        "acting_state", {"have_version": self.params_version})
    self.params_learner_step = int(reply["params_learner_step"])
    self.params_hop = int(reply.get("params_hop", 0))
    if reply.get("state") is None:
      return False
    self.state = reply["state"]
    self.params_version = int(reply["params_version"])
    return True


def _inject_crash(mode: str, sink: FleetReplaySession) -> None:
  """Pod-side twin of `actor._inject_crash`: the mid_episode mode
  stages one wire batch in a host-side session before dying, so the
  disconnect-abort contract is exercised by pod-sized payloads too."""
  if mode == "mid_episode":
    sink.begin_episode()
    if sink.last_transitions is not None:
      sink.append(sink.last_transitions)
    os._exit(CRASH_EXIT_CODE)
  if mode == "hard":
    os._exit(CRASH_EXIT_CODE)
  raise RuntimeError("injected pod crash (FleetConfig.actor_crash_*)")


def pod_main(config, pod_index: int, address, stop_event,
             heartbeat, incarnation: int = 0) -> None:
  """Child-process entry: connect → refresh/collect/commit until told
  to stop."""
  proc.scrub_inherited_distributed_env()
  pod_id = f"pod-{pod_index}"
  telemetry.configure(
      pod_id, trace_dir=getattr(config, "telemetry_dir", "") or None,
      actor_id=pod_id)
  from tensor2robot_tpu.telemetry import perf as perf_lib
  perf_lib.start_resource_sampler()
  injector = faults_lib.install(config, pod_id,
                                incarnation=incarnation)
  rpc_kwargs = dict(
      authkey=config.authkey,
      call_timeout_secs=config.rpc_call_timeout_secs,
      max_retries=config.rpc_max_retries,
      transport=getattr(config, "transport", "loopback"),
      sndbuf=getattr(config, "tcp_sndbuf", 0),
      rcvbuf=getattr(config, "tcp_rcvbuf", 0))
  book = address_book(address)
  serving = book["serving"]
  # Same placement rule as actors: refresh from this pod's serving
  # replica (round-robin over the broadcast tree), commit to the
  # rendezvous-hash home shard.
  refresh_address = serving[pod_index % len(serving)]
  client = RpcClient(refresh_address, **rpc_kwargs)
  commit_client: Optional[RpcClient] = None
  try:
    t_before = time.monotonic()
    hello = client.call("hello")
    t_after = time.monotonic()
    if "monotonic" in hello and refresh_address == serving[0]:
      telemetry.get_tracer().set_clock_offset(
          telemetry.clock_offset_from_handshake(
              hello["monotonic"], t_before, t_after))
    if refresh_address != serving[0]:
      # The reference clock is the root's — one transient hello
      # aligns this trace (the actor_main contract).
      with RpcClient(serving[0], **rpc_kwargs) as root:
        t_before = time.monotonic()
        root_hello = root.call("hello")
        t_after = time.monotonic()
        if "monotonic" in root_hello:
          telemetry.get_tracer().set_clock_offset(
              telemetry.clock_offset_from_handshake(
                  root_hello["monotonic"], t_before, t_after))
    params = PodParamClient(client)
    if book["shards"]:
      shard = home_shard(pod_id, len(book["shards"]))
      commit_client = RpcClient(book["shards"][shard], **rpc_kwargs)
      sink = FleetReplaySession(commit_client, pod_id, params)
      log.info("%s commits to replay shard %d at %s", pod_id, shard,
               book["shards"][shard])
    else:
      sink = FleetReplaySession(client, pod_id, params)

    # jax from here down: build the on-device collector. The serving
    # engine publishes version 0 at construction, so the first refresh
    # always lands acting params before any rollout runs.
    import jax

    from tensor2robot_tpu.envs.pose import PoseBanditEnv
    from tensor2robot_tpu.envs.procgen import ProcGenGraspEnv
    from tensor2robot_tpu.envs.rollout import (
        flatten_devices,
        make_anakin_collect_fn,
    )
    from tensor2robot_tpu.fleet.host import _build_learner

    family = pod_env_family(config.env)
    if family == "pose":
      env = PoseBanditEnv(image_size=config.image_size,
                          action_dim=config.action_dim)
    else:
      env = ProcGenGraspEnv(image_size=config.image_size,
                            action_dim=config.action_dim)
    devices = trim_devices(jax.local_devices(), config.envs_per_pod)
    learner = _build_learner(config)
    init_fn, collect_fn = make_anakin_collect_fn(
        learner, env,
        num_envs=config.envs_per_pod,
        rollout_length=config.pod_rollout_length,
        epsilon=config.epsilon,
        devices=devices,
        cem_population=getattr(config, "cem_population", None),
        cem_iterations=getattr(config, "cem_iterations", None))
    segment_rows = config.envs_per_pod * config.pod_rollout_length

    key = jax.random.PRNGKey(
        config.seed + 7013 * (pod_index + 1) + incarnation)
    key, init_key = jax.random.split(key)
    env_states = init_fn(init_key)
    if not params.refresh():
      # version 0 exists from engine construction; an empty reply
      # means the engine was released under us — fatal, like an
      # actor's first act failing.
      raise RuntimeError(
          f"{pod_id}: serving replica at {refresh_address} returned "
          "no acting state")

    segments = 0
    tm_env_steps = tmetrics.counter("fleet.pod.env_steps")
    tm_segments = tmetrics.counter("fleet.pod.segments")
    tm_dropped = tmetrics.counter("fleet.pod.segments_dropped")
    tm_refreshes = tmetrics.counter("fleet.pod.param_refreshes")
    tm_version = tmetrics.gauge("fleet.pod.params_version")
    push_period = (max(float(getattr(config, "telemetry_poll_secs",
                                     0.0)), 1.0)
                   if getattr(config, "telemetry_dir", "")
                   and getattr(config, "telemetry_poll_secs", 0.0)
                   else None)
    t_last_push = 0.0
    while not stop_event.is_set():
      # Refresh BEFORE the segment (not after): the segment trains
      # someone else, but the pod should act on the freshest
      # publication its replica holds.
      if params.refresh():
        tm_refreshes.inc()
      tm_version.set(params.params_version)
      key, collect_key = jax.random.split(key)
      with telemetry.span("pod.collect_segment",
                          rows=segment_rows):
        env_states, batch = collect_fn(params.state, env_states,
                                       collect_key)
        wire = {k: np.asarray(v)
                for k, v in flatten_devices(batch).items()}
      if sink.add(wire):
        tm_env_steps.inc(segment_rows)
      else:
        tm_dropped.inc()
      segments += 1
      tm_segments.inc()
      # Fault-plan seam between segments, before the beat — the same
      # placement actors use (an injected hang leaves the heartbeat
      # one full segment stale).
      event = injector.on_batch(segments)
      if event is not None:
        if event.fault == faults_lib.ACTOR_HANG:
          proc.hang(event.duration_secs)
        else:
          _inject_crash(event.mode, sink)
      proc.beat(heartbeat)
      if (push_period is not None
          and time.monotonic() - t_last_push >= push_period):
        t_last_push = time.monotonic()
        _push_telemetry(client, pod_id)
    if push_period is not None:
      _push_telemetry(client, pod_id)
    log.info("pod %s stopping cleanly: %d segments (%d rows each), "
             "last params version %d", pod_id, segments, segment_rows,
             params.params_version)
  except BaseException as e:
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir, f"{pod_id}: {e!r}")
    raise
  finally:
    perf_lib.stop_resource_sampler()
    telemetry.get_tracer().close()
    if commit_client is not None:
      commit_client.close()
    client.close()
