"""Replicated serving-front host: the multi-tenant front ON the wire.

PR 13's `ServingFront` (arena + admission + continuous batching) is an
in-process object; the "millions of users" tier needs it behind real
sockets and replicated. This module is the host kind that does it:
each `front_main` process owns one complete front stack —
`ModelArena` (budgeted pinned params), `AdmissionController`
(per-tenant token buckets), `ServingFront` (ONE continuous-batching
dispatcher) — behind the same `fleet.rpc` server every other fleet
host uses, so remote callers get admission, fair-share batching, and
arena budgets over the deadline/retry envelope actors already ride.

Topology (docs/SERVING.md "Replicated tier"):

  * N front hosts sit behind `serving.router.ServingRouter`, which
    places tenants by rendezvous hashing — the SAME rule that homes
    actors on replay shards — so arena budgets shard across hosts and
    a hot tenant spreads over `front_spread` replicas.
  * Learner publications reach every front over the existing
    broadcast tree: front hosts implement the same `publish` /
    `configure_broadcast` surface as serving hosts and forward to
    their tree children, so one fan-out spans both host kinds.
  * A front replica death is SURVIVABLE: the router fails its tenants
    over to HRW survivors on the caller side while the orchestrator
    records the membership change (serving replicas and shards stay
    fatal — they are load-bearing for training; fronts only serve).

Latency levers live here too: with `speculative_cem` on, each tenant
serves the 1-iteration CEM program inline and refines with the full
program in the background (`serving.speculative.SpeculativeCEM` —
refined actions are version-stamped and never cross a param
hot-swap).

Chaos: the `serving_replica_crash` fault class triggers through
`FaultInjector.on_serve`, consulted once per predict — the replica
flight-records and hard-exits, exercising the router's reshed path
deterministically.

Kept importable jax-free (heavy imports live inside `_FrontState`):
`fleet.orchestrator` pulls this module in and must stay in the
worker-safe closure.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import proc
from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.fleet.actor import CRASH_EXIT_CODE
from tensor2robot_tpu.fleet.host import (
    _build_learner,
    _client_kwargs,
    _handshake_clock,
    _server_kwargs,
)
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)


class _FrontState:
  """One front replica's serving stack + RPC method table."""

  def __init__(self, config, front_index: int,
               injector: Optional[faults_lib.FaultInjector] = None):
    # jax + the serving stack load HERE, in the front process.
    import jax

    from tensor2robot_tpu.serving.admission import AdmissionController
    from tensor2robot_tpu.serving.arena import ModelArena
    from tensor2robot_tpu.serving.front import ServingFront
    from tensor2robot_tpu.serving.speculative import SpeculativeCEM
    from tensor2robot_tpu.specs import (
        TensorSpecStruct,
        make_random_tensors,
    )

    self._config = config
    self.front_index = int(front_index)
    self._injector = injector
    self._struct_cls = TensorSpecStruct
    telemetry.configure(
        f"front{front_index}",
        trace_dir=getattr(config, "telemetry_dir", "") or None)
    from tensor2robot_tpu.telemetry import perf as perf_lib
    from tensor2robot_tpu.utils import profiling
    perf_lib.start_resource_sampler(
        sources=[profiling.device_memory_source()])
    learner = _build_learner(config)
    state0 = learner.create_state(
        jax.random.PRNGKey(config.seed), batch_size=2)
    acting0 = state0.train_state.replace(opt_state=None)
    example = make_random_tensors(
        learner.observation_specification(), batch_size=1, seed=0)
    full_policy = learner.build_policy()
    self.arena = ModelArena()
    self.front = ServingFront(
        self.arena,
        AdmissionController(
            slo_ms=float(getattr(config, "front_slo_ms", 100.0))))
    self.tenants: Tuple[str, ...] = tuple(
        getattr(config, "front_tenants", ("policy",)))
    self._speculative: Dict[str, SpeculativeCEM] = {}
    speculative_on = bool(getattr(config, "speculative_cem", False))
    fast_policy = (learner.build_policy(cem_iterations=1)
                   if speculative_on else None)
    self._registered: List[str] = []
    for tenant in self.tenants:
      self.front.register_tenant(
          tenant, (lambda p=full_policy: (p, acting0, example)),
          max_batch=config.serve_max_batch, takes_rng=True,
          preload=True)
      self._registered.append(tenant)
      if speculative_on:
        # The fast twin shares the SAME state object (one set of
        # device buffers; the arena double-counts the bytes — see
        # docs/SERVING.md sizing) and serves the 1-iteration program.
        fast_name = f"{tenant}-fast"
        self.front.register_tenant(
            fast_name, (lambda p=fast_policy: (p, acting0, example)),
            max_batch=config.serve_max_batch, takes_rng=True,
            preload=True)
        self._registered.append(fast_name)
        self._speculative[tenant] = SpeculativeCEM(
            fast_predict=(
                lambda feats, t=fast_name: self.front.predict(t, feats)),
            full_predict=(
                lambda feats, t=tenant: self.front.predict(t, feats)),
            version_fn=lambda: self.params_version)
    self._lock = threading.Lock()
    self._version = 0
    self.publishes = 0
    self.serves = 0
    self._children: List[Tuple[str, int]] = []
    self._tree_depth = 0
    self._tm_depth = tmetrics.gauge("fleet.broadcast.depth")
    self._tm_forwards = tmetrics.counter("fleet.broadcast.forwards")
    self._tm_publish_ms = tmetrics.histogram(
        "fleet.broadcast.publish_ms", faults_lib.RECOVERY_MS_BOUNDS)
    self.shutdown_requested = threading.Event()

  @property
  def params_version(self) -> int:
    with self._lock:
      return self._version

  # ---- broadcast fan-out (same contract as host._HostState) ----

  def _forward_publish(self, payload: Dict[str, Any],
                       ctx: dict) -> None:
    with self._lock:
      children = list(self._children)
    if not children:
      return
    forwarded = dict(payload)
    forwarded["hop"] = int(payload.get("hop", 0)) + 1
    clients = ctx.setdefault("broadcast_clients", {})
    for child in children:
      client = clients.get(child)
      if client is None:
        client = rpc_lib.RpcClient(
            child,
            call_timeout_secs=getattr(
                self._config, "rpc_call_timeout_secs",
                rpc_lib.DEFAULT_CALL_TIMEOUT_SECS),
            max_retries=getattr(self._config, "rpc_max_retries",
                                rpc_lib.DEFAULT_MAX_RETRIES),
            **_client_kwargs(self._config))
        clients[child] = client
      client.call("publish", forwarded)
      self._tm_forwards.inc()

  # ---- the RPC method table ----

  def _predict(self, payload: Dict[str, Any]) -> Dict[str, Any]:
    tenant = str(payload["tenant"])
    features = payload["features"]
    if isinstance(features, dict):
      features = self._struct_cls.from_flat_dict(dict(features))
    with self._lock:
      self.serves += 1
      serve_index = self.serves
    if self._injector is not None:
      event = self._injector.on_serve(serve_index)
      if event is not None:
        # The injected replica death: flight record already dumped by
        # the injector; exit hard so the router sees a socket error,
        # not a clean close.
        os._exit(CRASH_EXIT_CODE)
    speculative = self._speculative.get(tenant)
    if speculative is not None:
      action = speculative.predict(features)
    else:
      action = self.front.predict(tenant, features)
    return {"action": np.asarray(action),
            "params_version": self.params_version,
            "front_index": self.front_index}

  def handle(self, method: str, payload: Any, ctx: dict) -> Any:
    if method == "predict":
      return self._predict(payload)
    if method == "publish":
      state = payload["state"]
      step = int(payload["step"])
      for tenant in self._registered:
        self.arena.swap_state(tenant, state, learner_step=step)
      with self._lock:
        self._version = step
        self.publishes += 1
      for speculative in self._speculative.values():
        speculative.on_publish(step)
      tmetrics.counter("fleet.param_publishes").inc()
      if payload.get("origin_wall") is not None:
        self._tm_publish_ms.observe(
            max(0.0, (time.time() - float(payload["origin_wall"]))
                * 1e3))
      self._forward_publish(payload, ctx)
      return self.params_version
    if method == "configure_broadcast":
      with self._lock:
        self._children = [tuple(c) for c in payload.get("children", ())]
        self._tree_depth = int(payload.get("depth", 0))
      self._tm_depth.set(self._tree_depth)
      return True
    if method == "hello":
      return {"kind": "front",
              "front_index": self.front_index,
              "tenants": list(self.tenants),
              "speculative": sorted(self._speculative),
              "params_version": self.params_version,
              "monotonic": time.monotonic()}
    if method == "metrics_scalars":
      return {"front_serves": float(self.serves),
              "front_publishes": float(self.publishes)}
    if method == "metrics":
      with self._lock:
        broadcast = {"depth": self._tree_depth,
                     "children": len(self._children)}
      return {
          "front_index": self.front_index,
          "tenants": list(self.tenants),
          "serves": self.serves,
          "publishes": self.publishes,
          "params_version": self.params_version,
          "dispatches": self.front.dispatches,
          "arena": self.arena.stats(),
          "speculative": {t: s.stats()
                          for t, s in self._speculative.items()},
          "broadcast": broadcast,
      }
    if method == "telemetry":
      return {"host": tmetrics.registry().snapshot(),
              "pushed": {},
              "monotonic": time.monotonic()}
    if method == "slo_report":
      # The control plane's SLO scorecard pull (ISSUE 18): per-tenant
      # dispatch + e2e views off this replica's own histograms.
      return self.front.admission.slo_report()
    if method == "admission_retune":
      # The `retune_admission` actuator lands here; kwargs pass
      # through to `AdmissionController.retune` (absolute rate OR
      # factor, clamped). Unknown tenants raise — the RPC error
      # surfaces in the controller's decision record.
      kwargs = {k: payload[k]
                for k in ("rate_rps", "factor", "burst",
                          "min_rate_rps", "max_rate_rps")
                if k in payload}
      policy = self.front.admission.retune(str(payload["tenant"]),
                                           **kwargs)
      return {"tenant": str(payload["tenant"]),
              "rate_rps": policy.rate_rps,
              "burst": policy.burst}
    if method == "flight_record":
      return flightrec.dump(payload["out_dir"],
                            payload.get("reason", "requested"))
    if method == "shutdown":
      self.shutdown_requested.set()
      return True
    if method == rpc_lib.DISCONNECT_METHOD:
      for client in ctx.get("broadcast_clients", {}).values():
        client.close()
      return None
    raise ValueError(f"unknown front rpc method {method!r}")

  def close(self) -> None:
    for speculative in self._speculative.values():
      speculative.close()
    self.front.close()


def front_main(config, front_index: int, root_address,
               ready_conn, stop_event, heartbeat) -> None:
  """Child-process entry for one front replica (ISSUE 17).

  Same lifecycle contract as `host_main`/`replay_shard_main`: address
  handshake over `ready_conn` once the engines are warm, heartbeat
  while serving, drain on `stop_event` or the RPC `shutdown`. The
  fault role is `front-<i>` (the `serving_replica_crash` target
  name).
  """
  proc.scrub_inherited_distributed_env()
  role = f"front-{front_index}"
  injector = faults_lib.install(config, role)
  try:
    state = _FrontState(config, front_index, injector)
    server = rpc_lib.RpcServer(state.handle, **_server_kwargs(config))
  except BaseException as e:
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir,
                     f"{role} launch failed: {e!r}")
    raise
  try:
    ready_conn.send({"address": server.address})
    ready_conn.close()
    _handshake_clock(config, root_address)
    while not (stop_event.is_set() or state.shutdown_requested.is_set()):
      proc.beat(heartbeat)
      time.sleep(0.1)
  finally:
    from tensor2robot_tpu.telemetry import perf as perf_lib
    perf_lib.stop_resource_sampler()
    server.close()
    state.close()
    telemetry.get_tracer().close()


class FrontTier:
  """A standalone replicated front tier: N `front_main` processes +
  broadcast wiring, WITHOUT the rest of the fleet.

  The bench and the e2e tests drive the replicated tier against
  synthetic load; they need fronts and a router, not actors, shards,
  or a learner. `launch()` spawns every front, awaits the ready
  handshakes, and wires the `broadcast_degree`-ary publish tree over
  the front list (front 0 is the tree root — `publish()` here sends
  to it only, exactly like the learner's single uplink).
  """

  def __init__(self, config, num_fronts: int):
    import multiprocessing as mp
    if num_fronts < 1:
      raise ValueError(f"num_fronts must be >= 1, got {num_fronts}")
    self._config = config
    self._num = int(num_fronts)
    self._ctx = mp.get_context("spawn")
    self._stop = self._ctx.Event()
    self.processes: Dict[int, Any] = {}
    self.addresses: Dict[int, Tuple[str, int]] = {}
    self._heartbeats: Dict[int, Any] = {}
    self._root_client: Optional[rpc_lib.RpcClient] = None

  def launch(self, timeout_secs: float = 240.0) -> "FrontTier":
    pending = [self._start_front(i) for i in range(self._num)]
    deadline = time.monotonic() + timeout_secs
    for i, parent_conn, process in pending:
      remaining = max(0.0, deadline - time.monotonic())
      self._await_front(i, parent_conn, process, remaining,
                        timeout_secs)
    self._configure_broadcast()
    return self

  def _start_front(self, index: int):
    """Forks one front replica; returns the pending ready handshake."""
    parent_conn, child_conn = self._ctx.Pipe()
    heartbeat = self._ctx.Value("d", time.monotonic())
    process = self._ctx.Process(
        target=front_main,
        args=(self._config, index, None, child_conn, self._stop,
              heartbeat),
        name=f"t2r-front-{index}", daemon=True)
    process.start()
    child_conn.close()
    self.processes[index] = process
    self._heartbeats[index] = heartbeat
    return index, parent_conn, process

  def _await_front(self, index: int, parent_conn, process,
                   remaining: float, timeout_secs: float) -> None:
    if not parent_conn.poll(max(0.0, remaining)):
      raise RuntimeError(
          f"front {index} did not report ready within "
          f"{timeout_secs:.0f}s (exitcode={process.exitcode})")
    try:
      info = parent_conn.recv()
    except (EOFError, OSError):
      process.join(timeout=10.0)
      raise RuntimeError(
          f"front {index} died before reporting ready "
          f"(exitcode={process.exitcode})") from None
    parent_conn.close()
    self.addresses[index] = tuple(info["address"])

  # ---- elastic surface (the control plane's front levers) ----

  def scale_to(self, num_fronts: int,
               timeout_secs: float = 240.0) -> List[int]:
    """Grows/shrinks the live tier to `num_fronts` replicas (ISSUE 18
    — the standalone `scale_fronts` actuator for bench legs; inside a
    full fleet the orchestrator's `scale_fronts_to` owns this).

    Growth spawns at fresh indices past the highest ever used; shrink
    drains the HIGHEST-indexed live replicas via the RPC `shutdown`
    (front 0, the broadcast root, is never shed). Dead replicas are
    pruned from the address book and the publish tree is rewired over
    the survivors. Returns the live index list."""
    if num_fronts < 1:
      raise ValueError(f"num_fronts must be >= 1, got {num_fronts}")
    self._prune_dead()
    live = self.alive()
    if len(live) < num_fronts:
      base = max(self.processes, default=-1) + 1
      pending = [self._start_front(base + k)
                 for k in range(num_fronts - len(live))]
      deadline = time.monotonic() + timeout_secs
      for i, parent_conn, process in pending:
        self._await_front(i, parent_conn, process,
                          deadline - time.monotonic(), timeout_secs)
    elif len(live) > num_fronts:
      for index in sorted(live, reverse=True)[:len(live) - num_fronts]:
        client = self._client(index)
        try:
          client.call("shutdown", {})
        finally:
          if index != 0:
            client.close()
        self.processes[index].join(timeout=timeout_secs)
        self._forget(index)
    self._configure_broadcast()
    return self.alive()

  def respawn(self, index: int, timeout_secs: float = 240.0
              ) -> Tuple[str, int]:
    """Respawns a DEAD replica at its original index and rewires the
    tree; returns the new address (the caller re-routes via the
    router's `mark_alive`). Raises if the old process still runs —
    respawn is recovery, not restart."""
    process = self.processes.get(index)
    if process is not None and process.exitcode is None:
      raise RuntimeError(f"front {index} is still alive")
    self._forget(index)
    i, parent_conn, new_process = self._start_front(index)
    self._await_front(i, parent_conn, new_process, timeout_secs,
                      timeout_secs)
    self._configure_broadcast()
    return self.addresses[index]

  def _forget(self, index: int) -> None:
    self.processes.pop(index, None)
    self.addresses.pop(index, None)
    self._heartbeats.pop(index, None)
    if index == 0 and self._root_client is not None:
      self._root_client.close()
      self._root_client = None

  def _prune_dead(self) -> None:
    for index, process in list(self.processes.items()):
      if process.exitcode is not None:
        self._forget(index)

  def _configure_broadcast(self) -> None:
    from tensor2robot_tpu.fleet.orchestrator import (
        broadcast_children,
        broadcast_depths,
    )
    degree = int(getattr(self._config, "broadcast_degree", 2))
    order = sorted(self.addresses)
    depths = broadcast_depths(len(order), degree)
    for pos, index in enumerate(order):
      children = [list(self.addresses[order[c]])
                  for c in broadcast_children(pos, len(order), degree)]
      client = self._client(index)
      try:
        client.call("configure_broadcast",
                    {"children": children, "depth": depths[pos]})
      finally:
        if index != 0:
          client.close()

  def _client(self, index: int) -> rpc_lib.RpcClient:
    if index == 0:
      if self._root_client is None:
        self._root_client = rpc_lib.RpcClient(
            self.addresses[0], **_client_kwargs(self._config))
      return self._root_client
    return rpc_lib.RpcClient(
        self.addresses[index], **_client_kwargs(self._config))

  def publish(self, state: Any, step: int) -> int:
    """One uplink send to the tree root; the tree fans it out."""
    return self._client(0).call(
        "publish", {"state": state, "step": int(step), "hop": 0,
                    "origin_wall": time.time()})

  def kill(self, index: int) -> None:
    """Hard-kills one front replica (the chaos/bench shed leg)."""
    process = self.processes[index]
    process.kill()
    process.join(timeout=10.0)

  def alive(self) -> List[int]:
    return [i for i, p in self.processes.items()
            if p.exitcode is None]

  def close(self, timeout_secs: float = 30.0) -> None:
    if self._root_client is not None:
      self._root_client.close()
      self._root_client = None
    self._stop.set()
    for process in self.processes.values():
      process.join(timeout=timeout_secs)
      if process.is_alive():
        process.terminate()
        process.join(timeout=5.0)
      if process.is_alive():
        process.kill()
        process.join(timeout=5.0)
