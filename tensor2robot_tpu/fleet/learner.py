"""Fleet learner process: `train_qtopt` on the host's sharded store.

The learner is the unmodified QT-Opt loop — same jitted Bellman step,
same checkpoint writer, same metric logger — handed two fleet-shaped
seams instead of an in-process buffer:

  * `RemoteReplay` — the `replay_buffer=` facade. Sampling rides the
    host's `ReplayBatchSampler` (so staleness is accounted where the
    data lives), `set_learner_step` tags the store every dispatch
    (the staleness + lag clock), and the train log's replay metrics
    come back over the control channel. Two RPC clients on purpose:
    the prefetch thread owns the sampling connection, the train loop
    owns control — `rpc.RpcClient` is single-owner by design.
  * `ParamPublishHook` — the Podracer param-publication channel. On
    every checkpoint it ships the acting half of the train state
    (params + batch stats, `opt_state` stripped — the same handoff
    shape `ActorStateRefreshHook` uses in-process) to the host, which
    hot-swaps it into the serving engine stamped with the learner
    step. It declares `drives_online_collection`, so the trainer's
    prefetch depth drops to the online-correct 1 (the round-5
    sampling-lead finding applies to fleets too).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import proc
from tensor2robot_tpu.fleet.actor import address_book
from tensor2robot_tpu.fleet.rpc import RpcClient
from tensor2robot_tpu.hooks.hook import Hook
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)


class RemoteReplay:
  """`train_qtopt`-facing replay facade over the fleet's replay plane.

  Unsharded (the single-host default): every call rides the host's
  control/stream clients, unchanged. Sharded (ISSUE 16,
  `replay_hosts > 0`): a batch is assembled from per-shard `sample`
  RPCs — counts proportional to shard fill, concatenated SHARD-MAJOR
  (`replay.sampler.shard_fanout_counts` / `concat_shard_major`, the
  PR-3 gather contract) — and `set_learner_step` tags every shard's
  store so staleness/lag stay correct where each shard lives. Client
  ownership follows the module contract: `*_controls` belong to the
  train thread, `*_streams` to the prefetch thread.
  """

  def __init__(self, control: RpcClient, stream: RpcClient,
               capacity: int,
               shard_controls: Sequence[RpcClient] = (),
               shard_streams: Sequence[RpcClient] = ()):
    self._control = control
    self._stream = stream
    self._capacity = int(capacity)
    self._shard_controls = list(shard_controls)
    self._shard_streams = list(shard_streams)

  @property
  def capacity(self) -> int:
    return self._capacity

  def __len__(self) -> int:
    if self._shard_controls:
      return sum(int(c.call("size")) for c in self._shard_controls)
    return int(self._control.call("size"))

  def wait_until_size(self, min_size: int,
                      timeout_secs: Optional[float] = None) -> bool:
    deadline = (time.monotonic() + timeout_secs
                if timeout_secs is not None else None)
    while len(self) < min_size:
      if deadline is not None and time.monotonic() > deadline:
        return False
      time.sleep(0.05)
    return True

  def _to_struct(self, flat: Dict[str, Any]):
    from tensor2robot_tpu.specs import TensorSpecStruct
    return TensorSpecStruct.from_flat_dict(flat)

  def _fanout_sample(self, clients: List[RpcClient], batch_size: int):
    """One shard-major batch via per-shard RPCs on `clients` (which
    must belong to the calling thread — single-owner rule)."""
    from tensor2robot_tpu.replay.sampler import (
        concat_shard_major,
        shard_fanout_counts,
    )
    sizes = tuple(int(c.call("size")) for c in clients)
    counts = shard_fanout_counts(batch_size, sizes)
    parts = [client.call("sample", count)
             for client, count in zip(clients, counts) if count]
    return self._to_struct(concat_shard_major(parts))

  def sample(self, batch_size: int):
    """Control-channel sample (int8 calibration runs pre-loop, on the
    train thread, before the prefetcher owns the stream channel)."""
    if self._shard_controls:
      return self._fanout_sample(self._shard_controls, int(batch_size))
    return self._to_struct(self._control.call("sample", int(batch_size)))

  def as_stream(self, batch_size: int) -> Iterator[Any]:
    def _gen():
      while True:
        if self._shard_streams:
          yield self._fanout_sample(self._shard_streams,
                                    int(batch_size))
        else:
          yield self._to_struct(
              self._stream.call("sample", int(batch_size)))
    return _gen()

  def set_learner_step(self, step: int) -> None:
    # The root host always gets the tag (its learner-window/resume
    # witness), and on the sharded plane so does every shard — the
    # staleness/lag clock must tick WHERE the rows live.
    self._control.call("set_learner_step", int(step))
    for client in self._shard_controls:
      client.call("set_learner_step", int(step))

  def metrics_scalars(self) -> Dict[str, float]:
    out = dict(self._control.call("metrics_scalars"))
    merged: Dict[str, float] = {}
    for client in self._shard_controls:
      for key, value in client.call("metrics_scalars").items():
        if any(tag in key for tag in ("mean", "max", "p95")):
          # Distributional scalars don't sum across shards; the
          # pessimistic envelope (max) is the honest merge.
          merged[key] = max(merged.get(key, 0.0), float(value))
        else:
          merged[key] = merged.get(key, 0.0) + float(value)
    out.update(merged)
    return out


class ParamPublishHook(Hook):
  """Publishes each checkpoint's acting params to the fleet host."""

  drives_online_collection = True

  def __init__(self, control: RpcClient, telemetry_push: bool = True):
    self._control = control
    self._telemetry_push = telemetry_push
    self.publishes = 0

  def after_checkpoint(self, step: int, state, model_dir: str) -> None:
    import jax

    acting = (state.replace(opt_state=None)
              if hasattr(state, "replace")
              and hasattr(state, "opt_state") else state)
    with telemetry.span("learner.publish_params", step=int(step)):
      # `origin_wall`/`hop` seed the broadcast tree's per-hop
      # accounting: every host that swaps this publication — root or
      # forwarded — measures origin→swap against the shared wall
      # clock and tags its depth (ISSUE 16).
      self._control.call("publish", {
          "step": int(step),
          "state": jax.device_get(acting),
          "origin_wall": time.time(),
          "hop": 0,
      })
    self.publishes += 1
    tmetrics.counter("learner.param_publishes").inc()
    # Publish cadence doubles as the learner's telemetry-push cadence
    # (the control client is owned by this thread — RpcClient is
    # single-owner). Skipped when the plane is off.
    if not self._telemetry_push:
      return
    try:
      self._control.call("telemetry_push", {
          "role": "learner",
          "snapshot": tmetrics.registry().snapshot()})
    except Exception:  # noqa: BLE001 — instrumentation only
      log.warning("learner telemetry push failed", exc_info=True)


class _HeartbeatHook(Hook):
  """Stamps the orchestrator-visible heartbeat every train step."""

  def __init__(self, heartbeat):
    self._heartbeat = heartbeat

  def after_step(self, step: int, metrics) -> None:
    proc.beat(self._heartbeat)


class _CrashAfterHook(Hook):
  """Fault injection: kill the learner mid-run (tests/bench)."""

  def __init__(self, crash_after_steps: int):
    self._after = int(crash_after_steps)

  def after_step(self, step: int, metrics) -> None:
    if step >= self._after:
      raise RuntimeError(
          "injected learner crash "
          "(FleetConfig.learner_crash_after_steps)")


class _FaultPlanHook(Hook):
  """The learner's fault-plan seam: `on_step` after every train step.

  A due `learner_crash` raises out of the train loop — the same except
  path a real crash takes (flight record in `learner_main`, exit code
  seen by the orchestrator, `resume` policy respawns from the latest
  checkpoint)."""

  def __init__(self, injector: faults_lib.FaultInjector):
    self._injector = injector

  def after_step(self, step: int, metrics) -> None:
    event = self._injector.on_step(step)
    if event is not None:
      raise RuntimeError(
          f"injected learner crash (fault plan, step {step})")


def learner_group_plan(config, world_size: int = 1,
                       rank: int = 0) -> Dict[str, Any]:
  """The learner group's per-rank contract, as pure math (ISSUE 19).

  One place decides what a rank DOES so tests can pin it without
  spawning processes: every rank samples and feeds `local_batch_size`
  rows (the mesh assembles the global batch via
  `make_array_from_process_local_data`), but ONLY rank 0 publishes
  params and owns the side-effect surfaces (`train_qtopt` gates
  checkpoints/logs on `jax.process_index() == 0`). At
  `world_size == 1` this degenerates to exactly the single-learner
  path — same role name, same batch, publishing on — which is what
  keeps N=1 bitwise-pinned against it.
  """
  world_size = int(world_size)
  rank = int(rank)
  if world_size < 1:
    raise ValueError(f"world_size must be >= 1, got {world_size}")
  if not 0 <= rank < world_size:
    raise ValueError(
        f"rank must be in [0, {world_size}), got {rank}")
  if config.batch_size % world_size != 0:
    raise ValueError(
        f"batch_size ({config.batch_size}) must divide evenly "
        f"across the learner group (world_size={world_size})")
  return {
      "role": "learner" if rank == 0 else f"learner-r{rank}",
      "local_batch_size": config.batch_size // world_size,
      "publishes": rank == 0,
  }


def learner_main(config, model_dir: str, address, heartbeat,
                 coordinator_address: Optional[str] = None,
                 incarnation: int = 0, world_size: int = 1,
                 rank: int = 0) -> None:
  """Child-process entry: connect → train_qtopt → clean exit.

  ``incarnation`` > 0 is the `learner_crash_policy="resume"` respawn:
  `train_qtopt` restores from the latest checkpoint in `model_dir`
  (the host kept the replay store and serving engine alive), and
  non-recurring planned faults do not re-fire.

  ``world_size`` > 1 makes this process rank ``rank`` of a LEARNER
  GROUP (ISSUE 19): every rank adopts the same ephemeral coordinator,
  `maybe_initialize_distributed` joins them into one gloo mesh, and
  the unmodified jitted train step runs as one cross-process GSPMD
  program — each rank feeds its own `batch_size / world_size` replay
  shard and the mesh all-reduces the gradients. Rank 0 is the chief:
  the only rank that publishes params, writes checkpoints, and logs.
  """
  plan = learner_group_plan(config, world_size, rank)
  proc.scrub_inherited_distributed_env()
  telemetry.configure(
      plan["role"],
      trace_dir=getattr(config, "telemetry_dir", "") or None)
  injector = faults_lib.install(config, plan["role"],
                                incarnation=incarnation)
  if incarnation:
    log.warning("learner incarnation %d: resuming from the latest "
                "checkpoint in %s", incarnation, model_dir)
  if world_size > 1:
    # Group ranks present ONE host device each to the gloo mesh — an
    # inherited forced multi-device CPU topology tears the group's
    # first collective (see proc.pin_single_host_device).
    proc.pin_single_host_device()
  if coordinator_address and (config.distributed_learner
                              or world_size > 1):
    # The orchestrator picked this address with
    # ephemeral_coordinator_address(); adopt it before any jax use so
    # concurrent fleets on one host never race on a fixed port.
    proc.adopt_coordinator(coordinator_address,
                           num_processes=world_size, process_id=rank)

  rpc_kwargs = dict(
      authkey=config.authkey,
      call_timeout_secs=config.rpc_call_timeout_secs,
      max_retries=config.rpc_max_retries,
      transport=getattr(config, "transport", "loopback"),
      sndbuf=getattr(config, "tcp_sndbuf", 0),
      rcvbuf=getattr(config, "tcp_rcvbuf", 0))
  book = address_book(address)
  root = book["serving"][0]
  control = RpcClient(root, **rpc_kwargs)
  stream = RpcClient(root, **rpc_kwargs)
  # Sharded replay plane: control clients for the train thread,
  # stream clients for the prefetch thread — two per shard, same
  # single-owner discipline as the root pair.
  shard_controls = [RpcClient(a, **rpc_kwargs) for a in book["shards"]]
  shard_streams = [RpcClient(a, **rpc_kwargs) for a in book["shards"]]
  try:
    from tensor2robot_tpu.parallel.distributed import (
        maybe_initialize_distributed,
    )
    maybe_initialize_distributed()
    tmetrics.gauge("fleet.learner_group.size").set(world_size)
    tmetrics.gauge("fleet.learner_group.rank").set(rank)

    from tensor2robot_tpu.fleet.host import _build_learner
    from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt

    t_before = time.monotonic()
    hello = control.call("hello")
    t_after = time.monotonic()
    if "monotonic" in hello:
      telemetry.get_tracer().set_clock_offset(
          telemetry.clock_offset_from_handshake(
              hello["monotonic"], t_before, t_after))
    replay = RemoteReplay(control, stream, capacity=hello["capacity"],
                          shard_controls=shard_controls,
                          shard_streams=shard_streams)
    hooks: List[Hook] = [_HeartbeatHook(heartbeat)]
    if plan["publishes"]:
      # Rank 0 only: publication (and the crash-injection hooks that
      # model "the learner" dying — a group death is modelled by the
      # chief; any rank's death is fatal either way).
      hooks.insert(0, ParamPublishHook(
          control,
          telemetry_push=bool(getattr(config, "telemetry_dir", ""))))
      if config.learner_crash_after_steps:
        hooks.append(_CrashAfterHook(config.learner_crash_after_steps))
    if injector.active:
      hooks.append(_FaultPlanHook(injector))
    train_qtopt(
        learner=_build_learner(config),
        model_dir=model_dir,
        replay_buffer=replay,
        max_train_steps=config.max_train_steps,
        # The PER-PROCESS batch: `device_put_batch` assembles the
        # global batch from every rank's local shard, so the group
        # trains on `batch_size` rows total per step — same global
        # batch as the single learner, split across samplers.
        batch_size=plan["local_batch_size"],
        min_replay_size=config.min_replay_size,
        save_checkpoints_steps=config.publish_every_steps,
        log_every_steps=config.log_every_steps,
        hooks=hooks,
        seed=config.seed)
  except BaseException as e:
    # The latched-error flight record: the learner's last spans +
    # metrics survive its death (the crash-policy contract pinned by
    # tests/test_telemetry.py).
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir, f"learner: {e!r}")
    raise
  finally:
    # Stop the perf plane's sampler thread BEFORE the process exits: a
    # daemon thread mid-call into jax during interpreter teardown
    # aborts the process (SIGABRT) — the atexit hook in telemetry.perf
    # is the backstop; this is the explicit path.
    from tensor2robot_tpu.telemetry import perf as perf_lib
    perf_lib.stop_resource_sampler()
    telemetry.get_tracer().close()
    for client in shard_streams + shard_controls:
      client.close()
    stream.close()
    control.close()
