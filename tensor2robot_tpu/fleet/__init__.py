"""Podracer-style learner/actor fleet (Sebulba topology, one host).

The composition layer over every organ PRs 1–6 built: N jax-free actor
PROCESSES (each a `GraspActor` driving `MuJoCoPoseEnv` through the
`PoseGraspBandit` adapter) pull actions from, and commit atomic
episodes into, ONE replay/serving host process (`CEMPolicyServer` +
`ReplayWriteService`/`ReplayStore`), which feeds a learner process
running the unmodified `train_qtopt` loop; fresh checkpoints flow back
as param publications hot-swapped into the serving engine, stamped
with the learner step so `param_refresh_lag` is measured next to
replay staleness. See docs/FLEET.md; `bench.py --fleet` measures it.

  * `orchestrator` — `FleetConfig` / `Fleet` / `run_fleet`: the
    launch gate, heartbeat + exit-code supervision, actor-crash
    policy, and the zero-leak shutdown barrier.
  * `host` — the replay/serving host process.
  * `actor` — the jax-free actor process + the RPC-backed
    policy-server and replay-session seams for `GraspActor`.
  * `learner` — `RemoteReplay` + `ParamPublishHook` around
    `train_qtopt`.
  * `rpc` — the loopback request/response transport.

This package init stays light (no jax): `run_t2r_trainer` imports it
for gin registration in every mode, including `--validate_only`.
"""

from tensor2robot_tpu.fleet.orchestrator import (
    Fleet,
    FleetConfig,
    FleetError,
    FleetResult,
    run_fleet,
)
from tensor2robot_tpu.fleet.rpc import RpcClient, RpcError, RpcServer

__all__ = [
    "Fleet",
    "FleetConfig",
    "FleetError",
    "FleetResult",
    "RpcClient",
    "RpcError",
    "RpcServer",
    "run_fleet",
]
