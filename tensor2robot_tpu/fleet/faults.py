"""Deterministic fault injection for the fleet: the chaos plan.

RLAX-scale distributed RL treats preemption and membership churn as
the NORMAL operating regime (PAPERS.md), which means the recovery
paths — actor respawn, learner resume, RPC retry — are product code
that must be exercised as deterministically as the happy path. This
module is that exercise rig:

  * `FaultEvent` / `FaultPlan` — a picklable, seeded schedule of
    faults. Triggers are COUNT-based (actor batch index, learner step,
    Nth RPC call of a method), never wall-clock, so the same seed
    replays the same schedule on any host; `FaultPlan.digest()` is the
    SHA-256 of the canonical event list and is pinned by
    tests/test_fleet_faults.py.
  * `FaultInjector` — the per-process runtime. Each fleet child builds
    one from the plan shipped in `FleetConfig.fault_plan` (filtered to
    its own role) and injects through seams in the REAL code paths:
    `rpc.py` consults `rpc_action()` on every client call and server
    handler turn (delay / drop / disconnect), `actor_main` consults
    `on_batch()` between collect batches (crash / hang via
    `proc.hang`), the learner's fault hook consults `on_step()`.
    No mocks anywhere: an injected `rpc_drop` times out through the
    client's real deadline and recovers through its real
    reconnect-and-retry machinery.

Every injection emits a telemetry event (`fleet.fault_injected`),
bumps `fleet.faults.injected.<class>`, and — for process-killing
faults — dumps a flight record first, so post-mortems of injected
chaos look exactly like post-mortems of real chaos.

Non-recurring events (the default) fire only in a process's FIRST
incarnation: a respawned actor replays a fault-free schedule, so
recovery converges instead of crash-looping. `recurring=True` events
fire in every incarnation — the crash-loop fixture the rate-based
restart budget is tested against.

Kept jax-free: actors import this module (IMP401 worker-safe set,
pinned by tests/test_fleet.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# The fault taxonomy (docs/FLEET.md "Failure & recovery contract").
ACTOR_CRASH = "actor_crash"          # process dies between batches
ACTOR_HANG = "actor_hang"            # process stops beating, stays up
LEARNER_CRASH = "learner_crash"      # train loop raises mid-run
RPC_DELAY = "rpc_delay"              # client-side added latency
RPC_DROP = "rpc_drop"                # request lost: deadline + retry
RPC_DISCONNECT = "rpc_disconnect"    # server drops the connection
SLOW_HOST = "slow_host"              # server-side handler stall
SERVING_REPLICA_CRASH = "serving_replica_crash"  # front replica dies

FAULT_CLASSES = (ACTOR_CRASH, ACTOR_HANG, LEARNER_CRASH, RPC_DELAY,
                 RPC_DROP, RPC_DISCONNECT, SLOW_HOST)

# The full taxonomy. `FAULT_CLASSES` stays the 7-class default set so
# `FaultPlan.generate`'s seeded digest pin holds; serving_replica_crash
# (ISSUE 17 — a replicated-front host hard-exits mid-traffic, the
# router must reshed its tenants) is opt-in: it only generates when a
# caller asks for it AND declares `num_fronts`.
ALL_FAULT_CLASSES = FAULT_CLASSES + (SERVING_REPLICA_CRASH,)

# Which process injects each class: client-side faults run in the
# caller (actor/learner), server-side faults run in the host's RPC
# handler threads.
_CLIENT_RPC = (RPC_DELAY, RPC_DROP)
_SERVER_RPC = (RPC_DISCONNECT, SLOW_HOST)

# Recovery-time histogram bounds (ms): recoveries span RPC retries
# (tens of ms) to learner respawn + checkpoint restore (tens of
# seconds). One source of truth for every process that observes
# `fleet.recovery_ms`.
RECOVERY_MS_BOUNDS = (10.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
                      2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
                      120000.0)


def recovery_histogram() -> tmetrics.Histogram:
  """The process's `fleet.recovery_ms` histogram (shared bounds)."""
  return tmetrics.histogram("fleet.recovery_ms", RECOVERY_MS_BOUNDS)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
  """One scheduled fault.

  `at` is a deterministic COUNT in the target's own unit: collect
  batches for actor crash/hang, learner steps for learner_crash, and
  matching RPC calls for the rpc_*/slow_host classes. `count` extends
  rpc delay faults over that many consecutive calls (a slow host is
  slow for a while, not for one call). `method` filters rpc faults to
  one RPC method ("" = any).
  """

  fault: str
  target: str                 # "actor-<i>", "learner", or "host"
  at: int
  mode: str = "hard"          # actor_crash: raise | hard | mid_episode
  duration_secs: float = 0.0  # hang / delay / stall length
  method: str = ""
  count: int = 1
  recurring: bool = False

  def to_json(self) -> Dict[str, Any]:
    return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
  """A deterministic, seeded schedule of `FaultEvent`s (picklable —
  it ships to every child inside `FleetConfig`)."""

  seed: int
  events: Tuple[FaultEvent, ...]

  def digest(self) -> str:
    """SHA-256 over the canonical event list: the replay pin."""
    canonical = json.dumps(
        [event.to_json() for event in self.events], sort_keys=True)
    return hashlib.sha256(canonical.encode()).hexdigest()

  def for_target(self, target: str) -> Tuple[FaultEvent, ...]:
    return tuple(e for e in self.events if e.target == target)

  def classes(self) -> Tuple[str, ...]:
    return tuple(sorted({e.fault for e in self.events}))

  @classmethod
  def generate(cls,
               seed: int,
               num_actors: int,
               classes: Sequence[str] = FAULT_CLASSES,
               actor_batch_range: Tuple[int, int] = (2, 6),
               learner_step_range: Tuple[int, int] = (6, 20),
               rpc_call_range: Tuple[int, int] = (4, 16),
               hang_secs: float = 20.0,
               delay_secs: float = 0.2,
               stall_secs: float = 0.3,
               num_fronts: int = 0) -> "FaultPlan":
    """One event per requested class, targets/triggers drawn from a
    `random.Random(seed)` stream — same seed, same plan, any host.

    Ranges are in the class's own trigger unit; durations are the
    knobs a caller sizes against its heartbeat timeout (a hang must
    outlive it) and RPC deadline (a delay must not).
    """
    rng = random.Random(seed)
    events: List[FaultEvent] = []
    for fault in classes:
      if fault not in ALL_FAULT_CLASSES:
        raise ValueError(
            f"unknown fault class {fault!r}; one of "
            f"{ALL_FAULT_CLASSES}")
      if fault == SERVING_REPLICA_CRASH:
        if num_fronts < 1:
          raise ValueError(
              "serving_replica_crash needs num_fronts >= 1")
        events.append(FaultEvent(
            fault=fault, target=f"front-{rng.randrange(num_fronts)}",
            at=rng.randint(*rpc_call_range), mode="hard"))
      elif fault in (ACTOR_CRASH, ACTOR_HANG):
        target = f"actor-{rng.randrange(num_actors)}"
        at = rng.randint(*actor_batch_range)
        mode = (rng.choice(("raise", "hard", "mid_episode"))
                if fault == ACTOR_CRASH else "hard")
        events.append(FaultEvent(
            fault=fault, target=target, at=at, mode=mode,
            duration_secs=hang_secs if fault == ACTOR_HANG else 0.0))
      elif fault == LEARNER_CRASH:
        events.append(FaultEvent(
            fault=fault, target="learner",
            at=rng.randint(*learner_step_range), mode="raise"))
      elif fault in _CLIENT_RPC:
        target = rng.choice(
            [f"actor-{i}" for i in range(num_actors)] + ["learner"])
        events.append(FaultEvent(
            fault=fault, target=target,
            at=rng.randint(*rpc_call_range),
            duration_secs=delay_secs if fault == RPC_DELAY else 0.0,
            count=3 if fault == RPC_DELAY else 1))
      else:  # server-side: the host injects
        events.append(FaultEvent(
            fault=fault, target="host",
            at=rng.randint(*rpc_call_range),
            duration_secs=stall_secs if fault == SLOW_HOST else 0.0,
            count=6 if fault == SLOW_HOST else 1))
    return cls(seed=seed, events=tuple(events))


class _Armed:
  """Mutable per-event trigger state (the plan itself stays frozen)."""

  __slots__ = ("event", "remaining")

  def __init__(self, event: FaultEvent):
    self.event = event
    self.remaining = int(event.count)


class FaultInjector:
  """The per-process fault runtime; one per fleet child.

  Thread-safe: the host consults `rpc_action` from every handler
  thread. A disabled injector (no plan, or a non-recurring event in a
  respawned incarnation) costs one `None` check per seam.
  """

  def __init__(self,
               plan: Optional[FaultPlan],
               role: str,
               incarnation: int = 0,
               flightrec_dir: str = ""):
    self._role = role
    self._flightrec_dir = flightrec_dir
    self._lock = threading.Lock()
    self._rpc_calls: Dict[Tuple[str, str], int] = {}
    self._armed: List[_Armed] = []
    if plan is not None:
      for event in plan.for_target(role):
        if incarnation == 0 or event.recurring:
          self._armed.append(_Armed(event))
    self.injected: List[Dict[str, Any]] = []

  @property
  def active(self) -> bool:
    return bool(self._armed)

  def _record_injection(self, event: FaultEvent,
                        flight_record: bool = False) -> None:
    """Every injection is observable: a telemetry event, a per-class
    counter, and — for process-killing faults — a flight record dumped
    BEFORE the process dies (a hard `os._exit` has no except path)."""
    entry = {"fault": event.fault, "target": event.target,
             "at": event.at, "mode": event.mode}
    self.injected.append(entry)
    telemetry.event("fleet.fault_injected", **entry)
    tmetrics.counter(f"fleet.faults.injected.{event.fault}").inc()
    log.warning("fault injected: %s", entry)
    if flight_record and self._flightrec_dir:
      flightrec.dump(self._flightrec_dir,
                     f"injected {event.fault} ({self._role})",
                     extra={"fault_event": event.to_json()})

  # ---- the three seams ----

  def on_batch(self, batch_index: int) -> Optional[FaultEvent]:
    """Actor seam: called between collect batches. Returns the due
    crash/hang event (recorded + flight-dumped) or None."""
    with self._lock:
      for armed in self._armed:
        event = armed.event
        if (event.fault in (ACTOR_CRASH, ACTOR_HANG)
            and armed.remaining > 0 and batch_index >= event.at):
          armed.remaining = 0
          break
      else:
        return None
    self._record_injection(event, flight_record=True)
    return event

  def on_serve(self, serve_index: int) -> Optional[FaultEvent]:
    """Serving-front seam: called per predict dispatch by a front
    replica host (`fleet.front`). Returns the due
    serving_replica_crash event (recorded + flight-dumped) or None —
    the host then hard-exits and the router/orchestrator recover."""
    with self._lock:
      for armed in self._armed:
        event = armed.event
        if (event.fault == SERVING_REPLICA_CRASH
            and armed.remaining > 0 and serve_index >= event.at):
          armed.remaining = 0
          break
      else:
        return None
    self._record_injection(event, flight_record=True)
    return event

  def on_step(self, step: int) -> Optional[FaultEvent]:
    """Learner seam: called after each train step."""
    with self._lock:
      for armed in self._armed:
        event = armed.event
        if (event.fault == LEARNER_CRASH and armed.remaining > 0
            and step >= event.at):
          armed.remaining = 0
          break
      else:
        return None
    self._record_injection(event, flight_record=True)
    return event

  def rpc_action(self, side: str,
                 method: str) -> Optional[Tuple[str, float]]:
    """RPC seam (rpc.py consults this on every call/handle).

    Returns None (the overwhelmingly common case) or an action tuple:
    client side — ("delay", secs) sleep before send, ("drop", 0) skip
    the send so the REAL deadline fires; server side — ("delay", secs)
    stall the handler, ("disconnect", 0) close the connection (which
    runs the real disconnect/session-abort path).
    """
    wanted = _CLIENT_RPC if side == "client" else _SERVER_RPC
    with self._lock:
      key = (side, method)
      calls = self._rpc_calls[key] = self._rpc_calls.get(key, 0) + 1
      for armed in self._armed:
        event = armed.event
        if (event.fault in wanted and armed.remaining > 0
            and (not event.method or event.method == method)
            and calls >= event.at):
          armed.remaining -= 1
          break
      else:
        return None
    self._record_injection(event)
    if event.fault == RPC_DELAY or event.fault == SLOW_HOST:
      return ("delay", event.duration_secs)
    if event.fault == RPC_DROP:
      return ("drop", 0.0)
    return ("disconnect", 0.0)


def install(config, role: str, incarnation: int = 0) -> FaultInjector:
  """Builds this process's injector from `FleetConfig.fault_plan` and
  installs it into the RPC seam. Always returns an injector (inactive
  when no plan targets this role) so call sites stay branch-free."""
  from tensor2robot_tpu.fleet import rpc as rpc_lib

  injector = FaultInjector(
      getattr(config, "fault_plan", None), role,
      incarnation=incarnation,
      flightrec_dir=getattr(config, "flightrec_dir", "") or "")
  if injector.active:
    rpc_lib.set_fault_injector(injector)
  return injector
