"""Real-socket fleet transport: length-prefixed frames, zero-copy wire.

The loopback seam (`fleet/rpc.py` on `multiprocessing.connection`) is
what a single-host fleet needs; scaling past one host needs the same
request/response contract over a transport we control end to end. This
module is that transport — plain TCP sockets with a binary framing
protocol — selected per server/client with `transport="tcp"` while
`"loopback"` stays the bitwise default.

Wire format (one FRAME per `send`):

    magic  "t2rw"                     4 bytes
    body_len                          u64 LE   (pickle stream length)
    nbuf                              u32 LE   (out-of-band buffer count)
    buf_len[nbuf]                     u64 LE each
    body                              body_len bytes (pickle protocol 5)
    buffers...                        buf_len[i] bytes each, raw

Large array payloads — param publications, episode batches, sampled
Bellman batches — ride pickle protocol 5 **out-of-band buffers**: the
sender's `pickle.dumps(obj, buffer_callback=...)` leaves every
contiguous array OUT of the pickle stream, and `sendmsg` gathers the
header + body + raw buffer memoryviews straight from the arrays' own
memory (ZERO user-space payload copies on the send side — the only
copy is user→kernel inside the syscall). The receiver `recv_into`s
each buffer exactly once into a preallocated bytearray and
`pickle.loads(body, buffers=...)` reconstructs arrays as VIEWS of
those bytearrays (the one kernel→user copy is the only copy). That is
the "≤1 copy per side" contract `tests/test_fleet_transport.py` proves
with `np.shares_memory`, not assumes — versus the loopback's in-band
pickle, which serializes arrays INTO the stream and back out (two full
extra payload copies, measured 6–12× slower at ≥1 MiB payloads on the
`bench.py --fleet` wire microbench).

Connection hygiene:

  * `TCP_NODELAY` always (request/response RPC — Nagle only adds
    latency); `SO_SNDBUF`/`SO_RCVBUF` configurable for long-fat links
    (0 = OS default).
  * AUTH — the per-fleet authkey rides a mutual HMAC-SHA256
    challenge/response on connect (domain-separated both directions,
    `hmac.compare_digest`), mirroring the stdlib Listener contract:
    two fleets on one network can never cross-connect, and a stray
    connector is rejected before any frame is parsed.
  * OVERSIZED-FRAME GUARD — a declared length beyond
    `max_frame_bytes` raises `FrameError` and kills the connection
    before any allocation: a corrupt or hostile header can never
    balloon memory. Send-side oversizes raise `ValueError` (caller
    bug; the connection stays healthy).
  * Partial reads/writes are the NORMAL case (`recv_into` loops until
    each section fills; `sendmsg` loops over partially-sent iovecs).
    EOF mid-frame surfaces as `EOFError` — exactly the stdlib
    connection's signal, so `rpc.py`'s deadline/retry/poisoning
    machinery works unchanged on both transports.

Jax-free by construction (actor processes import this via `fleet.rpc`;
pinned by the IMP401 worker-safe set and tests/test_fleet.py).
"""

from __future__ import annotations

import hmac
import os
import pickle
import select
import socket
import struct
from multiprocessing import AuthenticationError
from typing import Any, List, Optional, Tuple

from tensor2robot_tpu.telemetry import metrics as tmetrics

MAGIC = b"t2rw"
_HEADER = struct.Struct("<4sQI")  # magic, body_len, nbuf
_BUFLEN = struct.Struct("<Q")

# One frame may not declare more than this many payload bytes (body +
# out-of-band buffers). Generous — a full param publication or a
# sampled batch is megabytes — while still refusing a corrupt header
# before it allocates.
DEFAULT_MAX_FRAME_BYTES = 1 << 30  # 1 GiB

_HANDSHAKE_TIMEOUT_SECS = 10.0
_CHALLENGE_BYTES = 32
# Domain separation: the two handshake directions can never be
# reflected into each other.
_SERVER_DOMAIN = b"t2r-fleet-transport:server:"
_CLIENT_DOMAIN = b"t2r-fleet-transport:client:"


class FrameError(OSError):
  """A malformed or over-limit frame arrived; the connection is dead."""


def _digest(authkey: bytes, domain: bytes, challenge: bytes) -> bytes:
  return hmac.new(authkey, domain + challenge, "sha256").digest()


def _configure_socket(sock: socket.socket, sndbuf: int,
                      rcvbuf: int) -> None:
  sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
  if sndbuf:
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, int(sndbuf))
  if rcvbuf:
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, int(rcvbuf))


def encode_frame(obj: Any, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES
                 ) -> List[memoryview]:
  """[header, body, raw buffers...] — ready for gather-send.

  Contiguous buffer-protocol payloads (numpy arrays) stay OUT of the
  pickle stream (protocol-5 out-of-band); anything that cannot expose
  raw contiguous memory falls back to the in-band stream.
  """
  buffers: List[pickle.PickleBuffer] = []
  try:
    body = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    raws = [b.raw() for b in buffers]
  except BufferError:
    # A non-contiguous out-of-band buffer slipped through (not a numpy
    # array — those only emit contiguous PickleBuffers): re-serialize
    # everything in-band rather than copy behind the caller's back.
    body = pickle.dumps(obj, protocol=5)
    raws = []
  total = len(body) + sum(r.nbytes for r in raws)
  if total > max_frame_bytes:
    raise ValueError(
        f"frame of {total} bytes exceeds max_frame_bytes="
        f"{max_frame_bytes}")
  parts = [memoryview(_HEADER.pack(MAGIC, len(body), len(raws)))]
  if raws:
    lens = b"".join(_BUFLEN.pack(r.nbytes) for r in raws)
    parts.append(memoryview(lens))
  parts.append(memoryview(body))
  parts.extend(raws)
  return parts


class TcpConnection:
  """One framed, authenticated socket — the stdlib-Connection shape
  (`send`/`recv`/`poll`/`close`) `rpc.py` is written against.

  NOT thread-safe: single owner, like `rpc.RpcClient`; the server
  gives each connection its own handler thread.
  """

  def __init__(self, sock: socket.socket,
               max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
               track_buffers: bool = False):
    sock.settimeout(None)  # blocking data phase; poll() bounds waits
    self._sock: Optional[socket.socket] = sock
    self._max_frame = int(max_frame_bytes)
    self._track = track_buffers
    # Copy-count instrumentation (the wire contract's proof handles):
    # payload copies beyond the single unavoidable kernel crossing per
    # side. Out-of-band buffers are sent straight from the object's
    # memory and received straight into their final backing store, so
    # both stay 0; the in-band pickle stream itself costs 1 (dumps on
    # send, loads on receive).
    self.last_send_oob_copies = 0
    self.last_recv_oob_copies = 0
    self.last_recv_buffers: List[bytearray] = []
    self._tm_bytes_sent = tmetrics.counter("fleet.wire.bytes_sent")
    self._tm_bytes_recv = tmetrics.counter("fleet.wire.bytes_received")
    self._tm_frames_sent = tmetrics.counter("fleet.wire.frames_sent")
    self._tm_frames_recv = tmetrics.counter("fleet.wire.frames_received")
    self._tm_oob = tmetrics.counter("fleet.wire.oob_buffers_sent")

  # ---- send ----

  def send(self, obj: Any) -> None:
    if self._sock is None:
      raise OSError("connection is closed")
    parts = encode_frame(obj, self._max_frame)
    noob = len(parts) - 2 - (1 if len(parts) > 2 else 0)
    total = sum(p.nbytes for p in parts)
    self._sendmsg_all(parts)
    self.last_send_oob_copies = 0  # gather-send: no user-space copy
    self._tm_bytes_sent.inc(total)
    self._tm_frames_sent.inc()
    if noob > 0:
      self._tm_oob.inc(noob)

  def _sendmsg_all(self, views: List[memoryview]) -> None:
    """Gather-send with partial-write handling (the normal TCP case)."""
    pending = [v.cast("B") if v.ndim != 1 or v.format != "B" else v
               for v in views]
    while pending:
      sent = self._sock.sendmsg(pending)
      while sent:
        head = pending[0]
        if sent >= head.nbytes:
          sent -= head.nbytes
          pending.pop(0)
        else:
          pending[0] = head[sent:]
          sent = 0

  # ---- recv ----

  def _recv_exact(self, view: memoryview) -> None:
    """Fills `view` across however many partial reads it takes."""
    got = 0
    while got < len(view):
      n = self._sock.recv_into(view[got:])
      if n == 0:
        raise EOFError("connection closed mid-frame")
      got += n

  def recv(self) -> Any:
    if self._sock is None:
      raise OSError("connection is closed")
    header = bytearray(_HEADER.size)
    self._recv_exact(memoryview(header))
    magic, body_len, nbuf = _HEADER.unpack(header)
    if magic != MAGIC:
      raise FrameError(f"bad frame magic {bytes(magic)!r}")
    # The guard runs on DECLARED lengths, before any allocation.
    if body_len > self._max_frame or nbuf > self._max_frame // 8:
      raise FrameError(
          f"frame declares body of {body_len} bytes / {nbuf} buffers "
          f"(max_frame_bytes={self._max_frame})")
    lens: List[int] = []
    if nbuf:
      raw_lens = bytearray(_BUFLEN.size * nbuf)
      self._recv_exact(memoryview(raw_lens))
      lens = [_BUFLEN.unpack_from(raw_lens, i * _BUFLEN.size)[0]
              for i in range(nbuf)]
    total = body_len + sum(lens)
    if total > self._max_frame:
      raise FrameError(
          f"frame declares {total} payload bytes "
          f"(max_frame_bytes={self._max_frame})")
    body = bytearray(body_len)
    self._recv_exact(memoryview(body))
    oob: List[bytearray] = []
    for length in lens:
      buf = bytearray(length)
      # recv_into the FINAL backing store: pickle.loads below hands
      # out views of these bytearrays, so the kernel→user read is the
      # only copy the payload ever takes on this side.
      self._recv_exact(memoryview(buf))
      oob.append(buf)
    self._tm_bytes_recv.inc(_HEADER.size + len(lens) * _BUFLEN.size
                            + total)
    self._tm_frames_recv.inc()
    self.last_recv_oob_copies = 0
    self.last_recv_buffers = oob if self._track else []
    return pickle.loads(body, buffers=[memoryview(b) for b in oob])

  # ---- the stdlib-Connection surface rpc.py uses ----

  def poll(self, timeout: Optional[float] = 0.0) -> bool:
    if self._sock is None:
      raise OSError("connection is closed")
    readable, _, _ = select.select([self._sock], [], [], timeout)
    return bool(readable)

  def fileno(self) -> int:
    if self._sock is None:
      raise OSError("connection is closed")
    return self._sock.fileno()

  def close(self) -> None:
    sock, self._sock = self._sock, None
    if sock is not None:
      try:
        sock.shutdown(socket.SHUT_RDWR)
      except OSError:
        pass
      sock.close()


# ---- handshake ----


def _send_block(sock: socket.socket, payload: bytes) -> None:
  sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_block(sock: socket.socket, limit: int = 256) -> bytes:
  raw = bytearray(4)
  view = memoryview(raw)
  got = 0
  while got < 4:
    n = sock.recv_into(view[got:])
    if n == 0:
      raise EOFError("connection closed during handshake")
    got += n
  (length,) = struct.unpack("<I", raw)
  if length > limit:
    raise FrameError(f"handshake block of {length} bytes (limit {limit})")
  payload = bytearray(length)
  view = memoryview(payload)
  got = 0
  while got < length:
    n = sock.recv_into(view[got:])
    if n == 0:
      raise EOFError("connection closed during handshake")
    got += n
  return bytes(payload)


def _server_handshake(sock: socket.socket, authkey: bytes) -> None:
  challenge = os.urandom(_CHALLENGE_BYTES)
  _send_block(sock, challenge)
  answer = _recv_block(sock)
  if not hmac.compare_digest(
      answer, _digest(authkey, _SERVER_DOMAIN, challenge)):
    raise AuthenticationError("client failed the authkey challenge")
  client_challenge = _recv_block(sock)
  _send_block(sock, _digest(authkey, _CLIENT_DOMAIN, client_challenge))


def _client_handshake(sock: socket.socket, authkey: bytes) -> None:
  challenge = _recv_block(sock)
  _send_block(sock, _digest(authkey, _SERVER_DOMAIN, challenge))
  my_challenge = os.urandom(_CHALLENGE_BYTES)
  _send_block(sock, my_challenge)
  answer = _recv_block(sock)
  if not hmac.compare_digest(
      answer, _digest(authkey, _CLIENT_DOMAIN, my_challenge)):
    raise AuthenticationError("server failed the authkey challenge")


class TcpListener:
  """Bound TCP listener whose `accept` yields authenticated
  `TcpConnection`s — the stdlib-Listener shape `rpc.RpcServer` drives.
  """

  def __init__(self, host: str = "127.0.0.1", port: int = 0,
               authkey: bytes = b"", sndbuf: int = 0, rcvbuf: int = 0,
               max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
               backlog: int = 64):
    if not authkey:
      raise ValueError("TcpListener requires a non-empty authkey")
    self._authkey = authkey
    self._sndbuf = int(sndbuf)
    self._rcvbuf = int(rcvbuf)
    self._max_frame = int(max_frame_bytes)
    self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    self._sock.bind((host, int(port)))
    self._sock.listen(backlog)
    self.address: Tuple[str, int] = self._sock.getsockname()[:2]

  def accept(self) -> TcpConnection:
    """Blocks for one connection; auth/handshake failures raise
    `AuthenticationError` (the accept loop logs and keeps serving);
    only a closed listener raises `OSError` out of here."""
    sock, _ = self._sock.accept()  # OSError here = listener closed
    try:
      _configure_socket(sock, self._sndbuf, self._rcvbuf)
      sock.settimeout(_HANDSHAKE_TIMEOUT_SECS)
      _server_handshake(sock, self._authkey)
    except AuthenticationError:
      sock.close()
      raise
    except Exception as e:  # timeout / EOF / bad block mid-handshake
      sock.close()
      raise AuthenticationError(
          f"transport handshake failed: {e!r}") from e
    return TcpConnection(sock, max_frame_bytes=self._max_frame)

  def close(self) -> None:
    self._sock.close()


def connect_tcp(address: Tuple[str, int], authkey: bytes,
                sndbuf: int = 0, rcvbuf: int = 0,
                max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                track_buffers: bool = False) -> TcpConnection:
  """Dial + authenticate; raises `ConnectionRefusedError`/`OSError`
  while the server is still warming (the rpc.py connect-retry window)
  and `AuthenticationError` on a key mismatch (never retried)."""
  if not authkey:
    raise ValueError("connect_tcp requires a non-empty authkey")
  sock = socket.create_connection(tuple(address),
                                  timeout=_HANDSHAKE_TIMEOUT_SECS)
  try:
    _configure_socket(sock, sndbuf, rcvbuf)
    _client_handshake(sock, authkey)
  except AuthenticationError:
    sock.close()
    raise
  except Exception as e:
    sock.close()
    raise AuthenticationError(
        f"transport handshake failed: {e!r}") from e
  return TcpConnection(sock, max_frame_bytes=max_frame_bytes,
                       track_buffers=track_buffers)
