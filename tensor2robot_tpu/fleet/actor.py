"""Fleet actor process: env stepping only — no jax, no device, no model.

The Podracer actor is deliberately cheap: it steps environments and
speaks RPC. Action selection happens in the host's serving engine
(every actor's requests coalesce in the micro-batcher there), episode
commits go through the host's replay sessions, and parameters never
touch this process at all — so an actor costs a Python interpreter +
an env, and `import jax` (seconds of spin-up, an XLA runtime of
memory) never runs here. tests/test_fleet.py pins the jax-free import.

The in-process building blocks are reused, not forked: the loop IS
`GraspActor.collect_once` — this module just supplies its two seams
with RPC-backed implementations:

  * `FleetPolicyClient` — the `policy_server=` seam. Each `act` reply
    carries the engine's params version + the learner step those
    params were published at, so every episode is stamped with the
    policy that produced it (the `param_refresh_lag` measurement
    seam).
  * `FleetReplaySession` — the replay-sink seam. One `add` = one
    atomic episode commit server-side; the drop-policy bool comes
    back so the actor's `episodes_dropped` accounting keeps working.
    `begin/append/end` are exposed too (multi-chunk episodes, crash
    injection): rows staged server-side between `begin` and `end` are
    aborted if the connection dies — the mid-episode crash contract.
"""

from __future__ import annotations

import hashlib
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import proc
from tensor2robot_tpu.fleet.rpc import RpcClient
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Exit code for injected hard crashes (tests/bench assert on it being
# distinguishable from a clean 0 and a Python-exception 1).
CRASH_EXIT_CODE = 13


def home_shard(actor_id: str, num_shards: int) -> int:
  """The actor's consistent-hash home replay shard (ISSUE 16).

  Rendezvous (highest-random-weight) hashing: each (actor, shard)
  pair gets a deterministic pseudo-random weight and the actor homes
  on its max. The property that matters operationally: when the shard
  set changes, ONLY the actors homed on a removed shard remap —
  everyone else's episodes keep landing where they always did
  (pinned by tests/test_fleet_transport.py).

  The canonical, bucket-set-generalized form of this rule lives in
  `replay.sampler.rendezvous_choose` (the serving router places
  tenants with it); this module must stay jax-free and so keeps a
  local copy, pinned byte-identical by tests/test_serving_router.py.
  """
  if num_shards <= 0:
    raise ValueError(f"num_shards must be positive, got {num_shards}")
  best, best_weight = 0, -1
  for shard in range(num_shards):
    digest = hashlib.sha256(
        f"{actor_id}|shard-{shard}".encode()).digest()
    weight = int.from_bytes(digest[:8], "big")
    if weight > best_weight:
      best, best_weight = shard, weight
  return best


def address_book(address) -> Dict[str, List[Tuple[str, int]]]:
  """Normalizes an RPC target into the fleet's address book.

  A bare `(host, port)` tuple — every pre-sharding caller — means one
  serving host that also owns the replay plane. The orchestrator's
  multi-host launches pass `{"serving": [...], "shards": [...]}`
  instead: serving[0] is the ROOT (reference clock, learner control),
  and a non-empty `shards` list moves every commit/sample to the
  shard services.
  """
  if isinstance(address, dict):
    return {"serving": [tuple(a) for a in address.get("serving", ())],
            "shards": [tuple(a) for a in address.get("shards", ())]}
  return {"serving": [tuple(address)], "shards": []}


class FleetPolicyClient:
  """`GraspActor.policy_server`-shaped proxy to the host's CEM server."""

  def __init__(self, client: RpcClient, max_batch: int):
    self._client = client
    self.max_batch = int(max_batch)
    self.params_version = 0
    self.params_learner_step = 0
    self.params_hop = 0

  @property
  def engine(self) -> "FleetPolicyClient":
    # GraspActor chunks requests to `policy_server.engine.max_batch`;
    # the remote engine's bucket table is what bounds us, so this
    # proxy doubles as its own `engine`.
    return self

  def select_actions(self,
                     observations: Dict[str, Any]) -> np.ndarray:
    reply = self._client.call(
        "act", {k: np.asarray(v) for k, v in observations.items()})
    self.params_version = int(reply["params_version"])
    self.params_learner_step = int(reply["params_learner_step"])
    # The acting host's broadcast-tree depth: stamped into commits so
    # the shard attributes param_refresh_lag PER HOP (ISSUE 16).
    self.params_hop = int(reply.get("params_hop", 0))
    return np.asarray(reply["actions"])

  def update_state(self, state) -> None:
    raise NotImplementedError(
        "fleet actors never push params; the learner publishes to the "
        "host's engine directly")


class FleetReplaySession:
  """`GraspActor` replay sink committing through the host's sessions.

  Every call stamps the episode with the policy version/learner-step
  the paired `FleetPolicyClient` last acted with, which is how the
  host attributes `param_refresh_lag` to committed rows.
  """

  def __init__(self, client: RpcClient, actor_id: str,
               policy: Optional[FleetPolicyClient] = None):
    self._client = client
    self._policy = policy
    self.actor_id = actor_id
    self.last_transitions: Optional[Dict[str, np.ndarray]] = None

  def _stamp(self) -> Dict[str, Any]:
    if self._policy is None:
      return {"policy_version": None, "policy_learner_step": None}
    return {"policy_version": self._policy.params_version,
            "policy_learner_step": self._policy.params_learner_step,
            "policy_hop": self._policy.params_hop}

  def add(self, transitions: Dict[str, Any]) -> bool:
    flat = {k: np.asarray(v) for k, v in transitions.items()}
    self.last_transitions = flat
    payload = {"actor_id": self.actor_id, "transitions": flat}
    payload.update(self._stamp())
    return bool(self._client.call("commit", payload))

  def begin_episode(self) -> None:
    self._client.call("begin_episode", self.actor_id)

  def append(self, transitions: Dict[str, Any]) -> None:
    self._client.call("append", {
        "actor_id": self.actor_id,
        "transitions": {k: np.asarray(v)
                        for k, v in transitions.items()}})

  def end_episode(self) -> bool:
    payload = {"actor_id": self.actor_id}
    payload.update(self._stamp())
    return bool(self._client.call("end_episode", payload))


def build_env(config, actor_index: int):
  """The per-actor environment, seeded per index.

  `mujoco_pose` is the fleet default: `GraspActor` driving the
  physics-backed `MuJoCoPoseEnv` through the `PoseGraspBandit`
  adapter. `pose` is the numpy variant (no mujoco dependency);
  `toy_grasp` is the original QT-Opt bandit.
  """
  seed = config.seed + 1009 * (actor_index + 1)
  if config.env == "toy_grasp":
    from tensor2robot_tpu.research.qtopt.grasping_env import ToyGraspEnv
    return ToyGraspEnv(image_size=config.image_size,
                       action_dim=config.action_dim, seed=seed)
  if config.env in ("pose", "mujoco_pose"):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        PoseGraspBandit,
    )
    return PoseGraspBandit(image_size=config.image_size,
                           action_dim=config.action_dim,
                           physics=(config.env == "mujoco_pose"),
                           seed=seed)
  raise ValueError(f"unknown fleet env {config.env!r}")


def _inject_crash(mode: str, sink: FleetReplaySession) -> None:
  """Test/bench fault injection (FleetConfig.actor_crash_*)."""
  if mode == "mid_episode":
    # Die BETWEEN append and end_episode: rows are staged in the
    # host-side session when the process vanishes. The disconnect
    # abort (host.py) must discard them — the partial-episode pin.
    sink.begin_episode()
    if sink.last_transitions is not None:
      sink.append(sink.last_transitions)
    os._exit(CRASH_EXIT_CODE)
  if mode == "hard":
    os._exit(CRASH_EXIT_CODE)
  raise RuntimeError("injected actor crash (FleetConfig.actor_crash_*)")


def _push_telemetry(client: RpcClient, role: str) -> None:
  """Ships this process's registry snapshot to the host (best-effort:
  telemetry must never take an actor down)."""
  try:
    client.call("telemetry_push", {
        "role": role,
        "snapshot": tmetrics.registry().snapshot()})
  except Exception:  # noqa: BLE001 — instrumentation only
    log.warning("telemetry push failed", exc_info=True)


def actor_main(config, actor_index: int, address, stop_event,
               heartbeat, incarnation: int = 0) -> None:
  """Child-process entry: connect → collect until told to stop."""
  proc.scrub_inherited_distributed_env()
  actor_id = f"actor-{actor_index}"
  telemetry.configure(
      actor_id, trace_dir=getattr(config, "telemetry_dir", "") or None,
      actor_id=actor_id)
  # Resource watermarks (ISSUE 15): host RSS for this jax-free role;
  # rsrc.* gauges ride the existing telemetry_push to the host.
  from tensor2robot_tpu.telemetry import perf as perf_lib
  perf_lib.start_resource_sampler()
  # The fault-plan seam (ISSUE 14): non-recurring events fire only in
  # incarnation 0, so a respawned actor replays a fault-free schedule.
  # `install` also arms the RPC client-side seam for this process.
  injector = faults_lib.install(config, actor_id,
                                incarnation=incarnation)
  rpc_kwargs = dict(
      authkey=config.authkey,
      call_timeout_secs=config.rpc_call_timeout_secs,
      max_retries=config.rpc_max_retries,
      transport=getattr(config, "transport", "loopback"),
      sndbuf=getattr(config, "tcp_sndbuf", 0),
      rcvbuf=getattr(config, "tcp_rcvbuf", 0))
  book = address_book(address)
  serving = book["serving"]
  # Multi-host placement (ISSUE 16): act against this actor's serving
  # host (round-robin over the broadcast tree — deeper hosts see
  # params later, which the per-hop lag attribution measures), commit
  # to the rendezvous-hash home shard (or the same host when the
  # replay plane is unsharded).
  act_address = serving[actor_index % len(serving)]
  client = RpcClient(act_address, **rpc_kwargs)
  commit_client: Optional[RpcClient] = None
  try:
    t_before = time.monotonic()
    hello = client.call("hello")
    t_after = time.monotonic()
    if "monotonic" in hello and act_address == serving[0]:
      # The clock handshake: this actor's spans merge onto the ROOT
      # host's monotonic timeline (telemetry.merge).
      telemetry.get_tracer().set_clock_offset(
          telemetry.clock_offset_from_handshake(
              hello["monotonic"], t_before, t_after))
    if act_address != serving[0]:
      # Acting against a replica: the reference clock is still the
      # root's — one transient hello aligns this trace.
      with RpcClient(serving[0], **rpc_kwargs) as root:
        t_before = time.monotonic()
        root_hello = root.call("hello")
        t_after = time.monotonic()
        if "monotonic" in root_hello:
          telemetry.get_tracer().set_clock_offset(
              telemetry.clock_offset_from_handshake(
                  root_hello["monotonic"], t_before, t_after))
    policy = FleetPolicyClient(client, max_batch=hello["max_batch"])
    if book["shards"]:
      shard = home_shard(actor_id, len(book["shards"]))
      commit_client = RpcClient(book["shards"][shard], **rpc_kwargs)
      sink = FleetReplaySession(commit_client, actor_id, policy)
      log.info("%s commits to replay shard %d at %s", actor_id, shard,
               book["shards"][shard])
    else:
      sink = FleetReplaySession(client, actor_id, policy)
    env = build_env(config, actor_index)

    from tensor2robot_tpu.research.qtopt.actor import GraspActor

    actor = GraspActor(
        learner=None,
        replay_buffer=sink,
        env=env,
        batch_episodes=config.batch_episodes,
        epsilon=config.epsilon,
        seed=config.seed + 101 * (actor_index + 1),
        policy_server=policy,
        name=actor_id)
    crash_after = (
        config.actor_crash_after_episodes
        if (actor_index == config.crash_actor_index and incarnation == 0)
        else None)
    batches = 0
    episodes = tmetrics.gauge("actor.episodes_collected")
    dropped = tmetrics.gauge("actor.episodes_dropped")
    # Snapshot pushes ride the acting connection, so they are (a) off
    # with the plane (telemetry_dir="off" — the orchestrator never
    # polls), and (b) rate-limited to the orchestrator's poll cadence:
    # pushing faster than anyone reads is pure dead-write latency on
    # the act/commit path.
    push_period = (max(float(getattr(config, "telemetry_poll_secs",
                                     0.0)), 1.0)
                   if getattr(config, "telemetry_dir", "")
                   and getattr(config, "telemetry_poll_secs", 0.0)
                   else None)
    t_last_push = 0.0
    while not stop_event.is_set():
      with telemetry.span("actor.collect_batch",
                          batch=config.batch_episodes):
        actor.collect_once()
      # Mirror the actor's cumulative accounting into the registry
      # (gauges: the actor object owns the true counters).
      episodes.set(actor.episodes_collected)
      dropped.set(actor.episodes_dropped)
      batches += 1
      # Fault-plan seam, consulted BETWEEN batches and BEFORE the
      # beat: an injected hang leaves the heartbeat one full batch
      # stale (exactly what a wedged env binding looks like), and an
      # injected crash dies with the batch committed — partial rows
      # can only come from the mid_episode mode, whose staged rows the
      # host aborts on disconnect.
      event = injector.on_batch(batches)
      if event is not None:
        if event.fault == faults_lib.ACTOR_HANG:
          proc.hang(event.duration_secs)
        else:
          _inject_crash(event.mode, sink)
      proc.beat(heartbeat)
      if (push_period is not None
          and time.monotonic() - t_last_push >= push_period):
        t_last_push = time.monotonic()
        _push_telemetry(client, actor_id)
      if crash_after is not None and batches >= crash_after:
        _inject_crash(config.actor_crash_mode, sink)
    if push_period is not None:
      # Final snapshot as the actor drains: the orchestrator's
      # end-of-run telemetry read (shutdown barrier) must see this
      # incarnation's rpc retry/recovery counters.
      _push_telemetry(client, actor_id)
    log.info("actor %s stopping cleanly: %d committed / %d dropped "
             "episodes, last policy version %s", actor_id,
             actor.episodes_collected, actor.episodes_dropped,
             actor.last_policy_version)
  except BaseException as e:
    # The crash-policy flight record: the orchestrator sees exit
    # codes; THIS preserves what the actor was doing when it died.
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir, f"{actor_id}: {e!r}")
    raise
  finally:
    perf_lib.stop_resource_sampler()
    telemetry.get_tracer().close()
    if commit_client is not None:
      commit_client.close()
    client.close()
