"""Shared helpers for fleet child processes (host / actors / learner).

Every fleet child runs this module's `scrub_inherited_distributed_env`
FIRST: a fleet is often launched from a process that itself sits
inside a multi-host training context (`JAX_COORDINATOR_ADDRESS` and
friends in the environment), and `multiprocessing`'s spawn children
inherit the parent's environ wholesale. A fleet child that kept those
variables would call `jax.distributed.initialize` against a
coordinator it is not part of and block forever waiting for peers —
the exact class of same-host collision the collision-safe coordinator
contract exists to prevent (see
`parallel.distributed.ephemeral_coordinator_address`). Children that
DO want a distributed runtime (the learner with
`FleetConfig.distributed_learner=True`) get a fresh ephemeral
coordinator address handed to them explicitly by the orchestrator.

Kept jax-free so actor processes can import it without paying the XLA
runtime (pinned by tests/test_fleet.py).
"""

from __future__ import annotations

import os
import time

# The launch-contract variables `maybe_initialize_distributed` reads.
_DISTRIBUTED_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "JAX_NUM_PROCESSES",
    "JAX_PROCESS_ID",
)


def scrub_inherited_distributed_env() -> None:
  """Drops inherited multi-host launch variables from this process."""
  for var in _DISTRIBUTED_ENV_VARS:
    os.environ.pop(var, None)


def pin_single_host_device() -> None:
  """Forces ONE host-platform device in this process's XLA runtime.

  Learner-group ranks (ISSUE 19, `learner_hosts > 1`) must present a
  symmetric single-device topology to gloo: the CPU backend's
  cross-process collectives desync when each rank carries a forced
  multi-device host platform (a parent that set
  `--xla_force_host_platform_device_count=8` — the test suite does —
  hands every spawned rank 8 fake devices, and the group's first
  collective tears with a gloo preamble-size mismatch). Strip any
  inherited count and pin 1; the flag only affects the host platform,
  so this is a no-op on real accelerators. Must run before the
  process's first jax import.
  """
  flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
           if not f.startswith("--xla_force_host_platform_device_count")]
  flags.append("--xla_force_host_platform_device_count=1")
  os.environ["XLA_FLAGS"] = " ".join(flags)


def adopt_coordinator(address: str, num_processes: int = 1,
                      process_id: int = 0) -> None:
  """Installs an orchestrator-issued coordinator triple into env.

  The orchestrator (not the child) picked `address` with
  `ephemeral_coordinator_address()`, so two fleets on one machine can
  never race on a fixed port; the child just adopts it before its
  first jax import.
  """
  os.environ["JAX_COORDINATOR_ADDRESS"] = address
  os.environ["JAX_NUM_PROCESSES"] = str(num_processes)
  os.environ["JAX_PROCESS_ID"] = str(process_id)


def beat(heartbeat) -> None:
  """Stamps a shared heartbeat slot with the current monotonic time.

  `heartbeat` is a `multiprocessing.Value('d')`; CLOCK_MONOTONIC is
  system-wide on Linux, so the orchestrator compares stamps from any
  process against its own clock.
  """
  if heartbeat is not None:
    heartbeat.value = time.monotonic()


def hang(duration_secs: float) -> None:
  """Deterministic hang injection: sleep WITHOUT beating.

  The `actor_hang` fault class (`fleet/faults.py`): the process stays
  alive but its heartbeat goes stale, which is exactly what a wedged
  env binding or a deadlocked native call looks like from the
  orchestrator — detected by the heartbeat timer, recovered by
  kill-and-respawn under the restart policy. A real hang would not
  check a stop event either, so this one doesn't.
  """
  time.sleep(duration_secs)
