"""Async export hook: checkpoint → SavedModel handoff during training.

Reference parity: tensor2robot `hooks/async_export_hook_builder.py` —
the QT-Opt robot-fleet handoff: during training, each new checkpoint is
converted to a SavedModel and published to a serving directory that
robots poll (SURVEY.md §3 "Hooks", §4.4; file:line unavailable — empty
reference mount).

Async here means off the training thread: export (jax2tf trace + TF
save, seconds of host work) runs in a single background worker while
device steps continue. If a new checkpoint lands while an export is
still running, the older request is dropped — robots always want the
newest model, never a backlog.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

import jax

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.hooks.hook import Hook

log = logging.getLogger(__name__)


@gin.configurable
class AsyncExportHook(Hook):
  """Exports a serving artifact after every Nth checkpoint."""

  def __init__(self, export_generator,
               export_every_n_checkpoints: int = 1,
               export_dir_base: Optional[str] = None,
               block: bool = False):
    """Args:
      export_generator: an AbstractExportGenerator.
      export_every_n_checkpoints: cadence (1 = every checkpoint).
      export_dir_base: overrides the generator's target directory.
      block: run exports inline (tests / deterministic pipelines).
    """
    self._generator = export_generator
    if export_dir_base is not None:
      self._generator.set_export_dir_base(export_dir_base)
    self._every_n = max(1, int(export_every_n_checkpoints))
    self._block = block
    self._model = None
    self._count = 0
    self._lock = threading.Lock()
    self._pending: Optional[tuple] = None
    self._worker: Optional[threading.Thread] = None
    self.export_paths = []

  def begin(self, model, model_dir: str) -> None:
    self._model = model

  def after_checkpoint(self, step: int, state: Any,
                       model_dir: str) -> None:
    self._count += 1
    if self._count % self._every_n != 0:
      return
    # Snapshot to host now: the training loop donates/overwrites the
    # device state buffers on the very next step. Only the pieces the
    # export reads — pulling optimizer moments (~2x params for Adam)
    # would stall the training thread for nothing.
    if hasattr(state, "replace") and hasattr(state, "opt_state"):
      host_state = jax.device_get(state.replace(opt_state=None))
    else:
      host_state = jax.device_get(state)
    if self._block:
      self._export(host_state, model_dir)
      return
    with self._lock:
      self._pending = (host_state, model_dir)
      if self._worker is None:
        self._worker = threading.Thread(
            target=self._drain, name="async-export", daemon=True)
        self._worker.start()

  def _drain(self) -> None:
    while True:
      with self._lock:
        if self._pending is None:
          # Hand back the worker slot under the same lock that guards
          # _pending: a checkpoint thread setting _pending either sees
          # it taken (this loop will pick the work up) or free (it
          # starts a fresh worker). No request can fall in between.
          self._worker = None
          return
        host_state, model_dir = self._pending
        self._pending = None
      self._export(host_state, model_dir)

  def _export(self, host_state, model_dir: str) -> None:
    try:
      path = self._generator.export(self._model, host_state, model_dir)
      self.export_paths.append(path)
      log.info("Exported serving model to %s", path)
    except Exception:  # noqa: BLE001 — export failure must not kill training
      log.exception("Async export failed; training continues.")

  def end(self, step: int, state: Any, model_dir: str) -> None:
    while True:
      with self._lock:
        worker = self._worker
      if worker is None:
        break
      worker.join(timeout=300.0)
      if worker.is_alive():
        log.warning("Async export still running at shutdown; detaching.")
        return
    # Belt and braces: drain anything that slipped in as the last
    # worker exited, so the final model always gets published.
    with self._lock:
      pending, self._pending = self._pending, None
    if pending is not None:
      self._export(*pending)
