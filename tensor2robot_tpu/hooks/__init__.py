"""Trainer hooks (reference: tensor2robot hooks/ SessionRunHook builders).

Exports resolve LAZILY (PEP 562, the `data/__init__` pattern): the
base `Hook`/`HookList` protocol is pure Python and is imported by
fleet actor/learner process entry modules at spawn, but
`async_export_hook` drags jax at module level — an eager package init
would pull the XLA runtime into jax-free actor processes
(tests/test_fleet.py pins the import). Gin registration for the
configurable hooks is declared via `register_lazy_configurables` so
shipped configs (`@SuccessEvalHook()`, ...) still resolve right after
`run_t2r_trainer`'s bare package import.
"""

from tensor2robot_tpu import config as _gin
# The protocol itself stays eager: it is jax-free and nearly every
# consumer wants it.
from tensor2robot_tpu.hooks.hook import Hook, HookList

_EXPORTS = {
    "AsyncExportHook": "async_export_hook",
    "QTOptSuccessEvalHook": "success_eval_hook",
    "ScenarioSuccessEvalHook": "success_eval_hook",
    "SuccessEvalHook": "success_eval_hook",
}

__all__ = ["Hook", "HookList"] + sorted(_EXPORTS)

# Every lazy export here is a @gin.configurable (unlike the
# qtopt/pose_env inits, where the registered set is a deliberate
# subset), so _EXPORTS is the single source of truth.
for _name, _mod in _EXPORTS.items():
  _gin.register_lazy_configurables(f"{__name__}.{_mod}", (_name,))
del _name, _mod


def __getattr__(name):
  module_name = _EXPORTS.get(name)
  if module_name is None:
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
  import importlib

  module = importlib.import_module(f"{__name__}.{module_name}")
  value = getattr(module, name)
  globals()[name] = value
  return value
