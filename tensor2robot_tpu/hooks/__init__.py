"""Trainer hooks (reference: tensor2robot hooks/ SessionRunHook builders)."""

from tensor2robot_tpu.hooks.hook import Hook, HookList
from tensor2robot_tpu.hooks.async_export_hook import AsyncExportHook
from tensor2robot_tpu.hooks.success_eval_hook import (
    QTOptSuccessEvalHook,
    SuccessEvalHook,
)
