"""Hook protocol for the training loop.

Reference parity: tensor2robot `hooks/hook_builder.py` — estimator
`SessionRunHook`s, chiefly the async-export-on-checkpoint path
(SURVEY.md §3 "Hooks"). The JAX trainer has no session, so hooks get
explicit callbacks at well-defined loop points.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional


class Hook:
  """Base hook: override any subset of the callbacks."""

  # Trainers inspect this to detect the ONLINE regime (actors feeding
  # replay concurrently with training): it changes data-plane defaults
  # like the prefetch lookahead depth (sampling-lead vs throughput).
  drives_online_collection: bool = False

  def begin(self, model, model_dir: str) -> None:
    """Called once before the first step."""

  def after_step(self, step: int, metrics: dict) -> None:
    """Called after every train step (metrics are device arrays)."""

  def after_checkpoint(self, step: int, state: Any,
                       model_dir: str) -> None:
    """Called after a checkpoint save is initiated at `step`."""

  def end(self, step: int, state: Any, model_dir: str) -> None:
    """Called once after training finishes."""


class HookList(Hook):
  """Fans callbacks out to a list of hooks."""

  def __init__(self, hooks: Optional[Iterable[Hook]] = None):
    self._hooks = list(hooks or [])

  def append(self, hook: Hook) -> None:
    self._hooks.append(hook)

  @property
  def drives_online_collection(self) -> bool:  # type: ignore[override]
    return any(getattr(h, "drives_online_collection", False)
               for h in self._hooks)

  def begin(self, model, model_dir):
    for h in self._hooks:
      h.begin(model, model_dir)

  def after_step(self, step, metrics):
    for h in self._hooks:
      h.after_step(step, metrics)

  def after_checkpoint(self, step, state, model_dir):
    for h in self._hooks:
      h.after_checkpoint(step, state, model_dir)

  def end(self, step, state, model_dir):
    for h in self._hooks:
      h.end(step, state, model_dir)
