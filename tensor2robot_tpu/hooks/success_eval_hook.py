"""Per-checkpoint closed-loop success evaluation hook.

Reference parity: the reference's policy checkpoints were scored by
closed-loop success on held-out task variation, ≥500 episodes per
checkpoint, reported per checkpoint (BASELINE.md protocol step 3); the
reference ran this on a separate eval fleet. Here the trainer itself
drives it after each checkpoint and a `success_rate` line lands in
`metrics_<tag>.jsonl` next to the train/eval metrics.

Two flavors:
  * `SuccessEvalHook` — wraps any `eval_fn(predict_fn, **kwargs)`
    protocol (evaluate_gripper_policy, evaluate_pose_model,
    grasp2vec's evaluate_retrieval): the hook builds the batched
    `predict(np) → np` function from the in-memory train state, so no
    checkpoint round-trip is paid.
  * `QTOptSuccessEvalHook` — wraps `evaluate_grasp_policy(learner,
    state, ...)`: the CEM policy needs the learner, not predict_step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.hooks.hook import Hook


def _write_metrics(model_dir: str, tag: str, step: int,
                   metrics: Dict[str, float]) -> None:
  from tensor2robot_tpu.train_eval import MetricLogger  # lazy: cycle

  logger = MetricLogger(model_dir)
  try:
    logger.write(tag, step, metrics)
  finally:
    logger.close()


@gin.configurable
class SuccessEvalHook(Hook):
  """Runs `eval_fn(predict_fn, **eval_kwargs)` after each checkpoint.

  Args:
    eval_fn: e.g. `evaluate_gripper_policy`; receives a batched
      `predict(features: np dict) -> np dict` plus `eval_kwargs`
      (episode counts, held-out seeds/offsets — the PROTOCOL lives in
      these kwargs; defaults in the eval fns are test-sized).
    eval_kwargs: forwarded verbatim.
    tag: metrics file suffix (metrics_<tag>.jsonl).
    every_n_checkpoints: thin out when eval is expensive.
  """

  def __init__(self,
               eval_fn: Callable[..., Dict[str, float]],
               eval_kwargs: Optional[Dict[str, Any]] = None,
               tag: str = "success_eval",
               every_n_checkpoints: int = 1):
    self._eval_fn = eval_fn
    self._eval_kwargs = dict(eval_kwargs or {})
    self._tag = tag
    self._every = max(1, every_n_checkpoints)
    self._model = None
    self._jit_predict = None
    self._checkpoints_seen = 0

  def begin(self, model, model_dir: str) -> None:
    self._model = model
    self._jit_predict = None
    self._checkpoints_seen = 0

  def after_checkpoint(self, step: int, state: Any,
                       model_dir: str) -> None:
    self._checkpoints_seen += 1
    if (self._checkpoints_seen - 1) % self._every:
      return
    import jax
    import numpy as np
    from tensor2robot_tpu.specs import TensorSpecStruct

    if self._jit_predict is None:
      self._jit_predict = jax.jit(self._model.predict_step)

    def predict(features: Dict[str, np.ndarray]) -> Dict[str, Any]:
      packed = TensorSpecStruct.from_flat_dict(
          {k: np.asarray(v) for k, v in features.items()})
      outputs = self._jit_predict(state, packed)
      if not isinstance(outputs, dict):
        outputs = (outputs.to_flat_dict()
                   if hasattr(outputs, "to_flat_dict")
                   else {"output": outputs})
      return {k: np.asarray(jax.device_get(v))
              for k, v in outputs.items()}

    metrics = self._eval_fn(predict, **self._eval_kwargs)
    _write_metrics(model_dir, self._tag, step, metrics)


@gin.configurable
class ScenarioSuccessEvalHook(Hook):
  """Per-checkpoint PROCEDURAL-scenario robustness sweep (envs family).

  The on-device counterpart of `QTOptSuccessEvalHook` for the
  anakin/pod trainers: after each checkpoint it runs
  `envs.evaluate_scenarios` — the seeded procgen sweep
  `run_success_protocol envs` commits, success grouped by scenario
  bucket (distractor count) with the random-policy baseline on the
  SAME scenarios — against the checkpointed critic, then

    * logs the headline metrics (overall + per-bucket success, random
      baseline) to ``metrics_<tag>.jsonl`` next to the train metrics,
    * APPENDS one success-protocol-shaped record per checkpoint to
      ``artifacts_path`` (default
      ``<model_dir>/success_protocol/scenarios_by_checkpoint.jsonl``)
      — the `qtopt_envs_scenarios.jsonl` row format plus step
      provenance, so per-checkpoint robustness trajectories land in
      the same artifact family as the end-of-training protocol run.

  The sweep is seeded: every checkpoint is scored on the SAME
  scenario set, so the per-bucket trajectory measures the policy, not
  scenario-sampling noise. `train_anakin` hands hooks the device-0
  critic TrainState; `build_policy` accepts it directly.
  """

  def __init__(self,
               learner=None,
               env=None,
               num_scenarios: int = 256,
               seed: int = 0,
               cem_population: Optional[int] = None,
               cem_iterations: Optional[int] = None,
               tag: str = "scenario_eval",
               every_n_checkpoints: int = 1,
               artifacts_path: Optional[str] = None):
    self._learner = learner
    self._env = env
    self._num_scenarios = int(num_scenarios)
    self._seed = int(seed)
    self._cem_population = cem_population
    self._cem_iterations = cem_iterations
    self._tag = tag
    self._every = max(1, every_n_checkpoints)
    self._artifacts_path = artifacts_path
    self._checkpoints_seen = 0

  def begin(self, model, model_dir: str) -> None:
    self._checkpoints_seen = 0

  def after_checkpoint(self, step: int, state: Any,
                       model_dir: str) -> None:
    self._checkpoints_seen += 1
    if (self._checkpoints_seen - 1) % self._every:
      return
    import json
    import os

    from tensor2robot_tpu.envs import evaluate_scenarios

    sweep = evaluate_scenarios(
        self._learner, state, env=self._env,
        num_scenarios=self._num_scenarios, seed=self._seed,
        cem_population=self._cem_population,
        cem_iterations=self._cem_iterations)
    metrics = {
        "success_rate": sweep["success_rate"],
        "random_baseline_success_rate":
            sweep["random_baseline_success_rate"],
        "num_scenarios": sweep["num_scenarios"],
    }
    for bucket, stats in sorted(sweep["per_bucket"].items()):
      if stats["success_rate"] is not None:
        metrics[f"bucket_{bucket}_success_rate"] = \
            stats["success_rate"]
    _write_metrics(model_dir, self._tag, step, metrics)

    path = self._artifacts_path or os.path.join(
        model_dir, "success_protocol", "scenarios_by_checkpoint.jsonl")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    record = {
        "phase": "checkpoint_sweep",
        "step": int(step),
        "scenario_family": type(self._env).__name__
                           if self._env is not None else "procgen",
        **{k: sweep[k] for k in (
            "success_rate", "random_baseline_success_rate",
            "num_scenarios", "per_bucket", "action_digest",
            "scenario_digest")},
    }
    with open(path, "a") as f:
      f.write(json.dumps(record) + "\n")


@gin.configurable
class QTOptSuccessEvalHook(Hook):
  """CEM-policy grasp success per checkpoint (QT-Opt loop).

  `train_qtopt` hands hooks the critic TrainState; the CEM policy
  reads exactly that (the target net never acts), so the hook passes
  the state straight to `evaluate_grasp_policy` — `build_policy`
  accepts a bare TrainState.
  """

  def __init__(self,
               learner=None,
               eval_kwargs: Optional[Dict[str, Any]] = None,
               tag: str = "success_eval",
               every_n_checkpoints: int = 1):
    self._learner = learner
    self._eval_kwargs = dict(eval_kwargs or {})
    self._tag = tag
    self._every = max(1, every_n_checkpoints)
    self._checkpoints_seen = 0

  def begin(self, model, model_dir: str) -> None:
    self._checkpoints_seen = 0

  def after_checkpoint(self, step: int, state: Any,
                       model_dir: str) -> None:
    self._checkpoints_seen += 1
    if (self._checkpoints_seen - 1) % self._every:
      return
    from tensor2robot_tpu.research.qtopt.grasping_env import (
        evaluate_grasp_policy,
    )

    # build_policy accepts the critic TrainState directly — no need
    # to fabricate a QTOptState with dummy target params.
    metrics = evaluate_grasp_policy(self._learner, state,
                                    **self._eval_kwargs)
    _write_metrics(model_dir, self._tag, step, metrics)
