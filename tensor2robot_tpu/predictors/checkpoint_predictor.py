"""Checkpoint-backed predictor: model class + orbax params.

Reference parity: tensor2robot `predictors/checkpoint_predictor.py` —
restore from the trainer's raw checkpoints given the model class,
polling `model_dir` for new steps (SURVEY.md §3, §4.4; file:line
unavailable — empty reference mount).

TPU-native: `predict_step` is jitted once; checkpoint refreshes swap the
param pytree without recompiling (same treedef/shapes). Runs on
whatever the local jax backend is — TPU chip on the robot's host, or
CPU.

Serving mode (`max_batch` set): the jitted-per-call path is replaced by
the `serving` engine — per-bucket AOT-compiled programs warmed at
construction, donated request buffers, a pinned device-resident params
tree that `restore()` hot-swaps lock-free, and a micro-batcher so
concurrent `predict()` callers coalesce into shared dispatches (see
docs/SERVING.md).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.predictors.abstract_predictor import (
    AbstractPredictor,
)
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.utils import checkpoints as ckpt_lib


@gin.configurable
class CheckpointPredictor(AbstractPredictor):
  """Serves a model directly from its training checkpoints."""

  def __init__(self, model, checkpoint_dir: Optional[str] = None,
               init_batch_size: int = 1,
               max_batch: Optional[int] = None,
               max_wait_us: int = 200,
               warmup: bool = True,
               overlap_startup: bool = True):
    """`max_batch=None` keeps the classic one-jit path. Setting it
    turns on the serving engine: powers-of-two buckets up to
    `max_batch` are AOT-compiled (at construction when `warmup`, else
    on first use), and `predict()` goes through a micro-batcher with a
    `max_wait_us` coalescing deadline — thread-safe, so one predictor
    serves many control loops.

    `overlap_startup` (with `warmup`): bucket compile-ahead runs on a
    background thread from construction so the caller's `restore()` —
    checkpoint disk I/O — overlaps it instead of queueing behind it;
    `restore()` and `warmup_seconds` both join the warmup, so after
    either the hot path is fully compiled. False keeps the serial
    compile-then-restore reference behavior."""
    from tensor2robot_tpu.startup import compile_cache
    compile_cache.configure_compilation_cache()
    self._model = model
    self._checkpoint_dir = checkpoint_dir
    # Inference-only state: no optimizer moments on the robot.
    self._state = model.create_inference_state(
        jax.random.PRNGKey(0), batch_size=init_batch_size)
    self._restored_step = -1
    self._predict = jax.jit(model.predict_step)
    # Immutable for the predictor's lifetime; predict() validates
    # against it every control tick, so compute it once.
    self._feature_spec = specs_lib.flatten_spec_structure(
        model.preprocessor.get_in_feature_specification(Mode.PREDICT))
    self._engine = None
    self._batcher = None
    if max_batch is not None:
      from tensor2robot_tpu.serving import (
          BucketedServingEngine,
          MicroBatcher,
      )
      example = specs_lib.make_random_tensors(
          self._feature_spec, batch_size=1, seed=0)
      self._engine = BucketedServingEngine(
          model.predict_step, self._state, example, max_batch=max_batch)
      if warmup and overlap_startup:
        self._engine.warmup_async()
      elif warmup:
        self._engine.warmup()
      self._batcher = MicroBatcher(self._engine, max_wait_us=max_wait_us)

  @property
  def warmup_seconds(self) -> float:
    """Wall seconds the engine spent compiling buckets (joins an
    in-flight async warmup first)."""
    if self._engine is None:
      return 0.0
    self._engine.wait_warmup()
    return self._engine.warmup_seconds

  @property
  def feature_specification(self) -> TensorSpecStruct:
    return self._feature_spec

  @property
  def label_specification(self):
    return self._model.preprocessor.get_in_label_specification(
        Mode.PREDICT)

  @property
  def model_version(self) -> int:
    return self._restored_step

  def init_randomly(self) -> None:
    self._restored_step = 0

  def restore(self, timeout_secs: Optional[float] = None) -> bool:
    """Loads the newest params; blocks up to `timeout_secs` for one."""
    if self._checkpoint_dir is None:
      raise ValueError("CheckpointPredictor needs a checkpoint_dir.")
    last = self._restored_step if self._restored_step > 0 else None
    step = ckpt_lib.wait_for_new_checkpoint(
        self._checkpoint_dir, last_step=last, timeout_secs=timeout_secs,
        subdir="params")
    if step is None:
      if self._engine is not None:
        self._engine.wait_warmup()
      return self._restored_step >= 0
    # Restore params AND batch-norm stats: serving with fresh-init
    # moving averages silently degrades BN models.
    variables = ckpt_lib.restore_variables(
        self._checkpoint_dir,
        like={"params": self._state.params,
              "batch_stats": self._state.batch_stats},
        step=step)
    self._state = self._state.replace(
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}))
    self._restored_step = step
    if self._engine is not None:
      # Publish to the serving engine only after the FULL restore
      # above succeeded: in-flight dispatches keep the old tree, the
      # next dispatch reads the new one — never a mix.
      self._engine.swap_state(self._state)
      # Join the overlapped compile-ahead: the restore's disk I/O ran
      # concurrently with it, and after restore() the hot path must
      # be fully compiled (the cold-start overlap contract).
      self._engine.wait_warmup()
    return True

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    self.assert_is_loaded()
    packed = self._validate(features)
    arrays = jax.tree_util.tree_map(np.asarray, packed)
    if self._batcher is not None:
      outputs = self._batcher.predict(arrays)
    else:
      outputs = self._predict(self._state, arrays)
    if isinstance(outputs, TensorSpecStruct):
      outputs = outputs.to_flat_dict()
    if not isinstance(outputs, dict):
      outputs = {"output": outputs}
    return {k: np.asarray(jax.device_get(v)) for k, v in outputs.items()}

  @property
  def serving_engine(self):
    """The serving-mode engine (None on the classic path)."""
    return self._engine

  def close(self) -> None:
    if self._batcher is not None:
      self._batcher.close()
