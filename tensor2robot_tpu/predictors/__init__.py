"""Predictors: on-robot inference (reference: tensor2robot predictors/)."""

from tensor2robot_tpu.predictors.abstract_predictor import (
    AbstractPredictor,
)
from tensor2robot_tpu.predictors.checkpoint_predictor import (
    CheckpointPredictor,
)
from tensor2robot_tpu.predictors.saved_model_predictor import (
    SavedModelPredictor,
)
