"""SavedModel-backed predictor: specs rebuilt from exported t2r assets.

Reference parity: tensor2robot `predictors/
exported_savedmodel_predictor.py` — load the newest SavedModel from an
export dir (polling with timeout), rebuild ExtendedTensorSpecs from the
t2r assets shipped inside it, and serve `predict` (SURVEY.md §3, §4.4;
file:line unavailable — empty reference mount).

This is the robot-fleet handoff consumer: it needs NO model class, only
the export directory the trainer's async-export hook publishes into.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.export.abstract_export_generator import (
    latest_export_dir,
    sanitize_signature_key,
)
from tensor2robot_tpu.predictors.abstract_predictor import (
    AbstractPredictor,
)
from tensor2robot_tpu.specs import TensorSpecStruct


@gin.configurable
class SavedModelPredictor(AbstractPredictor):
  """Serves the newest SavedModel under `export_dir_base`."""

  def __init__(self, export_dir_base: str,
               signature: str = "serving_default"):
    self._export_dir_base = export_dir_base
    self._signature = signature
    self._loaded = None
    self._serving_fn = None
    self._feature_spec: Optional[TensorSpecStruct] = None
    self._label_spec: Optional[TensorSpecStruct] = None
    self._serving_metadata: Optional[dict] = None
    self._version = -1
    self._global_step = -1

  @property
  def feature_specification(self) -> TensorSpecStruct:
    self.assert_is_loaded()
    return self._feature_spec

  @property
  def label_specification(self):
    return self._label_spec

  @property
  def model_version(self) -> int:
    return self._version

  @property
  def global_step(self) -> int:
    return self._global_step

  @property
  def serving_metadata(self) -> Optional[dict]:
    """The exporter's recommended serving config (bucket table,
    micro-batch deadline) from the asset payload, when shipped —
    fleet consumers size their engines from this (docs/SERVING.md)."""
    return self._serving_metadata

  def restore(self, timeout_secs: Optional[float] = None,
              poll_interval_secs: float = 1.0) -> bool:
    """Loads an export NEWER than the currently loaded one.

    `timeout_secs=None` blocks until one appears (the
    AbstractPredictor contract, matching CheckpointPredictor /
    wait_for_new_checkpoint). On timeout, returns whether the
    predictor is serviceable (some version already loaded).
    """
    deadline = (time.time() + timeout_secs) if timeout_secs is not None \
        else None
    while True:
      path = latest_export_dir(self._export_dir_base)
      if path is not None:
        version = int(os.path.basename(path))
        if version > self._version:
          self._load(path, version)
          return True
      if deadline is not None and time.time() >= deadline:
        return self._version >= 0
      time.sleep(poll_interval_secs)

  def _load(self, path: str, version: int) -> None:
    import tensorflow as tf  # lazy

    # Read assets and resolve the signature FIRST: a broken export must
    # leave the predictor fully on its previous version, never mixing
    # new serving fn with old specs.
    assets = specs_lib.read_assets(
        os.path.join(path, "assets.extra", specs_lib.ASSET_FILENAME))
    loaded = tf.saved_model.load(path)
    serving_fn = loaded.signatures[self._signature]

    self._serving_fn = serving_fn
    self._loaded = loaded  # keep alive: signatures hold weakrefs
    self._feature_spec = assets["feature_spec"]
    self._label_spec = assets.get("label_spec")
    self._global_step = assets.get("global_step", -1)
    self._serving_metadata = assets.get("extra", {}).get("serving")
    self._version = version

  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    import tensorflow as tf  # lazy

    self.assert_is_loaded()
    if self._signature == "parse_tf_example":
      # The proto signature takes ONE string tensor of serialized
      # tf.Examples; spec validation happens inside the graph's parser.
      value = features.get("examples", features) \
          if isinstance(features, dict) else features
      serialized = tf.convert_to_tensor(
          np.asarray(value, dtype=object), dtype=tf.string)
      outputs = self._serving_fn(examples=serialized)
      return {k: v.numpy() for k, v in outputs.items()}
    packed = self._validate(features)
    flat = packed.to_flat_dict() if isinstance(packed, TensorSpecStruct) \
        else dict(packed)
    # Signature inputs are flat keys; TF rejects '/' in arg names, so
    # exported signatures use the sanitized form (shared wire contract).
    feed = {sanitize_signature_key(k): tf.convert_to_tensor(np.asarray(v))
            for k, v in flat.items()}
    outputs = self._serving_fn(**feed)
    return {k: v.numpy() for k, v in outputs.items()}
