"""Predictor protocol: the on-robot inference API.

Reference parity: tensor2robot `predictors/abstract_predictor.py` —
`AbstractPredictor` with `predict(np_dict) -> np_dict`, `restore()`,
`init_randomly()`, spec properties, and checkpoint polling (SURVEY.md
§3 "Predictors", §4.4; file:line unavailable — empty reference mount).

The control-loop contract is unchanged: a robot process constructs a
predictor, calls `restore()` (blocking until the trainer publishes
something), then calls `predict` with raw numpy features each control
tick; input validation happens against the declared feature spec.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.specs import TensorSpecStruct


class AbstractPredictor(abc.ABC):
  """Loads trained parameters and serves `predict` on the host/robot."""

  @abc.abstractmethod
  def predict(self, features: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Runs inference on a batch of raw (wire-spec) numpy features."""

  @abc.abstractmethod
  def restore(self, timeout_secs: Optional[float] = None) -> bool:
    """Loads the newest available parameters; returns success."""

  def init_randomly(self) -> None:
    """Initializes parameters randomly (testing without a trainer)."""
    raise NotImplementedError(
        f"{type(self).__name__} does not support random init.")

  @property
  @abc.abstractmethod
  def feature_specification(self) -> TensorSpecStruct:
    """The wire feature spec `predict` inputs must conform to."""

  @property
  def label_specification(self) -> Optional[TensorSpecStruct]:
    return None

  @property
  @abc.abstractmethod
  def model_version(self) -> int:
    """Monotonic version (global step or export timestamp); -1 if none."""

  def get_feature_specification(self) -> TensorSpecStruct:
    """Method alias (reference predictors exposed both styles)."""
    return self.feature_specification

  def assert_is_loaded(self) -> None:
    if self.model_version < 0:
      raise ValueError(
          f"{type(self).__name__} has no restored model; call restore() "
          f"or init_randomly() first.")

  def _validate(self, features: Dict[str, np.ndarray],
                batched: bool = True) -> TensorSpecStruct:
    struct = features if isinstance(features, TensorSpecStruct) else \
        TensorSpecStruct.from_flat_dict(dict(features))
    return specs_lib.validate_and_pack(
        self.feature_specification, struct, ignore_batch=batched)

  def close(self) -> None:
    """Releases resources; predictors are also context managers."""

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False
