"""Entry-point binaries (reference: tensor2robot bin/)."""
