"""Main training binary: flags → gin configs → train_eval_model().

Reference parity: tensor2robot `bin/run_t2r_trainer.py` — absl flags
`--gin_configs` / `--gin_bindings` parsed into gin, then
`train_eval_model()` (SURVEY.md §3 "Main binary", §4.1; file:line
unavailable — empty reference mount).

Usage:
  python -m tensor2robot_tpu.bin.run_t2r_trainer \
    --gin_configs path/to/config.gin \
    --gin_bindings "train_eval_model.model_dir='/tmp/run'"
"""

from __future__ import annotations

import importlib
import os

from absl import app
from absl import flags

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import train_eval

FLAGS = flags.FLAGS

flags.DEFINE_multi_string(
    "gin_configs", [], "Paths to gin config files, comma-ok.")
flags.DEFINE_multi_string(
    "gin_bindings", [], "Individual gin binding strings.")
flags.DEFINE_multi_string(
    "import_modules", [],
    "Extra modules to import before parsing (to register configurables).")
flags.DEFINE_bool(
    "validate_only", False,
    "Statically validate --gin_configs (t2rcheck gin rules: unknown "
    "configurables/params, dangling macros/refs, bad includes) and "
    "exit non-zero on findings instead of training.")
flags.DEFINE_string(
    "jax_coordinator_address", None,
    "host:port of process 0 for multi-host training "
    "(jax.distributed.initialize). On TPU pods leave unset — workers "
    "auto-discover; --jax_init_distributed still opts in.")
flags.DEFINE_integer("jax_num_processes", None,
                     "Total process count for multi-host training.")
flags.DEFINE_integer("jax_process_id", None,
                     "This process's index for multi-host training.")
flags.DEFINE_bool(
    "jax_init_distributed", False,
    "Force jax.distributed.initialize() even without an explicit "
    "coordinator (TPU pod auto-discovery).")
flags.DEFINE_integer(
    "prometheus_port", None,
    "Start the telemetry/prometheus.py scrape endpoint on this port "
    "in THIS process before training (0 = ephemeral; the bound port "
    "is printed). Unset, the gin-backed default applies "
    "(`default_port.port` in telemetry.prometheus) — so scraping no "
    "longer requires bench-side wiring (docs/OBSERVABILITY.md).")
flags.DEFINE_enum(
    "trainer", "train_eval", ["train_eval", "qtopt", "fleet",
                              "anakin"],
    "Entry to run after gin parsing: the supervised "
    "train_eval_model() loop (default), the QT-Opt learner loop "
    "(train_qtopt — configs binding train_qtopt.*, e.g. "
    "research/qtopt/configs/qtopt_int8.gin), the multi-process "
    "learner/actor fleet (run_fleet — configs binding run_fleet.* / "
    "FleetConfig.*, e.g. research/qtopt/configs/qtopt_fleet.gin; "
    "docs/FLEET.md), or the fully-on-device Anakin online mode "
    "(train_anakin — configs binding train_anakin.*, e.g. "
    "research/qtopt/configs/qtopt_anakin.gin; docs/ENVS.md).")

# Configurable registration happens at import; pull in every in-tree
# family so configs can reference them without import lines.
_DEFAULT_MODULES = (
    "tensor2robot_tpu.models",
    "tensor2robot_tpu.data",
    "tensor2robot_tpu.preprocessors",
    "tensor2robot_tpu.export",
    "tensor2robot_tpu.predictors",
    "tensor2robot_tpu.hooks",
    "tensor2robot_tpu.meta_learning",
    "tensor2robot_tpu.fleet",
    "tensor2robot_tpu.envs",
    "tensor2robot_tpu.serving",
    "tensor2robot_tpu.research.grasp2vec",
    "tensor2robot_tpu.research.pose_env",
    "tensor2robot_tpu.research.qtopt",
    "tensor2robot_tpu.research.vrgripper",
)


def main(argv):
  del argv
  configs = [c for entry in FLAGS.gin_configs for c in entry.split(",")]
  if FLAGS.validate_only:
    # Fleet pre-flight: catch a typo'd binding in seconds instead of
    # minutes into a training run (docs/ANALYSIS.md). Runs BEFORE the
    # multi-host wiring — validation needs registrations, not devices,
    # and a lone pre-flight process must never block inside
    # jax.distributed.initialize waiting for peers that aren't there.
    import sys

    # Distributed pre-flight (ISSUE 20) runs FIRST and pure-AST —
    # before the configurable families (and therefore jax) load, and
    # long before any jax.distributed init: a typo'd rpc method or a
    # chief-gated collective fails here in a second instead of as a
    # wedged barrier minutes into a fleet spawn.
    from tensor2robot_tpu.analysis import cli as t2rcheck_cli

    dist_rc = t2rcheck_cli.main(["--checks", "fleet,spmd", "--quiet"])

    from tensor2robot_tpu.analysis import gin_check

    _import_configurable_families()
    findings = []
    for config in configs:
      resolved = gin.resolve_config_path(config) or config
      findings.extend(gin_check.validate_config_file(
          resolved, os.getcwd()))
    for finding in findings:
      print(finding.render())
    print(f"validate_only: {len(findings)} finding(s) in "
          f"{len(configs)} config(s)")
    sys.exit(1 if (findings or dist_rc) else 0)
  # Multi-host wiring comes first: jax.distributed must initialize
  # before any device use (SURVEY §3 "multi-slice via jax distributed
  # init"). Single-process runs no-op.
  from tensor2robot_tpu.parallel import maybe_initialize_distributed
  maybe_initialize_distributed(
      coordinator_address=FLAGS.jax_coordinator_address,
      num_processes=FLAGS.jax_num_processes,
      process_id=FLAGS.jax_process_id,
      force=FLAGS.jax_init_distributed,
  )
  _import_configurable_families()
  gin.parse_config_files_and_bindings(configs, FLAGS.gin_bindings)
  # Prometheus scrape endpoint (ISSUE 15): flag wins, else the
  # gin-backed default (telemetry.prometheus.default_port). Started
  # here so EVERY trainer entry — and the fleet orchestrator — serves
  # /metrics off its live registry with no bench-side wiring.
  from tensor2robot_tpu.telemetry import prometheus as prometheus_lib
  prometheus_port = FLAGS.prometheus_port
  if prometheus_port is None:
    prometheus_port = prometheus_lib.default_port()
  if prometheus_port is not None and prometheus_port >= 0:
    endpoint = prometheus_lib.serve(port=prometheus_port)
    print(f"prometheus: serving /metrics on port {endpoint.port}")
  if FLAGS.trainer == "qtopt":
    from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt
    train_qtopt()
  elif FLAGS.trainer == "fleet":
    # The orchestrator re-runs these configs through --validate_only
    # as its pre-spawn launch gate (docs/FLEET.md).
    from tensor2robot_tpu.fleet import run_fleet
    run_fleet(gin_configs=configs)
  elif FLAGS.trainer == "anakin":
    from tensor2robot_tpu.envs import train_anakin
    train_anakin()
  else:
    train_eval.train_eval_model()


def _import_configurable_families() -> None:
  for module in list(_DEFAULT_MODULES) + list(FLAGS.import_modules):
    try:
      importlib.import_module(module)
    except ImportError as e:
      if module in FLAGS.import_modules:
        raise
      # In-tree families are best-effort (optional deps may be absent).
      print(f"Note: skipping {module}: {e}")


if __name__ == "__main__":
  app.run(main)
