"""Closed-loop control plane: the policy layer over telemetry and
actuators (ISSUE 18, docs/CONTROL.md).

The fleet could already DETECT (the alert sentinel, ISSUE 15) and
ACT (elastic scaling, kill-and-respawn, admission retuning — ISSUEs
13/14/17) — this package closes the loop between them:

  * `rules` — the `ControlRule` grammar: condition over metric
    windows → action, with hysteresis bands, per-rule cooldowns, and
    sustained-breach semantics;
  * `controller` — the `Controller` loop: ordered rule evaluation
    over the orchestrator's aggregated scalar view, a global
    rate-based actuation budget, dry-run mode, and full decision
    observability (envelope records, `control.*` counters,
    flight-record integration);
  * `actuators` — the lever catalog over already-shipped seams
    (`Fleet.scale_to`, front scale/respawn, admission retune, the
    degradation ladder, page-as-fallback);
  * `policies` — the standing gin-tunable fleet rule table
    (`qtopt_fleet_autopilot.gin` binds it).

The whole package is jax-free BY CONTRACT (IMP401 worker-safe set;
subprocess-pinned by tests/test_control.py): a policy plane that
drags an XLA runtime into the supervising process would cost more
than the regressions it remediates.
"""

from tensor2robot_tpu.control import actuators
from tensor2robot_tpu.control import controller
from tensor2robot_tpu.control import policies
from tensor2robot_tpu.control import rules
from tensor2robot_tpu.control.actuators import (
    ActuationError,
    Actuator,
    DegradationLadder,
    fleet_actuators,
)
from tensor2robot_tpu.control.controller import (
    DECISIONS_FILENAME,
    OUTCOMES,
    Controller,
    read_decisions,
)
from tensor2robot_tpu.control.policies import fleet_rules
from tensor2robot_tpu.control.rules import ControlRule, RuleState

__all__ = [
    "ActuationError",
    "Actuator",
    "ControlRule",
    "Controller",
    "DECISIONS_FILENAME",
    "DegradationLadder",
    "OUTCOMES",
    "RuleState",
    "actuators",
    "controller",
    "fleet_actuators",
    "fleet_rules",
    "policies",
    "read_decisions",
    "rules",
]
