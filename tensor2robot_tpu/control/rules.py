"""ControlRule: the policy plane's condition→action grammar (ISSUE 18).

A rule watches ONE metric of the orchestrator's aggregated scalar
view (the exact payload `fleet_metrics.jsonl` records) and names the
actuator to drive when its condition holds. The grammar extends the
sentinel's (telemetry/sentinel.py) with the three properties a loop
that ACTS — instead of merely alerting — cannot live without:

  * WINDOWS — the condition is evaluated over the rolling mean of the
    last `window` observations, so one noisy poll cannot actuate;
  * HYSTERESIS — after a rule fires it DISARMS until the windowed
    value crosses back over the `clear` bound (defaults to the
    threshold itself; set a band, e.g. fire above 150 ms / re-arm
    below 120 ms, to keep a signal hovering at the threshold from
    flapping the actuator);
  * COOLDOWNS — `cooldown_secs` is the minimum spacing between two
    actuations of the SAME rule, even across re-arms, so an actuator
    whose effect takes time to land (a scale-up warming a replica)
    is never stacked.

Condition kinds:

  kind        fires while
  ----------  ----------------------------------------------------
  above       windowed value > threshold
  below       windowed value < threshold
  ewma_drop   windowed value < ewma · (1 − threshold)
  ewma_spike  windowed value > ewma · (1 + threshold)
  rate_above  per-second delta of a counter > threshold
  rate_below  per-second delta of a counter < threshold

Like the sentinel, the EWMA baseline absorbs only NON-breaching
values (a sustained drop cannot normalize itself away) and `warmup`
evaluations can never fire. `sustain` consecutive breaching
evaluations are required before the rule triggers.

In the aggregated view metrics arrive role-prefixed
(``front0/serving.policy.request_ms_p95``). `aggregate` chooses how
the matching keys combine: ``mean``/``max``/``min``/``sum`` fold them
into one fleet-wide value, while ``each`` evaluates every key
separately with per-key state — the slow-host shape, where the
decision carries the offending ROLE so a targeted actuator
(kill-and-respawn) knows whom to kick.

jax-free (IMP401 worker-safe set) like the rest of the package.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

from tensor2robot_tpu import config as gin

KINDS = ("above", "below", "ewma_drop", "ewma_spike",
         "rate_above", "rate_below")
AGGREGATES = ("mean", "max", "min", "sum", "each")


@gin.configurable
@dataclasses.dataclass(frozen=True)
class ControlRule:
  """One ordered condition→action rule (see the module docstring)."""

  name: str = gin.REQUIRED
  metric: str = gin.REQUIRED    # flat scalar key (histograms: _p50/_p95)
  action: str = gin.REQUIRED    # actuator name (controller validates)
  kind: str = "above"
  threshold: float = 0.0
  # Hysteresis re-arm bound; None = the threshold (re-arm as soon as
  # the condition stops holding). Must sit on the HEALTHY side of the
  # threshold; ignored by the ewma/rate kinds (they re-arm on any
  # non-breaching evaluation, like the sentinel).
  clear: Optional[float] = None
  window: int = 1               # rolling-mean width (observations)
  warmup: int = 0               # evaluations before the rule can fire
  sustain: int = 1              # consecutive breaches required
  alpha: float = 0.2            # EWMA smoothing factor
  cooldown_secs: float = 60.0   # min spacing between actuations
  aggregate: str = "mean"       # fold role-prefixed twins, or "each"
  # Opaque kwargs handed to the actuator (e.g. {"delta": 1, "max": 8}).
  action_params: Dict[str, Any] = dataclasses.field(default_factory=dict)
  # Sentinel alert name this rule REMEDIATES: when the sentinel is
  # about to page for `alert`, the controller tries this rule first
  # and a successful actuation demotes the page to the act tier
  # (docs/CONTROL.md "Escalation"). "" = not an alert remediation.
  alert: str = ""

  def __post_init__(self):
    if self.kind not in KINDS:
      raise ValueError(f"rule {self.name!r}: kind must be one of "
                       f"{KINDS}, got {self.kind!r}")
    if self.aggregate not in AGGREGATES:
      raise ValueError(f"rule {self.name!r}: aggregate must be one of "
                       f"{AGGREGATES}, got {self.aggregate!r}")
    if self.window < 1 or self.sustain < 1:
      raise ValueError(
          f"rule {self.name!r}: window and sustain must be >= 1")
    if self.warmup < 0 or self.cooldown_secs < 0:
      raise ValueError(
          f"rule {self.name!r}: warmup and cooldown_secs must be >= 0")
    if not 0.0 < self.alpha <= 1.0:
      raise ValueError(f"rule {self.name!r}: alpha must be in (0, 1]")
    if self.clear is not None:
      if self.kind == "above" and self.clear > self.threshold:
        raise ValueError(
            f"rule {self.name!r}: clear ({self.clear}) must be <= "
            f"threshold ({self.threshold}) for kind='above'")
      if self.kind == "below" and self.clear < self.threshold:
        raise ValueError(
            f"rule {self.name!r}: clear ({self.clear}) must be >= "
            f"threshold ({self.threshold}) for kind='below'")


class RuleState:
  """Per-(rule, metric-key) evaluation state."""

  __slots__ = ("values", "ewma", "last", "last_t", "seen", "streak",
               "armed", "last_fired")

  def __init__(self, window: int):
    self.values = collections.deque(maxlen=window)
    self.ewma: Optional[float] = None
    self.last: Optional[float] = None     # rate kinds: previous value
    self.last_t: Optional[float] = None   # ...and its monotonic stamp
    self.seen = 0
    self.streak = 0
    self.armed = True
    self.last_fired = float("-inf")       # monotonic actuation stamp


def resolve_metric(metric: str, aggregate: str,
                   scalars: Dict[str, float]) -> List[Tuple[str, float]]:
  """The (key, value) targets one rule evaluates this pass.

  Matches the bare metric plus every role-prefixed twin (the
  sentinel's matching rule); `aggregate="each"` returns every match,
  anything else folds them into one value keyed by the bare metric.
  Empty when the metric is absent (a rule over a not-yet-published
  metric simply does not evaluate).
  """
  suffix = "/" + metric
  found: List[Tuple[str, float]] = []
  for key in scalars:
    if key == metric or key.endswith(suffix):
      try:
        found.append((key, float(scalars[key])))
      except (TypeError, ValueError):
        continue
  if not found:
    return []
  found.sort()
  if aggregate == "each":
    return found
  values = [v for _, v in found]
  if aggregate == "max":
    folded = max(values)
  elif aggregate == "min":
    folded = min(values)
  elif aggregate == "sum":
    folded = sum(values)
  else:
    folded = sum(values) / len(values)
  return [(metric, folded)]


def evaluate(rule: ControlRule, state: RuleState, observed: float,
             now: Optional[float] = None) -> Dict[str, Any]:
  """One observation through one rule's window/hysteresis machinery.

  Returns ``{"triggered", "value", "baseline", "breached"}`` —
  `value` is the windowed mean actually compared, `baseline` the EWMA
  or rate denominator where applicable. Cooldown is NOT applied here
  (the controller owns the actuation clock); `triggered` means the
  condition held, sustained, while armed — and the rule has now
  DISARMED itself until the clear bound is crossed.
  """
  if now is None:
    now = time.monotonic()
  state.values.append(float(observed))
  value = sum(state.values) / len(state.values)
  warming = state.seen < rule.warmup
  baseline: Optional[float] = None
  breached = False
  if rule.kind == "above":
    breached = value > rule.threshold
  elif rule.kind == "below":
    breached = value < rule.threshold
  elif rule.kind in ("rate_above", "rate_below"):
    if state.last is not None and state.last_t is not None:
      span = max(now - state.last_t, 1e-9)
      rate = (value - state.last) / span
      baseline = rate
      breached = (rate > rule.threshold if rule.kind == "rate_above"
                  else rate < rule.threshold)
    state.last = value
    state.last_t = now
  else:  # ewma_drop / ewma_spike
    baseline = state.ewma
    if state.ewma is not None:
      if rule.kind == "ewma_drop":
        breached = value < state.ewma * (1.0 - rule.threshold)
      else:
        breached = value > state.ewma * (1.0 + rule.threshold)
    if state.ewma is None:
      state.ewma = value
    elif warming or not breached:
      # The baseline only absorbs healthy values: a sustained breach
      # cannot drag its own baseline along and silence itself.
      state.ewma += rule.alpha * (value - state.ewma)
  state.seen += 1
  if warming:
    return {"triggered": False, "value": value, "baseline": baseline,
            "breached": False}
  if not state.armed:
    # Disarmed (the rule fired): re-arm only once the windowed value
    # crosses the clear bound on the healthy side. The ewma/rate
    # kinds re-arm on any non-breaching evaluation — their baseline
    # moves, so a fixed clear bound has no stable meaning.
    clear = rule.threshold if rule.clear is None else rule.clear
    if rule.kind == "above":
      rearmed = value <= clear
    elif rule.kind == "below":
      rearmed = value >= clear
    else:
      rearmed = not breached
    if rearmed:
      state.armed = True
      state.streak = 0
    return {"triggered": False, "value": value, "baseline": baseline,
            "breached": breached}
  if not breached:
    state.streak = 0
    return {"triggered": False, "value": value, "baseline": baseline,
            "breached": False}
  state.streak += 1
  if state.streak < rule.sustain:
    return {"triggered": False, "value": value, "baseline": baseline,
            "breached": True}
  state.armed = False  # hysteresis: hold until the clear bound
  state.streak = 0
  return {"triggered": True, "value": value, "baseline": baseline,
          "breached": True}
