"""The standing fleet rule table (gin-tunable) — ISSUE 18.

`fleet_rules()` is the autopilot's default policy, every rule a
composition of shipped seams (ROADMAP "Self-driving fleet"):

  * a sustained `slow_host`-shaped MFU drop that ISOLATES to one role
    (aggregate="each") is a targeted kill-and-respawn, not a page —
    and the same rule is bound to the sentinel's `mfu_drop` alert, so
    an alert-tier breach remediates instead of paging;
  * serving p95 / queue-depth pressure scales FRONT replicas (the
    router re-places tenants over the grown set);
  * the replay commit rate autoscales ACTORS toward a configured
    env-steps/s band (0 = off: there is no universal target — set it
    per deployment, like the sentinel's RSS budget);
  * sustained deep SLO breach retunes the tenant's admission token
    rate DOWN (shed at the door beats queueing past the deadline),
    and past that the degradation ladder sheds whole tenants,
    lowest priority first — paging is what happens only when every
    lever above is exhausted (the controller's budget fallback).

Thresholds, tenants, and bands are gin-bindable per deployment
(`qtopt_fleet_autopilot.gin` is the shipped example). Rule ORDER is
actuation priority under the global budget: cheap/reversible levers
first, degradation last.

jax-free (IMP401 worker-safe set) like the rest of the package.
"""

from __future__ import annotations

from typing import List, Tuple

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.control.rules import ControlRule


@gin.configurable
def fleet_rules(
    tenant: str = "policy",
    slo_ms: float = 100.0,
    queue_depth_max: float = 64.0,
    max_fronts: int = 4,
    min_fronts: int = 1,
    max_actors: int = 8,
    min_actors: int = 1,
    env_steps_per_sec_min: float = 0.0,
    env_steps_per_sec_max: float = 0.0,
    mfu_drop_fraction: float = 0.35,
    retune_factor: float = 0.8,
    cooldown_secs: float = 60.0,
    offered_load_slope_max: float = 0.0,
) -> List[ControlRule]:
  """The ordered autopilot table over the aggregated fleet view.

  Latency rules key on the e2e `request_ms` histogram's p95 scalar
  (`serving.<tenant>.request_ms_p95` — queueing included, the latency
  a caller experiences); `aggregate="max"` holds the WORST front
  replica to the SLO, not the average.
  """
  p95 = f"serving.{tenant}.request_ms_p95"
  rules = [
      # A sustained per-role MFU drop isolates a slow host: kick that
      # role and let supervision respawn it under the restart budget.
      # Doubles as the remediation for the sentinel's `mfu_drop`
      # page (alert binding — docs/CONTROL.md "Escalation").
      ControlRule(
          name="slow_host_respawn", metric="perf.mfu",
          kind="ewma_drop", threshold=mfu_drop_fraction,
          warmup=4, sustain=3, aggregate="each",
          action="respawn_role", cooldown_secs=3 * cooldown_secs,
          alert="mfu_drop"),
  ]
  if offered_load_slope_max > 0.0:
    # PREDICTIVE pre-scale (ISSUE 19, the ROADMAP control item): the
    # admitted-rows counter's per-second rate IS the offered load the
    # front tier absorbs, so a sustained climb past the slope bound
    # grows the tier BEFORE queueing pushes the p95 over the SLO —
    # the reactive p95/queue rules below remain the backstop. Rows/s
    # across the worst replica; default off (0.0): the right slope is
    # per deployment, like the env-steps band.
    rules.append(ControlRule(
        name="front_offered_prescale",
        metric=f"serving.{tenant}.admission.admitted",
        kind="rate_above", threshold=offered_load_slope_max,
        warmup=1, sustain=2, aggregate="max",
        action="scale_fronts",
        action_params={"delta": 1, "min": min_fronts,
                       "max": max_fronts},
        cooldown_secs=cooldown_secs))
  rules.extend([
      # Goodput pressure: the worst replica's e2e p95 over the SLO
      # grows the front tier; hysteresis re-arms at 80% of the SLO.
      ControlRule(
          name="front_p95_scale_up", metric=p95,
          kind="above", threshold=slo_ms, clear=0.8 * slo_ms,
          window=2, sustain=2, aggregate="max",
          action="scale_fronts",
          action_params={"delta": 1, "min": min_fronts,
                         "max": max_fronts},
          cooldown_secs=cooldown_secs),
      ControlRule(
          name="front_queue_scale_up",
          metric=f"serving.{tenant}.queue_depth",
          kind="above", threshold=queue_depth_max,
          clear=0.5 * queue_depth_max, window=2, sustain=2,
          aggregate="max", action="scale_fronts",
          action_params={"delta": 1, "min": min_fronts,
                         "max": max_fronts},
          cooldown_secs=cooldown_secs),
  ])
  if env_steps_per_sec_min > 0.0:
    # Hold the collection rate: the replay commit counter's
    # per-second rate under the band adds an actor...
    rules.append(ControlRule(
        name="actors_scale_up", metric="replay.adds",
        kind="rate_below", threshold=env_steps_per_sec_min,
        warmup=1, sustain=2, action="scale_actors",
        action_params={"delta": 1, "min": min_actors,
                       "max": max_actors},
        cooldown_secs=cooldown_secs))
  if env_steps_per_sec_max > 0.0:
    # ...and over the band drains one (device-seconds are the gated
    # cost — ROADMAP: goodput per device-second, not peak throughput).
    rules.append(ControlRule(
        name="actors_scale_down", metric="replay.adds",
        kind="rate_above", threshold=env_steps_per_sec_max,
        warmup=1, sustain=3, action="scale_actors",
        action_params={"delta": -1, "min": min_actors,
                       "max": max_actors},
        cooldown_secs=2 * cooldown_secs))
  rules.extend([
      # Deep sustained breach (1.5× SLO): shed at the door — retune
      # the tenant's token rate down so queueing stops amplifying.
      ControlRule(
          name="tenant_slo_retune", metric=p95,
          kind="above", threshold=1.5 * slo_ms, clear=slo_ms,
          window=2, sustain=3, aggregate="max",
          action="retune_admission",
          action_params={"tenant": tenant, "factor": retune_factor},
          cooldown_secs=2 * cooldown_secs),
      # Past 2× SLO the degradation ladder sheds whole tenants,
      # lowest priority first (FleetConfig.control_shed_priorities).
      ControlRule(
          name="overload_shed", metric=p95,
          kind="above", threshold=2.0 * slo_ms, clear=slo_ms,
          window=2, sustain=3, aggregate="max",
          action="shed_tenant", cooldown_secs=2 * cooldown_secs),
      # Recovery: sustained healthy latency restores every shed
      # tenant (long cooldown — restore/shed must not oscillate).
      ControlRule(
          name="recovered_restore", metric=p95,
          kind="below", threshold=0.5 * slo_ms, clear=0.75 * slo_ms,
          window=3, sustain=5, aggregate="max",
          action="restore_tenants", cooldown_secs=5 * cooldown_secs),
  ])
  return rules


@gin.configurable
def degradation_priorities(
    priorities: Tuple[str, ...] = (),
    shed_rate_rps: float = 1.0,
) -> Tuple[Tuple[str, ...], float]:
  """The gin seam for the shed ladder when rules come from gin but
  the ladder is built by a driver (bench legs); the orchestrator
  reads `FleetConfig.control_shed_priorities` instead."""
  return tuple(priorities), float(shed_rate_rps)
