"""The Controller loop: poll → evaluate → actuate → record (ISSUE 18).

One controller owns an ORDERED rule table (`rules.ControlRule`) and a
named actuator set (`actuators.Actuator`). Each `step()` evaluates
every rule over one aggregated scalar view — the same payload the
orchestrator appends to `fleet_metrics.jsonl`, so the controller sees
exactly what the operator's dashboard would — and drives at most a
budgeted number of actuations:

  * PER-RULE cooldown/hysteresis live in the rule state (rules.py);
  * the GLOBAL actuation budget is rate-based, exactly like the
    fleet's restart budget: at most `max_actions` actuations per
    `budget_window_secs` sliding window (0 = lifetime cap) across
    ALL rules — a flapping signal can never thrash the fleet, it can
    only exhaust the budget and fall back to paging;
  * DRY-RUN mode evaluates everything, charges the budget, and
    records `would_act` decisions without touching an actuator — the
    rollout workflow (docs/CONTROL.md): run dry, read the decision
    log, then flip live.

Every decision — actuated or skipped — is recorded three ways:

  * a `control.decision` telemetry event + the `control.*` counters
    (docs/OBSERVABILITY.md catalog);
  * one envelope record appended to ``control_decisions.jsonl``,
    schema-valid under `telemetry.records.validate_record` (numeric
    payload keyed ``control.<rule>.<field>``; outcome codes in
    `OUTCOMES` order);
  * the in-memory `decisions` ring, surfaced via `flight_extra()` so
    a flight record shows what the controller saw and did.

`handle_alert()` is the sentinel's act-tier entry: a page-severity
alert whose rule name matches some rule's `alert` binding is
remediated here (same cooldown/budget discipline), and a successful
actuation DEMOTES the page — flight records stay the terminal tier.

jax-free (IMP401 worker-safe set) like the rest of the package.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from tensor2robot_tpu.control import actuators as actuators_lib
from tensor2robot_tpu.control import rules as rules_lib
from tensor2robot_tpu.telemetry import core as tcore
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import records as trecords

log = logging.getLogger(__name__)

DECISIONS_FILENAME = "control_decisions.jsonl"
# Decision outcomes, in envelope-record code order (payload field
# `control.<rule>.outcome`): the index IS the recorded code.
OUTCOMES = ("actuated", "would_act", "cooldown", "budget", "error")


class Controller:
  """Ordered rule evaluation with a global actuation budget.

  One owner thread by design (the orchestrator's poll loop or a bench
  driver calls `step()`/`handle_alert()`); like the sentinel, no lock
  is held across actuator calls or file I/O (the CON301 contract this
  package is linted with).
  """

  def __init__(self,
               rules: Sequence[rules_lib.ControlRule],
               actuators: Dict[str, actuators_lib.Actuator],
               cadence_secs: float = 0.0,
               dry_run: bool = False,
               max_actions: int = 4,
               budget_window_secs: float = 300.0,
               decisions_path: Optional[str] = None,
               registry: Optional[tmetrics.MetricsRegistry] = None,
               tracer: Optional[tcore.Tracer] = None):
    """Args:
      rules: the ORDERED table — evaluation order is list order, and
        `handle_alert` picks the FIRST rule bound to an alert, so
        rule precedence is deterministic by construction.
      actuators: name → Actuator; every rule's `action` must resolve
        here at construction (a typo'd rule table must fail the
        launch gate, not the first 3am breach).
      cadence_secs: `maybe_step()`'s minimum spacing (0 = every call).
      max_actions / budget_window_secs: the global rate-based
        actuation budget (window 0 = lifetime cap).
    """
    self.rules = list(rules)
    names = [rule.name for rule in self.rules]
    if len(set(names)) != len(names):
      raise ValueError(f"duplicate rule names: {sorted(names)}")
    self.actuators = dict(actuators)
    for rule in self.rules:
      if rule.action not in self.actuators:
        raise ValueError(
            f"rule {rule.name!r} names unknown actuator "
            f"{rule.action!r} (have {sorted(self.actuators)})")
    if max_actions < 1:
      raise ValueError(f"max_actions must be >= 1, got {max_actions}")
    self.dry_run = bool(dry_run)
    self._cadence = float(cadence_secs)
    self._max_actions = int(max_actions)
    self._budget_window = float(budget_window_secs)
    self._action_times: collections.deque = collections.deque()
    self._decisions_path = decisions_path
    self._registry = registry or tmetrics.registry()
    self._tracer = tracer
    self._states: Dict[tuple, rules_lib.RuleState] = {}
    self._file: Optional[Any] = None
    self._t_last_step = float("-inf")
    self._steps = 0
    self.decisions: collections.deque = collections.deque(maxlen=1024)
    self._tm = {
        "decisions": self._registry.counter("control.decisions"),
        "actuated": self._registry.counter("control.actuated"),
        "would_act": self._registry.counter("control.would_act"),
        "cooldown": self._registry.counter("control.skipped.cooldown"),
        "budget": self._registry.counter("control.skipped.budget"),
        "error": self._registry.counter("control.errors"),
        "alert_handled": self._registry.counter(
            "control.alert_handled"),
        "alert_unhandled": self._registry.counter(
            "control.alert_unhandled"),
    }
    self._n = {key: 0 for key in self._tm}

  # ---- the global actuation budget ----

  def budget_remaining(self, now: Optional[float] = None) -> int:
    if now is None:
      now = time.monotonic()
    if self._budget_window:
      while (self._action_times
             and now - self._action_times[0] > self._budget_window):
        self._action_times.popleft()
    return max(0, self._max_actions - len(self._action_times))

  def _charge_budget(self, now: float) -> None:
    self._action_times.append(now)

  # ---- evaluation ----

  def _state_for(self, rule: rules_lib.ControlRule,
                 key: str) -> rules_lib.RuleState:
    state = self._states.get((rule.name, key))
    if state is None:
      state = self._states[(rule.name, key)] = rules_lib.RuleState(
          rule.window)
    return state

  def maybe_step(self, scalars: Dict[str, float],
                 step: Optional[int] = None) -> List[Dict[str, Any]]:
    """`step()` behind the cadence gate — callers on a faster clock
    (the orchestrator's 0.05s supervision poll) call this freely."""
    now = time.monotonic()
    if now - self._t_last_step < self._cadence:
      return []
    return self.step(scalars, step=step, now=now)

  def step(self, scalars: Dict[str, float],
           step: Optional[int] = None,
           now: Optional[float] = None) -> List[Dict[str, Any]]:
    """One evaluation pass over one aggregated scalar view; returns
    the decisions recorded this pass (triggered rules only — a rule
    whose condition holds but which is cooling down or over budget
    still records, with the skip outcome)."""
    if now is None:
      now = time.monotonic()
    self._t_last_step = now
    self._steps += 1
    decisions: List[Dict[str, Any]] = []
    for rule in self.rules:
      targets = rules_lib.resolve_metric(rule.metric, rule.aggregate,
                                         scalars)
      for key, observed in targets:
        state = self._state_for(rule, key)
        result = rules_lib.evaluate(rule, state, observed, now=now)
        if not result["triggered"]:
          continue
        role = (key.rsplit("/", 1)[0] if "/" in key else "fleet")
        decision = {
            "rule": rule.name, "action": rule.action, "metric": key,
            "role": role, "kind": rule.kind,
            "value": result["value"], "baseline": result["baseline"],
            "threshold": rule.threshold, "trigger": "rule",
            "wall": time.time(),
        }
        if step is not None:
          decision["step"] = int(step)
        self._decide(rule, decision, state, now)
        decisions.append(decision)
    return decisions

  def handle_alert(self, alert: Dict[str, Any]) -> bool:
    """The sentinel's act tier: remediate a paging alert through the
    FIRST rule bound to it (`ControlRule.alert`). True only when a
    remediation actually actuated — a cooldown/budget skip, an
    actuator error, or dry-run mode returns False so the page
    proceeds (paging is the fallback, and a dry controller must
    neither act nor silence pages)."""
    name = str(alert.get("rule", ""))
    rule = next((r for r in self.rules if r.alert and r.alert == name),
                None)
    if rule is None:
      return False
    now = time.monotonic()
    state = self._state_for(rule, "@alert")
    decision = {
        "rule": rule.name, "action": rule.action,
        "metric": str(alert.get("metric", "")),
        "role": str(alert.get("role", "fleet")) or "fleet",
        "kind": rule.kind,
        "value": float(alert.get("value", 0.0)),
        "baseline": alert.get("baseline"),
        "threshold": rule.threshold, "trigger": f"alert.{name}",
        "wall": time.time(),
    }
    if alert.get("step") is not None:
      decision["step"] = int(alert["step"])
    self._decide(rule, decision, state, now)
    handled = decision["outcome"] == "actuated"
    tally = "alert_handled" if handled else "alert_unhandled"
    self._tm[tally].inc()
    self._n[tally] += 1
    return handled

  # ---- the decision path ----

  def _decide(self, rule: rules_lib.ControlRule,
              decision: Dict[str, Any], state: rules_lib.RuleState,
              now: float) -> None:
    """Cooldown → budget → (dry-run | actuate); records the decision
    whatever the outcome."""
    if now - state.last_fired < rule.cooldown_secs:
      decision["outcome"] = "cooldown"
      decision["cooldown_remaining_secs"] = round(
          rule.cooldown_secs - (now - state.last_fired), 3)
    elif self.budget_remaining(now) <= 0:
      decision["outcome"] = "budget"
    elif self.dry_run:
      # Dry-run charges cooldown AND budget so the would-act log is
      # exactly the live actuation schedule, just without the acting.
      state.last_fired = now
      self._charge_budget(now)
      decision["outcome"] = "would_act"
    else:
      state.last_fired = now
      self._charge_budget(now)
      try:
        detail = self.actuators[rule.action].apply(
            rule.action_params, decision)
      except Exception as e:  # noqa: BLE001 — a broken lever must
        # not take down the loop that would pull the next one.
        decision["outcome"] = "error"
        decision["error"] = repr(e)
        log.warning("control actuator %r failed for rule %r",
                    rule.action, rule.name, exc_info=True)
      else:
        decision["outcome"] = "actuated"
        decision["detail"] = detail
    decision["dry_run"] = self.dry_run
    decision["budget_remaining"] = self.budget_remaining(now)
    self._record(decision)

  def _record(self, decision: Dict[str, Any]) -> None:
    outcome = decision["outcome"]
    self._tm["decisions"].inc()
    self._n["decisions"] += 1
    self._tm[outcome].inc()
    self._n[outcome] += 1
    self._registry.counter(f"control.rule.{decision['rule']}").inc()
    self.decisions.append(decision)
    (self._tracer.event if self._tracer is not None else tcore.event)(
        "control.decision", rule=decision["rule"],
        action=decision["action"], outcome=outcome,
        role=decision["role"], value=round(decision["value"], 6))
    log.log(
        logging.INFO if outcome in ("cooldown", "budget")
        else logging.WARNING,
        "control decision %s: rule=%s action=%s role=%s value=%.6g",
        outcome, decision["rule"], decision["action"],
        decision["role"], decision["value"])
    self._append(self.decision_record(decision))

  @staticmethod
  def decision_record(decision: Dict[str, Any]) -> Dict[str, Any]:
    """One decision as a telemetry ENVELOPE record ({step, wall,
    role, payload}) — numeric payload keyed `control.<rule>.<field>`,
    valid under `telemetry.records.validate_record`, so the decision
    log reads with the same tooling as every other metrics file."""
    rule = decision["rule"]
    payload: Dict[str, float] = {
        f"control.{rule}.value": float(decision["value"]),
        f"control.{rule}.threshold": float(decision["threshold"]),
        f"control.{rule}.outcome": float(
            OUTCOMES.index(decision["outcome"])),
        f"control.{rule}.actuated": float(
            decision["outcome"] == "actuated"),
        f"control.{rule}.dry_run": float(decision["dry_run"]),
        f"control.{rule}.budget_remaining": float(
            decision["budget_remaining"]),
    }
    if decision.get("baseline") is not None:
      payload[f"control.{rule}.baseline"] = float(decision["baseline"])
    return trecords.make_record(
        int(decision.get("step", 0)), payload,
        role=str(decision.get("role", "fleet")),
        wall=float(decision["wall"]))

  def _append(self, record: Dict[str, Any]) -> None:
    if not self._decisions_path:
      return
    try:
      if self._file is None:
        os.makedirs(os.path.dirname(self._decisions_path) or ".",
                    exist_ok=True)
        self._file = open(self._decisions_path, "a")
      self._file.write(json.dumps(record) + "\n")
      self._file.flush()
    except OSError:
      log.warning("could not append to %s; decision kept in memory",
                  self._decisions_path, exc_info=True)

  # ---- observability / lifecycle ----

  def stats(self) -> Dict[str, Any]:
    out: Dict[str, Any] = dict(self._n)
    out.update({
        "steps": self._steps,
        "rules": len(self.rules),
        "dry_run": self.dry_run,
        "budget_remaining": self.budget_remaining(),
    })
    return out

  def flight_extra(self, last: int = 50) -> Dict[str, Any]:
    """What a post-mortem needs: the recent decision tail + the
    budget state (the orchestrator folds this into its flight-record
    `extra`)."""
    return {"stats": self.stats(),
            "recent_decisions": list(self.decisions)[-last:]}

  def close(self) -> None:
    if self._file is not None:
      self._file.close()
      self._file = None


def read_decisions(path: str) -> List[Dict[str, Any]]:
  """All decision envelopes of one ``control_decisions.jsonl`` ([]
  for a missing file — a quiet run writes none)."""
  out: List[Dict[str, Any]] = []
  if not os.path.exists(path):
    return out
  with open(path) as f:
    for line in f:
      line = line.strip()
      if not line:
        continue
      try:
        out.append(json.loads(line))
      except ValueError:
        continue  # a torn line from a dying writer
  return out
