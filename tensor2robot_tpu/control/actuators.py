"""Actuator adapters: the levers the control plane may pull (ISSUE 18).

Every actuator wraps an ALREADY-SHIPPED seam — `Fleet.scale_to`
(PR 14), the front tier's respawn/scale machinery and the router's
`mark_alive` (PR 17), admission retuning (PR 13) — behind one tiny
uniform surface so the controller can drive them by NAME from a
gin-configured rule table. An actuator never decides; it applies one
decision and reports what it did (the detail dict lands in the
decision record).

The catalog (docs/CONTROL.md):

  scale_actors      Fleet.scale_to ± delta, clamped to [min, max]
  scale_fronts      Fleet.scale_fronts_to ± delta, clamped
  respawn_role      targeted kill of the decision's role; the fleet's
                    supervision respawns it under the restart budget
                    (fronts rejoin routers via the observer seam)
  retune_admission  multiply a tenant's token rate by `factor`,
                    clamped to [min_rate_rps, max_rate_rps]
  shed_tenant       graceful degradation: clamp the next tenant on
                    the priority ladder (lowest first) to
                    `shed_rate_rps`
  restore_tenants   undo every shed (pressure cleared)
  page              the FALLBACK tier: invoke the page hook (flight
                    records) — what every breach did before ISSUE 18

`fleet_actuators(fleet)` builds the standard set over a live
`fleet.orchestrator.Fleet`; the bench and tests compose their own
`Actuator` instances over whatever they drive (a FrontTier, a fake).

jax-free (IMP401 worker-safe set): the Fleet is duck-typed, never
imported.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Optional

log = logging.getLogger(__name__)


class ActuationError(RuntimeError):
  """An actuator could not apply its decision (the controller counts
  it and records the failure; the fleet keeps running)."""


class Actuator:
  """One named lever: ``fn(params, decision) -> detail dict``.

  `params` are the rule's `action_params`; `decision` is the
  controller's in-flight decision dict (rule, metric, role, value) so
  a targeted actuator can read WHO breached. The returned detail is
  logged verbatim into the decision record.
  """

  def __init__(self, name: str,
               fn: Callable[[Dict[str, Any], Dict[str, Any]],
                            Optional[Dict[str, Any]]],
               description: str = ""):
    self.name = name
    self.description = description
    self._fn = fn

  def apply(self, params: Dict[str, Any],
            decision: Dict[str, Any]) -> Dict[str, Any]:
    detail = self._fn(dict(params or {}), decision)
    return detail if isinstance(detail, dict) else {}


def _clamped(current: int, delta: int, lo: int, hi: int) -> int:
  return max(lo, min(hi, current + delta))


class DegradationLadder:
  """Shed bookkeeping for graceful degradation.

  `priorities` orders tenants LOWEST priority first — the shed order.
  Each shed clamps the next unshed tenant's admission rate to
  `shed_rate_rps`; `restore()` undoes every shed (back to
  `restore_rate_rps`, None = unlimited). The ladder only tracks; the
  retune itself goes through the caller's `retune` callable so the
  same ladder drives a Fleet, a FrontTier, or a fake.
  """

  def __init__(self, priorities, retune: Callable[..., Any],
               shed_rate_rps: float = 1.0,
               restore_rate_rps: Optional[float] = None):
    self.priorities = tuple(priorities)
    self._retune = retune
    self.shed_rate_rps = float(shed_rate_rps)
    self.restore_rate_rps = restore_rate_rps
    self._lock = threading.Lock()
    self._shed: list = []

  @property
  def shed(self) -> tuple:
    with self._lock:
      return tuple(self._shed)

  def shed_next(self) -> Optional[str]:
    """Sheds the lowest-priority tenant not yet shed; None when the
    ladder is exhausted (every tenant already shed — the controller
    falls through to its next rule, typically `page`)."""
    with self._lock:
      victim = next((t for t in self.priorities
                     if t not in self._shed), None)
      if victim is None:
        return None
      self._shed.append(victim)
    self._retune(victim, rate_rps=self.shed_rate_rps)
    return victim

  def restore(self) -> tuple:
    with self._lock:
      restored = tuple(self._shed)
      self._shed = []
    for tenant in restored:
      self._retune(tenant, rate_rps=self.restore_rate_rps)
    return restored


def fleet_actuators(
    fleet: Any,
    on_page: Optional[Callable[[Dict[str, Any]], None]] = None,
    degradation: Optional[DegradationLadder] = None,
) -> Dict[str, Actuator]:
  """The standard actuator set over a live Fleet (duck-typed:
  `scale_to`, `scale_fronts_to`, `kick`, `retune_admission`,
  `num_actors`, `num_fronts`)."""

  def scale_actors(params, decision):
    current = int(fleet.num_actors)
    target = _clamped(current, int(params.get("delta", 1)),
                      int(params.get("min", 1)),
                      int(params.get("max", 64)))
    if target == current:
      return {"noop": "at_bound", "actors": current}
    fleet.scale_to(target)
    return {"actors_before": current, "actors_after": target}

  def scale_fronts(params, decision):
    current = int(fleet.num_fronts)
    target = _clamped(current, int(params.get("delta", 1)),
                      int(params.get("min", 1)),
                      int(params.get("max", 16)))
    if target == current:
      return {"noop": "at_bound", "fronts": current}
    fleet.scale_fronts_to(target)
    return {"fronts_before": current, "fronts_after": target}

  def respawn_role(params, decision):
    role = str(params.get("role") or decision.get("role") or "")
    if not role or "/" in role or role == "fleet":
      raise ActuationError(
          f"respawn_role needs a concrete role, got {role!r} "
          f"(rule aggregate should be 'each')")
    fleet.kick(role)
    return {"kicked": role}

  def retune_admission(params, decision):
    tenant = str(params.get("tenant") or "")
    if not tenant:
      raise ActuationError("retune_admission needs a 'tenant' param")
    factor = float(params.get("factor", 0.8))
    lo = float(params.get("min_rate_rps", 1.0))
    hi = float(params.get("max_rate_rps", 1e9))
    replies = fleet.retune_admission(tenant, factor=factor,
                                     min_rate_rps=lo, max_rate_rps=hi)
    return {"tenant": tenant, "factor": factor, "fronts": replies}

  def shed_tenant(params, decision):
    if degradation is None:
      raise ActuationError("no degradation ladder configured")
    victim = degradation.shed_next()
    if victim is None:
      raise ActuationError("degradation ladder exhausted")
    return {"shed": victim,
            "rate_rps": degradation.shed_rate_rps,
            "ladder": list(degradation.shed)}

  def restore_tenants(params, decision):
    if degradation is None:
      raise ActuationError("no degradation ladder configured")
    return {"restored": list(degradation.restore())}

  def page(params, decision):
    if on_page is None:
      raise ActuationError("no page hook configured")
    on_page(decision)
    return {"paged": True}

  return {a.name: a for a in (
      Actuator("scale_actors", scale_actors,
               "Fleet.scale_to ± delta within [min, max]"),
      Actuator("scale_fronts", scale_fronts,
               "Fleet.scale_fronts_to ± delta within [min, max]"),
      Actuator("respawn_role", respawn_role,
               "targeted kill-and-respawn of the offending role"),
      Actuator("retune_admission", retune_admission,
               "multiply a tenant's admission token rate by factor"),
      Actuator("shed_tenant", shed_tenant,
               "shed the lowest-priority unshed tenant"),
      Actuator("restore_tenants", restore_tenants,
               "restore every shed tenant"),
      Actuator("page", page,
               "the fallback tier: flight records via the page hook"),
  )}
