"""Grasp2Vec: self-supervised object embeddings from grasping.

Reference parity: tensor2robot `research/grasp2vec/grasp2vec_model.py` —
`Grasp2VecModel` with scene tower φ and outcome tower ψ trained so that
φ(pregrasp) − φ(postgrasp) ≈ ψ(outcome) under an NPairs loss, enabling
goal-conditioned retrieval and embedding arithmetic (SURVEY.md §3
"Grasp2Vec" row; file:line unavailable — empty reference mount; paper:
arXiv:1811.06964).

TPU-first design decisions:
  * Pregrasp and postgrasp images run through the SAME scene tower in
    ONE batched pass (stacked on the batch axis) — a single conv
    program at 2B batch keeps the MXU fed instead of two half-size
    dispatches.
  * Embeddings come from ReLU'd 1×1-conv features mean-pooled over
    space: non-negative and additive, so scene embeddings compose as
    sums of object embeddings (the arithmetic the loss exploits) and
    the pre-pool map doubles as a localization heatmap basis.
  * uint8 images cross the host→device boundary; normalization fuses
    into the first conv.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.layers import ResNet, ResNetBlock
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.research.grasp2vec import losses as g2v_losses
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

PREGRASP_EMBEDDING = "pregrasp_embedding"
POSTGRASP_EMBEDDING = "postgrasp_embedding"
GOAL_EMBEDDING = "goal_embedding"
SCENE_SPATIAL = "scene_spatial"
GOAL_REWARD = "goal_similarity"


class _EmbeddingTower(nn.Module):
  """ResNet trunk → 1×1 conv to embedding channels → ReLU → mean pool.

  Returns (embedding (B, D), spatial map (B, H, W, D)). The ReLU before
  pooling keeps per-location contributions non-negative, which is what
  makes scene embeddings behave additively over objects.
  """

  stage_sizes: Sequence[int]
  num_filters: int
  embedding_size: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, images: jax.Array,
               train: bool = False) -> Tuple[jax.Array, jax.Array]:
    x = images.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
    _, spatial = ResNet(
        stage_sizes=tuple(self.stage_sizes),
        num_filters=self.num_filters,
        block_cls=ResNetBlock,
        num_classes=None,
        return_spatial=True,
        dtype=self.dtype,
        name="trunk",
    )(x, train=train)
    spatial = nn.Conv(self.embedding_size, (1, 1), dtype=self.dtype,
                      name="embed")(spatial.astype(self.dtype))
    spatial = nn.relu(spatial).astype(jnp.float32)
    embedding = jnp.mean(spatial, axis=(1, 2))
    return embedding, spatial


class _Grasp2VecNetwork(nn.Module):
  """Scene tower φ (shared for pre/post) + outcome tower ψ."""

  stage_sizes: Sequence[int]
  num_filters: int
  embedding_size: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False) -> Dict[str, Any]:
    scene_tower = _EmbeddingTower(
        stage_sizes=self.stage_sizes, num_filters=self.num_filters,
        embedding_size=self.embedding_size, dtype=self.dtype,
        name="scene_tower")
    goal_tower = _EmbeddingTower(
        stage_sizes=self.stage_sizes, num_filters=self.num_filters,
        embedding_size=self.embedding_size, dtype=self.dtype,
        name="goal_tower")

    pre = features["pregrasp_image"]
    post = features["postgrasp_image"]
    batch = pre.shape[0]
    # One 2B-batch pass through φ instead of two B-batch dispatches.
    stacked = jnp.concatenate([pre, post], axis=0)
    scene_emb, scene_spatial = scene_tower(stacked, train=train)
    pre_emb, post_emb = scene_emb[:batch], scene_emb[batch:]
    goal_emb, _ = goal_tower(features["goal_image"], train=train)
    return {
        PREGRASP_EMBEDDING: pre_emb,
        POSTGRASP_EMBEDDING: post_emb,
        GOAL_EMBEDDING: goal_emb,
        SCENE_SPATIAL: scene_spatial[:batch],
        GOAL_REWARD: g2v_losses.goal_similarity_reward(
            pre_emb, post_emb, goal_emb),
    }


@gin.configurable
class Grasp2VecModel(AbstractT2RModel):
  """Self-supervised scene/outcome embedding model.

  Features: pregrasp scene, postgrasp scene, and outcome ("goal") image
  of the grasped object. Label: an integer `object_id`, used ONLY for
  duplicate-aware loss targets and retrieval metrics — the training
  signal itself is self-supervised embedding arithmetic.
  """

  def __init__(self,
               image_size: int = 64,
               goal_image_size: Optional[int] = None,
               embedding_size: int = 128,
               stage_sizes: Sequence[int] = (2, 2, 2, 2),
               num_filters: int = 64,
               reg_lambda: float = 0.002,
               device_dtype=jnp.bfloat16,
               **kwargs):
    super().__init__(device_dtype=device_dtype, **kwargs)
    self._image_size = image_size
    self._goal_image_size = goal_image_size or image_size
    self._embedding_size = embedding_size
    self._stage_sizes = tuple(stage_sizes)
    self._num_filters = num_filters
    self._reg_lambda = reg_lambda

  @property
  def embedding_size(self) -> int:
    return self._embedding_size

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    scene_shape = (self._image_size, self._image_size, 3)
    goal_shape = (self._goal_image_size, self._goal_image_size, 3)
    st.pregrasp_image = ExtendedTensorSpec(
        shape=scene_shape, dtype=np.uint8, name="pregrasp_image",
        data_format="jpeg")
    st.postgrasp_image = ExtendedTensorSpec(
        shape=scene_shape, dtype=np.uint8, name="postgrasp_image",
        data_format="jpeg")
    st.goal_image = ExtendedTensorSpec(
        shape=goal_shape, dtype=np.uint8, name="goal_image",
        data_format="jpeg")
    return st

  def get_label_specification(
      self, mode: Mode) -> Optional[TensorSpecStruct]:
    if mode == Mode.PREDICT:
      return None
    st = TensorSpecStruct()
    st.object_id = ExtendedTensorSpec(
        shape=(), dtype=np.int64, name="object_id")
    return st

  def create_network(self) -> nn.Module:
    return _Grasp2VecNetwork(
        stage_sizes=self._stage_sizes,
        num_filters=self._num_filters,
        embedding_size=self._embedding_size,
        dtype=self.device_dtype,
    )

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    anchor = (outputs[PREGRASP_EMBEDDING]
              - outputs[POSTGRASP_EMBEDDING])
    object_ids = labels["object_id"] if labels is not None else None
    loss, metrics = g2v_losses.npairs_loss(
        anchor, outputs[GOAL_EMBEDDING], object_ids=object_ids,
        reg_lambda=self._reg_lambda)
    metrics["goal_similarity"] = jnp.mean(outputs[GOAL_REWARD])
    return loss, metrics
