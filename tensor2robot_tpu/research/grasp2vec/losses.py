"""Grasp2Vec metric-learning losses and retrieval metrics.

Reference parity: tensor2robot `research/grasp2vec/losses.py` — the
NPairs loss (tf.contrib metric_learning) between scene-difference and
outcome embeddings, plus the embedding-arithmetic consistency metrics
(SURVEY.md §3 "Grasp2Vec" row; file:line unavailable — empty reference
mount).

TPU-first: the whole loss is one (B, B) similarity matmul + softmax —
a single MXU op per direction, no pairwise python loops. Duplicate
object ids inside a batch (common with a small object vocabulary) are
handled with multi-label targets instead of the reference's assumption
of unique classes per batch.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def npairs_loss(
    anchor: jax.Array,
    positive: jax.Array,
    object_ids: Optional[jax.Array] = None,
    reg_lambda: float = 0.002,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
  """Symmetric N-pairs loss between two embedding sets.

  `anchor[i]` should score highest against `positive[i]` among all
  `positive[j]` in the batch (and vice versa). With `object_ids`, rows
  sharing an id are all treated as correct matches (multi-label soft
  targets), so duplicate objects in a batch don't fight the loss.

  Returns (loss, metrics) where metrics carries in-batch retrieval
  top-1 accuracy and the embedding regularization term.
  """
  anchor = anchor.astype(jnp.float32)
  positive = positive.astype(jnp.float32)
  logits = anchor @ positive.T  # (B, B) — one MXU call.
  batch = anchor.shape[0]
  if object_ids is None:
    same = jnp.eye(batch, dtype=jnp.float32)
  else:
    ids = object_ids.reshape(-1)
    same = (ids[:, None] == ids[None, :]).astype(jnp.float32)
  targets = same / jnp.maximum(same.sum(axis=1, keepdims=True), 1.0)

  def directional(lg):
    log_probs = jax.nn.log_softmax(lg, axis=1)
    return -jnp.mean(jnp.sum(targets * log_probs, axis=1))

  xent = 0.5 * (directional(logits) + directional(logits.T))
  # L2 activation regularizer (the tf.contrib npairs `reg_lambda`):
  # keeps embedding norms from inflating logits instead of alignment.
  reg = reg_lambda * 0.5 * (
      jnp.mean(jnp.sum(jnp.square(anchor), axis=1))
      + jnp.mean(jnp.sum(jnp.square(positive), axis=1)))
  loss = xent + reg

  top1 = jnp.argmax(logits, axis=1)
  correct = jnp.take_along_axis(same, top1[:, None], axis=1)[:, 0]
  metrics = {
      "npairs_xent": xent,
      "embedding_reg": reg,
      "retrieval_top1": jnp.mean(correct),
  }
  return loss, metrics


def cosine_similarity(a: jax.Array, b: jax.Array,
                      eps: float = 1e-8) -> jax.Array:
  """Row-wise cosine similarity between two (B, D) arrays."""
  a = a.astype(jnp.float32)
  b = b.astype(jnp.float32)
  num = jnp.sum(a * b, axis=-1)
  den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
  return num / jnp.maximum(den, eps)


def goal_similarity_reward(
    pregrasp_embedding: jax.Array,
    postgrasp_embedding: jax.Array,
    goal_embedding: jax.Array,
) -> jax.Array:
  """Self-supervised grasp reward: cos(φ(pre) − φ(post), ψ(goal)).

  The paper's goal-conditioned reward signal for QT-Opt: 1-ish when the
  object removed from the scene matches the goal, ~0 otherwise. Pure
  elementwise/cosine math — composes into the QT-Opt learner's fused
  Bellman step without leaving the device.
  """
  return cosine_similarity(
      pregrasp_embedding - postgrasp_embedding, goal_embedding)
