"""Grasp2Vec → QT-Opt glue: self-supervised goal-conditioned rewards.

Reference parity: grasp2vec existed to LABEL grasping data — the paper
(arXiv:1811.06964 §4) trains goal-conditioned QT-Opt with reward
1[cos(φ(pre) − φ(post), ψ(goal)) > threshold] instead of human labels.
The reference repo shipped the embedding model; this module ships the
actual handoff: a jitted reward labeler and a transition relabeler
that emits the QT-Opt replay layout (goal embedding riding as an
extra state feature of the Q-function).

One device program per batch: both embedding towers + the cosine +
the threshold run fused; the output feeds `ReplayBuffer.add` directly.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
    GOAL_EMBEDDING,
    GOAL_REWARD,
    Grasp2VecModel,
)
from tensor2robot_tpu.specs import TensorSpecStruct

GOAL_EMBEDDING_FEATURE = "goal_embedding"


def make_grasp2vec_reward_fn(
    model: Grasp2VecModel,
    state,
    threshold: float = 0.5,
    binary: bool = True,
) -> Callable[[np.ndarray, np.ndarray, np.ndarray], Dict[str, np.ndarray]]:
  """Builds `(pregrasp, postgrasp, goal) → {reward, goal_embedding}`.

  `binary=True` applies the paper's success threshold on the cosine;
  otherwise the raw similarity is the (shaped) reward. Also returns
  ψ(goal) so relabeled transitions can condition the Q-function.
  """
  jitted = jax.jit(model.predict_step)

  def reward_fn(pregrasp_image, postgrasp_image, goal_image):
    features = TensorSpecStruct.from_flat_dict({
        "pregrasp_image": jnp.asarray(pregrasp_image),
        "postgrasp_image": jnp.asarray(postgrasp_image),
        "goal_image": jnp.asarray(goal_image),
    })
    outputs = jitted(state, features)
    similarity = np.asarray(jax.device_get(outputs[GOAL_REWARD]),
                            np.float32)
    reward = ((similarity > threshold).astype(np.float32)
              if binary else similarity)
    return {
        "reward": reward,
        "similarity": similarity,
        GOAL_EMBEDDING_FEATURE: np.asarray(
            jax.device_get(outputs[GOAL_EMBEDDING]), np.float32),
    }

  return reward_fn


def relabel_transitions(
    reward_fn,
    pregrasp_images: np.ndarray,
    postgrasp_images: np.ndarray,
    goal_images: np.ndarray,
    actions: np.ndarray,
    next_images: Optional[np.ndarray] = None,
) -> Dict[str, np.ndarray]:
  """Grasping attempts → QT-Opt replay transitions, grasp2vec-labeled.

  Output layout matches `QTOptLearner.transition_specification()` for
  a `GraspingQModel(extra_state_features={"goal_embedding": (D,)})`:
  the scene image + goal embedding are the state, the attempt is the
  action, the self-supervised outcome similarity is the reward, and
  episodes are single-step grasps (done=1, paper's setting).
  """
  labels = reward_fn(pregrasp_images, postgrasp_images, goal_images)
  n = pregrasp_images.shape[0]
  goal_emb = labels[GOAL_EMBEDDING_FEATURE]
  return {
      "image": pregrasp_images,
      GOAL_EMBEDDING_FEATURE: goal_emb,
      "action": np.asarray(actions, np.float32),
      "reward": labels["reward"][:, None],
      "done": np.ones((n, 1), np.float32),
      "next_image": (postgrasp_images if next_images is None
                     else next_images),
      f"next_{GOAL_EMBEDDING_FEATURE}": goal_emb,
  }
