"""Grasp2Vec research family (reference: research/grasp2vec/)."""

from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
    GOAL_EMBEDDING,
    GOAL_REWARD,
    Grasp2VecModel,
    POSTGRASP_EMBEDDING,
    PREGRASP_EMBEDDING,
    SCENE_SPATIAL,
)
from tensor2robot_tpu.research.grasp2vec.goal_reward import (
    GOAL_EMBEDDING_FEATURE,
    make_grasp2vec_reward_fn,
    relabel_transitions,
)
from tensor2robot_tpu.research.grasp2vec.grasp_env import (
    GraspSceneGenerator,
    collect_grasp_triplets,
    evaluate_retrieval,
)
from tensor2robot_tpu.research.grasp2vec.losses import (
    cosine_similarity,
    goal_similarity_reward,
    npairs_loss,
)
from tensor2robot_tpu.research.grasp2vec.visualization import (
    goal_localization_heatmap,
    heatmap_argmax,
)
