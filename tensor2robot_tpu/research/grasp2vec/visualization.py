"""Goal localization heatmaps from Grasp2Vec embeddings.

Reference parity: tensor2robot `research/grasp2vec/visualization.py` —
correlating an outcome embedding ψ(goal) against the scene tower's
spatial feature map to localize "where is this object in the scene"
(SURVEY.md §3 "Grasp2Vec" row; the paper's Figure-4 heatmaps).

Pure jnp: composes into jitted eval/serving programs; also callable on
numpy inputs host-side for visualization dumps.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def goal_localization_heatmap(
    scene_spatial: jax.Array,
    goal_embedding: jax.Array,
    temperature: float = 1.0,
) -> jax.Array:
  """Softmax heatmap of goal-embedding correlation over scene locations.

  Args:
    scene_spatial: (B, H, W, D) pre-pool scene features (the model's
      `scene_spatial` output).
    goal_embedding: (B, D) outcome embeddings ψ(goal).
    temperature: softmax temperature; lower = sharper peaks.

  Returns (B, H, W) heatmaps, each summing to 1.
  """
  scene = scene_spatial.astype(jnp.float32)
  goal = goal_embedding.astype(jnp.float32)
  scores = jnp.einsum("bhwd,bd->bhw", scene, goal)
  b, h, w = scores.shape
  flat = scores.reshape(b, h * w) / jnp.maximum(temperature, 1e-6)
  return jax.nn.softmax(flat, axis=-1).reshape(b, h, w)


def heatmap_argmax(heatmap: jax.Array) -> Tuple[jax.Array, jax.Array]:
  """Peak (row, col) per heatmap — grasp-point proposal from a goal."""
  b, h, w = heatmap.shape
  idx = jnp.argmax(heatmap.reshape(b, h * w), axis=-1)
  return idx // w, idx % w
