"""Synthetic grasping scenes for Grasp2Vec: collect + retrieval eval.

Reference parity: the reference trained grasp2vec on logged robot
grasping triplets (pregrasp scene, postgrasp scene, grasped-object
image) and evaluated object retrieval (SURVEY.md §3 "Grasp2Vec" row).
The robot logs aren't reproducible here; this module generates scenes
with the same causal structure — the postgrasp image is the pregrasp
image with exactly the target object removed — so embedding arithmetic
has real compositional signal to learn, and ships the same
collect-to-TFRecord and retrieval-eval entry points the reference's
scripts provided.

Objects are distinct-colored square patches from a fixed palette;
distractor objects stay in place across pre/post so φ(pre) − φ(post)
must isolate the removed object, not the scene.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Tuple

import numpy as np

from tensor2robot_tpu import config as gin

# Maximally-spread hues; index = object id.
_PALETTE = np.array([
    [220, 40, 40], [40, 200, 40], [60, 60, 230], [230, 210, 40],
    [210, 50, 210], [40, 210, 210], [240, 140, 30], [140, 70, 200],
    [120, 200, 120], [200, 120, 120], [90, 130, 220], [180, 180, 80],
], np.uint8)

NUM_OBJECT_TYPES = len(_PALETTE)


class GraspSceneGenerator:
  """Renders (pregrasp, postgrasp, goal) triplets with shared layout."""

  def __init__(self,
               image_size: int = 64,
               num_object_types: int = 6,
               num_distractors: int = 2,
               patch_fraction: float = 0.22,
               noise: float = 0.02,
               seed: int = 0):
    if num_object_types > NUM_OBJECT_TYPES:
      raise ValueError(
          f"num_object_types <= {NUM_OBJECT_TYPES} (palette size)")
    self._size = image_size
    self._num_types = num_object_types
    self._num_distractors = num_distractors
    self._patch = max(2, int(patch_fraction * image_size))
    self._noise = noise
    self._rng = np.random.default_rng(seed)

  def _background(self) -> np.ndarray:
    size = self._size
    image = np.full((size, size, 3), 96, np.float64)
    image += self._rng.normal(0, 255 * self._noise, (size, size, 3))
    return image

  def _paint(self, image: np.ndarray, object_id: int,
             center: Tuple[int, int]) -> None:
    half = self._patch // 2
    cx, cy = center
    x0, x1 = max(0, cx - half), min(self._size, cx + half + 1)
    y0, y1 = max(0, cy - half), min(self._size, cy + half + 1)
    image[y0:y1, x0:x1] = _PALETTE[object_id]

  def _random_center(self) -> Tuple[int, int]:
    half = self._patch // 2
    lo, hi = half, self._size - half - 1
    return (int(self._rng.integers(lo, hi + 1)),
            int(self._rng.integers(lo, hi + 1)))

  def sample(self) -> Dict[str, np.ndarray]:
    """One triplet: {pregrasp_image, postgrasp_image, goal_image,
    object_id, target_center}."""
    target = int(self._rng.integers(self._num_types))
    distractors = [
        int(t) for t in self._rng.choice(
            [t for t in range(self._num_types) if t != target],
            size=min(self._num_distractors, self._num_types - 1),
            replace=False)
    ] if self._num_types > 1 and self._num_distractors > 0 else []

    base = self._background()
    post = base.copy()
    placed = []
    for obj in distractors:
      center = self._random_center()
      placed.append((obj, center))
    target_center = self._random_center()

    pre = base.copy()
    for obj, center in placed:
      self._paint(pre, obj, center)
      self._paint(post, obj, center)
    self._paint(pre, target, target_center)  # target only in pregrasp

    goal = np.full((self._size, self._size, 3), 20, np.float64)
    goal += self._rng.normal(0, 255 * self._noise,
                             (self._size, self._size, 3))
    self._paint(goal, target, (self._size // 2, self._size // 2))

    clip = lambda x: np.clip(x, 0, 255).astype(np.uint8)
    return {
        "pregrasp_image": clip(pre),
        "postgrasp_image": clip(post),
        "goal_image": clip(goal),
        "object_id": np.int64(target),
        "target_center": np.array(target_center, np.int64),
    }

  def goal_gallery(self) -> np.ndarray:
    """One canonical goal image per object type: (K, S, S, 3) uint8."""
    images = []
    for obj in range(self._num_types):
      goal = np.full((self._size, self._size, 3), 20, np.float64)
      self._paint(goal, obj, (self._size // 2, self._size // 2))
      images.append(np.clip(goal, 0, 255).astype(np.uint8))
    return np.stack(images)


@gin.configurable
def collect_grasp_triplets(
    output_path: str,
    num_episodes: int = 256,
    image_size: int = 64,
    num_object_types: int = 6,
    num_distractors: int = 2,
    seed: int = 0,
) -> str:
  """Writes spec-conforming TFRecords of grasping triplets."""
  from tensor2robot_tpu.data.abstract_input_generator import Mode
  from tensor2robot_tpu.data.tfrecord_input_generator import (
      write_tfrecord,
  )
  from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
      Grasp2VecModel,
  )

  gen = GraspSceneGenerator(
      image_size=image_size, num_object_types=num_object_types,
      num_distractors=num_distractors, seed=seed)
  model = Grasp2VecModel(image_size=image_size)
  examples = []
  for _ in range(num_episodes):
    triplet = gen.sample()
    examples.append({k: triplet[k] for k in
                     ("pregrasp_image", "postgrasp_image", "goal_image",
                      "object_id")})
  os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
  write_tfrecord(
      output_path, examples,
      model.get_feature_specification(Mode.TRAIN),
      model.get_label_specification(Mode.TRAIN))
  return output_path


@gin.configurable
def evaluate_retrieval(
    predict_fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]],
    num_queries: int = 50,
    image_size: int = 64,
    num_object_types: int = 6,
    num_distractors: int = 2,
    seed: int = 1,
    batch_size: int = 16,
) -> Dict[str, float]:
  """Goal-conditioned retrieval: does φ(pre)−φ(post) find its object?

  Embeds a K-image goal gallery with ψ, then for `num_queries` held-out
  scene pairs retrieves argmax_k <φ(pre)−φ(post), ψ(gallery_k)>.
  Returns top-1 accuracy (chance = 1/K) and the mean matched-goal
  cosine similarity.
  """
  from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
      GOAL_EMBEDDING,
      POSTGRASP_EMBEDDING,
      PREGRASP_EMBEDDING,
  )

  gen = GraspSceneGenerator(
      image_size=image_size, num_object_types=num_object_types,
      num_distractors=num_distractors, seed=seed)
  gallery_images = gen.goal_gallery()
  k = gallery_images.shape[0]
  # ψ over the gallery: scene inputs are dummies for this pass.
  dummy_scene = np.zeros_like(gallery_images)
  out = predict_fn({
      "pregrasp_image": dummy_scene,
      "postgrasp_image": dummy_scene,
      "goal_image": gallery_images,
  })
  gallery = np.asarray(out[GOAL_EMBEDDING], np.float32)  # (K, D)

  correct = 0
  sims: List[float] = []
  for start in range(0, num_queries, batch_size):
    triplets = [gen.sample()
                for _ in range(min(batch_size, num_queries - start))]
    batch = {
        key: np.stack([t[key] for t in triplets])
        for key in ("pregrasp_image", "postgrasp_image", "goal_image")
    }
    out = predict_fn(batch)
    diff = (np.asarray(out[PREGRASP_EMBEDDING], np.float32)
            - np.asarray(out[POSTGRASP_EMBEDDING], np.float32))
    scores = diff @ gallery.T  # (B, K)
    picks = scores.argmax(axis=1)
    for t, pick, row, d in zip(triplets, picks, scores, diff):
      target = int(t["object_id"])
      correct += int(pick == target)
      denom = (np.linalg.norm(d) *
               np.linalg.norm(gallery[target])) or 1.0
      sims.append(float(row[target] / denom))
  return {
      "retrieval_top1": correct / float(num_queries),
      "chance_top1": 1.0 / k,
      "matched_goal_cosine": float(np.mean(sims)),
      "num_queries": float(num_queries),
  }
