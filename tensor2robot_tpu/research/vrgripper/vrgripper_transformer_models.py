"""Long-context transformer BC over full gripper episodes.

The reference's sequence policies were SNAIL-style causal convs over
short fixed windows (`vrgripper_env_meta_models.py` parity lives in
`vrgripper_meta_models.py`); this model is the framework's long-context
counterpart: behavioral cloning where the policy attends over the
ENTIRE episode history — the regime the TPU stack makes first-class
(flash attention within a chip, ring attention across chips; same
exact-attention math, so checkpoints are portable between backends).

Consumes episode batches straight from `TFRecordEpisodeInputGenerator`
(image/gripper_pose sequences + true lengths; the wire layout
`collect_demo_episodes` writes), encodes each step with the shared
`GripperObsEncoder` folded into one conv batch, runs the causal
transformer over steps, and clones per-step actions with a
length-masked loss — padding steps never contribute gradient.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.tfexample import SEQUENCE_LENGTH_KEY
from tensor2robot_tpu.layers.transformer import CausalTransformer
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.regression_model import INFERENCE_OUTPUT
from tensor2robot_tpu.research.vrgripper.vrgripper_models import (
    ACTION,
    GripperObsEncoder,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
)


class _EpisodeTransformerNet(nn.Module):
  """Per-step obs encoder → causal transformer → per-step actions."""

  action_dim: int
  filters: Sequence[int]
  embedding_size: int
  width: int
  depth: int
  num_heads: int
  max_len: int
  attention_impl: str
  mesh: Optional[Any] = None
  dtype: Any = jnp.bfloat16
  moe_experts: int = 0
  moe_every: int = 2
  pipeline_stages: int = 0
  pipeline_microbatches: int = 2
  pipeline_remat: bool = False

  @nn.compact
  def __call__(self, features, train: bool = False):
    flat = (features.to_flat_dict()
            if hasattr(features, "to_flat_dict") else dict(features))
    image = flat["image"]
    pose = flat["gripper_pose"]
    b, t = image.shape[:2]
    # All steps of all episodes through ONE conv batch (MXU-sized).
    folded = TensorSpecStruct.from_flat_dict({
        "image": image.reshape((b * t,) + image.shape[2:]),
        "gripper_pose": pose.reshape((b * t,) + pose.shape[2:]),
    })
    emb = GripperObsEncoder(
        filters=tuple(self.filters),
        embedding_size=self.embedding_size,
        use_batch_norm=False, dtype=self.dtype,
        name="obs_encoder")(folded, train=train)
    emb = emb.reshape(b, t, -1)
    if self.pipeline_stages:
      from tensor2robot_tpu.layers.pipelined_transformer import (
          PipelinedCausalTransformer,
      )
      trunk = PipelinedCausalTransformer(
          width=self.width, depth=self.depth,
          num_heads=self.num_heads, max_len=self.max_len,
          num_stages=self.pipeline_stages,
          num_microbatches=self.pipeline_microbatches,
          remat=self.pipeline_remat,
          attention_impl=self.attention_impl, causal=True,
          mesh=self.mesh, dtype=self.dtype,
          name="trunk")(emb, train=train)
    else:
      trunk = CausalTransformer(
          width=self.width, depth=self.depth,
          num_heads=self.num_heads, max_len=self.max_len,
          attention_impl=self.attention_impl,
          causal=True, mesh=self.mesh, dtype=self.dtype,
          moe_experts=self.moe_experts, moe_every=self.moe_every,
          name="trunk")(emb, train=train)
    action = nn.Dense(self.action_dim, dtype=self.dtype,
                      name="action_head")(
        trunk.astype(self.dtype)).astype(jnp.float32)
    return {ACTION: action, INFERENCE_OUTPUT: action}


@gin.configurable
class VRGripperTransformerModel(AbstractT2RModel):
  """Episode-level BC: every action conditioned on the full history."""

  def __init__(self,
               image_size: int = 48,
               state_dim: int = 3,
               action_dim: int = 3,
               filters: Sequence[int] = (16, 32),
               embedding_size: int = 64,
               width: int = 64,
               depth: int = 2,
               num_heads: int = 4,
               max_context_length: int = 512,
               attention_impl: str = "auto",
               mesh: Optional[Any] = None,
               moe_experts: int = 0,
               moe_every: int = 2,
               pipeline_stages: int = 0,
               pipeline_microbatches: int = 2,
               pipeline_remat: bool = False,
               device_dtype=jnp.bfloat16,
               **kwargs):
    """`mesh`: required for attention_impl="ring"/"ring_flash" — the
    device mesh whose `seq` axis the episode dimension shards over
    (sequence parallelism); unused by single-device backends.
    `moe_experts`/`moe_every`: swap every `moe_every`-th block's MLP
    for that many routed experts (`parallel/moe.py`); with a mesh
    `expert` axis they run expert-parallel, and the load-balance aux
    loss joins training via the base model's aux_loss_weight.
    `pipeline_stages`: split the trunk's depth into that many GPipe
    stages (`layers/pipelined_transformer.py`); with a mesh `stage`
    axis of the same size + sharding_strategy="pipeline" each device
    holds one stage's weights and activations ppermute through the
    microbatch schedule. Without a stage axis the SAME params run the
    sequential fallback — pod-trained checkpoints serve on one chip.
    The global batch must divide into pipeline_microbatches × the
    mesh's data-axis size (set train_eval_model.init_batch_size
    accordingly). Mutually exclusive with moe_experts (one trunk)."""
    super().__init__(device_dtype=device_dtype, **kwargs)
    if pipeline_stages and moe_experts:
      raise ValueError(
          "pipeline_stages and moe_experts are mutually exclusive: "
          "the pipelined trunk stacks dense blocks (stage-stacked MoE "
          "routing is not implemented).")
    self._image_size = image_size
    self._state_dim = state_dim
    self._action_dim = action_dim
    self._filters = tuple(filters)
    self._embedding_size = embedding_size
    self._width = width
    self._depth = depth
    self._num_heads = num_heads
    self._max_len = max_context_length
    self._attention_impl = attention_impl
    self._mesh = mesh
    self._moe_experts = moe_experts
    self._moe_every = moe_every
    self._pipeline_stages = pipeline_stages
    self._pipeline_microbatches = pipeline_microbatches
    self._pipeline_remat = pipeline_remat
    if pipeline_stages and mesh is not None:
      from tensor2robot_tpu.parallel.mesh import STAGE_AXIS
      if (STAGE_AXIS in mesh.axis_names
          and mesh.shape[STAGE_AXIS] != pipeline_stages):
        raise ValueError(
            f"pipeline_stages={pipeline_stages} must equal the mesh's "
            f"{STAGE_AXIS!r} axis size {mesh.shape[STAGE_AXIS]} (each "
            "device materializes exactly one stage).")
    if mesh is not None:
      from tensor2robot_tpu.parallel.mesh import SEQ_AXIS
      if (SEQ_AXIS in mesh.axis_names
          and max_context_length % mesh.shape[SEQ_AXIS]):
        raise ValueError(
            f"max_context_length={max_context_length} must be a "
            f"multiple of the mesh's {SEQ_AXIS!r} axis size "
            f"{mesh.shape[SEQ_AXIS]} for sequence parallelism.")

  @property
  def init_sequence_length(self):
    """Sequence-parallel attention needs init T divisible by the
    mesh's `seq` axis; single-device backends keep the default."""
    if self._mesh is not None:
      from tensor2robot_tpu.parallel.mesh import SEQ_AXIS
      if SEQ_AXIS in self._mesh.axis_names:
        # Valid by the constructor check: max_len % seq_size == 0.
        return self._mesh.shape[SEQ_AXIS]
    return None

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(
        shape=(self._image_size, self._image_size, 3), dtype=np.uint8,
        name="image", data_format="png", is_sequence=True)
    st.gripper_pose = ExtendedTensorSpec(
        shape=(self._state_dim,), dtype=np.float32,
        name="gripper_pose", is_sequence=True)
    # NOTE: the true episode lengths arrive as the episode generator's
    # extra `sequence_length` key (reserved — the parser forbids
    # declaring it as a spec); the masked loss picks it up when
    # present and treats all steps as real otherwise.
    return st

  def get_label_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.action = ExtendedTensorSpec(
        shape=(self._action_dim,), dtype=np.float32, name=ACTION,
        is_sequence=True)
    return st

  def create_network(self) -> nn.Module:
    return _EpisodeTransformerNet(
        action_dim=self._action_dim,
        filters=self._filters,
        embedding_size=self._embedding_size,
        width=self._width,
        depth=self._depth,
        num_heads=self._num_heads,
        max_len=self._max_len,
        attention_impl=self._attention_impl,
        mesh=self._mesh,
        moe_experts=self._moe_experts,
        moe_every=self._moe_every,
        pipeline_stages=self._pipeline_stages,
        pipeline_microbatches=self._pipeline_microbatches,
        pipeline_remat=self._pipeline_remat,
        dtype=self.device_dtype,
    )

  def make_context_policy(self, state,
                          context_length: Optional[int] = None
                          ) -> "EpisodeContextPolicy":
    """A closed-loop policy that feeds the growing episode history."""
    return EpisodeContextPolicy(
        self, state, context_length or self._max_len)

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    target = labels[ACTION].astype(jnp.float32)      # [B, T, A]
    predicted = outputs[ACTION].astype(jnp.float32)
    b, t = target.shape[:2]
    flat = features.to_flat_dict()
    if SEQUENCE_LENGTH_KEY in flat:
      lengths = flat[SEQUENCE_LENGTH_KEY].reshape(b)
      mask = (jnp.arange(t)[None, :]
              < lengths[:, None]).astype(jnp.float32)
    else:
      mask = jnp.ones((b, t), jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    sq = jnp.sum(jnp.square(predicted - target), axis=-1)
    loss = jnp.sum(sq * mask) / denom
    action_error = jnp.sum(
        jnp.sum(jnp.abs(predicted - target), axis=-1) * mask) / denom
    return loss, {"mse": loss, "action_error": action_error}


class EpisodeContextPolicy:
  """On-robot wrapper: accumulates history, serves the latest action.

  The control loop calls `policy(single_observation_batch)` per step
  and `policy.reset()` at episode boundaries (the protocol
  `evaluate_gripper_policy` speaks). History is padded to the FIXED
  context length, so one compiled program serves every step —
  XLA-friendly static shapes, causal masking makes padding harmless.
  """

  def __init__(self, model: VRGripperTransformerModel, state,
               context_length: int):
    self._model = model
    self._state = state
    self._t = context_length
    self._jit = jax.jit(model.predict_step)
    self._history: list = []

  def reset(self) -> None:
    self._history = []

  def __call__(self, features: Dict[str, np.ndarray]
               ) -> Dict[str, np.ndarray]:
    obs = {k: np.asarray(v)[0] for k, v in features.items()}
    self._history.append(obs)
    self._history = self._history[-self._t:]
    steps = len(self._history)

    def pad(key):
      stacked = np.stack([h[key] for h in self._history])
      return np.pad(
          stacked,
          [(0, self._t - steps)] + [(0, 0)] * (stacked.ndim - 1))

    batch = TensorSpecStruct.from_flat_dict({
        "image": jnp.asarray(pad("image")[None]),
        "gripper_pose": jnp.asarray(pad("gripper_pose")[None]),
    })
    outputs = self._jit(self._state, batch)
    action = np.asarray(jax.device_get(outputs[ACTION]))
    # The CURRENT step's action is at the last real history slot.
    return {ACTION: action[:, steps - 1]}
