"""VRGripper / Watch-Try-Learn research family.

Reference parity: tensor2robot `research/vrgripper/` — behavioral
cloning from demonstrations (plain + MDN policies), episode→transition
munging, meta-BC (MAML / SNAIL), and Watch-Try-Learn trial-conditioned
policies (SURVEY.md §3 "VRGripper / WTL").
"""

from tensor2robot_tpu.research.vrgripper.episode_to_transitions import (
    TransitionInputGenerator,
    episode_batch_to_transitions,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_env import (
    VRGripperEnv,
    collect_demo_episodes,
    collect_expert_episode,
    evaluate_gripper_policy,
    sample_wtl_meta_batch,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_models import (
    GripperObsEncoder,
    VRGripperRegressionModel,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_meta_models import (
    VRGripperMAMLModel,
    VRGripperSNAILModel,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_transformer_models import (  # noqa: E501
    VRGripperTransformerModel,
)
from tensor2robot_tpu.research.vrgripper.vrgripper_wtl_models import (
    VRGripperWTLModel,
)
