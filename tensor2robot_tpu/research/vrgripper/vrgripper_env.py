"""VRGripper environment: scripted-demo reach-and-grasp episodes.

Reference parity: the reference's vrgripper family trained behavioral
cloning on VR teleop demonstrations of a gripper env (SURVEY.md §3
"VRGripper / WTL": `research/vrgripper/vrgripper_env_models.py`;
file:line unavailable — empty reference mount). The actual env was
in-house Unity VR and never shipped; what the repo needs is episode
data with demonstrable structure, so this rebuild provides a
dependency-free numpy env with a scripted expert — the same role the
reference's recorded demos played: supervised (obs → action) episode
streams that a policy can clone and an eval loop can score.

Task: a gripper (green dot) must reach a block (red square) on a
table and close. Observation: RGB render + gripper pose
[x, y, closed]. Action: [dx, dy, close_cmd], all in [-1, 1]. The
scripted expert walks toward the block and closes on arrival.
Per-episode variation for the meta families: an optional task offset —
the expert targets block_pose + offset, which demonstrations reveal
but a single observation does not (the meta-BC signal).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin

IMAGE_SIZE = 48
WORKSPACE_LOW = np.array([-0.4, -0.4], np.float32)
WORKSPACE_HIGH = np.array([0.4, 0.4], np.float32)
# World units per unit action: one expert step covers this distance.
ACTION_SCALE = 0.1
# Forgiving gripper aperture (a compliant gripper, as real ones are):
# the expert aims well inside it, a cloned policy succeeds from the
# whole aperture.
GRASP_RADIUS = 0.09


class VRGripperEnv:
  """Numpy reach-and-grasp task with a scripted expert."""

  def __init__(self, image_size: int = IMAGE_SIZE, seed: int = 0,
               max_steps: int = 12, noise: float = 0.02,
               task_offset_scale: float = 0.0):
    self._image_size = image_size
    self._rng = np.random.default_rng(seed)
    self._max_steps = max_steps
    self._noise = noise
    self._task_offset_scale = task_offset_scale
    self._block: Optional[np.ndarray] = None
    self._gripper: Optional[np.ndarray] = None
    self._closed = 0.0
    self._offset = np.zeros(2, np.float32)
    self._steps = 0

  @property
  def image_size(self) -> int:
    return self._image_size

  @property
  def max_steps(self) -> int:
    return self._max_steps

  @property
  def task_offset(self) -> np.ndarray:
    return self._offset

  def reset(self, task_offset: Optional[np.ndarray] = None
            ) -> Dict[str, np.ndarray]:
    self._block = self._rng.uniform(
        WORKSPACE_LOW * 0.8, WORKSPACE_HIGH * 0.8).astype(np.float32)
    self._gripper = self._rng.uniform(
        WORKSPACE_LOW, WORKSPACE_HIGH).astype(np.float32)
    if task_offset is not None:
      self._offset = np.asarray(task_offset, np.float32)
    elif self._task_offset_scale > 0:
      self._offset = self._rng.uniform(
          -self._task_offset_scale, self._task_offset_scale,
          2).astype(np.float32)
    else:
      self._offset = np.zeros(2, np.float32)
    self._closed = 0.0
    self._steps = 0
    return self.observation()

  @property
  def target(self) -> np.ndarray:
    """The (latent) point the expert aims for: block + task offset."""
    return np.clip(self._block + self._offset,
                   WORKSPACE_LOW, WORKSPACE_HIGH)

  def step(self, action: np.ndarray
           ) -> Tuple[Dict[str, np.ndarray], float, bool]:
    """Applies [dx, dy, close]; returns (obs, reward, done)."""
    action = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
    self._gripper = np.clip(
        self._gripper + action[:2] * ACTION_SCALE,
        WORKSPACE_LOW, WORKSPACE_HIGH).astype(np.float32)
    self._closed = float(action[2] > 0)
    self._steps += 1
    success = self.success()
    done = success or self._steps >= self._max_steps
    return self.observation(), float(success), done

  def success(self) -> bool:
    return (self._closed > 0 and
            float(np.linalg.norm(self._gripper - self.target))
            < GRASP_RADIUS)

  def expert_action(self) -> np.ndarray:
    """Scripted demonstration policy toward the (latent) target."""
    delta = self.target - self._gripper
    dist = float(np.linalg.norm(delta))
    if dist < GRASP_RADIUS * 0.6:
      return np.array([0.0, 0.0, 1.0], np.float32)
    move = np.clip(delta / ACTION_SCALE, -1.0, 1.0)
    return np.array([move[0], move[1], -1.0], np.float32)

  def _world_to_pixel(self, xy: np.ndarray) -> Tuple[int, int]:
    frac = (xy - WORKSPACE_LOW) / (WORKSPACE_HIGH - WORKSPACE_LOW)
    px = np.clip((frac * self._image_size).astype(int), 0,
                 self._image_size - 1)
    return int(px[0]), int(px[1])

  def observation(self) -> Dict[str, np.ndarray]:
    size = self._image_size
    image = np.full((size, size, 3), 96, np.uint8)
    noise = self._rng.normal(0, 255 * self._noise, (size, size, 3))
    image = np.clip(image + noise, 0, 255).astype(np.uint8)
    # Block: red square.
    bx, by = self._world_to_pixel(self._block)
    e = max(1, size // 16)
    image[max(0, by - e):by + e + 1, max(0, bx - e):bx + e + 1] = (
        np.array([200, 40, 40], np.uint8))
    # Gripper: green dot (brighter when closed).
    gx, gy = self._world_to_pixel(self._gripper)
    g = max(1, size // 24)
    color = np.array([40, 230 if self._closed else 160, 40], np.uint8)
    image[max(0, gy - g):gy + g + 1, max(0, gx - g):gx + g + 1] = color
    return {
        "image": image,
        "gripper_pose": np.array(
            [self._gripper[0], self._gripper[1], self._closed],
            np.float32),
    }


def collect_expert_episode(env: VRGripperEnv,
                           task_offset: Optional[np.ndarray] = None,
                           action_noise: float = 0.0,
                           min_steps: int = 1,
                           rng: Optional[np.random.Generator] = None,
                           ) -> Dict[str, np.ndarray]:
  """Rolls the scripted expert; returns a [T, ...] episode dict.

  `min_steps` keeps recording hold-in-place grasp steps after success
  until the episode has at least that many timesteps (capped by the
  env's max_steps) — consumers that split episodes into condition/
  inference sets need a guaranteed minimum length.
  """
  rng = rng or np.random.default_rng(0)
  obs = env.reset(task_offset=task_offset)
  images, poses, actions, rewards = [], [], [], []
  done = False
  while not done or len(actions) < min(min_steps, env.max_steps):
    action = env.expert_action()
    if action_noise > 0:
      action = np.clip(
          action + rng.normal(0, action_noise, 3).astype(np.float32),
          -1.0, 1.0)
    images.append(obs["image"])
    poses.append(obs["gripper_pose"])
    actions.append(action.astype(np.float32))
    obs, reward, done = env.step(action)
    rewards.append(np.array([reward], np.float32))
    if len(actions) >= env.max_steps:
      break
  return {
      "image": np.stack(images),
      "gripper_pose": np.stack(poses),
      "action": np.stack(actions),
      "reward": np.stack(rewards),
  }


@gin.configurable
def collect_demo_episodes(
    output_path: str,
    num_episodes: int = 100,
    image_size: int = IMAGE_SIZE,
    seed: int = 0,
    action_noise: float = 0.05,
    task_offset_scale: float = 0.0,
    min_episode_steps: int = 8,
) -> str:
  """Writes scripted-expert episodes as SequenceExample TFRecords.

  The wire layout matches VRGripperRegressionModel's specs lifted to
  sequences (image/gripper_pose per step as features, action per step
  as label) — the role of the reference's recorded VR demo datasets.
  `min_episode_steps` defaults to 8 so the shipped meta configs'
  4 condition + 4 inference splits always fit inside real data.
  """
  from tensor2robot_tpu.data.abstract_input_generator import Mode
  from tensor2robot_tpu.data.tfrecord_input_generator import (
      write_episode_tfrecord,
  )
  from tensor2robot_tpu.research.vrgripper.vrgripper_models import (
      VRGripperRegressionModel,
  )
  from tensor2robot_tpu.specs import as_sequence_specs

  env = VRGripperEnv(image_size=image_size, seed=seed,
                     task_offset_scale=task_offset_scale)
  rng = np.random.default_rng(seed + 1)
  episodes = [
      collect_expert_episode(env, action_noise=action_noise,
                             min_steps=min_episode_steps, rng=rng)
      for _ in range(num_episodes)
  ]
  model = VRGripperRegressionModel(image_size=image_size)
  os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
  write_episode_tfrecord(
      output_path, episodes,
      as_sequence_specs(model.get_feature_specification(Mode.TRAIN)),
      as_sequence_specs(model.get_label_specification(Mode.TRAIN)))
  return output_path


def _sample_steps(episode: Dict[str, np.ndarray], n: int,
                  rng: np.random.Generator) -> Dict[str, np.ndarray]:
  """Samples n timesteps (with replacement when the episode is short)."""
  t = len(episode["action"])
  idx = np.sort(rng.choice(t, size=n, replace=t < n))
  return {k: v[idx] for k, v in episode.items()}


def sample_wtl_meta_batch(
    num_tasks: int,
    num_condition: int = 4,
    num_trial: int = 4,
    num_inference: int = 4,
    image_size: int = IMAGE_SIZE,
    seed: int = 0,
    task_offset_scale: float = 0.15,
    trial_noise: float = 0.4,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
  """Builds one Watch-Try-Learn meta batch from scripted rollouts.

  Per task (a random offset the policy can only learn from the demo):
  a clean expert demo (condition), a noisy suboptimal rollout with its
  rewards (trial), and held-out expert steps to imitate (inference).
  Returns flat (features, labels) dicts matching VRGripperWTLModel's
  retrial specs; trial keys are simply dropped for the trial policy.
  """
  rng = np.random.default_rng(seed)
  env = VRGripperEnv(image_size=image_size, seed=seed)
  f: Dict[str, List[np.ndarray]] = {}
  l: Dict[str, List[np.ndarray]] = {}

  def put(store, key, value):
    store.setdefault(key, []).append(value)

  for _ in range(num_tasks):
    offset = rng.uniform(-task_offset_scale, task_offset_scale,
                         2).astype(np.float32)
    demo = _sample_steps(
        collect_expert_episode(env, task_offset=offset, rng=rng),
        num_condition, rng)
    trial = _sample_steps(
        collect_expert_episode(env, task_offset=offset,
                               action_noise=trial_noise, rng=rng),
        num_trial, rng)
    query = _sample_steps(
        collect_expert_episode(env, task_offset=offset, rng=rng),
        num_inference, rng)
    put(f, "condition/image", demo["image"])
    put(f, "condition/gripper_pose", demo["gripper_pose"])
    put(f, "trial/image", trial["image"])
    put(f, "trial/gripper_pose", trial["gripper_pose"])
    put(f, "trial/action", trial["action"])
    put(f, "trial/reward", trial["reward"])
    put(f, "inference/image", query["image"])
    put(f, "inference/gripper_pose", query["gripper_pose"])
    put(l, "condition/action", demo["action"])
    put(l, "inference/action", query["action"])

  features = {k: np.stack(v) for k, v in f.items()}
  labels = {k: np.stack(v) for k, v in l.items()}
  return features, labels


@gin.configurable
def evaluate_gripper_policy(
    predict_fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]],
    num_episodes: int = 50,
    image_size: int = IMAGE_SIZE,
    seed: int = 1,
    task_offset_scale: float = 0.0,
    action_key: str = "action",
) -> Dict[str, float]:
  """Closed-loop policy rollout; returns success rate + final distance.

  `predict_fn` maps a batched feature dict {image, gripper_pose} to an
  output dict containing the action (the predictor API). Stateful
  policies (e.g. full-history transformer policies) expose a
  `.reset()` method, called at each episode boundary.
  """
  env = VRGripperEnv(image_size=image_size, seed=seed,
                     task_offset_scale=task_offset_scale)
  successes, final_dists = [], []
  for _ in range(num_episodes):
    obs = env.reset()
    if hasattr(predict_fn, "reset"):
      predict_fn.reset()
    done = False
    while not done:
      batch = {"image": obs["image"][None],
               "gripper_pose": obs["gripper_pose"][None]}
      out = predict_fn(batch)
      value = out.get(action_key, next(iter(out.values())))
      action = np.asarray(value)[0].reshape(-1)[:3]
      obs, _, done = env.step(action)
    successes.append(float(env.success()))
    final_dists.append(
        float(np.linalg.norm(
            obs["gripper_pose"][:2] - env.target)))
  return {
      "success_rate": float(np.mean(successes)),
      "mean_final_distance": float(np.mean(final_dists)),
      "num_episodes": float(num_episodes),
  }
