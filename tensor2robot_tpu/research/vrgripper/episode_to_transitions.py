"""Episode → transition munging for behavioral cloning.

Reference parity: tensor2robot `research/vrgripper/
episode_to_transitions.py` — the data-munging layer turning recorded
demo episodes into flat per-timestep transitions for BC training
(SURVEY.md §3 "VRGripper / WTL"; file:line unavailable — empty
reference mount).

Host-side numpy only: padding is masked out using the parser's true
episode lengths (a zero-padded timestep must never become a training
transition), and flat transitions are re-batched to the trainer's
requested batch size. The device never sees ragged data — batches stay
static-shaped for XLA.
"""

from __future__ import annotations

import warnings
from typing import Iterator, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.data.tfexample import SEQUENCE_LENGTH_KEY
from tensor2robot_tpu.specs import TensorSpecStruct, as_sequence_specs


def episode_batch_to_transitions(
    features: TensorSpecStruct,
    labels: Optional[TensorSpecStruct],
    sequence_keys: Optional[frozenset] = None,
) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]:
  """Flattens [B, T, ...] episode batches into [N, ...] transitions.

  Only real timesteps survive: the `sequence_length` feature (true
  pre-pad lengths from the episode parser) masks out padding. Without
  it, every timestep is assumed real. Keys without a time axis
  (per-episode context) are repeated across their episode's timesteps.

  Args:
    features: [B, T, ...] episode feature batch.
    labels: matching label batch, or None.
    sequence_keys: flat keys known (from specs) to carry a time axis.
      When given, the time axis comes from a sequence key and context vs
      sequence classification is exact. When None, the time axis falls
      back to the first rank>=2 value — ambiguous if a [B, D] context
      key precedes every sequence key — and a RuntimeWarning fires so
      the guess never goes unnoticed. Spec-aware callers (derive the
      set from `get_feature_specification(...).is_sequence`, as
      `TransitionInputGenerator` does) should always pass it.
  """
  flat_f = features.to_flat_dict()
  lengths = flat_f.pop(SEQUENCE_LENGTH_KEY, None)
  anchor = None
  if sequence_keys:
    anchor = next((v for k, v in flat_f.items() if k in sequence_keys),
                  None)
    if labels is not None and anchor is None:
      anchor = next((v for k, v in labels.to_flat_dict().items()
                     if k in sequence_keys), None)
  if anchor is None:
    anchor_key, anchor = next(
        ((k, v) for k, v in flat_f.items() if v.ndim >= 2),
        next(iter(flat_f.items())))
    if sequence_keys:
      reason = (f"sequence_keys={sorted(sequence_keys)!r} matched no "
                f"feature/label key (present: {sorted(flat_f)!r}) — "
                "likely a flat-name mismatch")
    else:
      reason = "called without sequence_keys"
    warnings.warn(
        f"episode_batch_to_transitions {reason}: guessing the time "
        f"axis from {anchor_key!r} (first rank>=2 value). A [B, D] "
        "per-episode context key ahead of the sequence keys makes "
        "this guess WRONG silently — pass sequence_keys derived from "
        "the model's specs (spec.is_sequence).",
        RuntimeWarning, stacklevel=2)
  batch, time = anchor.shape[0], anchor.shape[1] if anchor.ndim > 1 else 1
  if lengths is None:
    mask = np.ones((batch, time), bool)
  else:
    mask = (np.arange(time)[None, :]
            < np.asarray(lengths).reshape(batch, 1))
  mask_flat = mask.reshape(-1)

  def flatten(struct_flat):
    out = {}
    for key, value in struct_flat.items():
      is_seq = (key in sequence_keys if sequence_keys is not None
                else value.ndim >= 2 and value.shape[:2] == (batch, time))
      if is_seq:
        if value.shape[:2] != (batch, time):
          raise ValueError(
              f"{key!r} declared a sequence but has shape {value.shape}; "
              f"expected leading dims {(batch, time)}.")
        flat = value.reshape((batch * time,) + value.shape[2:])
      else:
        # Per-episode context: repeat across the episode's timesteps.
        flat = np.repeat(value, time, axis=0)
      out[key] = flat[mask_flat]
    return TensorSpecStruct.from_flat_dict(out)

  out_labels = None
  if labels is not None:
    out_labels = flatten(labels.to_flat_dict())
  return flatten(flat_f), out_labels


@gin.configurable
class TransitionInputGenerator(AbstractInputGenerator):
  """Re-batches an episode generator's output into transition batches.

  Reference parity: the episode_to_transitions input pipelines. Wraps
  any episode generator ([B, T, ...] batches + true lengths); yields
  flat [batch_size, ...] transition batches, buffering across episode
  boundaries so every batch is full (XLA static shapes).
  """

  def __init__(self,
               episode_generator: AbstractInputGenerator,
               batch_size: int = 32,
               shuffle_transitions: bool = True,
               seed: Optional[int] = None):
    super().__init__(batch_size=batch_size)
    self._episodes = episode_generator
    self._shuffle = shuffle_transitions
    self._seed = seed
    self._sequence_keys: Optional[frozenset] = None

  def set_specification_from_model(self, model, mode: Mode) -> None:
    # The model consumes flat transitions; the wire carries episodes of
    # the same keys, so the episode generator gets the specs lifted to
    # sequences.
    preprocessor = getattr(model, "preprocessor", None)
    if preprocessor is not None:
      feat = preprocessor.get_in_feature_specification(mode)
      label = preprocessor.get_in_label_specification(mode)
    else:
      feat = model.get_feature_specification(mode)
      label = model.get_label_specification(mode)
    self._episodes.set_specification(
        as_sequence_specs(feat),
        as_sequence_specs(label) if label is not None else None)
    self._sequence_keys = frozenset(feat.to_flat_dict()) | frozenset(
        label.to_flat_dict() if label is not None else ())
    self.set_specification(feat, label)

  def _create_dataset(self, mode: Mode, batch_size: int
                      ) -> Iterator[Tuple[TensorSpecStruct,
                                          Optional[TensorSpecStruct]]]:
    rng = np.random.default_rng(self._seed)
    buf_f: dict = {}
    buf_l: Optional[dict] = None
    episode_batch = max(1, batch_size // 4)
    for ep_features, ep_labels in self._episodes.create_dataset(
        mode, batch_size=episode_batch):
      features, labels = episode_batch_to_transitions(
          ep_features, ep_labels, sequence_keys=self._sequence_keys)
      flat_f = features.to_flat_dict()
      for k, v in flat_f.items():
        buf_f.setdefault(k, []).append(v)
      if labels is not None:
        buf_l = buf_l or {}
        for k, v in labels.to_flat_dict().items():
          buf_l.setdefault(k, []).append(v)
      count = sum(a.shape[0] for a in buf_f[next(iter(buf_f))])
      while count >= batch_size:
        joined_f = {k: np.concatenate(v) for k, v in buf_f.items()}
        joined_l = ({k: np.concatenate(v) for k, v in buf_l.items()}
                    if buf_l else None)
        if self._shuffle:
          perm = rng.permutation(count)
          joined_f = {k: v[perm] for k, v in joined_f.items()}
          if joined_l is not None:
            joined_l = {k: v[perm] for k, v in joined_l.items()}
        out_f = {k: v[:batch_size] for k, v in joined_f.items()}
        out_l = ({k: v[:batch_size] for k, v in joined_l.items()}
                 if joined_l is not None else None)
        buf_f = {k: [v[batch_size:]] for k, v in joined_f.items()}
        if joined_l is not None:
          buf_l = {k: [v[batch_size:]] for k, v in joined_l.items()}
        count -= batch_size
        yield (TensorSpecStruct.from_flat_dict(out_f),
               TensorSpecStruct.from_flat_dict(out_l)
               if out_l is not None else None)
