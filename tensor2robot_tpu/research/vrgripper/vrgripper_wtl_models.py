"""Watch-Try-Learn: trial-conditioned gripper policies.

Reference parity: tensor2robot `research/vrgripper/
vrgripper_env_wtl_models.py` — Watch-Try-Learn (Zhou et al. 2019,
arXiv:1906.03352): a TRIAL policy conditioned on a watched
demonstration proposes an attempt; a RETRIAL policy conditioned on the
demonstration AND the executed trial (with its rewards) improves on it
(SURVEY.md §3 "VRGripper / WTL"; file:line unavailable — empty
reference mount).

TPU-first: episode embeddings are mean-pooled per-step encodings with
the step dim folded into the batch dim (one conv batch for all tasks ×
steps — MXU-sized), conditioning is plain concatenation, everything
static-shaped. Both policies are one class: `policy_type='trial'`
drops the trial split from the specs and the network.

Meta-batch layout (B tasks):
  features.condition/…   demo observations     [B, N_demo, …]
  features.trial/…       trial obs + action + reward  [B, N_trial, …]
                         (retrial policy only; actions/rewards are
                         features — the robot executed and observed them)
  features.inference/…   query observations    [B, N_query, …]
  labels.condition/action  demo actions [B, N_demo, A]
  labels.inference/action  target actions [B, N_query, A]
At predict time demo actions ride in features under
condition_labels/action (optional ⇒ absent = unconditioned), the same
serving convention as the MAML/SNAIL models.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.layers import MLP
from tensor2robot_tpu.layers.mdn import MDNHead, mdn_mode
from tensor2robot_tpu.meta_learning.maml_model import (
    CONDITION,
    CONDITION_LABELS,
    INFERENCE,
)
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.regression_model import INFERENCE_OUTPUT
from tensor2robot_tpu.research.vrgripper.vrgripper_models import (
    ACTION,
    GripperObsEncoder,
    action_supervision_loss,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

TRIAL = "trial"
REWARD = "reward"

TRIAL_POLICY = "trial"
RETRIAL_POLICY = "retrial"


class _WTLPolicyNet(nn.Module):
  """Demo (+ trial) episode embeddings conditioning a query policy."""

  action_dim: int
  num_condition: int
  num_trial: int  # 0 for the trial policy (no trial conditioning)
  num_inference: int
  filters: Sequence[int]
  embedding_size: int
  hidden_sizes: Sequence[int]
  num_mixture_components: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False):
    num_tasks = jax.tree_util.tree_leaves(
        features[CONDITION])[0].shape[0]
    encoder = GripperObsEncoder(
        filters=tuple(self.filters),
        embedding_size=self.embedding_size,
        use_batch_norm=False, dtype=self.dtype, name="obs_encoder")

    def encode(split, n):
      folded = jax.tree_util.tree_map(
          lambda x: x.reshape((num_tasks * n,) + x.shape[2:]), split)
      return encoder(folded, train=train).reshape(num_tasks, n, -1)

    flat = features.to_flat_dict()

    # Demonstration embedding: per-step [obs_emb ‖ action] → MLP →
    # mean over steps (order-invariant, static-shaped).
    cond_emb = encode(features[CONDITION], self.num_condition)
    demo_key = f"{CONDITION_LABELS}/{ACTION}"
    if demo_key in flat:
      demo_actions = flat[demo_key].astype(self.dtype)
    else:
      demo_actions = jnp.zeros(
          (num_tasks, self.num_condition, self.action_dim), self.dtype)
    demo_step = jnp.concatenate(
        [cond_emb.astype(self.dtype), demo_actions], axis=-1)
    demo_embed = MLP(hidden_sizes=(self.embedding_size,),
                     output_size=self.embedding_size, dtype=self.dtype,
                     name="demo_embed")(
        demo_step.reshape(num_tasks * self.num_condition, -1),
        train=train).reshape(num_tasks, self.num_condition, -1)
    demo_embed = jnp.mean(demo_embed, axis=1)  # [B, E]

    context = [demo_embed.astype(self.dtype)]

    if self.num_trial > 0:
      trial = features[TRIAL]
      trial_obs = TensorSpecStruct.from_flat_dict(
          {k: v for k, v in trial.to_flat_dict().items()
           if k not in (ACTION, REWARD)})
      trial_emb = encode(trial_obs, self.num_trial)
      trial_step = jnp.concatenate([
          trial_emb.astype(self.dtype),
          trial[ACTION].astype(self.dtype),
          trial[REWARD].astype(self.dtype),
      ], axis=-1)
      trial_embed = MLP(hidden_sizes=(self.embedding_size,),
                        output_size=self.embedding_size,
                        dtype=self.dtype, name="trial_embed")(
          trial_step.reshape(num_tasks * self.num_trial, -1),
          train=train).reshape(num_tasks, self.num_trial, -1)
      context.append(jnp.mean(trial_embed, axis=1).astype(self.dtype))

    # Query policy: [query_emb ‖ context…] → trunk → action head.
    inf_emb = encode(features[INFERENCE], self.num_inference)
    ctx = jnp.concatenate(context, axis=-1)[:, None, :]
    ctx = jnp.broadcast_to(
        ctx, (num_tasks, self.num_inference, ctx.shape[-1]))
    query = jnp.concatenate([inf_emb.astype(self.dtype), ctx], axis=-1)
    trunk = MLP(hidden_sizes=tuple(self.hidden_sizes),
                output_size=None, activate_final=True,
                dtype=self.dtype, name="trunk")(
        query.reshape(num_tasks * self.num_inference, -1), train=train)

    if self.num_mixture_components > 0:
      params = MDNHead(num_components=self.num_mixture_components,
                       output_size=self.action_dim, dtype=self.dtype,
                       name="mdn_head")(trunk)
      reshape = lambda a: a.reshape(  # noqa: E731
          (num_tasks, self.num_inference) + a.shape[1:])
      action = reshape(mdn_mode(params))
      return {ACTION: action, INFERENCE_OUTPUT: action,
              "mdn_logits": reshape(params.logits),
              "mdn_means": reshape(params.means),
              "mdn_log_scales": reshape(params.log_scales)}
    action = nn.Dense(self.action_dim, dtype=self.dtype,
                      name="action_head")(trunk)
    action = action.astype(jnp.float32).reshape(
        num_tasks, self.num_inference, self.action_dim)
    return {ACTION: action, INFERENCE_OUTPUT: action}


@gin.configurable
class VRGripperWTLModel(AbstractT2RModel):
  """Watch-Try-Learn policy (`policy_type`: 'trial' or 'retrial')."""

  def __init__(self,
               policy_type: str = RETRIAL_POLICY,
               image_size: int = 48,
               state_dim: int = 3,
               action_dim: int = 3,
               filters: Sequence[int] = (16, 32),
               embedding_size: int = 64,
               hidden_sizes: Sequence[int] = (64,),
               num_mixture_components: int = 0,
               num_condition_samples_per_task: int = 4,
               num_trial_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               device_dtype=jnp.bfloat16,
               **kwargs):
    if policy_type not in (TRIAL_POLICY, RETRIAL_POLICY):
      raise ValueError(f"Unknown policy_type: {policy_type!r}")
    super().__init__(device_dtype=device_dtype, **kwargs)
    self._policy_type = policy_type
    self._image_size = image_size
    self._state_dim = state_dim
    self._action_dim = action_dim
    self._filters = tuple(filters)
    self._embedding_size = embedding_size
    self._hidden_sizes = tuple(hidden_sizes)
    self._num_mixture_components = num_mixture_components
    self._num_condition = num_condition_samples_per_task
    self._num_trial = (num_trial_samples_per_task
                       if policy_type == RETRIAL_POLICY else 0)
    self._num_inference = num_inference_samples_per_task

  @property
  def policy_type(self) -> str:
    return self._policy_type

  def _obs_specs(self, n: int, prefix: str) -> Dict[str, Any]:
    return {
        "image": ExtendedTensorSpec(
            shape=(n, self._image_size, self._image_size, 3),
            dtype=np.uint8, name=f"{prefix}_image"),
        "gripper_pose": ExtendedTensorSpec(
            shape=(n, self._state_dim), dtype=np.float32,
            name=f"{prefix}_gripper_pose"),
    }

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    flat = {}
    for key, spec in self._obs_specs(self._num_condition,
                                     CONDITION).items():
      flat[f"{CONDITION}/{key}"] = spec
    if self._num_trial > 0:
      for key, spec in self._obs_specs(self._num_trial, TRIAL).items():
        flat[f"{TRIAL}/{key}"] = spec
      flat[f"{TRIAL}/{ACTION}"] = ExtendedTensorSpec(
          shape=(self._num_trial, self._action_dim), dtype=np.float32,
          name="trial_action")
      flat[f"{TRIAL}/{REWARD}"] = ExtendedTensorSpec(
          shape=(self._num_trial, 1), dtype=np.float32,
          name="trial_reward")
    for key, spec in self._obs_specs(self._num_inference,
                                     INFERENCE).items():
      flat[f"{INFERENCE}/{key}"] = spec
    if mode == Mode.PREDICT:
      # Demo actions for serving-time conditioning (absent ⇒ zeros).
      flat[f"{CONDITION_LABELS}/{ACTION}"] = ExtendedTensorSpec(
          shape=(self._num_condition, self._action_dim),
          dtype=np.float32, name="condition_action", is_optional=True)
    return TensorSpecStruct.from_flat_dict(flat)

  def get_label_specification(self, mode: Mode) -> TensorSpecStruct:
    flat = {
        f"{CONDITION}/{ACTION}": ExtendedTensorSpec(
            shape=(self._num_condition, self._action_dim),
            dtype=np.float32, name="demo_action"),
        f"{INFERENCE}/{ACTION}": ExtendedTensorSpec(
            shape=(self._num_inference, self._action_dim),
            dtype=np.float32, name="target_action"),
    }
    return TensorSpecStruct.from_flat_dict(flat)

  def create_network(self) -> nn.Module:
    return _WTLPolicyNet(
        action_dim=self._action_dim,
        num_condition=self._num_condition,
        num_trial=self._num_trial,
        num_inference=self._num_inference,
        filters=self._filters,
        embedding_size=self._embedding_size,
        hidden_sizes=self._hidden_sizes,
        num_mixture_components=self._num_mixture_components,
        dtype=self.device_dtype,
    )

  def network_inputs_from_labels(self, features, labels, mode):
    """Demo actions are conditioning INPUT: lift them from labels into
    the feature struct (predict-time they arrive via condition_labels
    directly — the shared serving convention)."""
    if labels is None:
      return features
    flat = features.to_flat_dict()
    flat[f"{CONDITION_LABELS}/{ACTION}"] = labels[CONDITION][ACTION]
    return TensorSpecStruct.from_flat_dict(flat)

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    return action_supervision_loss(outputs, labels[INFERENCE][ACTION])
