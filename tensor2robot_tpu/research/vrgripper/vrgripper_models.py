"""VRGripper behavioral-cloning policies: MSE and MDN heads.

Reference parity: tensor2robot `research/vrgripper/
vrgripper_env_models.py` — behavioral cloning from demonstration
transitions with plain-regression and mixture-density (MDN) action
heads (SURVEY.md §3 "VRGripper / WTL"; file:line unavailable — empty
reference mount; the reference's MDN head lived on tfp, ours is the
in-repo jnp MDN from layers/mdn.py).

TPU-first: uint8 images cross the host→device boundary and normalize
on device (the cast fuses into the first conv); the policy torso is a
ConvTower + spatial softmax (keypoints are the right pooling for
"where is the block / where am I"), state features concatenate after
pooling; everything static-shaped, bf16 activations on the MXU.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.layers import ImageEncoder, MLP
from tensor2robot_tpu.layers.mdn import (
    MDNHead,
    MDNParams,
    mdn_loss,
    mdn_mode,
    mdn_sample,
)
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.regression_model import INFERENCE_OUTPUT
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

ACTION = "action"
# Auxiliary output keys for the MDN head (mixture parameters ride along
# so serving-side samplers can draw their own actions).
MDN_LOGITS = "mdn_logits"
MDN_MEANS = "mdn_means"
MDN_LOG_SCALES = "mdn_log_scales"


class GripperObsEncoder(nn.Module):
  """{image, gripper_pose} → embedding vector.

  Shared torso for every vrgripper policy (BC, meta-BC, WTL): conv
  tower + spatial softmax over the image, proprioceptive state
  concatenated after pooling, joint MLP projection.
  """

  filters: Sequence[int] = (32, 64)
  embedding_size: int = 64
  use_batch_norm: bool = False
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False) -> jax.Array:
    image = features["image"]
    x = image.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
    emb = ImageEncoder(
        filters=tuple(self.filters),
        embedding_size=self.embedding_size,
        pooling="spatial_softmax",
        use_batch_norm=self.use_batch_norm,
        dtype=self.dtype,
        name="image_encoder",
    )(x, train=train)
    state = features["gripper_pose"].astype(self.dtype)
    joint = jnp.concatenate([emb, state], axis=-1)
    return nn.Dense(self.embedding_size, dtype=self.dtype,
                    name="joint_proj")(joint)


class _GripperPolicyNet(nn.Module):
  """Observation encoder + action head (plain or mixture-density)."""

  action_dim: int
  filters: Sequence[int]
  embedding_size: int
  hidden_sizes: Sequence[int]
  num_mixture_components: int  # 0 = plain MSE head
  use_batch_norm: bool
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False):
    emb = GripperObsEncoder(
        filters=tuple(self.filters),
        embedding_size=self.embedding_size,
        use_batch_norm=self.use_batch_norm,
        dtype=self.dtype,
        name="obs_encoder",
    )(features, train=train)
    trunk = MLP(hidden_sizes=tuple(self.hidden_sizes),
                output_size=None, activate_final=True, dtype=self.dtype,
                name="trunk")(emb, train=train)
    if self.num_mixture_components > 0:
      params = MDNHead(
          num_components=self.num_mixture_components,
          output_size=self.action_dim, dtype=self.dtype,
          name="mdn_head")(trunk)
      action = mdn_mode(params)
      return {
          ACTION: action,
          INFERENCE_OUTPUT: action,
          MDN_LOGITS: params.logits,
          MDN_MEANS: params.means,
          MDN_LOG_SCALES: params.log_scales,
      }
    action = nn.Dense(self.action_dim, dtype=self.dtype,
                      name="action_head")(trunk)
    action = action.astype(jnp.float32)
    return {ACTION: action, INFERENCE_OUTPUT: action}


def mdn_params_from_outputs(outputs) -> Optional[MDNParams]:
  """Recovers mixture parameters from a policy's output dict."""
  if MDN_LOGITS not in outputs:
    return None
  return MDNParams(outputs[MDN_LOGITS], outputs[MDN_MEANS],
                   outputs[MDN_LOG_SCALES])


def action_supervision_loss(outputs, target
                            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
  """(loss, metrics) for action cloning: MDN NLL when the output dict
  carries mixture params, MSE otherwise. The one action-supervision
  implementation every gripper policy (BC, WTL, SNAIL) shares."""
  target = target.astype(jnp.float32)
  predicted = outputs[ACTION].astype(jnp.float32)
  action_error = jnp.mean(jnp.abs(predicted - target))
  params = mdn_params_from_outputs(outputs)
  if params is not None:
    loss = mdn_loss(params, target)
    return loss, {"nll": loss, "action_error": action_error}
  loss = jnp.mean(jnp.square(predicted - target))
  return loss, {"mse": loss, "action_error": action_error}


@gin.configurable
class VRGripperRegressionModel(AbstractT2RModel):
  """BC policy: clone expert actions from (image, gripper_pose).

  `num_mixture_components=0` gives the plain MSE regression policy;
  `>0` the MDN policy (NLL loss, greedy-mode action at predict time) —
  the reference's two vrgripper_env_models heads as one configurable.
  """

  def __init__(self,
               image_size: int = 48,
               state_dim: int = 3,
               action_dim: int = 3,
               filters: Sequence[int] = (32, 64),
               embedding_size: int = 64,
               hidden_sizes: Sequence[int] = (64,),
               num_mixture_components: int = 0,
               use_batch_norm: bool = False,
               device_dtype=jnp.bfloat16,
               **kwargs):
    super().__init__(device_dtype=device_dtype, **kwargs)
    self._image_size = image_size
    self._state_dim = state_dim
    self._action_dim = action_dim
    self._filters = tuple(filters)
    self._embedding_size = embedding_size
    self._hidden_sizes = tuple(hidden_sizes)
    self._num_mixture_components = num_mixture_components
    self._use_batch_norm = use_batch_norm

  @property
  def action_dim(self) -> int:
    return self._action_dim

  @property
  def uses_mdn(self) -> bool:
    return self._num_mixture_components > 0

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(
        shape=(self._image_size, self._image_size, 3), dtype=np.uint8,
        name="image", data_format="png")
    st.gripper_pose = ExtendedTensorSpec(
        shape=(self._state_dim,), dtype=np.float32, name="gripper_pose")
    return st

  def get_label_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.action = ExtendedTensorSpec(
        shape=(self._action_dim,), dtype=np.float32, name=ACTION)
    return st

  def create_network(self) -> nn.Module:
    return _GripperPolicyNet(
        action_dim=self._action_dim,
        filters=self._filters,
        embedding_size=self._embedding_size,
        hidden_sizes=self._hidden_sizes,
        num_mixture_components=self._num_mixture_components,
        use_batch_norm=self._use_batch_norm,
        dtype=self.device_dtype,
    )

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    return action_supervision_loss(outputs, labels[ACTION])

  def sample_action(self, state, features, rng: jax.Array) -> jax.Array:
    """Draws a stochastic action (MDN) or returns the mean (MSE)."""
    outputs = self.predict_step(state, features)
    params = mdn_params_from_outputs(outputs)
    if params is None:
      return outputs[ACTION]
    return mdn_sample(params, rng)
