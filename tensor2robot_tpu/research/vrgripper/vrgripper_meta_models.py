"""VRGripper meta-BC: MAML and SNAIL (in-context) variants.

Reference parity: tensor2robot `research/vrgripper/
vrgripper_env_meta_models.py` — behavioral cloning wrapped for
meta-learning: gradient-based adaptation (MAML) and in-context
conditioning over demonstration sequences (SNAIL/TEC-style)
(SURVEY.md §3 "VRGripper / WTL"; file:line unavailable — empty
reference mount).

TPU-first: the MAML variant inherits the scanned-`jax.grad` inner loop
(one XLA program, second-order for free); the SNAIL variant runs the
shared observation encoder over ALL task steps folded into the batch
dim (one big MXU-friendly conv batch), then one causal SNAIL trunk
over [demo steps ‖ query steps] — demonstrations condition queries
through attention, no per-task python, fully static shapes.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.layers import SNAIL
from tensor2robot_tpu.layers.mdn import MDNHead, mdn_mode
from tensor2robot_tpu.meta_learning import MAMLModel
from tensor2robot_tpu.meta_learning.maml_model import (
    CONDITION,
    CONDITION_LABELS,
    INFERENCE,
)
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.regression_model import INFERENCE_OUTPUT
from tensor2robot_tpu.research.vrgripper.vrgripper_models import (
    ACTION,
    GripperObsEncoder,
    VRGripperRegressionModel,
    action_supervision_loss,
)
from tensor2robot_tpu.specs import TensorSpecStruct


@gin.configurable
class VRGripperMAMLModel(MAMLModel):
  """MAML over the (BN-free) gripper BC policy.

  Per-task demonstrations adapt the policy by K inner gradient steps;
  the adapted policy is scored on held-out steps of the same task.
  """

  def __init__(self,
               image_size: int = 48,
               state_dim: int = 3,
               action_dim: int = 3,
               filters: Sequence[int] = (16, 32),
               embedding_size: int = 64,
               hidden_sizes: Sequence[int] = (64,),
               num_mixture_components: int = 0,
               num_inner_steps: int = 1,
               inner_lr: float = 0.05,
               first_order: bool = False,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               **kwargs):
    base = VRGripperRegressionModel(
        image_size=image_size, state_dim=state_dim,
        action_dim=action_dim, filters=filters,
        embedding_size=embedding_size, hidden_sizes=hidden_sizes,
        num_mixture_components=num_mixture_components,
        use_batch_norm=False)
    super().__init__(
        base_model=base,
        num_inner_steps=num_inner_steps,
        inner_lr=inner_lr,
        first_order=first_order,
        num_condition_samples_per_task=num_condition_samples_per_task,
        num_inference_samples_per_task=num_inference_samples_per_task,
        **kwargs)


class _SNAILMetaPolicy(nn.Module):
  """Demo-conditioned policy: encoder per step, SNAIL across steps.

  Input: the meta feature struct (condition/…, inference/…, optionally
  condition_labels/action). Demo steps enter the sequence with their
  actions appended (+1 presence flag); query steps with zeros. The
  causal trunk lets each query attend to the full demonstration and to
  earlier queries. Output: per-query action (or MDN params).
  """

  action_dim: int
  num_condition: int
  num_inference: int
  filters: Sequence[int]
  embedding_size: int
  snail_filters: int
  num_mixture_components: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False):
    cond = features[CONDITION]
    inf = features[INFERENCE]
    num_tasks = jax.tree_util.tree_leaves(cond)[0].shape[0]
    n_c, n_i = self.num_condition, self.num_inference

    encoder = GripperObsEncoder(
        filters=tuple(self.filters),
        embedding_size=self.embedding_size,
        use_batch_norm=False,
        dtype=self.dtype,
        name="obs_encoder")

    def encode(split, n):
      folded = jax.tree_util.tree_map(
          lambda x: x.reshape((num_tasks * n,) + x.shape[2:]), split)
      emb = encoder(folded, train=train)
      return emb.reshape(num_tasks, n, -1)

    cond_emb = encode(cond, n_c)
    inf_emb = encode(inf, n_i)

    # Demo actions ride along when provided (training labels at train
    # time, condition_labels at predict time); zeros at init.
    flat = features.to_flat_dict()
    demo_key = f"{CONDITION_LABELS}/{ACTION}"
    if demo_key in flat:
      demo_actions = flat[demo_key].astype(self.dtype)
    else:
      demo_actions = jnp.zeros((num_tasks, n_c, self.action_dim),
                               self.dtype)
    ones = jnp.ones((num_tasks, n_c, 1), self.dtype)
    zeros_a = jnp.zeros((num_tasks, n_i, self.action_dim), self.dtype)
    zeros_f = jnp.zeros((num_tasks, n_i, 1), self.dtype)
    cond_in = jnp.concatenate(
        [cond_emb.astype(self.dtype), demo_actions, ones], axis=-1)
    inf_in = jnp.concatenate([inf_emb.astype(self.dtype), zeros_a,
                              zeros_f], axis=-1)
    seq = jnp.concatenate([cond_in, inf_in], axis=1)

    out = SNAIL(seq_len=n_c + n_i, filters=self.snail_filters,
                dtype=self.dtype, name="snail_trunk")(seq)
    query = out[:, n_c:, :]  # [B, n_i, D]

    if self.num_mixture_components > 0:
      params = MDNHead(num_components=self.num_mixture_components,
                       output_size=self.action_dim, dtype=self.dtype,
                       name="mdn_head")(query)
      action = mdn_mode(params)
      return {ACTION: action, INFERENCE_OUTPUT: action,
              "mdn_logits": params.logits, "mdn_means": params.means,
              "mdn_log_scales": params.log_scales}
    action = nn.Dense(self.action_dim, dtype=self.dtype,
                      name="action_head")(query).astype(jnp.float32)
    return {ACTION: action, INFERENCE_OUTPUT: action}


@gin.configurable
class VRGripperSNAILModel(MAMLModel):
  """In-context meta-BC: demonstrations condition through attention.

  Reuses MAMLModel's meta spec layout and preprocessor (condition/
  inference splits; predict-time demonstration actions under
  condition_labels) but replaces gradient adaptation with a SNAIL
  trunk — the reference's SNAIL/TEC-style vrgripper meta policies.
  """

  def __init__(self,
               image_size: int = 48,
               state_dim: int = 3,
               action_dim: int = 3,
               filters: Sequence[int] = (16, 32),
               embedding_size: int = 64,
               snail_filters: int = 32,
               num_mixture_components: int = 0,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               **kwargs):
    base = VRGripperRegressionModel(
        image_size=image_size, state_dim=state_dim,
        action_dim=action_dim, filters=filters,
        embedding_size=embedding_size,
        num_mixture_components=num_mixture_components,
        use_batch_norm=False)
    super().__init__(
        base_model=base,
        num_condition_samples_per_task=num_condition_samples_per_task,
        num_inference_samples_per_task=num_inference_samples_per_task,
        **kwargs)
    self._action_dim = action_dim
    self._filters = tuple(filters)
    self._embedding_size = embedding_size
    self._snail_filters = snail_filters
    self._num_mixture_components = num_mixture_components

  def create_network(self) -> nn.Module:
    return _SNAILMetaPolicy(
        action_dim=self._action_dim,
        num_condition=self._num_condition,
        num_inference=self._num_inference,
        filters=self._filters,
        embedding_size=self._embedding_size,
        snail_filters=self._snail_filters,
        num_mixture_components=self._num_mixture_components,
        dtype=self._base.device_dtype,
    )

  def network_inputs_from_labels(self, features, labels, mode):
    """Demonstration labels condition the trunk: lift every condition
    label under condition_labels/… (predict-time they arrive there
    directly — the shared serving convention)."""
    if labels is None:
      return features
    flat = features.to_flat_dict()
    for key, value in labels[CONDITION].to_flat_dict().items():
      flat[f"{CONDITION_LABELS}/{key}"] = value
    return TensorSpecStruct.from_flat_dict(flat)

  def loss_fn(self, params, batch_stats, features, labels, rng,
              mode: Mode):
    # In-context conditioning replaces gradient adaptation: the plain
    # supervised loss path (with the labels-as-inputs hook) applies,
    # not MAMLModel's inner-loop loss.
    return AbstractT2RModel.loss_fn(self, params, batch_stats,
                                    features, labels, rng, mode)

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    return action_supervision_loss(outputs, labels[INFERENCE][ACTION])

  def predict_step(self, state, features) -> Any:
    features, _ = self.preprocessor.preprocess(
        features, None, Mode.PREDICT, None)
    # Demonstration actions (if supplied) already ride in features
    # under condition_labels/ via the MAML preprocessor.
    return self.network.apply({"params": state.params}, features,
                              train=False)
