"""QT-Opt grasping Q-network.

Reference parity: tensor2robot `research/qtopt/t2r_models.py` +
`networks.py` — the grasping Q-network: camera image + proposed action
(+ gripper/height state) → grasp-success Q logit (SURVEY.md §3 "QT-Opt
models"; exact class names tagged [U-low] there; file:line unavailable —
empty reference mount). Architecture follows the QT-Opt paper
(arXiv:1806.10293): conv torso over the image, the action/state vector
embedded and broadcast-added into mid-level conv features, conv head,
then a dense head to a scalar logit.

TPU-first: NHWC bf16 convs sized in MXU-friendly multiples, uint8
images cast+scaled on device, the action merge is a 1×1-conv-equivalent
dense broadcast (fuses into the surrounding convs), no dynamic shapes.

The network is split at the action merge into two callable halves:
`encode(image)` — everything action-independent — and
`head(encoded, features)` — action embed + conv head + dense. CEM
exploits the split: the torso runs ONCE per state and only the (much
cheaper) head runs per population candidate, instead of re-convolving
the full image population × iterations times per Bellman target.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import MLP
from tensor2robot_tpu.models.critic_model import Q_VALUE


def _gather_action_extras(features, dtype):
  """Flattens action + every non-image float feature, sorted by key."""
  flat = (features.to_flat_dict() if hasattr(features, "to_flat_dict")
          else dict(features))
  action = flat["action"]
  extras = [action.reshape(action.shape[0], -1).astype(dtype)]
  for key in sorted(flat):
    if key in ("image", "action"):
      continue
    value = flat[key]
    if jnp.issubdtype(value.dtype, jnp.floating):
      extras.append(value.reshape(value.shape[0], -1).astype(dtype))
  return jnp.concatenate(extras, axis=-1)


class GraspingQNetwork(nn.Module):
  """Image + action → Q logit, QT-Opt-paper style."""

  torso_filters: Sequence[int] = (32, 64)
  head_filters: Sequence[int] = (64, 64)
  action_embedding_size: int = 64
  dense_sizes: Sequence[int] = (64, 64)
  use_batch_norm: bool = True
  # TPU stem: rearrange s×s spatial blocks into channels before the
  # first conv (1 = off). A 3-channel image leaves the MXU's reduce
  # dimension ~90% padding in the stem conv (3×3×3 = 27 taps);
  # space_to_depth=4 turns [H, W, 3] into [H/4, W/4, 48] so the first
  # conv contracts 432 taps instead — the standard TPU trick for
  # large-image stems. The first torso conv then runs stride 1 (the
  # rearrange already downsampled 4×); remaining convs are unchanged.
  space_to_depth: int = 1
  dtype: Any = jnp.bfloat16

  def setup(self):
    conv = lambda f, name, s=(2, 2): nn.Conv(  # noqa: E731
        f, (3, 3), strides=s, padding="SAME",
        use_bias=not self.use_batch_norm, dtype=self.dtype, name=name)
    norm = lambda name: nn.BatchNorm(  # noqa: E731
        momentum=0.9, dtype=self.dtype, name=name)
    self._torso_convs = [
        conv(f, f"torso_conv_{i}",
             s=(1, 1) if i == 0 and self.space_to_depth > 1 else (2, 2))
        for i, f in enumerate(self.torso_filters)]
    self._torso_bns = ([norm(f"torso_bn_{i}")
                        for i in range(len(self.torso_filters))]
                       if self.use_batch_norm else [])
    self._head_convs = [conv(f, f"head_conv_{i}")
                        for i, f in enumerate(self.head_filters)]
    self._head_bns = ([norm(f"head_bn_{i}")
                       for i in range(len(self.head_filters))]
                      if self.use_batch_norm else [])
    self._action_embed_0 = nn.Dense(
        self.action_embedding_size, dtype=self.dtype,
        name="action_embed_0")
    # The merge adds the embedded action onto the torso's output
    # channels (3 = raw RGB when the torso is empty).
    merge_channels = (self.torso_filters[-1] if self.torso_filters
                      else 3)
    self._action_embed_1 = nn.Dense(
        merge_channels, dtype=self.dtype, name="action_embed_1")
    self._q_head = MLP(hidden_sizes=tuple(self.dense_sizes),
                       output_size=1, dtype=self.dtype, name="q_head")

  def encode(self, image, train: bool = False):
    """Action-independent half: image → torso feature map [B,h,w,C].

    CEM callers run this once per state and tile the (small) result
    over the candidate population instead of the full image.
    """
    x = image.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
    if self.space_to_depth > 1:
      s = self.space_to_depth
      b, h, w, c = x.shape
      if h % s or w % s:
        raise ValueError(
            f"Image {h}x{w} must divide space_to_depth={s}.")
      # [B, H, W, C] -> [B, H/s, W/s, s*s*C]: each s×s block's pixels
      # become channels of one coarse position.
      x = x.reshape(b, h // s, s, w // s, s, c)
      x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
          b, h // s, w // s, s * s * c)
    for i, conv in enumerate(self._torso_convs):
      x = conv(x)
      if self.use_batch_norm:
        x = self._torso_bns[i](x, use_running_average=not train)
      x = nn.relu(x)
    return x

  def head(self, encoded, features, train: bool = False):
    """Action-dependent half: (torso features, action+extras) → Q."""
    a = _gather_action_extras(features, self.dtype)
    a = nn.relu(self._action_embed_0(a))
    a = self._action_embed_1(a)
    x = encoded + a[:, None, None, :]
    for i, conv in enumerate(self._head_convs):
      x = conv(x)
      if self.use_batch_norm:
        x = self._head_bns[i](x, use_running_average=not train)
      x = nn.relu(x)
    x = jnp.mean(x, axis=(1, 2))
    logit = self._q_head(x, train=train)
    return {Q_VALUE: logit[..., 0].astype(jnp.float32)}

  def score_population(self, encoded, extras, actions):
    """Scores a CEM population without materializing tiled torso maps.

    The naive population path tiles `encoded` to [B*P, h, w, C] — at
    QT-Opt bench scale a ~0.5 GB materialization per CEM iteration that
    profiles as the single most expensive op in the Bellman step. The
    first head conv is linear, so conv(encoded + broadcast(a)) splits
    exactly into conv(encoded) — once per STATE — plus the action
    contribution conv(broadcast(a)), which for a spatially-constant
    input reduces to an einsum with the kernel's per-position tap sums
    V[c, h', w', o] (border positions see fewer taps; V is computed
    border-exactly by pushing a one-hot channel basis through the conv).
    Only the post-merge [B, P, h', w', C'] activation is ever
    materialized, after most of the head FLOPs are already spent.

    Args:
      encoded: [B, h, w, C] torso features from `encode`.
      extras: dict of non-image state features keyed like the feature
        struct (values [B, ...] floats); may be empty.
      actions: [B, P, A] candidate actions.
    Eval-mode only (CEM target/policy scoring): BN uses running stats.
    Returns [B, P] Q values.
    """
    b, p, a_dim = actions.shape
    parts = [actions.astype(self.dtype)]
    for key in sorted(extras):
      value = extras[key]
      if jnp.issubdtype(value.dtype, jnp.floating):
        tiled = jnp.broadcast_to(
            value.reshape(b, 1, -1).astype(self.dtype),
            (b, p, int(np.prod(value.shape[1:]))))
        parts.append(tiled)
    a = jnp.concatenate(parts, axis=-1)
    a = nn.relu(self._action_embed_0(a))
    a = self._action_embed_1(a)  # [B, P, C]

    if self._head_convs:
      conv0 = self._head_convs[0]
      c = encoded.shape[-1]
      enc0 = conv0(encoded)  # [B, h', w', C'] — bias (if any) included.
      # Tap-sum tensor: push the one-hot channel basis (constant over
      # space) through the conv; subtract the zero-input response so a
      # conv bias isn't double-counted into every channel's row.
      basis = jnp.broadcast_to(
          jnp.eye(c, dtype=self.dtype)[:, None, None, :],
          (c,) + encoded.shape[1:])
      v = conv0(basis)  # [C, h', w', C']
      if not self.use_batch_norm:  # bias active ⇒ remove from basis rows
        v = v - conv0(jnp.zeros((1,) + encoded.shape[1:], self.dtype))
      if self.use_batch_norm:
        # Eval-mode BN is per-channel affine: BN(enc0 + act) =
        # BN(enc0) + s·act. Fold s into the tap-sum tensor so the big
        # population tensor never enters flax BN (whose float32
        # internals force a layout-changing f32 copy of the whole
        # tensor — profiled as the top op of the Bellman step).
        bn0 = self._head_bns[0]
        out_c = v.shape[-1]
        shift = bn0(jnp.zeros((1, 1, 1, out_c), self.dtype),
                    use_running_average=True)
        scale = bn0(jnp.ones((1, 1, 1, out_c), self.dtype),
                    use_running_average=True) - shift
        enc0 = bn0(enc0, use_running_average=True)
        v = v * scale.astype(self.dtype)
      # The action contribution as a flat 2-D GEMM in P-MAJOR row
      # order: a bphwo einsum (and a B-major GEMM) both leave XLA
      # layout assignment inserting a transpose copy of the whole
      # population tensor before the next conv (profiled at up to 60%
      # of the Bellman step). With rows ordered (p, b), the enc0
      # addend is a CONTIGUOUS axis-0 replication (see the
      # concatenate note below) — no transpose anywhere, and the GEMM
      # output is already NHWC for the conv. Measured end to end:
      # 225 (einsum) -> 362 (B-major GEMM) -> 441 (P-major, round 3).
      h2, w2, oc = v.shape[1:]
      a_pm = a.transpose(1, 0, 2).reshape(p * b, c)
      act = (a_pm @ v.reshape(c, -1)).reshape(p * b, h2, w2, oc)
      # Population-replicating enc0, three measured variants (bench
      # primary, round 4): jnp.tile = 487 steps/s (lowers as broadcast
      # + layout-changing reshape — two full copies, profiled at ~36%
      # of device time); 5-D broadcast-add then reshape = 414 (layout
      # assignment re-transposes the population tensor before the
      # add's consumer); axis-0 concatenate of p views = 620 — ONE
      # contiguous write, no relayout. Don't "simplify" back to tile.
      enc_rep = jnp.concatenate([enc0.astype(self.dtype)] * p, axis=0)
      x = nn.relu(act + enc_rep)
      for i, conv in enumerate(self._head_convs[1:], start=1):
        x = conv(x)
        if self.use_batch_norm:
          x = self._head_bns[i](x, use_running_average=True)
        x = nn.relu(x)
      x = jnp.mean(x, axis=(1, 2))
      logit = self._q_head(x, train=False)
      return logit[..., 0].astype(jnp.float32).reshape(p, b).T
    x = encoded[:, None] + a[:, :, None, None, :]
    x = x.reshape((b * p,) + x.shape[2:])
    x = jnp.mean(x, axis=(1, 2))
    logit = self._q_head(x, train=False)
    return logit[..., 0].astype(jnp.float32).reshape(b, p)

  def __call__(self, features, train: bool = False):
    encoded = self.encode(features["image"], train=train)
    return self.head(encoded, features, train=train)
