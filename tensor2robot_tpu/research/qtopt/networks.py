"""QT-Opt grasping Q-network.

Reference parity: tensor2robot `research/qtopt/t2r_models.py` +
`networks.py` — the grasping Q-network: camera image + proposed action
(+ gripper/height state) → grasp-success Q logit (SURVEY.md §3 "QT-Opt
models"; exact class names tagged [U-low] there; file:line unavailable —
empty reference mount). Architecture follows the QT-Opt paper
(arXiv:1806.10293): conv torso over the image, the action/state vector
embedded and broadcast-added into mid-level conv features, conv head,
then a dense head to a scalar logit.

TPU-first: NHWC bf16 convs sized in MXU-friendly multiples, uint8
images cast+scaled on device, the action merge is a 1×1-conv-equivalent
dense broadcast (fuses into the surrounding convs), no dynamic shapes.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers import MLP
from tensor2robot_tpu.models.critic_model import Q_VALUE


class GraspingQNetwork(nn.Module):
  """Image + action → Q logit, QT-Opt-paper style."""

  torso_filters: Sequence[int] = (32, 64)
  head_filters: Sequence[int] = (64, 64)
  action_embedding_size: int = 64
  dense_sizes: Sequence[int] = (64, 64)
  use_batch_norm: bool = True
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False):
    image = features["image"]
    action = features["action"]
    x = image.astype(self.dtype) / jnp.asarray(255.0, self.dtype)

    norm = lambda name: nn.BatchNorm(  # noqa: E731
        use_running_average=not train, momentum=0.9, dtype=self.dtype,
        name=name)

    # Conv torso over the image alone.
    for i, f in enumerate(self.torso_filters):
      x = nn.Conv(f, (3, 3), strides=(2, 2), padding="SAME",
                  use_bias=not self.use_batch_norm, dtype=self.dtype,
                  name=f"torso_conv_{i}")(x)
      if self.use_batch_norm:
        x = norm(f"torso_bn_{i}")(x)
      x = nn.relu(x)

    # Action (plus any extra flat float features) embedded and
    # broadcast-added into the spatial features — the paper's merge.
    extras = [action.reshape(action.shape[0], -1).astype(self.dtype)]
    for key in sorted(features.to_flat_dict()
                      if hasattr(features, "to_flat_dict") else features):
      if key in ("image", "action"):
        continue
      value = (features.to_flat_dict() if hasattr(features, "to_flat_dict")
               else features)[key]
      if jnp.issubdtype(value.dtype, jnp.floating):
        extras.append(value.reshape(value.shape[0], -1).astype(self.dtype))
    a = jnp.concatenate(extras, axis=-1)
    a = nn.Dense(self.action_embedding_size, dtype=self.dtype,
                 name="action_embed_0")(a)
    a = nn.relu(a)
    a = nn.Dense(x.shape[-1], dtype=self.dtype,
                 name="action_embed_1")(a)
    x = x + a[:, None, None, :]

    # Conv head over the merged features.
    for i, f in enumerate(self.head_filters):
      x = nn.Conv(f, (3, 3), strides=(2, 2), padding="SAME",
                  use_bias=not self.use_batch_norm, dtype=self.dtype,
                  name=f"head_conv_{i}")(x)
      if self.use_batch_norm:
        x = norm(f"head_bn_{i}")(x)
      x = nn.relu(x)

    x = jnp.mean(x, axis=(1, 2))
    logit = MLP(hidden_sizes=tuple(self.dense_sizes), output_size=1,
                dtype=self.dtype, name="q_head")(x, train=train)
    return {Q_VALUE: logit[..., 0].astype(jnp.float32)}
