"""QT-Opt grasping Q-network.

Reference parity: tensor2robot `research/qtopt/t2r_models.py` +
`networks.py` — the grasping Q-network: camera image + proposed action
(+ gripper/height state) → grasp-success Q logit (SURVEY.md §3 "QT-Opt
models"; exact class names tagged [U-low] there; file:line unavailable —
empty reference mount). Architecture follows the QT-Opt paper
(arXiv:1806.10293): conv torso over the image, the action/state vector
embedded and broadcast-added into mid-level conv features, conv head,
then a dense head to a scalar logit.

TPU-first: NHWC bf16 convs sized in MXU-friendly multiples, uint8
images cast+scaled on device, the action merge is a 1×1-conv-equivalent
dense broadcast (fuses into the surrounding convs), no dynamic shapes.

The network is split at the action merge into two callable halves:
`encode(image)` — everything action-independent — and
`head(encoded, features)` — action embed + conv head + dense. CEM
exploits the split: the torso runs ONCE per state and only the (much
cheaper) head runs per population candidate, instead of re-convolving
the full image population × iterations times per Bellman target.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import MLP
from tensor2robot_tpu.models.critic_model import Q_VALUE


def _gather_action_extras(features, dtype):
  """Flattens action + every non-image float feature, sorted by key."""
  flat = (features.to_flat_dict() if hasattr(features, "to_flat_dict")
          else dict(features))
  action = flat["action"]
  extras = [action.reshape(action.shape[0], -1).astype(dtype)]
  for key in sorted(flat):
    if key in ("image", "action"):
      continue
    value = flat[key]
    if jnp.issubdtype(value.dtype, jnp.floating):
      extras.append(value.reshape(value.shape[0], -1).astype(dtype))
  return jnp.concatenate(extras, axis=-1)


class GraspingQNetwork(nn.Module):
  """Image + action → Q logit, QT-Opt-paper style."""

  torso_filters: Sequence[int] = (32, 64)
  head_filters: Sequence[int] = (64, 64)
  action_embedding_size: int = 64
  dense_sizes: Sequence[int] = (64, 64)
  use_batch_norm: bool = True
  # TPU stem: rearrange s×s spatial blocks into channels before the
  # first conv (1 = off). A 3-channel image leaves the MXU's reduce
  # dimension ~90% padding in the stem conv (3×3×3 = 27 taps);
  # space_to_depth=4 turns [H, W, 3] into [H/4, W/4, 48] so the first
  # conv contracts 432 taps instead — the standard TPU trick for
  # large-image stems. The first torso conv then runs stride 1 (the
  # rearrange already downsampled 4×); remaining convs are unchanged.
  space_to_depth: int = 1
  dtype: Any = jnp.bfloat16

  def setup(self):
    conv = lambda f, name, s=(2, 2): nn.Conv(  # noqa: E731
        f, (3, 3), strides=s, padding="SAME",
        use_bias=not self.use_batch_norm, dtype=self.dtype, name=name)
    norm = lambda name: nn.BatchNorm(  # noqa: E731
        momentum=0.9, dtype=self.dtype, name=name)
    self._torso_convs = [
        conv(f, f"torso_conv_{i}",
             s=(1, 1) if i == 0 and self.space_to_depth > 1 else (2, 2))
        for i, f in enumerate(self.torso_filters)]
    self._torso_bns = ([norm(f"torso_bn_{i}")
                        for i in range(len(self.torso_filters))]
                       if self.use_batch_norm else [])
    self._head_convs = [conv(f, f"head_conv_{i}")
                        for i, f in enumerate(self.head_filters)]
    self._head_bns = ([norm(f"head_bn_{i}")
                       for i in range(len(self.head_filters))]
                      if self.use_batch_norm else [])
    self._action_embed_0 = nn.Dense(
        self.action_embedding_size, dtype=self.dtype,
        name="action_embed_0")
    # The merge adds the embedded action onto the torso's output
    # channels (3 = raw RGB when the torso is empty).
    merge_channels = (self.torso_filters[-1] if self.torso_filters
                      else 3)
    self._action_embed_1 = nn.Dense(
        merge_channels, dtype=self.dtype, name="action_embed_1")
    self._q_head = MLP(hidden_sizes=tuple(self.dense_sizes),
                       output_size=1, dtype=self.dtype, name="q_head")

  def encode(self, image, train: bool = False, taps=None):
    """Action-independent half: image → torso feature map [B,h,w,C].

    CEM callers run this once per state and tile the (small) result
    over the candidate population instead of the full image. `taps`
    (optional dict) records each conv's INPUT tensor under
    ``torso_in_<i>`` — the int8 calibration points
    (`calibration_stats`); passing it changes nothing else.
    """
    x = image.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
    if self.space_to_depth > 1:
      s = self.space_to_depth
      b, h, w, c = x.shape
      if h % s or w % s:
        raise ValueError(
            f"Image {h}x{w} must divide space_to_depth={s}.")
      # [B, H, W, C] -> [B, H/s, W/s, s*s*C]: each s×s block's pixels
      # become channels of one coarse position.
      x = x.reshape(b, h // s, s, w // s, s, c)
      x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
          b, h // s, w // s, s * s * c)
    for i, conv in enumerate(self._torso_convs):
      if taps is not None:
        taps[f"torso_in_{i}"] = x
      x = conv(x)
      if self.use_batch_norm:
        x = self._torso_bns[i](x, use_running_average=not train)
      x = nn.relu(x)
    return x

  def head(self, encoded, features, train: bool = False):
    """Action-dependent half: (torso features, action+extras) → Q."""
    a = _gather_action_extras(features, self.dtype)
    a = nn.relu(self._action_embed_0(a))
    a = self._action_embed_1(a)
    x = encoded + a[:, None, None, :]
    for i, conv in enumerate(self._head_convs):
      x = conv(x)
      if self.use_batch_norm:
        x = self._head_bns[i](x, use_running_average=not train)
      x = nn.relu(x)
    x = jnp.mean(x, axis=(1, 2))
    logit = self._q_head(x, train=train)
    return {Q_VALUE: logit[..., 0].astype(jnp.float32)}

  def score_population(self, encoded, extras, actions):
    """Scores a CEM population without materializing tiled torso maps.

    The naive population path tiles `encoded` to [B*P, h, w, C] — at
    QT-Opt bench scale a ~0.5 GB materialization per CEM iteration that
    profiles as the single most expensive op in the Bellman step. The
    first head conv is linear, so conv(encoded + broadcast(a)) splits
    exactly into conv(encoded) — once per STATE — plus the action
    contribution conv(broadcast(a)), which for a spatially-constant
    input reduces to an einsum with the kernel's per-position tap sums
    V[c, h', w', o] (border positions see fewer taps; V is computed
    border-exactly by pushing a one-hot channel basis through the conv).
    Only the post-merge [B, P, h', w', C'] activation is ever
    materialized, after most of the head FLOPs are already spent.

    Args:
      encoded: [B, h, w, C] torso features from `encode`.
      extras: dict of non-image state features keyed like the feature
        struct (values [B, ...] floats); may be empty.
      actions: [B, P, A] candidate actions.
    Eval-mode only (CEM target/policy scoring): BN uses running stats.
    Returns [B, P] Q values.
    """
    b, p, a_dim = actions.shape
    a = self._population_action_embed(extras, actions)
    if self._head_convs:
      pooled = self._population_tail(
          self._population_merge(encoded, a))
      logit = self._q_head(pooled, train=False)
      return logit[..., 0].astype(jnp.float32).reshape(p, b).T
    x = encoded[:, None] + a[:, :, None, None, :]
    x = x.reshape((b * p,) + x.shape[2:])
    x = jnp.mean(x, axis=(1, 2))
    logit = self._q_head(x, train=False)
    return logit[..., 0].astype(jnp.float32).reshape(b, p)

  def _population_action_embed(self, extras, actions):
    """Action + extras → merge-channel embedding a [B, P, C]."""
    b, p, a_dim = actions.shape
    parts = [actions.astype(self.dtype)]
    for key in sorted(extras):
      value = extras[key]
      if jnp.issubdtype(value.dtype, jnp.floating):
        tiled = jnp.broadcast_to(
            value.reshape(b, 1, -1).astype(self.dtype),
            (b, p, int(np.prod(value.shape[1:]))))
        parts.append(tiled)
    a = jnp.concatenate(parts, axis=-1)
    a = nn.relu(self._action_embed_0(a))
    return self._action_embed_1(a)  # [B, P, C]

  def _population_merge(self, encoded, a):
    """The linearity-split merge: [P·B, h', w', C'] relu'd tensor.

    P-MAJOR row order throughout (see the GEMM/concatenate notes
    inline) — the single hottest tensor of the Bellman step.
    """
    p = a.shape[1]
    conv0 = self._head_convs[0]
    c = encoded.shape[-1]
    enc0 = conv0(encoded)  # [B, h', w', C'] — bias (if any) included.
    # Tap-sum tensor: push the one-hot channel basis (constant over
    # space) through the conv; subtract the zero-input response so a
    # conv bias isn't double-counted into every channel's row.
    basis = jnp.broadcast_to(
        jnp.eye(c, dtype=self.dtype)[:, None, None, :],
        (c,) + encoded.shape[1:])
    v = conv0(basis)  # [C, h', w', C']
    if not self.use_batch_norm:  # bias active ⇒ remove from basis rows
      v = v - conv0(jnp.zeros((1,) + encoded.shape[1:], self.dtype))
    if self.use_batch_norm:
      # Eval-mode BN is per-channel affine: BN(enc0 + act) =
      # BN(enc0) + s·act. Fold s into the tap-sum tensor so the big
      # population tensor never enters flax BN (whose float32
      # internals force a layout-changing f32 copy of the whole
      # tensor — profiled as the top op of the Bellman step).
      bn0 = self._head_bns[0]
      out_c = v.shape[-1]
      shift = bn0(jnp.zeros((1, 1, 1, out_c), self.dtype),
                  use_running_average=True)
      scale = bn0(jnp.ones((1, 1, 1, out_c), self.dtype),
                  use_running_average=True) - shift
      enc0 = bn0(enc0, use_running_average=True)
      v = v * scale.astype(self.dtype)
    # The action contribution as a flat 2-D GEMM in P-MAJOR row
    # order: a bphwo einsum (and a B-major GEMM) both leave XLA
    # layout assignment inserting a transpose copy of the whole
    # population tensor before the next conv (profiled at up to 60%
    # of the Bellman step). With rows ordered (p, b), the enc0
    # addend is a CONTIGUOUS axis-0 replication (see the
    # concatenate note below) — no transpose anywhere, and the GEMM
    # output is already NHWC for the conv. Measured end to end:
    # 225 (einsum) -> 362 (B-major GEMM) -> 441 (P-major, round 3).
    h2, w2, oc = v.shape[1:]
    b = encoded.shape[0]
    a_pm = a.transpose(1, 0, 2).reshape(p * b, c)
    act = (a_pm @ v.reshape(c, -1)).reshape(p * b, h2, w2, oc)
    # Population-replicating enc0, three measured variants (bench
    # primary, round 4): jnp.tile = 487 steps/s (lowers as broadcast
    # + layout-changing reshape — two full copies, profiled at ~36%
    # of device time); 5-D broadcast-add then reshape = 414 (layout
    # assignment re-transposes the population tensor before the
    # add's consumer); axis-0 concatenate of p views = 620 — ONE
    # contiguous write, no relayout. Don't "simplify" back to tile.
    enc_rep = jnp.concatenate([enc0.astype(self.dtype)] * p, axis=0)
    return nn.relu(act + enc_rep)

  def _population_tail(self, x, taps=None):
    """Remaining head convs + spatial pool: [P·B, h', w', C'] →
    pooled [P·B, C'']. `taps` records each conv's input under
    ``head_in_<i>`` (int8 calibration points)."""
    for i, conv in enumerate(self._head_convs[1:], start=1):
      if taps is not None:
        taps[f"head_in_{i}"] = x
      x = conv(x)
      if self.use_batch_norm:
        x = self._head_bns[i](x, use_running_average=True)
      x = nn.relu(x)
    return jnp.mean(x, axis=(1, 2))

  def pool_population(self, encoded, extras, actions):
    """`score_population` minus the q-head MLP: pooled population
    features in P-major [P, B, C''] (a free reshape of the P-major
    tail output — no transpose touches the hot path). The fused CEM
    select kernel (`ops.fused_cem_select`) consumes this and runs
    scoring + running top-k + elite stats in one kernel.
    """
    b, p, _ = actions.shape
    a = self._population_action_embed(extras, actions)
    if self._head_convs:
      pooled = self._population_tail(
          self._population_merge(encoded, a))
      return pooled.reshape(p, b, -1)
    x = encoded[:, None] + a[:, :, None, None, :]
    x = x.reshape((b * p,) + x.shape[2:])
    pooled = jnp.mean(x, axis=(1, 2))
    return pooled.reshape(b, p, -1).transpose(1, 0, 2)

  def calibration_stats(self, features):
    """Eval-mode forward recording max-abs at every int8 quantization
    point — the held-out-batch calibration `quantize_tower` consumes.

    `features` is a flat feature struct/dict with ``image``,
    ``action`` and any extra state floats; the batch's own actions
    stand in as a population of 1 (activation ranges are state-, not
    population-, dominated). Returns {point_name: f32 scalar}.
    """
    taps = {}
    flat = (features.to_flat_dict()
            if hasattr(features, "to_flat_dict") else dict(features))
    encoded = self.encode(flat["image"], train=False, taps=taps)
    action = flat["action"]
    actions = action.reshape(action.shape[0], 1, -1)
    extras = {k: v for k, v in flat.items()
              if k not in ("image", "action")}
    a = self._population_action_embed(extras, actions)
    if self._head_convs:
      self._population_tail(self._population_merge(encoded, a),
                            taps=taps)
    return {k: jnp.max(jnp.abs(v)).astype(jnp.float32)
            for k, v in taps.items()}

  def __call__(self, features, train: bool = False):
    encoded = self.encode(features["image"], train=train)
    return self.head(encoded, features, train=train)


# ---------------------------------------------------------------------------
# int8 CEM inference tower
#
# The CEM Q-tower forward is inference-only (Bellman targets + acting),
# and the profiled Bellman step is HBM-bound: the [B·P, h', w', C']
# merged population tensor's read dominates device time. Storing the
# tower's activations (and weights) as int8 halves that traffic; the
# arithmetic stays on the MXU in the network's compute dtype (bf16 in
# production — int8 values up to ±127 are exact in bf16, and the MXU
# accumulates partial products in f32 before the one bf16 rounding at
# output, the "bf16 accumulation" contract). Per-output-channel weight
# scales are computed from the CURRENT params inside the traced step
# (cheap elementwise work, so Polyak-drifting target params requantize
# every step); per-tensor activation scales come from a one-time
# held-out-batch calibration (`GraspingQNetwork.calibration_stats`).
# Selected by gin (`QTOptLearner.cem_inference = "int8"`), gated by the
# end-metric parity tests in tests/test_qtopt.py against bf16.
# ---------------------------------------------------------------------------

_BN_EPS = 1e-5  # flax nn.BatchNorm default; the eval-affine fold assumes it
_CONV_DIMS = ("NHWC", "HWIO", "NHWC")


def _eval_bn_affine(bn_params, bn_stats):
  """Eval-mode BN as per-channel (scale, shift) f32."""
  scale = (bn_params["scale"].astype(jnp.float32)
           / jnp.sqrt(bn_stats["var"].astype(jnp.float32) + _BN_EPS))
  shift = (bn_params["bias"].astype(jnp.float32)
           - bn_stats["mean"].astype(jnp.float32) * scale)
  return scale, shift


def _quantize_weight(w):
  """Per-output-channel symmetric int8: w ≈ w_q · scale[c_out]."""
  w = w.astype(jnp.float32)
  red = tuple(range(w.ndim - 1))
  scale = jnp.maximum(jnp.max(jnp.abs(w), axis=red) / 127.0, 1e-12)
  w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
  return w_q, scale


def _quantize_act(x, scale):
  """Per-tensor symmetric int8 with a calibrated scale."""
  return jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                  -127, 127).astype(jnp.int8)


def scales_from_stats(stats) -> dict:
  """max-abs calibration stats → per-tensor int8 scales (host floats,
  so they bake into the traced step as constants)."""
  return {k: max(float(v) / 127.0, 1e-8) for k, v in stats.items()}


def quantize_tower(network: GraspingQNetwork, variables,
                   act_scales: dict) -> dict:
  """Builds the int8 tower pytree from params + calibrated act scales.

  Pure and traceable — call INSIDE the step so drifting (target)
  params requantize each step. Each layer entry: ``w_q`` int8 HWIO
  kernel, ``eff_scale`` f32 [c_out] (activation · weight · BN scales
  folded into one multiplier), ``shift`` f32 [c_out] (BN shift or conv
  bias), ``act_scale`` f32 scalar for the layer's input quantizer.
  """
  params = variables["params"]
  stats = variables.get("batch_stats", {})

  def layer(conv_name, bn_name, act_key):
    w_q, w_scale = _quantize_weight(params[conv_name]["kernel"])
    a_scale = jnp.asarray(act_scales[act_key], jnp.float32)
    if network.use_batch_norm:
      bn_scale, shift = _eval_bn_affine(params[bn_name],
                                        stats[bn_name])
      eff = a_scale * w_scale * bn_scale
    else:
      eff = a_scale * w_scale
      shift = params[conv_name]["bias"].astype(jnp.float32)
    return {"w_q": w_q, "eff_scale": eff, "shift": shift,
            "act_scale": a_scale}

  return {
      "torso": [layer(f"torso_conv_{i}", f"torso_bn_{i}",
                      f"torso_in_{i}")
                for i in range(len(network.torso_filters))],
      "head": [layer(f"head_conv_{i}", f"head_bn_{i}",
                     f"head_in_{i}")
               for i in range(1, len(network.head_filters))],
  }


def _int8_conv(x, layer, stride, dtype):
  """quantize → int8-valued conv in `dtype` → fold scales → relu."""
  x_q = _quantize_act(x, layer["act_scale"])
  y = jax.lax.conv_general_dilated(
      x_q.astype(dtype), layer["w_q"].astype(dtype), stride, "SAME",
      dimension_numbers=_CONV_DIMS)
  y = (y.astype(jnp.float32) * layer["eff_scale"] + layer["shift"])
  return jnp.maximum(y, 0.0).astype(dtype)


def quantized_encode(network: GraspingQNetwork, tower: dict, image):
  """int8 twin of `GraspingQNetwork.encode` (eval mode)."""
  dt = network.dtype
  x = image.astype(dt) / jnp.asarray(255.0, dt)
  s = network.space_to_depth
  if s > 1:
    b, h, w, c = x.shape
    x = x.reshape(b, h // s, s, w // s, s, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // s, w // s, s * s * c)
  for i, layer in enumerate(tower["torso"]):
    stride = (1, 1) if i == 0 and s > 1 else (2, 2)
    x = _int8_conv(x, layer, stride, dt)
  return x


def _dense(params, name, x, dtype, relu=False):
  w = params[name]["kernel"].astype(dtype)
  b = params[name]["bias"].astype(dtype)
  y = x.astype(dtype) @ w + b
  return nn.relu(y) if relu else y


def _quantized_population_pooled(network: GraspingQNetwork,
                                 tower: dict, variables, encoded,
                                 extras, actions):
  """int8 twin of the population path up to the pooled features.

  Mirrors `_population_merge` + `_population_tail` with the SAME
  P-major layout tricks; the merged population tensor — the hot
  tensor — is stored int8 between the merge and the next conv.
  Returns pooled [P·B, C''] in the compute dtype.
  """
  params = variables["params"]
  dt = network.dtype
  b, p, _ = actions.shape

  parts = [actions.astype(dt)]
  for key in sorted(extras):
    value = extras[key]
    if jnp.issubdtype(value.dtype, jnp.floating):
      parts.append(jnp.broadcast_to(
          value.reshape(b, 1, -1).astype(dt),
          (b, p, int(np.prod(value.shape[1:])))))
  a = _dense(params, "action_embed_0", jnp.concatenate(parts, -1),
             dt, relu=True)
  a = _dense(params, "action_embed_1", a, dt)  # [B, P, C]

  if not network.head_filters:
    x = encoded[:, None] + a[:, :, None, None, :]
    x = x.reshape((b * p,) + x.shape[2:])
    return jnp.mean(x, axis=(1, 2)).reshape(b, p, -1) \
        .transpose(1, 0, 2).reshape(p * b, -1)

  # conv0 linearity split, on the raw kernel (bias only without BN).
  k0 = params["head_conv_0"]["kernel"].astype(dt)
  c = encoded.shape[-1]
  enc0 = jax.lax.conv_general_dilated(
      encoded.astype(dt), k0, (2, 2), "SAME",
      dimension_numbers=_CONV_DIMS)
  basis = jnp.broadcast_to(
      jnp.eye(c, dtype=dt)[:, None, None, :], (c,) + encoded.shape[1:])
  v = jax.lax.conv_general_dilated(
      basis, k0, (2, 2), "SAME", dimension_numbers=_CONV_DIMS)
  if network.use_batch_norm:
    bn_scale, bn_shift = _eval_bn_affine(params["head_bn_0"],
                                         variables["batch_stats"]
                                         ["head_bn_0"])
    enc0 = (enc0.astype(jnp.float32) * bn_scale
            + bn_shift).astype(dt)
    v = (v.astype(jnp.float32) * bn_scale).astype(dt)
  else:
    enc0 = enc0 + params["head_conv_0"]["bias"].astype(dt)
  h2, w2, oc = v.shape[1:]
  a_pm = a.transpose(1, 0, 2).reshape(p * b, c)
  act = (a_pm @ v.reshape(c, -1)).reshape(p * b, h2, w2, oc)
  enc_rep = jnp.concatenate([enc0] * p, axis=0)
  x = nn.relu(act + enc_rep)  # the hot tensor; int8 from here on
  for i, layer in enumerate(tower["head"]):
    x = _int8_conv(x, layer, (2, 2), dt)
  return jnp.mean(x, axis=(1, 2))


def _q_head_mlp(params, pooled, dtype):
  """The q-head MLP from raw params (bf16 — tiny, not quantized)."""
  q_head = params["q_head"]
  names = sorted(q_head, key=lambda n: int(n.split("_")[-1]))
  h = pooled
  for i, name in enumerate(names):
    h = _dense(q_head, name, h, dtype, relu=i < len(names) - 1)
  return h.astype(jnp.float32)


def q_head_dense_params(variables, dtype=None):
  """((w, b), ...) of the q-head MLP — the fused select kernel's
  scoring parameters, in MLP layer order."""
  q_head = variables["params"]["q_head"]
  names = sorted(q_head, key=lambda n: int(n.split("_")[-1]))
  out = []
  for name in names:
    w, b = q_head[name]["kernel"], q_head[name]["bias"]
    if dtype is not None:
      w, b = w.astype(dtype), b.astype(dtype)
    out.append((w, b))
  return tuple(out)


def quantized_score_population(network: GraspingQNetwork, tower: dict,
                               variables, encoded, extras, actions):
  """int8 twin of `GraspingQNetwork.score_population`: [B, P] Q."""
  b, p, _ = actions.shape
  pooled = _quantized_population_pooled(
      network, tower, variables, encoded, extras, actions)
  logit = _q_head_mlp(variables["params"], pooled, network.dtype)
  return logit[..., 0].reshape(p, b).T


def quantized_pool_population(network: GraspingQNetwork, tower: dict,
                              variables, encoded, extras, actions):
  """int8 twin of `GraspingQNetwork.pool_population`: [P, B, C'']."""
  b, p, _ = actions.shape
  pooled = _quantized_population_pooled(
      network, tower, variables, encoded, extras, actions)
  return pooled.reshape(p, b, -1)
