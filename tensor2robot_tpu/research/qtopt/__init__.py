"""QT-Opt research family (reference: tensor2robot research/qtopt/).

Exports resolve LAZILY (PEP 562, the `data/__init__` pattern): fleet
actor processes import `research.qtopt.actor` + `grasping_env` at
spawn, and an eager package init would drag `qtopt_learner`'s jax
import (seconds of spin-up, an XLA runtime of memory) into processes
that only step envs and speak RPC (tests/test_fleet.py pins the
jax-free actor import). Consumers see the same names; only the import
moment moves.

Gin registration must NOT move with it: `run_t2r_trainer` parses
shipped configs right after importing this package, so every
`@gin.configurable` below is declared via
`register_lazy_configurables` — the first config reference imports the
defining submodule (registering it) instead of failing unregistered.
"""

from tensor2robot_tpu import config as _gin

_EXPORTS = {
    "ActorStateRefreshHook": "actor",
    "GraspActor": "actor",
    "CEMResult": "cem",
    "cem_maximize": "cem",
    "make_q_score_fn": "cem",
    "ToyGraspEnv": "grasping_env",
    "evaluate_grasp_policy": "grasping_env",
    "GraspingQNetwork": "networks",
    "QTOptLearner": "qtopt_learner",
    "QTOptState": "qtopt_learner",
    "ReplayBuffer": "replay_buffer",
    "GraspingQModel": "t2r_models",
    "train_qtopt": "train_qtopt",
}

__all__ = sorted(_EXPORTS)

for _name, _mod in (("GraspActor", "actor"),
                    ("ActorStateRefreshHook", "actor"),
                    ("evaluate_grasp_policy", "grasping_env"),
                    ("QTOptLearner", "qtopt_learner"),
                    ("ReplayBuffer", "replay_buffer"),
                    ("GraspingQModel", "t2r_models"),
                    ("train_qtopt", "train_qtopt")):
  _gin.register_lazy_configurables(f"{__name__}.{_mod}", (_name,))
del _name, _mod


def __getattr__(name):
  module_name = _EXPORTS.get(name)
  if module_name is None:
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
  import importlib

  module = importlib.import_module(f"{__name__}.{module_name}")
  value = getattr(module, name)
  globals()[name] = value
  return value
