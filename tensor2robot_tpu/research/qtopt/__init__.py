"""QT-Opt research family (reference: tensor2robot research/qtopt/)."""

from tensor2robot_tpu.research.qtopt.actor import (
    ActorStateRefreshHook,
    GraspActor,
)
from tensor2robot_tpu.research.qtopt.cem import (
    CEMResult,
    cem_maximize,
    make_q_score_fn,
)
from tensor2robot_tpu.research.qtopt.grasping_env import (
    ToyGraspEnv,
    evaluate_grasp_policy,
)
from tensor2robot_tpu.research.qtopt.networks import GraspingQNetwork
from tensor2robot_tpu.research.qtopt.qtopt_learner import (
    QTOptLearner,
    QTOptState,
)
from tensor2robot_tpu.research.qtopt.replay_buffer import ReplayBuffer
from tensor2robot_tpu.research.qtopt.t2r_models import GraspingQModel
from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt
