"""QT-Opt training orchestrator: replay → sharded infeed → fused step.

The in-repo replacement for the reference's external distributed QT-Opt
system, arranged for the north-star throughput target: the host thread
only samples/collates; CEM targets + critic update are one jitted
program; checkpoints are async orbax; the robot handoff is the same
async SavedModel export the supervised trainer uses.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Iterable, Optional

import jax
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import telemetry
from tensor2robot_tpu.data import prefetch as prefetch_lib
from tensor2robot_tpu.hooks import Hook, HookList
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import sharding as sharding_lib
from tensor2robot_tpu.research.qtopt.qtopt_learner import (
    QTOptLearner,
    QTOptState,
)
from tensor2robot_tpu.research.qtopt.replay_buffer import ReplayBuffer
from tensor2robot_tpu.specs import make_random_tensors
from tensor2robot_tpu.train_eval import MetricLogger
from tensor2robot_tpu.utils import checkpoints as ckpt_lib

log = logging.getLogger(__name__)


@gin.configurable
def train_qtopt(
    learner: QTOptLearner = gin.REQUIRED,
    model_dir: str = gin.REQUIRED,
    replay_buffer: Optional[ReplayBuffer] = None,
    max_train_steps: int = 1000,
    batch_size: int = 256,
    min_replay_size: Optional[int] = None,
    save_checkpoints_steps: int = 500,
    max_checkpoints_to_keep: int = 5,
    log_every_steps: int = 100,
    mesh: Optional[jax.sharding.Mesh] = None,
    hooks: Iterable[Hook] = (),
    seed: int = 0,
    prefill_random: bool = False,
    steps_per_dispatch: int = 1,
    prefetch_buffer_size: Optional[int] = None,
    shard_weight_update: bool = False,
) -> QTOptState:
  """Runs the QT-Opt learner loop; resumes from model_dir checkpoints.

  `replay_buffer` must be fed by actors (or pre-filled from logged
  episodes); `prefill_random=True` fills it with spec-random
  transitions instead (benchmarks / smoke tests).

  `steps_per_dispatch` (K) is the reference TPUEstimator's
  `iterations_per_loop` (SURVEY.md §4.1: "the hot loop"): K train
  steps run as ONE device program per host call — a `lax.scan` over K
  host-stacked replay batches — so host/dispatch latency is paid once
  per K steps instead of every step (on a tunneled or remote-host
  chip, per-step dispatch caps throughput an order of magnitude below
  the chip's measured rate). The reference's quantization semantics
  apply: every cadence (log, checkpoint, max steps) must be a
  multiple of K, per-step hooks observe only each dispatch's LAST
  metrics, and the per-step PRNG stream is identical to K=1 (folded
  by absolute step inside the scan).

  ONLINE-run caveat (K>1 sampling lead): replay batches for a whole
  K-step dispatch are sampled BEFORE the dispatch runs, and each
  prefetched dispatch adds another K steps of lead, so with actors
  feeding the buffer concurrently the last step of a dispatch can
  train on samples drawn up to ~(depth+1)·K steps of parameter
  updates ago. Two things bound this now: `prefetch_buffer_size`
  (None = auto via `prefetch_lib.prefetch_buffer_size`, gin-tunable:
  depth 1 when any hook drives online collection — the round-5
  finding — else the throughput-friendly 2), and the replay data
  plane MEASURES it — when the buffer exposes `set_learner_step` /
  `metrics_scalars` (the `replay/` plane and its `ReplayBuffer`
  adapter do), every sampled batch's age-in-steps lands in a
  staleness histogram logged alongside the train metrics. The
  exact-K=1-equivalence claim (and its tests) remains scoped to
  static/offline buffers — logged episodes, prefill_random — where
  sample timing is irrelevant; online runs should treat K as a
  throughput/off-policy-staleness trade-off, now a measured one.

  `shard_weight_update=True` shards the optimizer step + moments over
  the mesh's data axis (reduce-scatter grads / all-gather params —
  `optimizers.shard_weight_update`, docs/PERF.md): each replica
  updates 1/N of every weight instead of all replicas repeating the
  full update. On a 1-device mesh it is a bitwise no-op (pinned);
  checkpoints are unaffected (save gathers to host either way).
  """
  if mesh is None:
    mesh = mesh_lib.create_mesh()
  # Validate the dispatch quantization BEFORE any side effects
  # (hook begin() starts actor threads; a late ValueError would leak
  # them past their teardown owner, the loop's try/finally).
  k = prefetch_lib.validate_steps_per_dispatch(
      steps_per_dispatch,
      log_every_steps=log_every_steps,
      save_checkpoints_steps=save_checkpoints_steps,
      max_train_steps=max_train_steps)
  os.makedirs(model_dir, exist_ok=True)
  # Multi-process learner group (ISSUE 19): every rank runs the SAME
  # jitted program (one GSPMD computation over the shared mesh, each
  # rank feeding its local batch shard), but HOST-side effects —
  # metric logs, sentinel pages, replay step-tags — belong to the
  # chief alone. Rank > 0 would otherwise race the chief on the same
  # model_dir files. Checkpoint saves are the one exception: orbax
  # save/wait are COLLECTIVE (`sync_global_processes` barriers inside
  # the writer), so every rank must make the calls — orbax's
  # primary-host ownership still makes process 0 the only rank that
  # writes checkpoint data. Single-process runs are process 0, so this
  # is bitwise the existing path there.
  chief = jax.process_index() == 0
  metric_logger = MetricLogger(model_dir) if chief else None
  hook_list = HookList(list(hooks))
  # Compile-cache traffic → telemetry registry (the CompileWatch tap):
  # a warm-path recompile lands in this loop's log, not only under
  # bench --coldstart.
  from tensor2robot_tpu.startup.compile_cache import CompileWatch
  CompileWatch.install_tap()
  # The always-on perf plane (ISSUE 15): resource watermarks sampled
  # per process, sentinel rules evaluated at log cadence, and the live
  # MFU gauges published below (the PerfMeter built once the state
  # exists — the analytic denominator wants the param count).
  from tensor2robot_tpu.telemetry import perf as perf_lib
  from tensor2robot_tpu.telemetry import sentinel as sentinel_lib
  from tensor2robot_tpu.utils import profiling
  perf_lib.start_resource_sampler(
      sources=[profiling.device_memory_source()])
  watch_sentinel = (sentinel_lib.build_for_run(model_dir)
                    if chief else None)

  if replay_buffer is None:
    replay_buffer = ReplayBuffer(learner.transition_specification())
  if prefill_random:
    fill = make_random_tensors(
        learner.transition_specification(),
        batch_size=min(replay_buffer.capacity, 4 * batch_size),
        seed=seed)
    replay_buffer.add(fill)
  rng = jax.random.PRNGKey(seed)
  # Keyed re-wrap on EVERY invocation (identity when the flag is off):
  # a reused learner must not keep a previous run's mesh-pinned ZeRO
  # wrapper. Wrap BEFORE the state exists so tx is final when the step
  # traces; init stays untouched (shardings come from placement).
  swu_wrapper = lambda tx: tx  # noqa: E731
  if shard_weight_update:
    from tensor2robot_tpu.models import optimizers as opt_lib
    swu_wrapper = lambda tx: opt_lib.shard_weight_update(tx, mesh)  # noqa: E731
  learner.model.wrap_optimizer(swu_wrapper, key="shard_weight_update")
  state = learner.create_state(rng, batch_size=2)
  repl = mesh_lib.replicated(mesh)
  data_sharding = mesh_lib.batch_sharding(mesh)
  # The carried-state sharding: fully replicated, or — under
  # shard_weight_update — optimizer moments sharded over the data
  # axis (they must STAY sharded across steps, so this pytree is used
  # for placement and both jit sharding sides).
  state_sharding = (
      sharding_lib.train_state_update_sharding(mesh, state)
      if shard_weight_update else repl)
  state = jax.device_put(state, state_sharding)
  resume_step = ckpt_lib.latest_step(model_dir)
  if resume_step is not None:
    log.info("Resuming QT-Opt from step %d", resume_step)
    state = ckpt_lib.restore_state(model_dir, like=state,
                                   step=resume_step)

  # Resume-alignment check BEFORE hooks begin (actor threads) and
  # before the prefetcher exists: raising later would leak both past
  # their teardown owner (the loop's try/finally).
  step = int(np.asarray(jax.device_get(state.step)))
  if k > 1 and step % k and step < max_train_steps:
    if metric_logger is not None:
      metric_logger.close()
    raise ValueError(
        f"Resumed at step {step}, not a multiple of "
        f"steps_per_dispatch={k}: the checkpoint/log boundaries "
        "would never align. Resume with K=1 (or a K dividing the "
        "resume step) first.")

  # Hooks begin BEFORE the replay wait: an ActorStateRefreshHook whose
  # actors bootstrap an empty buffer must start collecting now, or
  # this wait would deadlock.
  hook_list.begin(learner.model, model_dir)
  replay_buffer.wait_until_size(min_replay_size or batch_size)

  # int8 CEM tower: activation scales calibrate on a real held-out
  # replay batch BEFORE the step is traced (the scales are trace-time
  # constants; see QTOptLearner.calibrate / docs/PERF.md).
  if getattr(learner, "needs_calibration", False):
    learner.calibrate(state, replay_buffer.sample(batch_size))

  writer = ckpt_lib.CheckpointWriter(
      model_dir, max_to_keep=max_checkpoints_to_keep)

  # Live MFU attribution: the SAME analytic denominator bench.py uses
  # (utils.profiling.analytic_flops — the ISSUE-15 shared-path pin),
  # scaled to the mesh (batch_size is PER-PROCESS, so × process_count
  # is the global batch; peak × devices keeps perf.mfu the per-chip
  # fraction).
  perf_meter = perf_lib.PerfMeter(
      flops_per_step=profiling.qtopt_step_flops(
          learner, batch_size * jax.process_count(),
          params=state.train_state.params),
      peak_flops=profiling.device_peak_flops(),
      devices=mesh.size)

  if k == 1:
    train_step = jax.jit(
        learner.train_step,
        in_shardings=(state_sharding, data_sharding, repl),
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,),
    )
    stream = replay_buffer.as_stream(batch_size)
    stream_sharding = data_sharding
  else:
    def k_steps(st, stacked, rng, step0):
      return prefetch_lib.scan_k_steps(
          learner.train_step, st, (stacked,), rng, step0)

    stacked_sharding = prefetch_lib.stacked_sharding(data_sharding)
    train_step = jax.jit(
        k_steps,
        in_shardings=(state_sharding, stacked_sharding, repl, repl),
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,),
    )
    stream = prefetch_lib.stack_batches(
        replay_buffer.as_stream(batch_size), k)
    stream_sharding = stacked_sharding

  # buffer_size is forwarded ONLY when the caller set it: a positional
  # (or keyword) arg would shadow a `prefetch_buffer_size.buffer_size`
  # gin binding — explicit caller args win over config in ginlite.
  depth = prefetch_lib.prefetch_buffer_size(
      online=hook_list.drives_online_collection,
      **({} if prefetch_buffer_size is None
         else {"buffer_size": prefetch_buffer_size}))
  prefetcher = prefetch_lib.ShardedPrefetcher(
      stream, stream_sharding, buffer_size=depth)
  # The data plane tags rows with the learner step at add time; seed
  # the tag before actors race the first dispatch. Chief-only: on the
  # sharded plane the tag is an RPC fan-out to every shard, and N
  # ranks tagging the same step would N-plicate it.
  tag_step = (getattr(replay_buffer, "set_learner_step", None)
              if chief else None)
  if tag_step is not None:
    tag_step(step)
  step_rng = jax.random.PRNGKey(seed + 1)
  t_last = time.time()
  steps_since_log = 0
  last_saved = resume_step
  # input_wait_fraction: the measured input-boundness of the
  # replay→device seam (shared TimedIterator — wall blocked in the
  # prefetcher's __next__ per log interval), logged beside the
  # staleness metrics.
  prefetch_iter = prefetch_lib.TimedIterator(prefetcher)
  try:
    for transitions in prefetch_iter:
      if step >= max_train_steps:
        break
      with perf_meter.dispatch("qtopt.dispatch", step=step, k=k):
        if k == 1:
          state, metrics = train_step(
              state, transitions, jax.random.fold_in(step_rng, step))
        else:
          # Same per-step PRNG stream as K=1: the scan body folds
          # step_rng by ABSOLUTE step (step0 + i).
          state, metrics = train_step(state, transitions, step_rng,
                                      np.int32(step))
      step += k
      steps_since_log += k
      if tag_step is not None:
        tag_step(step)  # one int store; actors tag adds with it
      hook_list.after_step(step, metrics)
      if chief and (step % log_every_steps == 0
                    or step == max_train_steps):
        scalars = jax.device_get(metrics)
        dt = time.time() - t_last
        scalars["grad_steps_per_sec"] = steps_since_log / max(dt, 1e-9)
        scalars["input_wait_fraction"] = prefetch_iter.wait_fraction(dt)
        # Data-plane instrumentation rides the train log: fill,
        # add/sample rates, drops/evictions, staleness — next to the
        # loop's own throughput, the way stall_fraction is.
        replay_metrics = getattr(replay_buffer, "metrics_scalars", None)
        if replay_metrics is not None:
          scalars.update(replay_metrics())
        # Compile-cache counters from the telemetry registry: a miss
        # delta after the first interval is a warm-path recompile.
        scalars.update(telemetry.registry().scalars("compile_cache."))
        # Resource watermarks persist with the run (the report tool's
        # watermark section; the registry alone dies with the process).
        scalars.update(telemetry.registry().scalars("rsrc."))
        telemetry.registry().gauge("train.grad_steps_per_sec").set(
            scalars["grad_steps_per_sec"])
        # Live utilization (perf.mfu / flops_per_sec /
        # device_time_fraction) — same denominator as bench MFU.
        scalars.update(perf_meter.publish(
            scalars["grad_steps_per_sec"], dt))
        metric_logger.write("train", step, scalars)
        if watch_sentinel is not None:
          watch_sentinel.evaluate(
              {**telemetry.registry().scalars(), **scalars},
              step=step)
        t_last = time.time()
        steps_since_log = 0
      if step % save_checkpoints_steps == 0 or step == max_train_steps:
        # EVERY rank saves (orbax's save barrier is collective; a
        # chief-only call would wedge the chief in
        # `sync_global_processes` while the peers train on) — orbax's
        # primary-host rule keeps process 0 the only data writer.
        # `after_checkpoint` runs on every rank too (rank > 0 carries
        # no publish hook, so it is a no-op there) to keep per-rank
        # hook bookkeeping in step.
        host_state = jax.device_get(state)
        writer.save(step, host_state,
                    params=host_state.train_state.params,
                    batch_stats=host_state.train_state.batch_stats)
        last_saved = step
        hook_list.after_checkpoint(step, state.train_state, model_dir)
    if last_saved != step:
      host_state = jax.device_get(state)
      writer.save(step, host_state,
                  params=host_state.train_state.params,
                  batch_stats=host_state.train_state.batch_stats)
      hook_list.after_checkpoint(step, state.train_state, model_dir)
  finally:
    # end() in the FINALLY: hooks now own real teardown (actor
    # threads); a training-loop exception must not leak collectors.
    try:
      hook_list.end(step, state.train_state, model_dir)
    except Exception:  # noqa: BLE001 — don't mask the original error
      log.exception("hook end() failed during teardown")
    prefetcher.close()
    writer.close()
    if watch_sentinel is not None:
      watch_sentinel.close()
    if metric_logger is not None:
      metric_logger.close()
  return state
