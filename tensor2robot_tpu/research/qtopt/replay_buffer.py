"""ReplayBuffer: thin API-compatible adapter over the replay data plane.

Through round 5 this module WAS the replay system — a single-process
numpy ring buffer. The sharded store / ingestion service / streaming
sampler now live in `tensor2robot_tpu/replay/`; this class keeps the
old call surface (`add` / `sample` / `as_stream` / `wait_until_size`)
so every existing caller and gin config keeps working, delegating to a
`ReplayStore` underneath.

Compatibility contract (pinned by tests/test_replay.py): with the
defaults (one shard, uniform sampling) the adapter is BIT-IDENTICAL to
the legacy buffer — same seeded rng call per sample, same physical row
layout, same gather — so a training run through it reproduces the old
in-process path exactly. The new capabilities (shards, prioritized/FIFO
sampling, eviction spill, staleness metrics) are opt-in constructor
args and passthroughs.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.replay import ReplayBatchSampler, ReplayStore
from tensor2robot_tpu.specs import TensorSpecStruct


@gin.configurable
class ReplayBuffer:
  """Uniform-sampling ring buffer API over the sharded `ReplayStore`."""

  def __init__(self, transition_spec: TensorSpecStruct,
               capacity: int = 100_000, seed: int = 0,
               num_shards: int = 1, sampling: str = "uniform",
               spill_dir: Optional[str] = None):
    self._store = ReplayStore(
        transition_spec, capacity=capacity, num_shards=num_shards,
        seed=seed, sampling=sampling, spill_dir=spill_dir)
    self._stream_sampler: Optional[ReplayBatchSampler] = None

  def __len__(self) -> int:
    return len(self._store)

  @property
  def capacity(self) -> int:
    return self._store.capacity

  @property
  def store(self) -> ReplayStore:
    """The underlying data-plane store (service attachment point)."""
    return self._store

  def add(self, transitions: TensorSpecStruct,
          priority: Optional[float] = None) -> None:
    """Appends a BATCH of transitions (dict/struct of [N, ...] arrays)."""
    self._store.add(transitions, priority=priority)

  def sample(self, batch_size: int) -> TensorSpecStruct:
    """Seeded random batch (empty buffer raises, as before)."""
    try:
      return self._store.sample(batch_size)
    except ValueError as e:
      # Legacy message said "replay buffer"; keep tests/callers happy.
      raise ValueError(
          "Cannot sample from an empty replay buffer.") from e

  def as_stream(self, batch_size: int) -> Iterator[TensorSpecStruct]:
    """Infinite sampling stream (feeds ShardedPrefetcher).

    The stream's sampler handle is kept so `metrics_scalars` /
    `staleness_snapshot` report the live training stream's staleness.
    """
    self._stream_sampler = ReplayBatchSampler(self._store, batch_size)
    return iter(self._stream_sampler)

  def wait_until_size(self, min_size: int,
                      timeout_secs: Optional[float] = None) -> bool:
    """Blocks until `min_size` transitions are buffered (actor warmup)."""
    return self._store.wait_until_size(min_size, timeout_secs)

  # ---- data-plane passthroughs (new capability, optional to use) ----

  def set_learner_step(self, step: int) -> None:
    """Tags subsequent adds with the learner step (staleness source)."""
    self._store.set_learner_step(step)

  def metrics_scalars(self, prefix: str = "replay_") -> Dict[str, float]:
    """Store fill/throughput + stream staleness, for the train log."""
    out = self._store.metrics_scalars(prefix=prefix)
    if self._stream_sampler is not None:
      out.update(self._stream_sampler.metrics_scalars(prefix=prefix))
    return out

  def staleness_snapshot(self) -> Optional[Dict[str, object]]:
    if self._stream_sampler is None:
      return None
    return self._stream_sampler.staleness_snapshot()
