"""Host-side replay buffer streaming transition batches into the mesh.

The reference's replay buffer was an external Google-infra service
(SURVEY.md §3 "Async actor/learner distribution" — not open-sourced).
In-repo TPU-native version: a preallocated numpy ring buffer derived
mechanically from the transition spec, a uniform sampler, and a stream
adapter for `ShardedPrefetcher` so sampling/collation overlaps device
compute — the host never appears in the jitted hot loop.

Throughput notes:
  * storage is spec-dtype (uint8 images stay uint8 → 4× less host RAM
    and 4× less H2D traffic than float storage),
  * `sample()` is one `rng.integers` + one row gather per key — no
    per-example python. The gather runs through the native C++ module
    (`native/gather.cc`, threaded memcpy striped across cores) when
    the library builds, since numpy's fancy indexing is
    single-threaded and TPU hosts have tens of cores per chip;
    otherwise numpy, bit-identical,
  * writers (env actors / dataset readers) and the sampling reader are
    decoupled by a mutex; adds are batched (threaded scatter, same
    module).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.utils import native


@gin.configurable
class ReplayBuffer:
  """Uniform-sampling ring buffer over a flat transition spec."""

  def __init__(self, transition_spec: TensorSpecStruct,
               capacity: int = 100_000, seed: int = 0):
    self._spec = specs_lib.flatten_spec_structure(transition_spec)
    self._capacity = int(capacity)
    self._storage: Dict[str, np.ndarray] = {}
    for key, spec in self._spec.to_flat_dict().items():
      self._storage[key] = np.zeros(
          (self._capacity,) + tuple(spec.shape), dtype=spec.dtype)
    self._lock = threading.Lock()
    self._rng = np.random.default_rng(seed)
    self._insert_index = 0
    self._size = 0

  def __len__(self) -> int:
    return self._size

  @property
  def capacity(self) -> int:
    return self._capacity

  def add(self, transitions: TensorSpecStruct) -> None:
    """Appends a BATCH of transitions (dict/struct of [N, ...] arrays)."""
    flat = (transitions.to_flat_dict()
            if isinstance(transitions, TensorSpecStruct)
            else dict(transitions))
    n = next(iter(flat.values())).shape[0]
    if n > self._capacity:
      flat = {k: v[-self._capacity:] for k, v in flat.items()}
      n = self._capacity
    with self._lock:
      start = self._insert_index
      idx = (start + np.arange(n)) % self._capacity
      for key, store in self._storage.items():
        if key not in flat:
          raise KeyError(f"Transition batch missing key {key!r}.")
        native.scatter_rows(store, idx,
                            np.ascontiguousarray(flat[key]))
      self._insert_index = int((start + n) % self._capacity)
      self._size = int(min(self._size + n, self._capacity))

  def sample(self, batch_size: int) -> TensorSpecStruct:
    """Uniform random batch; one vectorized (threaded) gather per key."""
    with self._lock:
      if self._size == 0:
        raise ValueError("Cannot sample from an empty replay buffer.")
      idx = self._rng.integers(0, self._size, size=batch_size)
      out = {key: native.gather_rows(store, idx)
             for key, store in self._storage.items()}
    return TensorSpecStruct.from_flat_dict(out)

  def as_stream(self, batch_size: int) -> Iterator[TensorSpecStruct]:
    """Infinite sampling stream (feeds ShardedPrefetcher)."""
    while True:
      yield self.sample(batch_size)

  def wait_until_size(self, min_size: int,
                      timeout_secs: Optional[float] = None) -> bool:
    """Blocks until `min_size` transitions are buffered (actor warmup)."""
    import time
    deadline = (time.time() + timeout_secs) if timeout_secs is not None \
        else None
    while self._size < min_size:
      if deadline is not None and time.time() > deadline:
        return False
      time.sleep(0.01)
    return True
