"""Toy grasping environment + closed-loop success eval for QT-Opt.

Reference parity: the reference's QT-Opt success numbers came from real
robots / a sim fleet reporting grasp success per policy checkpoint
(BASELINE.md protocol step 3); the env itself was never open-sourced.
This module ships the smallest environment with QT-Opt's reward
structure — a single-step grasping bandit: an object is rendered at a
random position, the action IS the (normalized) grasp point, reward is
grasp success — so the full loop (random collect → fused Bellman
training → CEM policy → success eval) runs and can be scored.

TPU-first eval: the env is stateless per episode, so success eval is
VECTORIZED — all N episodes reset as one batch, the CEM policy scores
them in ONE device program (population folded into the batch dim), and
grading is one numpy comparison. 500-episode protocol evals cost one
dispatch, not 500 rollout loops.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin

IMAGE_SIZE = 64


class ToyGraspEnv:
  """Single-step grasping bandit: image → grasp point → success."""

  def __init__(self,
               image_size: int = IMAGE_SIZE,
               action_dim: int = 2,
               success_threshold: float = 0.35,
               block_half_extent: float = 0.1,
               noise: float = 0.02,
               workspace: float = 0.8,
               seed: int = 0):
    """`workspace`: object centers stay in [-w, w]² (normalized coords);
    actions live in [-1, 1]^action_dim, the first two dims being the
    grasp point. `success_threshold` is the max grasp-point error."""
    self._size = image_size
    self._action_dim = action_dim
    self._threshold = success_threshold
    self._half = block_half_extent
    self._noise = noise
    self._workspace = workspace
    self._rng = np.random.default_rng(seed)

  @property
  def action_dim(self) -> int:
    return self._action_dim

  def _render(self, positions: np.ndarray) -> np.ndarray:
    """Renders a batch of object positions to uint8 images."""
    n = positions.shape[0]
    size = self._size
    images = np.full((n, size, size, 3), 96, np.float64)
    images += self._rng.normal(0, 255 * self._noise,
                               (n, size, size, 3))
    half_px = max(1, int(self._half / 2.0 * size))
    centers = ((positions + 1.0) / 2.0 * (size - 1)).astype(int)
    for i, (cx, cy) in enumerate(centers):
      x0, x1 = max(0, cx - half_px), min(size, cx + half_px + 1)
      y0, y1 = max(0, cy - half_px), min(size, cy + half_px + 1)
      images[i, y0:y1, x0:x1] = (200, 40, 40)
    return np.clip(images, 0, 255).astype(np.uint8)

  def reset_batch(self, n: int
                  ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """N fresh episodes: ({image: [N, S, S, 3]}, object positions)."""
    positions = self._rng.uniform(
        -self._workspace, self._workspace, (n, 2)).astype(np.float32)
    return {"image": self._render(positions)}, positions

  def grade(self, actions: np.ndarray,
            positions: np.ndarray) -> np.ndarray:
    """Success per episode: grasp point within threshold of the object."""
    grasp = np.asarray(actions, np.float32)[:, :2]
    dist = np.linalg.norm(grasp - positions, axis=-1)
    return (dist < self._threshold).astype(np.float32)

  def sample_transitions(self, n: int) -> Dict[str, np.ndarray]:
    """N random-policy transitions in the learner's replay layout.

    Episodes are single-step: done=1 and next_image is the (unused,
    spec-required) terminal observation.
    """
    observations, positions = self.reset_batch(n)
    actions = self._rng.uniform(
        -1, 1, (n, self._action_dim)).astype(np.float32)
    reward = self.grade(actions, positions)
    return {
        "image": observations["image"],
        "action": actions,
        "reward": reward[:, None].astype(np.float32),
        "done": np.ones((n, 1), np.float32),
        "next_image": observations["image"],
    }


@gin.configurable
def evaluate_grasp_policy(
    learner,
    state,
    num_episodes: int = 512,
    image_size: int = IMAGE_SIZE,
    success_threshold: float = 0.35,
    seed: int = 1,
    cem_population: Optional[int] = None,
    cem_iterations: Optional[int] = None,
) -> Dict[str, float]:
  """Scores the learner's CEM policy on `num_episodes` fresh episodes.

  One batched device program selects every episode's action
  (`QTOptLearner.build_policy`); grading is vectorized numpy. Also
  reports the random-policy baseline on the same episodes so the
  number is interpretable without a second run.
  """
  import jax
  import jax.numpy as jnp
  from tensor2robot_tpu.specs import TensorSpecStruct

  env = ToyGraspEnv(image_size=image_size,
                    action_dim=learner.model.action_dim,
                    success_threshold=success_threshold, seed=seed)
  observations, positions = env.reset_batch(num_episodes)
  policy = jax.jit(learner.build_policy(
      cem_population=cem_population, cem_iterations=cem_iterations))
  actions = policy(
      state,
      TensorSpecStruct.from_flat_dict(
          {"image": jnp.asarray(observations["image"])}),
      jax.random.PRNGKey(seed))
  success = env.grade(np.asarray(jax.device_get(actions)), positions)
  random_actions = np.random.default_rng(seed + 1).uniform(
      -1, 1, (num_episodes, learner.model.action_dim))
  return {
      "success_rate": float(success.mean()),
      "random_baseline_success_rate": float(
          env.grade(random_actions, positions).mean()),
      "num_episodes": float(num_episodes),
  }
