"""Cross-entropy-method action optimization, fully on device.

Reference parity: QT-Opt's CEM action selection — the reference ran the
CEM loop host-side, calling the predictor N×M times per action choice
(SURVEY.md §4.4 note [U-med]). TPU-native redesign: the whole optimizer
is one XLA program — `lax.scan` over refinement iterations, the
population batched into the Q-network's batch dimension — so target
computation in the Bellman update AND on-robot action selection both run
without a single host round-trip.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class CEMResult(NamedTuple):
  best_action: jax.Array   # [B, A]
  best_score: jax.Array    # [B]
  mean: jax.Array          # [B, A] final distribution mean
  std: jax.Array           # [B, A]


def cem_maximize(
    score_fn: Optional[Callable[[jax.Array], jax.Array]],
    rng: jax.Array,
    batch_size: int,
    action_dim: int,
    iterations: int = 3,
    population: int = 64,
    num_elites: int = 6,
    low: float = -1.0,
    high: float = 1.0,
    init_mean: Optional[jax.Array] = None,
    init_std: Optional[jax.Array] = None,
    min_std: float = 1e-2,
    select_fn: Optional[Callable] = None,
) -> CEMResult:
  """Maximizes `score_fn` over actions per batch element.

  Args:
    score_fn: [B, P, A] actions → [B, P] scores. The caller folds the
      population into the network batch dim (reshape), so every Q eval
      rides the MXU at batch B*P. May be None when `select_fn` is
      given.
    rng: PRNG key.
    batch_size, action_dim: static sizes.
    iterations/population/num_elites: CEM hyperparameters (QT-Opt used
      3 rounds, 64 samples, 10% elites).
    low/high: action box bounds (scalar or [A] broadcastable).
    init_mean/init_std: optional [B, A] warm start.
    select_fn: optional fused replacement of the score→top-k→elite-
      stats tail: ([B, P, A] samples, min_std) → (elite_mean [B, A],
      elite_std [B, A] floored at the passed min_std, best_action
      [B, A], best_score [B]), with lax.top_k tie semantics. The
      min_std argument is this function's own `min_std` — forwarded so
      the two paths can never floor differently. The learner wires
      `ops.fused_cem_select` through this seam so scoring, the running
      arg-top-k, and the elite reduction run as ONE kernel without
      materializing the [B, P] score tensor; any callable honoring the
      contract works (tests pin equivalence against the default path).
  """
  if score_fn is None and select_fn is None:
    raise ValueError("one of score_fn / select_fn is required")
  low = jnp.asarray(low, jnp.float32)
  high = jnp.asarray(high, jnp.float32)
  mean = (jnp.zeros((batch_size, action_dim)) + (low + high) / 2.0
          if init_mean is None else init_mean)
  std = (jnp.ones((batch_size, action_dim)) * (high - low) / 2.0
         if init_std is None else init_std)

  def one_iteration(carry, it_rng):
    mean, std, best_action, best_score = carry
    noise = jax.random.normal(
        it_rng, (batch_size, population, action_dim))
    samples = mean[:, None, :] + std[:, None, :] * noise
    samples = jnp.clip(samples, low, high)

    if select_fn is not None:
      new_mean, new_std, it_best, it_best_score = select_fn(samples,
                                                            min_std)
    else:
      scores = score_fn(samples)  # [B, P]
      elite_scores, elite_idx = jax.lax.top_k(scores, num_elites)
      elites = jnp.take_along_axis(
          samples, elite_idx[..., None], axis=1)  # [B, E, A]
      new_mean = jnp.mean(elites, axis=1)
      new_std = jnp.maximum(jnp.std(elites, axis=1), min_std)
      it_best = elites[:, 0]              # top-1 this iteration
      it_best_score = elite_scores[:, 0]
    improved = it_best_score > best_score
    best_action = jnp.where(improved[:, None], it_best, best_action)
    best_score = jnp.maximum(best_score, it_best_score)
    return (new_mean, new_std, best_action, best_score), ()

  init = (mean, std,
          jnp.zeros((batch_size, action_dim)),
          jnp.full((batch_size,), -jnp.inf))
  # unroll=True: 2-3 iterations, so full unrolling costs nothing in
  # compile time, removes loop overhead, and keeps XLA cost analysis
  # honest (it counts a rolled while-body ONCE regardless of trip
  # count, which silently under-reports FLOPs/MFU in benchmarks).
  (mean, std, best_action, best_score), _ = jax.lax.scan(
      one_iteration, init, jax.random.split(rng, iterations),
      unroll=True)
  return CEMResult(best_action, best_score, mean, std)


def make_q_score_fn(
    apply_fn: Callable,
    variables,
    state_features,
    q_key: str = "q_value",
) -> Callable[[jax.Array], jax.Array]:
  """Builds score_fn: tiles state features over the CEM population.

  `apply_fn(variables, features, train=False)` is the Q-network; state
  features are broadcast to [B*P, ...] and actions folded into the
  batch dim, so one network call scores the whole population.
  """

  def score_fn(actions: jax.Array) -> jax.Array:
    b, p, a = actions.shape
    flat_actions = actions.reshape(b * p, a)

    def tile(x):
      reps = (1, p) + (1,) * (x.ndim - 1)
      return jnp.tile(x[:, None], reps).reshape((b * p,) + x.shape[1:])

    tiled = jax.tree_util.tree_map(tile, state_features)
    flat = dict(tiled.to_flat_dict() if hasattr(tiled, "to_flat_dict")
                else tiled)
    flat["action"] = flat_actions
    from tensor2robot_tpu.specs import TensorSpecStruct
    features = TensorSpecStruct.from_flat_dict(flat)
    outputs = apply_fn(variables, features, train=False)
    q = outputs[q_key] if isinstance(outputs, dict) else outputs
    return q.reshape(b, p)

  return score_fn


def make_encoded_q_score_fn(
    network,
    variables,
    state_features,
    q_key: str = "q_value",
) -> Callable[[jax.Array], jax.Array]:
  """Score fn exploiting an encode/head-split Q-network.

  The action-independent torso (`network.encode`) runs ONCE per state;
  only its (small) output feature map is tiled over the CEM population
  and fed to `network.head` per candidate. The naive path re-convolves
  the full image population × iterations times per action choice — at
  QT-Opt scale (population 64) that is ~64× redundant torso compute.
  """
  flat_state = dict(state_features.to_flat_dict()
                    if hasattr(state_features, "to_flat_dict")
                    else state_features)
  image = flat_state.pop("image")
  encoded = network.apply(variables, image, train=False,
                          method="encode")

  if hasattr(network, "score_population"):
    # Linearity-split population scoring: no tiled torso-map
    # materialization at all (see GraspingQNetwork.score_population).
    # A stale "action" in the state features would become an extra
    # input; the tiled path overrides it with the candidates, so drop
    # it here for the same semantics.
    extras = {k: v for k, v in flat_state.items() if k != "action"}

    def population_score_fn(actions: jax.Array) -> jax.Array:
      return network.apply(variables, encoded, extras, actions,
                           method="score_population")

    return population_score_fn

  def score_fn(actions: jax.Array) -> jax.Array:
    b, p, a = actions.shape
    flat_actions = actions.reshape(b * p, a)

    def tile(x):
      reps = (1, p) + (1,) * (x.ndim - 1)
      return jnp.tile(x[:, None], reps).reshape((b * p,) + x.shape[1:])

    flat = {k: tile(v) for k, v in flat_state.items()}
    flat["action"] = flat_actions
    from tensor2robot_tpu.specs import TensorSpecStruct
    features = TensorSpecStruct.from_flat_dict(flat)
    outputs = network.apply(variables, tile(encoded), features,
                            train=False, method="head")
    q = outputs[q_key] if isinstance(outputs, dict) else outputs
    return q.reshape(b, p)

  return score_fn
