"""Online actor: on-policy data collection feeding the QT-Opt learner.

Reference parity: the reference's QT-Opt ran a fleet of robots/sim
actors pulling policy checkpoints and pushing grasp episodes into the
replay service while Bellman updaters trained (SURVEY.md §3 "async
actor/learner distribution" — the system itself was never
open-sourced). In-repo TPU-native version: actor THREADS share the
process with the learner loop — the learner's hot path is device-bound
(one fused XLA program per step), so host threads are free to run
envs; the mutex'd `ReplayBuffer` is the meeting point, and the
policy-state handoff mirrors the reference's checkpoint pull via
`ActorStateRefreshHook` (actors re-pull the acting params whenever the
trainer checkpoints).

Exploration: ε-greedy over the CEM policy — each episode acts randomly
with probability ε, otherwise with the jitted batched CEM argmax.
Before the first state handoff the actor is purely random, which IS
the bootstrap phase (replaces `prefill_random`'s spec-random tensors
with real env transitions).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.hooks.hook import Hook
from tensor2robot_tpu.research.qtopt.grasping_env import ToyGraspEnv


@gin.configurable
class GraspActor:
  """Collects ToyGraspEnv episodes with the current CEM policy.

  Usable synchronously (`collect_once`) or as a background thread
  (`start`/`stop`). `update_state` swaps the acting parameters
  atomically; collection before the first swap is uniform-random.
  """

  def __init__(self,
               learner,
               replay_buffer,
               env: Optional[ToyGraspEnv] = None,
               batch_episodes: int = 64,
               epsilon: float = 0.1,
               cem_population: Optional[int] = None,
               cem_iterations: Optional[int] = None,
               seed: int = 0):
    import jax

    self._learner = learner
    self._replay = replay_buffer
    self._env = env or ToyGraspEnv(
        image_size=learner.model.image_size,
        action_dim=learner.model.action_dim, seed=seed)
    self._batch = batch_episodes
    self._epsilon = float(epsilon)
    self._policy = jax.jit(learner.build_policy(
        cem_population=cem_population,
        cem_iterations=cem_iterations))
    self._rng = np.random.default_rng(seed)
    self._jax_key = jax.random.PRNGKey(seed + 1)
    self._state = None
    self._state_lock = threading.Lock()
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self.episodes_collected = 0
    self.reward_sum = 0.0

  def update_state(self, state) -> None:
    """Swaps the acting parameters (called from the trainer thread)."""
    with self._state_lock:
      self._state = state

  def collect_once(self) -> float:
    """One batch of episodes → replay; returns the batch mean reward."""
    import jax
    from tensor2robot_tpu.specs import TensorSpecStruct

    observations, positions = self._env.reset_batch(self._batch)
    with self._state_lock:
      state = self._state
    n = self._batch
    random_actions = self._rng.uniform(
        -1, 1, (n, self._env.action_dim)).astype(np.float32)
    if state is None:
      actions = random_actions
    else:
      self._jax_key, key = jax.random.split(self._jax_key)
      actions = np.asarray(jax.device_get(self._policy(
          state,
          TensorSpecStruct.from_flat_dict(
              {"image": observations["image"]}), key)))
      explore = self._rng.random(n) < self._epsilon
      actions = np.where(explore[:, None], random_actions,
                         actions).astype(np.float32)
    reward = self._env.grade(actions, positions)
    self._replay.add({
        "image": observations["image"],
        "action": actions,
        "reward": reward[:, None].astype(np.float32),
        "done": np.ones((n, 1), np.float32),
        "next_image": observations["image"],
    })
    self.episodes_collected += n
    self.reward_sum += float(reward.sum())
    return float(reward.mean())

  # ---- background-thread lifecycle ----

  def start(self) -> None:
    """Starts background collection (idempotent — the caller usually
    starts the actor BEFORE train_qtopt so the random bootstrap can
    satisfy min_replay_size, and the refresh hook's begin() is then a
    no-op)."""
    if self._thread is not None:
      return
    self._stop.clear()
    self._thread = threading.Thread(target=self._run, daemon=True)
    self._thread.start()

  def _run(self) -> None:
    while not self._stop.is_set():
      self.collect_once()

  def stop(self) -> None:
    """Stops collection. If the thread is stuck in a long device
    compile/transfer past the join timeout, the handle is KEPT (so a
    later start() cannot spawn a second collector) and a warning is
    logged rather than raising — teardown must not crash a completed
    training run; the stop event stays set, so the thread exits at
    its next loop check."""
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=30.0)
      if self._thread.is_alive():
        import logging
        logging.getLogger(__name__).warning(
            "actor thread still running after 30s join (likely a "
            "long XLA compile); it will exit at its next loop check.")
        return
      self._thread = None


@gin.configurable
class ActorStateRefreshHook(Hook):
  """Hands each checkpoint's params to the actors — the in-process
  equivalent of the reference's actors pulling policy checkpoints."""

  def __init__(self, actors):
    self._actors = list(actors) if isinstance(actors, (list, tuple)) \
        else [actors]

  def begin(self, model, model_dir: str) -> None:
    for actor in self._actors:
      actor.start()

  def after_checkpoint(self, step: int, state, model_dir: str) -> None:
    import jax
    import jax.numpy as jnp

    # The trainer DONATES its state buffers into the next step; actors
    # hold theirs across many steps, so hand them an un-donated device
    # copy — and only the acting half (params + BN stats), not the
    # optimizer moments.
    acting = (state.replace(opt_state=None)
              if hasattr(state, "replace")
              and hasattr(state, "opt_state") else state)
    acting = jax.tree_util.tree_map(jnp.copy, acting)
    for actor in self._actors:
      actor.update_state(acting)

  def end(self, step: int, state, model_dir: str) -> None:
    for actor in self._actors:
      actor.stop()
