"""Online actor: on-policy data collection feeding the QT-Opt learner.

Reference parity: the reference's QT-Opt ran a fleet of robots/sim
actors pulling policy checkpoints and pushing grasp episodes into the
replay service while Bellman updaters trained (SURVEY.md §3 "async
actor/learner distribution" — the system itself was never
open-sourced). In-repo TPU-native version: actor THREADS share the
process with the learner loop — the learner's hot path is device-bound
(one fused XLA program per step), so host threads are free to run envs.

Two wiring choices per actor, both fleet-shaped:

  * REPLAY SINK — a legacy `ReplayBuffer`/`ReplayStore` (direct `add`)
    or a `replay.ReplayWriteService` (per-actor session: each collected
    batch commits as one atomic episode through the bounded ingestion
    queue, so a crash mid-episode never leaves partial rows and the
    queue's backpressure/drop policy governs an over-eager fleet).
  * ACTION SOURCE — a locally-jitted CEM policy (the in-process shape),
    or a `serving.CEMPolicyServer` (`policy_server=`): actions come
    through the bucketed AOT engine + micro-batcher, the same serving
    stack robots use, so N actors coalesce into shared dispatches and
    the policy-state handoff is the server's lock-free hot-swap.

Exploration: ε-greedy over the CEM policy — each episode acts randomly
with probability ε, otherwise with the CEM argmax. Before the first
state handoff a local-policy actor is purely random, which IS the
bootstrap phase (replaces `prefill_random`'s spec-random tensors with
real env transitions).

Crash/restart: the collection thread catches everything, aborts the
in-flight session episode, and parks (`crashed` flag + `crash_error`).
A later `start()` re-opens the session (the service counts the restart
and discards any stale staged rows) and resumes ingestion — pinned by
tests/test_replay.py.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.hooks.hook import Hook
from tensor2robot_tpu.research.qtopt.grasping_env import ToyGraspEnv

log = logging.getLogger(__name__)


@gin.configurable
class GraspActor:
  """Collects ToyGraspEnv episodes with the current CEM policy.

  Usable synchronously (`collect_once`) or as a background thread
  (`start`/`stop`). `update_state` swaps the acting parameters
  atomically; collection before the first swap is uniform-random.
  """

  def __init__(self,
               learner,
               replay_buffer,
               env: Optional[ToyGraspEnv] = None,
               batch_episodes: int = 64,
               epsilon: float = 0.1,
               cem_population: Optional[int] = None,
               cem_iterations: Optional[int] = None,
               seed: int = 0,
               policy_server=None,
               name: Optional[str] = None):
    self._learner = learner
    self._replay = replay_buffer
    self.name = name or f"actor-{seed}"
    # Sink resolution: a ReplayWriteService hands out per-actor
    # sessions; anything with .add (ReplayBuffer, ReplayStore, a
    # session itself) is written to directly.
    self._service = (replay_buffer
                     if hasattr(replay_buffer, "session") else None)
    self._session = (self._service.session(self.name)
                     if self._service is not None else None)
    if env is None and learner is None:
      raise ValueError(
          "GraspActor needs either an env or a learner (the default "
          "env is sized from the learner's model).")
    self._env = env or ToyGraspEnv(
        image_size=learner.model.image_size,
        action_dim=learner.model.action_dim, seed=seed)
    self._batch = batch_episodes
    self._epsilon = float(epsilon)
    self.policy_server = policy_server
    if policy_server is None:
      # jax loads ONLY on the local-policy path: server-wired actors
      # (fleet processes) never touch a device and must not pay the
      # XLA runtime import (pinned by tests/test_fleet.py).
      import jax

      self._policy = jax.jit(learner.build_policy(
          cem_population=cem_population,
          cem_iterations=cem_iterations))
      self._jax_key = jax.random.PRNGKey(seed + 1)
    else:
      self._policy = None
      self._jax_key = None
    self._rng = np.random.default_rng(seed)
    self._state = None
    self._state_lock = threading.Lock()
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self.episodes_collected = 0
    self.episodes_dropped = 0
    self.reward_sum = 0.0
    self.crashed = False
    self.crash_error: Optional[BaseException] = None
    # Per-episode policy attribution (the param_refresh_lag seam):
    # when the action source exposes `params_version` (CEMPolicyServer
    # / the fleet's policy client), every collected batch records the
    # version it acted with.
    self.last_policy_version: Optional[int] = None
    self.episodes_by_policy_version: Dict[int, int] = {}

  def update_state(self, state) -> None:
    """Swaps the acting parameters (called from the trainer thread).

    With a policy server attached the state goes to ITS hot-swap (the
    server must have been constructed with the same acting-state
    structure — params + BN stats, opt_state stripped); otherwise the
    local policy's state reference swaps under the lock.
    """
    if self.policy_server is not None:
      self.policy_server.update_state(state)
      with self._state_lock:
        self._state = state  # marks bootstrap as over
      return
    with self._state_lock:
      self._state = state

  def _greedy_actions(self, observations, n: int) -> np.ndarray:
    """CEM actions for the batch via the configured action source."""
    if self.policy_server is not None:
      # Through the serving stack: chunk to the engine's max_batch (a
      # fleet's request sizes all hit pre-compiled buckets). No jax on
      # this path — a server-wired actor process stays device-free.
      chunk = self.policy_server.engine.max_batch
      outs = []
      for lo in range(0, n, chunk):
        outs.append(self.policy_server.select_actions(
            {"image": observations["image"][lo:lo + chunk]}))
      version = getattr(self.policy_server, "params_version", None)
      if version is not None:
        self.last_policy_version = version
        self.episodes_by_policy_version[version] = (
            self.episodes_by_policy_version.get(version, 0) + n)
      return np.concatenate(outs, axis=0).astype(np.float32)
    import jax
    from tensor2robot_tpu.specs import TensorSpecStruct

    with self._state_lock:
      state = self._state
    self._jax_key, key = jax.random.split(self._jax_key)
    return np.asarray(jax.device_get(self._policy(
        state,
        TensorSpecStruct.from_flat_dict(
            {"image": observations["image"]}), key))).astype(np.float32)

  def collect_once(self) -> float:
    """One batch of episodes → replay; returns the batch mean reward."""
    observations, positions = self._env.reset_batch(self._batch)
    n = self._batch
    random_actions = self._rng.uniform(
        -1, 1, (n, self._env.action_dim)).astype(np.float32)
    with self._state_lock:
      bootstrapped = self._state is not None
    if not bootstrapped and self.policy_server is None:
      actions = random_actions
    else:
      actions = self._greedy_actions(observations, n)
      explore = self._rng.random(n) < self._epsilon
      actions = np.where(explore[:, None], random_actions,
                         actions).astype(np.float32)
    reward = self._env.grade(actions, positions)
    transitions = {
        "image": observations["image"],
        "action": actions,
        "reward": reward[:, None].astype(np.float32),
        "done": np.ones((n, 1), np.float32),
        "next_image": observations["image"],
    }
    if self._session is not None:
      # One collected batch = one atomic episode commit; the service's
      # overflow policy (drop/block) is the fleet's flow control. A
      # dropped commit never reached replay, so it must not inflate
      # episodes_collected (the success-protocol summary reports it).
      committed = self._session.add(transitions)
    else:
      # A bare ActorIngestSession passed as the sink also returns a
      # drop-policy bool from add(); buffers/stores return None/int.
      committed = self._replay.add(transitions) is not False
    if committed:
      self.episodes_collected += n
      self.reward_sum += float(reward.sum())
    else:
      self.episodes_dropped += n
    return float(reward.mean())

  # ---- background-thread lifecycle ----

  def start(self) -> None:
    """Starts background collection (idempotent — the caller usually
    starts the actor BEFORE train_qtopt so the random bootstrap can
    satisfy min_replay_size, and the refresh hook's begin() is then a
    no-op). After a crash, start() RESTARTS: the session is re-opened
    (stale staged rows discarded, restart counted) and collection
    resumes."""
    if self.crashed:
      # The crashing thread flips `crashed` from INSIDE its except
      # block, so it can still be mid-exit here — join it before
      # restarting or an is_alive() check would racily no-op the
      # restart.
      if self._thread is not None:
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():
          log.warning("actor %s crash handler still running after 30s "
                      "join; restart deferred.", self.name)
          return
        self._thread = None
      log.warning("actor %s restarting after crash: %r", self.name,
                  self.crash_error)
      self.crashed = False
      self.crash_error = None
      if self._service is not None:
        self._session = self._service.session(self.name)
    elif self._thread is not None:
      return  # alive, or cleanly stopped (stop() owns that lifecycle)
    self._stop.clear()
    self._thread = threading.Thread(target=self._run, daemon=True)
    self._thread.start()

  def _run(self) -> None:
    try:
      while not self._stop.is_set():
        self.collect_once()
    except BaseException as e:  # noqa: BLE001 — the crash path IS the point
      self.crash_error = e
      self.crashed = True
      if self._session is not None:
        self._session.abort()
      log.exception("actor %s crashed; partial episode discarded",
                    self.name)

  def stop(self) -> None:
    """Stops collection. If the thread is stuck in a long device
    compile/transfer past the join timeout, the handle is KEPT (so a
    later start() cannot spawn a second collector) and a warning is
    logged rather than raising — teardown must not crash a completed
    training run; the stop event stays set, so the thread exits at
    its next loop check."""
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=30.0)
      if self._thread.is_alive():
        log.warning(
            "actor thread still running after 30s join (likely a "
            "long XLA compile); it will exit at its next loop check.")
        return
      self._thread = None


@gin.configurable
class ActorStateRefreshHook(Hook):
  """Hands each checkpoint's params to the actors — the in-process
  equivalent of the reference's actors pulling policy checkpoints.
  (Server-wired actors forward the swap to their CEMPolicyServer.)"""

  drives_online_collection = True

  def __init__(self, actors):
    self._actors = list(actors) if isinstance(actors, (list, tuple)) \
        else [actors]

  def begin(self, model, model_dir: str) -> None:
    for actor in self._actors:
      actor.start()

  def after_checkpoint(self, step: int, state, model_dir: str) -> None:
    import jax
    import jax.numpy as jnp

    # The trainer DONATES its state buffers into the next step; actors
    # hold theirs across many steps, so hand them an un-donated device
    # copy — and only the acting half (params + BN stats), not the
    # optimizer moments.
    acting = (state.replace(opt_state=None)
              if hasattr(state, "replace")
              and hasattr(state, "opt_state") else state)
    acting = jax.tree_util.tree_map(jnp.copy, acting)
    for actor in self._actors:
      actor.update_state(acting)

  def end(self, step: int, state, model_dir: str) -> None:
    for actor in self._actors:
      actor.stop()
