"""QT-Opt grasping critic model: specs + network wiring.

Reference parity: tensor2robot `research/qtopt/t2r_models.py` — the
TPU-ready grasping Q-model declaring image/action specs over the
critic base (SURVEY.md §3 "QT-Opt models"; file:line unavailable —
empty reference mount). The distributed QT-Opt system around it (replay
buffer, Bellman updaters, CEM policy) was NOT in the reference repo;
here it IS in-repo — see qtopt_learner.py / replay_buffer.py — because
the north-star target is training throughput of the full loop.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.models.critic_model import CriticModel
from tensor2robot_tpu.research.qtopt.networks import GraspingQNetwork
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


@gin.configurable
class GraspingQModel(CriticModel):
  """Q(image, action) with sigmoid grasp-success head.

  Wire spec: uint8 camera image + float action (gripper pose delta +
  open/close + terminate, 4-7 dims in the paper) + optional extra state
  vectors (gripper aperture, height, ... — the paper's non-image state)
  declared via `extra_state_features`. The Bellman target label
  `target_q` is produced by the learner, not the dataset.
  """

  def __init__(self,
               image_size: int = 64,
               action_dim: int = 4,
               torso_filters: Sequence[int] = (32, 64),
               head_filters: Sequence[int] = (64, 64),
               dense_sizes: Sequence[int] = (64, 64),
               extra_state_features=None,
               use_batch_norm: bool = True,
               sigmoid_q: bool = True,
               space_to_depth: int = 1,
               device_dtype=jnp.bfloat16,
               **kwargs):
    super().__init__(sigmoid_q=sigmoid_q, target_q_key="target_q",
                     device_dtype=device_dtype, **kwargs)
    self._space_to_depth = space_to_depth
    self._image_size = image_size
    self._action_dim = action_dim
    self._torso_filters = tuple(torso_filters)
    self._head_filters = tuple(head_filters)
    self._dense_sizes = tuple(dense_sizes)
    # {name: shape} of float state vectors fed to Q(s, a) alongside the
    # action embedding (the network concatenates every float extra).
    self._extra_state_features = dict(extra_state_features or {})
    self._use_batch_norm = use_batch_norm

  @property
  def action_dim(self) -> int:
    return self._action_dim

  @property
  def image_size(self) -> int:
    return self._image_size

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(
        shape=(self._image_size, self._image_size, 3), dtype=np.uint8,
        name="image", data_format="jpeg")
    st.action = ExtendedTensorSpec(
        shape=(self._action_dim,), dtype=np.float32, name="action")
    for key, shape in self._extra_state_features.items():
      st[key] = ExtendedTensorSpec(
          shape=tuple(shape), dtype=np.float32, name=key)
    return st

  def get_label_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.target_q = ExtendedTensorSpec(
        shape=(1,), dtype=np.float32, name="target_q")
    return st

  def create_network(self) -> nn.Module:
    return GraspingQNetwork(
        torso_filters=self._torso_filters,
        head_filters=self._head_filters,
        dense_sizes=self._dense_sizes,
        use_batch_norm=self._use_batch_norm,
        space_to_depth=self._space_to_depth,
        dtype=self.device_dtype,
    )
