"""QT-Opt learner: Bellman targets via on-device CEM + critic updates.

The reference open-sourced only the grasping model and the export/
predict handoff — its distributed system (replay buffer service,
Bellman updater fleet, CEM policy server; SURVEY.md §3 parallelism
inventory "Async actor/learner distribution") stayed in Google infra.
This module IS that system, collapsed into a single XLA program per
step, which is what the hardware wants:

  one jitted `train_step(learner_state, transitions)`:
    1. CEM-maximize Q_target(s', ·) for the whole batch (population
       folded into the batch dim — every eval saturates the MXU),
    2. target = r + γ (1-done) max_a' Q_target(s', a'), clipped to
       [0, 1] for the sigmoid grasp-success head (paper's form),
    3. cross-entropy critic update on Q(s, a),
    4. Polyak (or periodic) target-network update.

Data parallel over the mesh: batch sharded on the data axis, params
replicated, GSPMD all-reduces gradients over ICI — the same step scales
from 1 chip to a v5e-64 pod unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import flax
import jax
import jax.numpy as jnp

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.models.abstract_model import TrainState
from tensor2robot_tpu.models.critic_model import Q_VALUE
from tensor2robot_tpu.research.qtopt import cem
from tensor2robot_tpu.research.qtopt.t2r_models import GraspingQModel
from tensor2robot_tpu.specs import TensorSpecStruct


def _polyak(tau, new, old):
  """Polyak average in the contraction-stable form `old+tau·(new-old)`.

  `optax.incremental_update`'s `tau·new + (1-tau)·old` leaves an
  inexact multiply feeding an add, and XLA backends contract that
  pair into an FMA (or don't) per compiled module — jit- and
  pmap-compiled modules of the SAME jaxpr measurably disagree by
  1 ulp on XLA:CPU, and HLO `optimization_barrier`s don't survive to
  LLVM to stop it. This form has a single multiply on the difference;
  when ``tau`` is a power of two (2^-k) that product is EXACT, so the
  FMA and non-FMA contractions round identically and the update is
  bit-stable across compilation modes regardless of backend ISA. (The
  pod-vs-single-program bitwise pin in tests/test_envs.py removes the
  remaining backward-pass contraction ambiguity by pinning under an
  FMA-less `--xla_cpu_max_isa`; this form keeps the default-ISA drift
  to 1 ulp per step.) For non-pow2 tau the value matches the textbook
  average to 1 ulp.
  """
  return old + tau * (new - old)


@flax.struct.dataclass
class QTOptState:
  """Learner state: critic TrainState + target network params."""

  train_state: TrainState
  target_params: Any

  @property
  def step(self):
    return self.train_state.step


@gin.configurable
class QTOptLearner:
  """Builds the jittable QT-Opt training step for a GraspingQModel."""

  def __init__(self,
               model: GraspingQModel,
               gamma: float = 0.9,
               cem_iterations: int = 2,
               cem_population: int = 64,
               cem_elites: int = 6,
               action_low: float = -1.0,
               action_high: float = 1.0,
               target_update_tau: float = 0.05,
               clip_targets: Optional[Tuple[float, float]] = (0.0, 1.0),
               cem_inference: str = "bf16",
               cem_select: str = "lax"):
    """See class docstring; the two perf levers (docs/PERF.md):

    cem_inference: "bf16" (the network's compute dtype, exact) or
      "int8" — the CEM Q-tower forward runs the quantized tower
      (`networks.quantize_tower`): int8 weights/activations, bf16
      accumulation, activation scales from `calibrate()` (a held-out
      batch) — halves the HBM traffic of the profiled-hottest merged
      population tensor. Bellman targets/acting only; the critic
      gradient path is untouched.
    cem_select: "lax" (top_k + gather, exact reference) or "fused" —
      scoring + running arg-top-k + elite stats run as one Pallas
      kernel (`ops.fused_cem_select`) through `cem_maximize`'s
      select_fn seam; interpret-mode on CPU backends.
    """
    if cem_inference not in ("bf16", "int8"):
      raise ValueError(f"cem_inference={cem_inference!r} not in "
                       "('bf16', 'int8')")
    if cem_select not in ("lax", "fused"):
      raise ValueError(f"cem_select={cem_select!r} not in "
                       "('lax', 'fused')")
    self._model = model
    self._gamma = gamma
    self._cem_iterations = cem_iterations
    self._cem_population = cem_population
    self._cem_elites = cem_elites
    self._action_low = action_low
    self._action_high = action_high
    self._tau = target_update_tau
    self._clip_targets = clip_targets if model.sigmoid_q else None
    self._cem_inference = cem_inference
    self._cem_select = cem_select
    self._act_scales: Optional[Dict[str, float]] = None
    # Pallas compiles Mosaic on TPU only; every other backend runs the
    # fused kernel through the interpreter (exact, just not fast).
    self._fused_interpret = jax.default_backend() != "tpu"

  @property
  def model(self) -> GraspingQModel:
    return self._model

  @property
  def cem_population(self) -> int:
    return self._cem_population

  @property
  def cem_iterations(self) -> int:
    return self._cem_iterations

  def create_state(self, rng: jax.Array,
                   batch_size: int = 2) -> QTOptState:
    train_state = self._model.create_train_state(rng, batch_size)
    # Materialize a distinct copy: aliasing the online params would make
    # donated train_step inputs share buffers (donation error).
    target = jax.tree_util.tree_map(jnp.copy, train_state.params)
    return QTOptState(train_state=train_state, target_params=target)

  # ---- int8 calibration ----

  @property
  def cem_inference(self) -> str:
    return self._cem_inference

  @property
  def needs_calibration(self) -> bool:
    """True when the int8 tower is selected but no activation scales
    exist yet — `calibrate()` (or `ensure_calibrated()`) must run
    before the step/policy is traced."""
    return self._cem_inference == "int8" and self._act_scales is None

  def calibrate(self, state, features) -> Dict[str, float]:
    """Computes the int8 activation scales from a held-out batch.

    Host-level (runs a jitted eval forward); the resulting per-tensor
    scales are plain floats that bake into subsequently traced
    steps/policies as constants. `state` is a QTOptState or TrainState
    (online params — at calibration time target ≈ online); `features`
    is a batch conforming to the model's TRAIN feature spec (the
    transition batch's s-side keys work).
    """
    from tensor2robot_tpu.research.qtopt import networks as net_lib
    ts = state.train_state if isinstance(state, QTOptState) else state
    variables = {"params": ts.params}
    if ts.batch_stats:
      variables["batch_stats"] = ts.batch_stats
    flat = (features.to_flat_dict()
            if hasattr(features, "to_flat_dict") else dict(features))
    flat = {k: v for k, v in flat.items()
            if not k.startswith("next_") and k not in ("reward",
                                                       "done")}
    stats = jax.jit(functools.partial(
        self._model.network.apply, method="calibration_stats"))(
            variables, flat)
    self._act_scales = net_lib.scales_from_stats(
        jax.device_get(stats))
    return self._act_scales

  def ensure_calibrated(self, state) -> None:
    """Calibrates from a spec-random batch when nothing better ran —
    serving contexts that never see a replay batch. Random uint8
    images land in the same post-BN activation range class as real
    frames; prefer `calibrate()` on real data when available."""
    if not self.needs_calibration:
      return
    from tensor2robot_tpu.specs import make_random_tensors
    from tensor2robot_tpu.data.abstract_input_generator import Mode
    batch = make_random_tensors(
        self._model.get_feature_specification(Mode.TRAIN),
        batch_size=16, seed=0)
    batch = jax.tree_util.tree_map(jnp.asarray, batch)
    self.calibrate(state, batch)

  # ---- CEM scoring/selection construction ----

  def _cem_fns(self, variables, state_features):
    """(score_fn, select_fn) for `cem_maximize` — exactly one is used.

    The four gin-selectable paths: bf16/int8 tower × lax/fused select.
    All encode-split paths run the torso ONCE per state; int8 swaps
    the tower forward for the quantized twin; "fused" routes the
    scoring tail through `ops.fused_cem_select` via the select seam.
    """
    network = self._model.network
    if not (hasattr(network, "encode") and hasattr(network, "head")):
      return cem.make_q_score_fn(
          functools.partial(network.apply), variables, state_features,
          q_key=Q_VALUE), None
    if self._cem_inference == "bf16" and self._cem_select == "lax":
      return cem.make_encoded_q_score_fn(
          network, variables, state_features, q_key=Q_VALUE), None

    from tensor2robot_tpu.ops import fused_cem_select
    from tensor2robot_tpu.research.qtopt import networks as net_lib
    flat_state = dict(state_features.to_flat_dict()
                      if hasattr(state_features, "to_flat_dict")
                      else state_features)
    image = flat_state.pop("image")
    extras = {k: v for k, v in flat_state.items() if k != "action"}
    if self._cem_inference == "int8":
      if self._act_scales is None:
        raise RuntimeError(
            "cem_inference='int8' needs activation scales: call "
            "learner.calibrate(state, batch) (or ensure_calibrated) "
            "before tracing the step/policy.")
      tower = net_lib.quantize_tower(network, variables,
                                     self._act_scales)
      encoded = net_lib.quantized_encode(network, tower, image)
      score_fn = lambda actions: net_lib.quantized_score_population(  # noqa: E731
          network, tower, variables, encoded, extras, actions)
      pool_fn = lambda actions: net_lib.quantized_pool_population(  # noqa: E731
          network, tower, variables, encoded, extras, actions)
    else:
      encoded = network.apply(variables, image, train=False,
                              method="encode")
      score_fn = lambda actions: network.apply(  # noqa: E731
          variables, encoded, extras, actions,
          method="score_population")
      pool_fn = lambda actions: network.apply(  # noqa: E731
          variables, encoded, extras, actions,
          method="pool_population")
    if self._cem_select != "fused":
      return score_fn, None

    dense = net_lib.q_head_dense_params(variables,
                                        dtype=network.dtype)
    sigmoid = self._model.sigmoid_q

    def select_fn(actions, min_std):
      return fused_cem_select(
          pool_fn(actions), actions, dense,
          num_elites=self._cem_elites, min_std=min_std,
          sigmoid=sigmoid, interpret=self._fused_interpret)

    return None, select_fn

  # ---- target computation ----

  def _target_q_values(self, target_params, batch_stats,
                       next_features: TensorSpecStruct,
                       rng: jax.Array) -> jax.Array:
    """max_a' Q_target(s', a') via CEM, one XLA region."""
    variables = {"params": target_params}
    if batch_stats:
      variables["batch_stats"] = batch_stats
    batch = jax.tree_util.tree_leaves(next_features)[0].shape[0]
    score_fn, select_fn = self._cem_fns(variables, next_features)

    sigmoid_score = None
    if score_fn is not None:
      def sigmoid_score(actions):
        q = score_fn(actions)
        return jax.nn.sigmoid(q) if self._model.sigmoid_q else q
    # select_fn case: the sigmoid (monotone — selection unchanged)
    # runs inside the fused kernel, so best_score is already on the
    # sigmoid scale (_cem_fns passes sigmoid=model.sigmoid_q).

    result = cem.cem_maximize(
        sigmoid_score, rng, batch, self._model.action_dim,
        iterations=self._cem_iterations,
        population=self._cem_population,
        num_elites=self._cem_elites,
        low=self._action_low, high=self._action_high,
        select_fn=select_fn)
    return result.best_score

  # ---- the fused train step ----

  def train_step(self, state: QTOptState, transitions: TensorSpecStruct,
                 rng: jax.Array, axis_name: Optional[str] = None
                 ) -> Tuple[QTOptState, Dict[str, jax.Array]]:
    """One Bellman update on a batch of transitions.

    transitions (flat struct): image, action [A], reward [1], done [1],
    next_image (+ any extra state features prefixed next_).

    `axis_name` (trace-time static) is the SPMD pod form: each device
    computes Bellman targets and gradients on its OWN transition
    batch, gradients are `lax.pmean`'d over the axis before the Adam
    update (the model's `train_step` seam), and the Polyak target
    update then runs on identical post-update params everywhere — so
    the replicated learner state stays replicated by construction.
    The q_next/target metrics are pmean'd too (device-0 reports the
    global means).

    Composition of `train_grads` + `apply_gradients` — the split the
    shard_map pod program drives directly (per-device backward under
    shard_map, GSPMD weight update; docs/SHARDING.md).
    """
    grads, new_stats, metrics = self.train_grads(
        state, transitions, rng, axis_name=axis_name)
    return self.apply_gradients(state, grads, new_stats), metrics

  def train_grads(self, state: QTOptState,
                  transitions: TensorSpecStruct, rng: jax.Array,
                  axis_name: Optional[str] = None
                  ) -> Tuple[Any, Any, Dict[str, jax.Array]]:
    """The forward/backward half of `train_step`: CEM Bellman targets
    + critic gradients (pmean'd over `axis_name`), no optimizer
    update. Returns ``(grads, new_batch_stats, metrics)``."""
    flat = transitions.to_flat_dict()
    rng_cem, rng_net = jax.random.split(rng)

    # Every non-next_, non-reward/done key is an online-critic feature:
    # models with state extras beyond {image, action} (gripper status,
    # height, ...) must see them in Q(s, a) just as the target network
    # sees their next_-prefixed twins.
    features = TensorSpecStruct.from_flat_dict({
        k: v for k, v in flat.items()
        if not k.startswith("next_") and k not in ("reward", "done")})
    next_features = TensorSpecStruct.from_flat_dict(
        {k[len("next_"):]: v for k, v in flat.items()
         if k.startswith("next_")})

    ts = state.train_state
    q_next = self._target_q_values(
        state.target_params, ts.batch_stats, next_features, rng_cem)
    reward = flat["reward"].reshape(-1).astype(jnp.float32)
    done = flat["done"].reshape(-1).astype(jnp.float32)
    target = reward + self._gamma * (1.0 - done) * q_next
    if self._clip_targets is not None:
      target = jnp.clip(target, *self._clip_targets)
    target = jax.lax.stop_gradient(target)

    labels = TensorSpecStruct.from_flat_dict(
        {"target_q": target[:, None]})
    grads, new_stats, metrics = self._model.train_grads(
        ts, features, labels, rng_net, axis_name=axis_name)
    metrics["q_next_mean"] = jnp.mean(q_next)
    metrics["target_mean"] = jnp.mean(target)
    if axis_name is not None:
      metrics["q_next_mean"] = jax.lax.pmean(metrics["q_next_mean"],
                                             axis_name)
      metrics["target_mean"] = jax.lax.pmean(metrics["target_mean"],
                                             axis_name)
    return grads, new_stats, metrics

  def apply_gradients(self, state: QTOptState, grads: Any,
                      new_stats: Any) -> QTOptState:
    """The update half: critic optimizer step + Polyak target sync."""
    new_ts = self._model.apply_gradients(state.train_state, grads,
                                         new_stats)
    new_target = jax.tree_util.tree_map(
        functools.partial(_polyak, self._tau),
        new_ts.params, state.target_params)
    return QTOptState(train_state=new_ts, target_params=new_target)

  # ---- on-robot / actor policy ----

  def build_policy(self, cem_population: Optional[int] = None,
                   cem_iterations: Optional[int] = None):
    """Returns a jittable (state, observation_features, rng) → action.

    The serving-side CEM: the reference's robots looped predict() calls
    host-side; here action selection is one device program.

    `state` may be the full learner `QTOptState` OR just the critic
    `TrainState`: acting reads only the online params (the target net
    exists for Bellman backups, never for action selection), so
    serving contexts that hold a bare TrainState — checkpoint hooks,
    exported policies — pass it directly instead of fabricating a
    learner state with dummy targets.
    """
    population = cem_population or self._cem_population
    iterations = cem_iterations or self._cem_iterations

    def policy(state, observations: TensorSpecStruct,
               rng: jax.Array) -> jax.Array:
      ts = state.train_state if isinstance(state, QTOptState) else state
      variables = {"params": ts.params}
      if ts.batch_stats:
        variables["batch_stats"] = ts.batch_stats
      batch = jax.tree_util.tree_leaves(observations)[0].shape[0]
      score_fn, select_fn = self._cem_fns(variables, observations)
      result = cem.cem_maximize(
          score_fn, rng, batch, self._model.action_dim,
          iterations=iterations, population=population,
          num_elites=self._cem_elites,
          low=self._action_low, high=self._action_high,
          select_fn=select_fn)
      return result.best_action

    return policy

  def observation_specification(self) -> TensorSpecStruct:
    """Serving-side observation spec: every state feature Q(s, ·)
    conditions on — the model's TRAIN feature spec minus the `action`
    CEM optimizes over. This is the wire contract of
    `serving.CEMPolicyServer.select_actions`."""
    feat = self._model.get_feature_specification(Mode.TRAIN).to_flat_dict()
    return TensorSpecStruct.from_flat_dict(
        {k: v for k, v in feat.items() if k != "action"})

  def transition_specification(self) -> TensorSpecStruct:
    """The replay-buffer transition spec, derived from the model specs."""
    import numpy as np
    from tensor2robot_tpu.specs import ExtendedTensorSpec

    model_feat = self._model.get_feature_specification(
        Mode.TRAIN).to_flat_dict()
    out = dict(model_feat)
    for key, spec in model_feat.items():
      if key != "action":
        out[f"next_{key}"] = spec.replace(name=f"next_{spec.name or key}")
    out["reward"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32,
                                       name="reward")
    out["done"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32,
                                     name="done")
    return TensorSpecStruct.from_flat_dict(out)
