"""Research model families (reference: tensor2robot research/)."""
