"""Physics-backed pose environment (MuJoCo contact dynamics).

Reference parity: the reference's pose_env task ran on PyBullet —
physics placed/settled the object and rendered the camera image
(SURVEY.md §3 pose_env row; the empty reference mount blocks a
file:line cite). PyBullet is not in this image, but MuJoCo is, so this
variant closes the physics half of the substitution the numpy env made:

  * `reset()` DROPS the block over the table at a random planar
    position, height, yaw, and lateral velocity, then steps MuJoCo's
    contact dynamics until the block settles (or a step budget runs
    out). The LABEL is the settled pose — genuinely physics-derived:
    blocks slide, bounce, and rotate before coming to rest, so the
    settled pose differs from the commanded drop pose (a property the
    tests pin), and out-of-workspace settles are rejected+resampled
    exactly like a real collect loop discards bad episodes.
  * The OBSERVATION still comes from the numpy rasterizer, rendered
    at the settled pose. MuJoCo's own renderer needs an OpenGL
    context and this image has none (verified at build time: osmesa,
    egl, and glfw backends all fail to load — no libOSMesa/libEGL/
    display). The seam is documented: swap `_observation` for
    `mujoco.Renderer` where GL exists.

The model/data/eval contracts are unchanged — `collect_random_episodes`
and `evaluate_pose_model` take the env class by gin config, so the
physics variant is a config switch, not a code fork.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.research.pose_env.pose_env import (
    IMAGE_SIZE,
    WORKSPACE_HIGH,
    WORKSPACE_LOW,
    PoseEnv,
)

_SCENE_XML = """
<mujoco model="pose_env">
  <option timestep="0.004"/>
  <worldbody>
    <geom name="table" type="plane" size="2 2 0.1" friction="0.8 0.005 0.0001"/>
    <body name="block" pos="0 0 1">
      <freejoint name="block_joint"/>
      <geom name="block_geom" type="box" size="{half} {half} {half}"
            density="400" friction="0.8 0.005 0.0001"/>
    </body>
  </worldbody>
</mujoco>
"""


@gin.configurable
class MuJoCoPoseEnv(PoseEnv):
  """Pose task with MuJoCo-settled block poses (see module docstring)."""

  def __init__(self, image_size: int = IMAGE_SIZE, seed: int = 0,
               block_half_extent: float = 0.06, noise: float = 0.02,
               drop_height: float = 0.25,
               max_settle_steps: int = 1500,
               settle_speed: float = 1e-3):
    # Config validation BEFORE the mujoco import: a zero/negative step
    # budget would otherwise surface as a NameError deep inside
    # `_settle_once` (the settle loop body never runs, so `step` is
    # unbound) instead of a config error at construction.
    if max_settle_steps < 1:
      raise ValueError(
          f"max_settle_steps must be >= 1 (got {max_settle_steps}): "
          "the settle loop needs at least one physics step to produce "
          "a pose.")
    super().__init__(image_size=image_size, seed=seed,
                     block_half_extent=block_half_extent, noise=noise)
    # Imported lazily so the numpy env never needs it.
    import mujoco

    self._mujoco = mujoco
    self._model = mujoco.MjModel.from_xml_string(
        _SCENE_XML.format(half=block_half_extent))
    self._data = mujoco.MjData(self._model)
    self._drop_height = drop_height
    self._max_settle_steps = max_settle_steps
    self._settle_speed = settle_speed
    self.last_drop_pose: Optional[np.ndarray] = None
    self.last_settle_steps: int = 0

  def _settle_once(self) -> Optional[np.ndarray]:
    """One drop → settled planar pose, or None if it left the table
    region (the collect loop resamples, like discarding a failed
    episode on a real rig)."""
    mujoco = self._mujoco
    rng = self._rng
    drop_xy = rng.uniform(WORKSPACE_LOW, WORKSPACE_HIGH)
    yaw = rng.uniform(0, 2 * np.pi)
    mujoco.mj_resetData(self._model, self._data)
    # Free joint qpos: [x, y, z, qw, qx, qy, qz].
    self._data.qpos[:3] = (drop_xy[0], drop_xy[1],
                           self._half + self._drop_height)
    self._data.qpos[3:7] = (np.cos(yaw / 2), 0.0, 0.0,
                            np.sin(yaw / 2))
    # Lateral shove so settles genuinely move off the drop point.
    self._data.qvel[:2] = rng.uniform(-0.5, 0.5, size=2)
    self._data.qvel[5] = rng.uniform(-2.0, 2.0)  # yaw spin
    self.last_drop_pose = drop_xy.astype(np.float32)

    for step in range(self._max_settle_steps):
      mujoco.mj_step(self._model, self._data)
      if (step > 10
          and float(np.linalg.norm(self._data.qvel)) <
          self._settle_speed):
        break
    self.last_settle_steps = step + 1
    settled = self._data.qpos[:2].astype(np.float32)
    inside = np.all((settled >= WORKSPACE_LOW)
                    & (settled <= WORKSPACE_HIGH))
    return settled if inside else None

  def reset(self, max_attempts: int = 50) -> Dict[str, np.ndarray]:
    """Drops until a block settles inside the workspace; renders it.

    Bounded: a configuration whose drops reliably slide off the
    workspace (tall drop_height, hot shoves, low friction) raises
    with a diagnostic instead of spinning the collect loop forever.
    """
    for _ in range(max_attempts):
      settled = self._settle_once()
      if settled is not None:
        self._pose = settled
        return self._observation()
    raise RuntimeError(
        f"No drop settled inside the workspace in {max_attempts} "
        "attempts — drop_height/velocity/friction leave the block "
        "outside [{}, {}]; retune the env config.".format(
            WORKSPACE_LOW.tolist(), WORKSPACE_HIGH.tolist()))
