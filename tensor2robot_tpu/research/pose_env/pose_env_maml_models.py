"""MAML variants of the pose models.

Reference parity: tensor2robot `research/pose_env/pose_env_maml_models.py`
— the pose regression task wrapped for meta-learning (SURVEY.md §3
"pose_env"; file:line unavailable — empty reference mount).

The base net here is BatchNorm-free (MAML requirement — per-task
adapted BN stats are ill-defined), so the encoder disables norm layers.
"""

from __future__ import annotations

from typing import Sequence

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.meta_learning import MAMLModel
from tensor2robot_tpu.research.pose_env.pose_env_models import (
    PoseEnvRegressionModel,
)


@gin.configurable
class PoseEnvRegressionModelMAML(MAMLModel):
  """MAML over a BN-free pose regression base."""

  def __init__(self,
               image_size: int = 64,
               pose_dim: int = 2,
               filters: Sequence[int] = (16, 32),
               embedding_size: int = 64,
               hidden_sizes: Sequence[int] = (64,),
               num_inner_steps: int = 1,
               inner_lr: float = 0.05,
               first_order: bool = False,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               **kwargs):
    base = PoseEnvRegressionModel(
        image_size=image_size, pose_dim=pose_dim, filters=filters,
        embedding_size=embedding_size, hidden_sizes=hidden_sizes,
        use_batch_norm=False)
    super().__init__(
        base_model=base,
        num_inner_steps=num_inner_steps,
        inner_lr=inner_lr,
        first_order=first_order,
        num_condition_samples_per_task=num_condition_samples_per_task,
        num_inference_samples_per_task=num_inference_samples_per_task,
        **kwargs)


