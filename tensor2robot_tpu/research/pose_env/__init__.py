"""pose_env research family (reference: tensor2robot research/pose_env/)."""

from tensor2robot_tpu.research.pose_env.pose_env import (
    PoseEnv,
    collect_random_episodes,
    evaluate_pose_model,
)
from tensor2robot_tpu.research.pose_env.mujoco_pose_env import (
    MuJoCoPoseEnv,
)
from tensor2robot_tpu.research.pose_env.pose_env_models import (
    PoseEnvRegressionModel,
)
