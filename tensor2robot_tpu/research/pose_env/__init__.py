"""pose_env research family (reference: tensor2robot research/pose_env/).

Exports resolve LAZILY (PEP 562, the `data/__init__` pattern): fleet
actor processes import `grasp_bandit` (numpy + mujoco) at spawn, and
an eager package init would drag `pose_env_models`' jax import into
processes that only step physics and speak RPC. Gin registration is
declared via `register_lazy_configurables` so shipped configs resolve
these names right after `run_t2r_trainer`'s bare package import.
"""

from tensor2robot_tpu import config as _gin

_EXPORTS = {
    "PoseEnv": "pose_env",
    "collect_random_episodes": "pose_env",
    "evaluate_pose_model": "pose_env",
    "MuJoCoPoseEnv": "mujoco_pose_env",
    "PoseEnvRegressionModel": "pose_env_models",
    "PoseGraspBandit": "grasp_bandit",
}

__all__ = sorted(_EXPORTS)

for _name, _mod in (("collect_random_episodes", "pose_env"),
                    ("evaluate_pose_model", "pose_env"),
                    ("MuJoCoPoseEnv", "mujoco_pose_env"),
                    ("PoseEnvRegressionModel", "pose_env_models"),
                    ("PoseGraspBandit", "grasp_bandit")):
  _gin.register_lazy_configurables(f"{__name__}.{_mod}", (_name,))
del _name, _mod


def __getattr__(name):
  module_name = _EXPORTS.get(name)
  if module_name is None:
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
  import importlib

  module = importlib.import_module(f"{__name__}.{module_name}")
  value = getattr(module, name)
  globals()[name] = value
  return value
