"""Pose envs as a batched grasp bandit: the fleet's env adapter.

`GraspActor` speaks the vectorized single-step bandit interface
`ToyGraspEnv` defined (`reset_batch` / `grade` / `action_dim`); the
pose envs speak per-episode `reset()` + a ground-truth `pose`. This
adapter bridges them so an actor fleet can drive the PHYSICS-BACKED
`MuJoCoPoseEnv` (contact dynamics settle the block; the settled pose
is the target) with QT-Opt's reward structure:

  * observation — the env's rendered RGB image of the settled scene;
  * action — the normalized grasp point in [-1, 1]², mapped linearly
    onto the pose workspace box;
  * reward — 1 when the grasp point lands within `success_threshold`
    WORLD units of the settled block pose, else 0.

Kept jax-free (numpy + the env) so fleet actor processes never pay the
XLA runtime; `physics=True` defers the mujoco import to construction,
mirroring `MuJoCoPoseEnv` itself.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.research.pose_env.pose_env import (
    IMAGE_SIZE,
    WORKSPACE_HIGH,
    WORKSPACE_LOW,
)


def grade_grasp(actions: np.ndarray, positions: np.ndarray,
                threshold: float) -> np.ndarray:
  """THE host grading rule: normalized grasp point → workspace box →
  proximity success. Module-level so it is one function, not a method
  buried in env plumbing: the JAX env family mirrors it exactly
  (`envs.pose.PoseBanditEnv.grasp_reward` — the host-vs-device parity
  pin in tests/test_envs.py compares the two on matched geometry)."""
  grasp = np.asarray(actions, np.float32)[:, :2] * WORKSPACE_HIGH
  dist = np.linalg.norm(grasp - np.asarray(positions, np.float32),
                        axis=-1)
  return (dist < threshold).astype(np.float32)


@gin.configurable
class PoseGraspBandit:
  """Batched single-step grasp bandit over a (MuJoCo) pose env."""

  def __init__(self,
               image_size: int = IMAGE_SIZE,
               action_dim: int = 2,
               success_threshold: float = 0.1,
               physics: bool = True,
               seed: int = 0,
               env=None,
               **env_kwargs):
    """Args:
      image_size: rendered observation size (must match the model's).
      action_dim: actor action width; the FIRST TWO dims are the grasp
        point, extras ride along unused (the paper's gripper command
        dims do the same in the toy env).
      success_threshold: max grasp-point error in WORLD units (the
        workspace box spans ±0.4; 0.1 gives a ~5% random baseline).
      physics: True → `MuJoCoPoseEnv` (drop + settle under contact
        dynamics); False → the numpy `PoseEnv`.
      env: an already-constructed pose env (overrides `physics`).
      **env_kwargs: forwarded to the env constructor.
    """
    if action_dim < 2:
      raise ValueError(
          f"action_dim must be >= 2 (grasp point), got {action_dim}")
    self._action_dim = int(action_dim)
    self._threshold = float(success_threshold)
    if env is not None:
      self._env = env
    elif physics:
      from tensor2robot_tpu.research.pose_env.mujoco_pose_env import (
          MuJoCoPoseEnv,
      )
      self._env = MuJoCoPoseEnv(image_size=image_size, seed=seed,
                                **env_kwargs)
    else:
      from tensor2robot_tpu.research.pose_env.pose_env import PoseEnv
      self._env = PoseEnv(image_size=image_size, seed=seed,
                          **env_kwargs)

  @property
  def action_dim(self) -> int:
    return self._action_dim

  @property
  def success_threshold(self) -> float:
    """Max grasp-point error in WORLD units — the grading geometry a
    device twin must match (`envs.pose.host_parity_env`)."""
    return self._threshold

  @property
  def env(self):
    return self._env

  def reset_batch(self, n: int
                  ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """N fresh episodes: ({image: [N, S, S, 3]}, settled poses [N, 2])."""
    images = []
    poses = []
    for _ in range(n):
      observation = self._env.reset()
      images.append(observation["image"])
      poses.append(self._env.pose)
    return {"image": np.stack(images)}, np.stack(poses)

  def grade(self, actions: np.ndarray,
            positions: np.ndarray) -> np.ndarray:
    """Success per episode: grasp point near the settled pose.

    `actions[:, :2]` in [-1, 1] map linearly onto the workspace box
    (symmetric about the origin), `positions` are world-unit poses
    from `reset_batch`.
    """
    return grade_grasp(actions, positions, self._threshold)

  def sample_transitions(self, n: int) -> Dict[str, np.ndarray]:
    """N random-policy transitions in the learner's replay layout
    (bootstrap/prefill parity with `ToyGraspEnv.sample_transitions`)."""
    rng = getattr(self._env, "_rng", np.random.default_rng(0))
    observations, positions = self.reset_batch(n)
    actions = rng.uniform(
        -1, 1, (n, self._action_dim)).astype(np.float32)
    reward = self.grade(actions, positions)
    return {
        "image": observations["image"],
        "action": actions,
        "reward": reward[:, None].astype(np.float32),
        "done": np.ones((n, 1), np.float32),
        "next_image": observations["image"],
    }


# Re-exported for callers that reason about the action mapping.
__all__ = ["PoseGraspBandit", "grade_grasp", "WORKSPACE_LOW",
           "WORKSPACE_HIGH"]
