"""Pose regression models: image → planar pose.

Reference parity: tensor2robot `research/pose_env/pose_env_models.py` —
`PoseEnvRegressionModel` (conv encoder + regression head over rendered
images; SURVEY.md §3 "pose_env"; file:line unavailable — empty
reference mount).

TPU-first: images stay uint8 across the host→device boundary (4× less
infeed traffic) and are normalized on device, where the cast fuses into
the first conv. The encoder is a small ConvTower + spatial softmax —
keypoint pooling is exactly right for "where is the block".
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.layers import ImageEncoder, MLP
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.regression_model import INFERENCE_OUTPUT
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


class _PoseNetwork(nn.Module):
  """uint8 image -> normalized floats -> encoder -> pose head."""

  filters: Sequence[int]
  embedding_size: int
  hidden_sizes: Sequence[int]
  output_size: int
  use_batch_norm: bool = True
  dtype: jnp.dtype = jnp.bfloat16

  @nn.compact
  def __call__(self, features, train: bool = False):
    image = features["image"]
    image = image.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
    emb = ImageEncoder(
        filters=tuple(self.filters),
        embedding_size=self.embedding_size,
        pooling="spatial_softmax",
        use_batch_norm=self.use_batch_norm,
        dtype=self.dtype,
        name="encoder",
    )(image, train=train)
    pose = MLP(hidden_sizes=tuple(self.hidden_sizes),
               output_size=self.output_size, dtype=self.dtype,
               name="head")(emb, train=train)
    return {INFERENCE_OUTPUT: pose}


@gin.configurable
class PoseEnvRegressionModel(AbstractT2RModel):
  """MSE pose regression from rendered images."""

  def __init__(self,
               image_size: int = 64,
               pose_dim: int = 2,
               filters: Sequence[int] = (32, 64, 128),
               embedding_size: int = 128,
               hidden_sizes: Sequence[int] = (64,),
               use_batch_norm: bool = True,
               device_dtype=jnp.bfloat16,
               **kwargs):
    super().__init__(device_dtype=device_dtype, **kwargs)
    self._image_size = image_size
    self._pose_dim = pose_dim
    self._filters = tuple(filters)
    self._embedding_size = embedding_size
    self._hidden_sizes = tuple(hidden_sizes)
    self._use_batch_norm = use_batch_norm

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(
        shape=(self._image_size, self._image_size, 3), dtype=np.uint8,
        name="image", data_format="jpeg")
    return st

  def get_label_specification(self, mode: Mode) -> TensorSpecStruct:
    st = TensorSpecStruct()
    st.target_pose = ExtendedTensorSpec(
        shape=(self._pose_dim,), dtype=np.float32, name="target_pose")
    return st

  def create_network(self) -> nn.Module:
    return _PoseNetwork(
        filters=self._filters,
        embedding_size=self._embedding_size,
        hidden_sizes=self._hidden_sizes,
        output_size=self._pose_dim,
        use_batch_norm=self._use_batch_norm,
        dtype=self.device_dtype,
    )

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    prediction = outputs[INFERENCE_OUTPUT].astype(jnp.float32)
    target = labels["target_pose"].astype(jnp.float32)
    loss = jnp.mean(jnp.square(prediction - target))
    pose_error = jnp.mean(
        jnp.linalg.norm(prediction - target, axis=-1))
    return loss, {"mse": loss, "pose_error": pose_error}
