"""Pose environment: the smallest end-to-end task in the framework.

Reference parity: tensor2robot `research/pose_env/pose_env.py` — a toy
PyBullet task (predict a target object's planar pose from a rendered
camera image) with random-collect and eval scripts; the reference's
minimal proof that specs → data → train → export → predict all work
(SURVEY.md §3 "pose_env"; file:line unavailable — empty reference mount).

This rebuild ships a dependency-free numpy renderer with the same task
semantics, plus a PHYSICS-BACKED variant
(`mujoco_pose_env.MuJoCoPoseEnv`, round 5): PyBullet isn't in the
image but MuJoCo is, so the physics env drops the block and lets
contact dynamics settle it — the label is the settled pose. (Camera
rendering stays numpy in both: MuJoCo's renderer needs a GL context
and the image has none — osmesa/egl/glfw all fail to load.) An
episode: a block lands at a planar pose on a table; the observation
is an RGB render; the label is the pose. `collect_random_episodes`
writes spec-conforming TFRecords, `evaluate_pose_model` scores a
predictor by mean pose error — the same collect/eval loop shape the
reference's scripts had; both take `env_cls` so the physics variant
is a gin switch.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin

IMAGE_SIZE = 64
# Reachable table region in world units; poses regress into this box.
WORKSPACE_LOW = np.array([-0.4, -0.4], np.float32)
WORKSPACE_HIGH = np.array([0.4, 0.4], np.float32)


class PoseEnv:
  """Numpy pose task: random block pose → rendered RGB observation."""

  def __init__(self, image_size: int = IMAGE_SIZE, seed: int = 0,
               block_half_extent: float = 0.06, noise: float = 0.02):
    self._image_size = image_size
    self._rng = np.random.default_rng(seed)
    self._half = block_half_extent
    self._noise = noise
    self._pose: Optional[np.ndarray] = None

  @property
  def image_size(self) -> int:
    return self._image_size

  def reset(self) -> Dict[str, np.ndarray]:
    """Samples a new block pose; returns the observation dict."""
    self._pose = self._rng.uniform(
        WORKSPACE_LOW, WORKSPACE_HIGH).astype(np.float32)
    return self._observation()

  def _world_to_pixel(self, xy: np.ndarray) -> Tuple[int, int]:
    frac = (xy - WORKSPACE_LOW) / (WORKSPACE_HIGH - WORKSPACE_LOW)
    px = np.clip((frac * self._image_size).astype(int), 0,
                 self._image_size - 1)
    return int(px[0]), int(px[1])

  def _observation(self) -> Dict[str, np.ndarray]:
    size = self._image_size
    # Table: textured gray background with sensor noise.
    image = np.full((size, size, 3), 96, np.uint8)
    noise = self._rng.normal(0, 255 * self._noise, (size, size, 3))
    image = np.clip(image + noise, 0, 255).astype(np.uint8)
    # Block: red square centered at the pose.
    cx, cy = self._world_to_pixel(self._pose)
    extent = max(1, int(self._half / float(
        WORKSPACE_HIGH[0] - WORKSPACE_LOW[0]) * size))
    x0, x1 = max(0, cx - extent), min(size, cx + extent + 1)
    y0, y1 = max(0, cy - extent), min(size, cy + extent + 1)
    image[y0:y1, x0:x1] = np.array([200, 40, 40], np.uint8)
    return {"image": image}

  @property
  def pose(self) -> np.ndarray:
    if self._pose is None:
      raise RuntimeError("Call reset() first.")
    return self._pose


@gin.configurable
def collect_random_episodes(
    output_path: str,
    num_episodes: int = 100,
    image_size: int = IMAGE_SIZE,
    seed: int = 0,
    env_cls: type = None,
) -> str:
  """Renders random poses into a TFRecord file of {image, target_pose}.

  Reference parity: pose_env's random-collect script writing training
  data for offline regression.
  """
  from tensor2robot_tpu.data.tfrecord_input_generator import (
      write_tfrecord,
  )
  from tensor2robot_tpu.research.pose_env.pose_env_models import (
      PoseEnvRegressionModel,
  )
  from tensor2robot_tpu.data.abstract_input_generator import Mode

  env = (env_cls or PoseEnv)(image_size=image_size, seed=seed)
  model = PoseEnvRegressionModel(image_size=image_size)
  examples = []
  for _ in range(num_episodes):
    obs = env.reset()
    examples.append({"image": obs["image"],
                     "target_pose": env.pose})
  os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
  write_tfrecord(
      output_path, examples,
      model.get_feature_specification(Mode.TRAIN),
      model.get_label_specification(Mode.TRAIN))
  return output_path


@gin.configurable
def evaluate_pose_model(
    predict_fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]],
    num_episodes: int = 50,
    image_size: int = IMAGE_SIZE,
    seed: int = 1,
    success_threshold: float = 0.05,
    env_cls: type = None,
) -> Dict[str, float]:
  """Rolls the env and scores predicted poses against ground truth.

  `predict_fn` maps a batched feature dict to an output dict whose first
  value is the predicted pose (the predictor API). Returns mean L2 pose
  error and success rate at `success_threshold` world units.
  """
  env = (env_cls or PoseEnv)(image_size=image_size, seed=seed)
  errors: List[float] = []
  for _ in range(num_episodes):
    obs = env.reset()
    batch = {"image": obs["image"][None]}
    out = predict_fn(batch)
    value = out.get("inference_output",
                    next(iter(out.values())))
    predicted = np.asarray(value)[0].reshape(-1)[:2]
    errors.append(float(np.linalg.norm(predicted - env.pose)))
  errors_arr = np.asarray(errors)
  return {
      "mean_pose_error": float(errors_arr.mean()),
      "success_rate": float((errors_arr < success_threshold).mean()),
      "num_episodes": float(num_episodes),
  }
