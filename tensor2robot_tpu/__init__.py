"""tensor2robot_tpu — a TPU-native robot-learning framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of
google-research/tensor2robot (surveyed in SURVEY.md): declarative tensor
specs that derive parsers, random test data, serving signatures and
sharding; spec-driven input pipelines; an abstract model interface with
regression / classification / critic bases; a pjit-sharded train/eval
orchestrator with async export and polling predictors; a MAML wrapper;
and the research model families (pose_env, QT-Opt, Grasp2Vec, VRGripper).
"""

__version__ = "0.1.0"
