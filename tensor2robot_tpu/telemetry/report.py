"""Single-run report: every telemetry artifact folded into one page.

    python -m tensor2robot_tpu.telemetry.report --run-dir DIR \
        [--out report.md] [--json report.json]

The human-readable face of the whole plane (ISSUE 15): one command
turns a run directory — `metrics_<tag>.jsonl` envelopes, the
orchestrator's aggregated `fleet_metrics.jsonl`, per-process
`trace_<role>.jsonl` files (or an already-merged
`merged_trace.json[.gz]` / `fleet_trace.json.gz`), `flightrec/`
dumps, and the sentinel's `alerts.jsonl` — into one markdown/JSON run
report: throughput rates, the MFU timeline, resource watermarks, the
alert log, and a per-role span summary. Every section is optional;
the report renders whatever the directory holds (the committed
`artifacts/telemetry/` run, which ships only the merged trace, still
reports — the tier-1 smoke pins that).

jax-free, standalone post-mortem tool like `telemetry.merge`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

from tensor2robot_tpu.telemetry import merge as merge_lib
from tensor2robot_tpu.telemetry import records as trecords
from tensor2robot_tpu.telemetry import sentinel as sentinel_lib

# Throughput scalars worth a headline row, in display order.
RATE_KEYS = ("steps_per_sec", "grad_steps_per_sec",
             "env_steps_per_sec", "bellman_batches_per_sec",
             "perf.flops_per_sec", "perf.mfu",
             "perf.device_time_fraction", "stall_fraction",
             "input_wait_fraction")
MERGED_TRACE_NAMES = ("merged_trace.json", "merged_trace.json.gz",
                      "fleet_trace.json.gz", "fleet_trace.json")


def _search_dirs(run_dir: str) -> List[str]:
  """The run dir itself plus its `telemetry/` subdir (fleet layout)."""
  dirs = [run_dir]
  sub = os.path.join(run_dir, "telemetry")
  if os.path.isdir(sub):
    dirs.append(sub)
  return dirs


def _find(run_dir: str, name: str) -> Optional[str]:
  for d in _search_dirs(run_dir):
    path = os.path.join(d, name)
    if os.path.exists(path):
      return path
  return None


def _load_trace_events(run_dir: str) -> List[Dict[str, Any]]:
  """Span events: raw per-process traces merged in memory, else a
  pre-merged Chrome-trace file (`.gz` ok)."""
  for d in _search_dirs(run_dir):
    if glob.glob(os.path.join(d, merge_lib.TRACE_GLOB)):
      return merge_lib.merge_traces(d).get("traceEvents", [])
  for name in MERGED_TRACE_NAMES:
    path = _find(run_dir, name)
    if path is None:
      continue
    try:
      if path.endswith(".gz"):
        import gzip
        with gzip.open(path, "rt") as f:
          trace = json.load(f)
      else:
        with open(path) as f:
          trace = json.load(f)
    except (OSError, ValueError):
      continue
    return trace.get("traceEvents", [])
  return []


def _span_summary(events: List[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
  """Per (role, span name): count + total/mean duration (ms)."""
  table: Dict[tuple, List[float]] = {}
  for event in events:
    if event.get("ph") != "X":
      continue
    key = (event.get("cat", "?"), event.get("name", "?"))
    entry = table.setdefault(key, [0.0, 0.0])
    entry[0] += 1
    entry[1] += float(event.get("dur", 0.0)) / 1e3  # µs → ms
  rows = []
  for (role, name), (count, total_ms) in table.items():
    rows.append({
        "role": role,
        "span": name,
        "count": int(count),
        "total_ms": round(total_ms, 1),
        "mean_ms": round(total_ms / count, 3) if count else 0.0,
    })
  rows.sort(key=lambda r: -r["total_ms"])
  return rows


def _metrics_summaries(run_dir: str) -> tuple:
  """(per-tag envelope summaries + perf.mfu timelines, rsrc.*
  watermarks) — ONE parse of each metrics file feeds both sections
  (the sampler's peaks are monotone, so last-seen == peak)."""
  out: Dict[str, Any] = {}
  marks: Dict[str, float] = {}
  for path in sorted(glob.glob(os.path.join(run_dir,
                                            "metrics_*.jsonl"))):
    tag = os.path.basename(path)[len("metrics_"):-len(".jsonl")]
    try:
      records = trecords.read_records(path)
    except (OSError, ValueError):
      continue
    if not records:
      continue
    for record in records:
      for key, value in record.items():
        if isinstance(key, str) and "rsrc." in key and isinstance(
            value, (int, float)):
          marks[key] = float(value)
    last = records[-1]
    summary: Dict[str, Any] = {
        "records": len(records),
        "first_step": records[0].get("step"),
        "last_step": last.get("step"),
        "role": last.get("role"),
        "last": {k: last[k] for k in RATE_KEYS if k in last},
    }
    timeline = [(r.get("step"), r["perf.mfu"])
                for r in records if "perf.mfu" in r]
    if timeline:
      values = [v for _, v in timeline]
      summary["mfu_timeline"] = timeline
      summary["mfu"] = {"min": min(values), "max": max(values),
                        "mean": sum(values) / len(values),
                        "last": values[-1]}
    out[tag] = summary
  return out, marks


def _fleet_watermarks(fleet_rows: List[Dict[str, Any]]
                      ) -> Dict[str, float]:
  """Last-seen role-prefixed ``rsrc.*`` values from the aggregated
  fleet poll records."""
  marks: Dict[str, float] = {}
  for record in fleet_rows:
    for key, value in record.items():
      if isinstance(key, str) and "rsrc." in key and isinstance(
          value, (int, float)):
        marks[key] = float(value)
  return marks


def build_report(run_dir: str) -> Dict[str, Any]:
  """Everything the run dir holds, as one JSON-able dict."""
  run_dir = os.path.abspath(run_dir)
  fleet_path = _find(run_dir, "fleet_metrics.jsonl")
  fleet_rows: List[Dict[str, Any]] = []
  if fleet_path:
    try:
      fleet_rows = trecords.read_records(fleet_path)
    except (OSError, ValueError):
      fleet_rows = []
  alerts_path = _find(run_dir, sentinel_lib.ALERTS_FILENAME)
  alerts = sentinel_lib.read_alerts(alerts_path) if alerts_path else []
  from tensor2robot_tpu.telemetry import flightrec
  dumps = flightrec.read_dumps(flightrec.flightrec_dir(run_dir))
  events = _load_trace_events(run_dir)
  metrics, watermarks = _metrics_summaries(run_dir)
  watermarks.update(_fleet_watermarks(fleet_rows))
  report = {
      "run_dir": run_dir,
      "metrics": metrics,
      "fleet_polls": len(fleet_rows),
      "fleet_last": ({k: v for k, v in fleet_rows[-1].items()
                      if isinstance(v, (int, float))}
                     if fleet_rows else {}),
      "watermarks": watermarks,
      "alerts": alerts,
      "flight_records": [
          {"role": d.get("role"), "pid": d.get("pid"),
           "reason": str(d.get("reason", ""))[:200],
           "wall": d.get("wall")} for d in dumps],
      "span_summary": _span_summary(events),
      "sources": {
          "metrics_files": sorted(
              os.path.basename(p) for p in glob.glob(
                  os.path.join(run_dir, "metrics_*.jsonl"))),
          "fleet_metrics": bool(fleet_path),
          "alerts": bool(alerts_path),
          "flight_records": len(dumps),
          "trace_events": len(events),
      },
  }
  return report


def _fmt(value: Any) -> str:
  if isinstance(value, float):
    return f"{value:.6g}"
  return str(value)


def render_markdown(report: Dict[str, Any],
                    max_span_rows: int = 15,
                    max_timeline_rows: int = 12) -> str:
  """The human-readable face: one markdown page."""
  lines: List[str] = [f"# Run report: `{report['run_dir']}`", ""]
  sources = report["sources"]
  lines.append(
      f"Sources: {len(sources['metrics_files'])} metrics file(s), "
      f"{report['fleet_polls']} fleet poll(s), "
      f"{sources['trace_events']} trace event(s), "
      f"{len(report['alerts'])} alert(s), "
      f"{sources['flight_records']} flight record(s).")
  lines.append("")

  if report["metrics"]:
    lines.append("## Rates")
    lines.append("")
    lines.append("| tag | role | steps | " + " | ".join(RATE_KEYS)
                 + " |")
    lines.append("|---" * (3 + len(RATE_KEYS)) + "|")
    for tag, summary in sorted(report["metrics"].items()):
      last = summary.get("last", {})
      cells = [_fmt(last[k]) if k in last else "—" for k in RATE_KEYS]
      lines.append(
          f"| {tag} | {summary.get('role', '?')} "
          f"| {summary.get('first_step')}→{summary.get('last_step')} | "
          + " | ".join(cells) + " |")
    lines.append("")

  for tag, summary in sorted(report["metrics"].items()):
    timeline = summary.get("mfu_timeline")
    if not timeline:
      continue
    stats = summary["mfu"]
    lines.append(f"## MFU timeline ({tag})")
    lines.append("")
    lines.append(
        f"min {stats['min']:.4f} · mean {stats['mean']:.4f} · "
        f"max {stats['max']:.4f} · last {stats['last']:.4f}")
    lines.append("")
    lines.append("| step | perf.mfu |")
    lines.append("|---|---|")
    shown = timeline[-max_timeline_rows:]
    if len(timeline) > len(shown):
      lines.append(f"| … | ({len(timeline) - len(shown)} earlier "
                   "rows elided) |")
    for step, value in shown:
      lines.append(f"| {step} | {value:.4f} |")
    lines.append("")

  if report["watermarks"]:
    lines.append("## Resource watermarks")
    lines.append("")
    lines.append("| metric | value |")
    lines.append("|---|---|")
    for name, value in sorted(report["watermarks"].items()):
      lines.append(f"| `{name}` | {_fmt(value)} |")
    lines.append("")

  lines.append("## Alerts")
  lines.append("")
  if report["alerts"]:
    lines.append("| rule | metric | role | value | baseline | "
                 "severity |")
    lines.append("|---|---|---|---|---|---|")
    for alert in report["alerts"]:
      lines.append(
          f"| alert.{alert.get('rule')} | `{alert.get('metric')}` "
          f"| {alert.get('role')} | {_fmt(alert.get('value'))} "
          f"| {_fmt(alert.get('baseline'))} "
          f"| {alert.get('severity')} |")
  else:
    lines.append("No alerts fired (quiet run).")
  lines.append("")

  if report["flight_records"]:
    lines.append("## Flight records")
    lines.append("")
    lines.append("| role | pid | reason |")
    lines.append("|---|---|---|")
    for dump in report["flight_records"]:
      lines.append(f"| {dump['role']} | {dump['pid']} | "
                   f"{dump['reason']} |")
    lines.append("")

  if report["span_summary"]:
    lines.append("## Span summary (per role, by total time)")
    lines.append("")
    lines.append("| role | span | count | total ms | mean ms |")
    lines.append("|---|---|---|---|---|")
    for row in report["span_summary"][:max_span_rows]:
      lines.append(
          f"| {row['role']} | `{row['span']}` | {row['count']} "
          f"| {row['total_ms']} | {row['mean_ms']} |")
    remaining = len(report["span_summary"]) - max_span_rows
    if remaining > 0:
      lines.append(f"| … | ({remaining} more span kinds) | | | |")
    lines.append("")
  return "\n".join(lines)


def has_content(report: Dict[str, Any]) -> bool:
  sources = report["sources"]
  return bool(sources["metrics_files"] or report["fleet_polls"]
              or sources["trace_events"] or report["alerts"]
              or report["flight_records"])


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      description="Fold a run directory's telemetry artifacts into "
                  "one markdown/JSON report.")
  parser.add_argument("--run-dir", required=True,
                      help="model_dir of a run (or any directory "
                      "holding telemetry artifacts, e.g. "
                      "artifacts/telemetry)")
  parser.add_argument("--out", default=None,
                      help="markdown output path (default: stdout)")
  parser.add_argument("--json", dest="json_out", default=None,
                      help="also write the raw report dict as JSON")
  args = parser.parse_args(argv)
  if not os.path.isdir(args.run_dir):
    print(f"report: {args.run_dir!r} is not a directory",
          file=sys.stderr)
    return 2
  report = build_report(args.run_dir)
  markdown = render_markdown(report)
  if args.json_out:
    with open(args.json_out, "w") as f:
      json.dump(report, f, indent=2)
  if args.out:
    with open(args.out, "w") as f:
      f.write(markdown + "\n")
    print(json.dumps({
        "out": args.out,
        "sections": {
            "metrics_tags": sorted(report["metrics"]),
            "alerts": len(report["alerts"]),
            "flight_records": len(report["flight_records"]),
            "span_rows": len(report["span_summary"]),
        }}))
  else:
    print(markdown)
  if not has_content(report):
    print(f"report: nothing to report under {args.run_dir!r}",
          file=sys.stderr)
    return 1
  return 0


if __name__ == "__main__":
  sys.exit(main())
