"""Prometheus text-format adapter over `MetricsRegistry.snapshot()`.

The ROADMAP telemetry follow-on: the registry every subsystem already
publishes into (replay, serving, data plane, trainers, compile cache)
becomes scrapeable by an external Prometheus without any new
instrumentation — this module only TRANSLATES the fixed snapshot
schema (telemetry/metrics.py) into the text exposition format
(version 0.0.4):

  * counters  → ``<name>_total`` with ``# TYPE ... counter``;
  * gauges    → ``<name>`` with ``# TYPE ... gauge``;
  * histograms → CUMULATIVE ``<name>_bucket{le="..."}`` series (the
    registry stores per-bucket counts; Prometheus wants running
    totals) plus ``_sum``/``_count``, with ``le="+Inf"`` closing the
    series.

Metric names sanitize to ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots and
dashes — the registry's namespacing convention — become underscores).

PER-TENANT LABELS: the serving tier publishes tenant-scoped metrics
under ``serving.<tenant>.<rest>`` (engine dispatch histograms, front
completion counters, admission shed counters — docs/SERVING.md). The
adapter renders the tenant as a LABEL instead of a name: every tenant's
``serving.a.bucket_8_ms`` / ``serving.b.bucket_8_ms`` lands in ONE
``t2r_serving_bucket_8_ms`` family with ``tenant="a"`` / ``tenant="b"``
series — the Prometheus data model for the same metric across
entities, so dashboards aggregate and alert across tenants without
per-tenant queries. The segments ``arena``/``front``/``admission`` are
RESERVED namespaces (arena pool gauges etc.), never tenants; tenant
ids are validated against the reservation at registration
(`serving.arena.RESERVED_TENANT_IDS` — kept in sync by a cross-module
test).

`serve()` is the ~endpoint: a daemon-threaded stdlib HTTP server
answering ``GET /metrics``, snapshotting at scrape time. jax-free BY
CONTRACT like the rest of the package (IMP401 worker-safe set) — an
actor or data-plane worker can expose its own scrape port.
"""

from __future__ import annotations

import http.server
import re
import threading
from typing import Dict, Optional

from tensor2robot_tpu.telemetry import metrics as metrics_lib

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Middle segments of `serving.<x>.*` that are serving SUBSYSTEM
# namespaces, not tenants. Must cover serving/arena.py's
# RESERVED_TENANT_IDS (tenant registration rejects these ids; a
# cross-module test pins the two sets against each other without
# importing jax here — this module stays worker-safe).
RESERVED_SERVING_NAMESPACES = frozenset({"arena", "front", "admission"})


def _sanitize(name: str) -> str:
  name = _NAME_RE.sub("_", name)
  if not name or name[0].isdigit():
    name = "_" + name
  return name


def _fmt(value) -> str:
  return repr(float(value))


def _split_tenant(name: str):
  """`serving.<tenant>.<rest>` → (`serving.<rest>`, tenant); anything
  else (incl. the reserved serving namespaces) passes through."""
  parts = name.split(".")
  if (len(parts) >= 3 and parts[0] == "serving"
      and parts[1] not in RESERVED_SERVING_NAMESPACES):
    return "serving." + ".".join(parts[2:]), parts[1]
  return name, None


def _escape_label(value: str) -> str:
  return (value.replace("\\", r"\\").replace('"', r'\"')
          .replace("\n", r"\n"))


def _labels(tenant: Optional[str], extra: str = "") -> str:
  items = []
  if tenant is not None:
    items.append(f'tenant="{_escape_label(tenant)}"')
  if extra:
    items.append(extra)
  return "{" + ",".join(items) + "}" if items else ""


def render_text(snapshot: Optional[Dict] = None,
                prefix: str = "t2r_") -> str:
  """One scrape body from a registry snapshot (default: the
  process-wide registry, snapshotted now). Tenant-scoped serving
  metrics merge into one family per metric with a ``tenant`` label;
  each family's ``# TYPE`` line is emitted exactly once."""
  if snapshot is None:
    snapshot = metrics_lib.registry().snapshot()
  lines = []

  def families_of(section):
    """name → family metric + per-series (tenant, payload) rows,
    grouped so multi-tenant series share one TYPE header."""
    families: Dict[str, list] = {}
    for name, payload in section.items():
      base, tenant = _split_tenant(name)
      families.setdefault(base, []).append((tenant, payload))
    for base in sorted(families):
      # Stable series order: unlabeled first, then tenants sorted.
      series = sorted(families[base],
                      key=lambda row: (row[0] is not None, row[0]))
      yield base, series

  for base, series in families_of(snapshot.get("counters", {})):
    metric = prefix + _sanitize(base)
    if not metric.endswith("_total"):
      metric += "_total"
    lines.append(f"# TYPE {metric} counter")
    for tenant, value in series:
      lines.append(f"{metric}{_labels(tenant)} {_fmt(value)}")
  for base, series in families_of(snapshot.get("gauges", {})):
    metric = prefix + _sanitize(base)
    lines.append(f"# TYPE {metric} gauge")
    for tenant, value in series:
      lines.append(f"{metric}{_labels(tenant)} {_fmt(value)}")
  for base, series in families_of(snapshot.get("histograms", {})):
    metric = prefix + _sanitize(base)
    lines.append(f"# TYPE {metric} histogram")
    for tenant, hist in series:
      running = 0
      for bound, count in zip(hist["bounds"], hist["counts"]):
        running += count
        bucket_labels = _labels(tenant, f'le="{_fmt(bound)}"')
        lines.append(f"{metric}_bucket{bucket_labels} {running}")
      inf_labels = _labels(tenant, 'le="+Inf"')
      lines.append(f'{metric}_bucket{inf_labels} {hist["count"]}')
      lines.append(f"{metric}_sum{_labels(tenant)} {_fmt(hist['sum'])}")
      lines.append(f"{metric}_count{_labels(tenant)} {hist['count']}")
  return "\n".join(lines) + "\n"


class PrometheusEndpoint:
  """``GET /metrics`` over a daemon-threaded stdlib HTTP server."""

  def __init__(self, port: int = 0, host: str = "127.0.0.1",
               prefix: str = "t2r_"):
    endpoint = self

    class Handler(http.server.BaseHTTPRequestHandler):

      def do_GET(self):  # noqa: N802 — stdlib handler contract
        if self.path.split("?")[0] != "/metrics":
          self.send_error(404)
          return
        body = render_text(prefix=endpoint._prefix).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

      def log_message(self, *args):  # scrapes stay out of stderr
        del args

    self._prefix = prefix
    self._server = http.server.ThreadingHTTPServer((host, port),
                                                   Handler)
    self.port = self._server.server_address[1]
    self._thread = threading.Thread(
        target=self._server.serve_forever, name="prometheus-scrape",
        daemon=True)
    self._thread.start()

  def close(self) -> None:
    self._server.shutdown()
    self._server.server_close()
    self._thread.join(timeout=5.0)


def serve(port: int = 0, host: str = "127.0.0.1",
          prefix: str = "t2r_") -> PrometheusEndpoint:
  """Starts (and returns) the scrape endpoint; `port=0` picks a free
  one (read it back from ``.port``)."""
  return PrometheusEndpoint(port=port, host=host, prefix=prefix)


def default_port(port: Optional[int] = None) -> Optional[int]:
  """The gin-backed default for `run_t2r_trainer --prometheus_port`
  (ISSUE 15): bind ``default_port.port`` in a config to start the
  scrape endpoint in ANY trainer/fleet process without passing the
  flag (0 = ephemeral port, None = off)."""
  return port


# Registered at import (the config engine is jax-free — it already
# rides the telemetry package import via the sentinel's watches).
from tensor2robot_tpu import config as _gin  # noqa: E402

default_port = _gin.configurable(default_port)
