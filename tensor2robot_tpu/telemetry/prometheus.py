"""Prometheus text-format adapter over `MetricsRegistry.snapshot()`.

The ROADMAP telemetry follow-on: the registry every subsystem already
publishes into (replay, serving, data plane, trainers, compile cache)
becomes scrapeable by an external Prometheus without any new
instrumentation — this module only TRANSLATES the fixed snapshot
schema (telemetry/metrics.py) into the text exposition format
(version 0.0.4):

  * counters  → ``<name>_total`` with ``# TYPE ... counter``;
  * gauges    → ``<name>`` with ``# TYPE ... gauge``;
  * histograms → CUMULATIVE ``<name>_bucket{le="..."}`` series (the
    registry stores per-bucket counts; Prometheus wants running
    totals) plus ``_sum``/``_count``, with ``le="+Inf"`` closing the
    series.

Metric names sanitize to ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots and
dashes — the registry's namespacing convention — become underscores).

`serve()` is the ~endpoint: a daemon-threaded stdlib HTTP server
answering ``GET /metrics``, snapshotting at scrape time. jax-free BY
CONTRACT like the rest of the package (IMP401 worker-safe set) — an
actor or data-plane worker can expose its own scrape port.
"""

from __future__ import annotations

import http.server
import re
import threading
from typing import Dict, Optional

from tensor2robot_tpu.telemetry import metrics as metrics_lib

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _sanitize(name: str) -> str:
  name = _NAME_RE.sub("_", name)
  if not name or name[0].isdigit():
    name = "_" + name
  return name


def _fmt(value) -> str:
  return repr(float(value))


def render_text(snapshot: Optional[Dict] = None,
                prefix: str = "t2r_") -> str:
  """One scrape body from a registry snapshot (default: the
  process-wide registry, snapshotted now)."""
  if snapshot is None:
    snapshot = metrics_lib.registry().snapshot()
  lines = []
  for name, value in sorted(snapshot.get("counters", {}).items()):
    metric = prefix + _sanitize(name)
    if not metric.endswith("_total"):
      metric += "_total"
    lines += [f"# TYPE {metric} counter", f"{metric} {_fmt(value)}"]
  for name, value in sorted(snapshot.get("gauges", {}).items()):
    metric = prefix + _sanitize(name)
    lines += [f"# TYPE {metric} gauge", f"{metric} {_fmt(value)}"]
  for name, hist in sorted(snapshot.get("histograms", {}).items()):
    metric = prefix + _sanitize(name)
    lines.append(f"# TYPE {metric} histogram")
    running = 0
    for bound, count in zip(hist["bounds"], hist["counts"]):
      running += count
      lines.append(f'{metric}_bucket{{le="{_fmt(bound)}"}} {running}')
    lines.append(f'{metric}_bucket{{le="+Inf"}} {hist["count"]}')
    lines.append(f"{metric}_sum {_fmt(hist['sum'])}")
    lines.append(f"{metric}_count {hist['count']}")
  return "\n".join(lines) + "\n"


class PrometheusEndpoint:
  """``GET /metrics`` over a daemon-threaded stdlib HTTP server."""

  def __init__(self, port: int = 0, host: str = "127.0.0.1",
               prefix: str = "t2r_"):
    endpoint = self

    class Handler(http.server.BaseHTTPRequestHandler):

      def do_GET(self):  # noqa: N802 — stdlib handler contract
        if self.path.split("?")[0] != "/metrics":
          self.send_error(404)
          return
        body = render_text(prefix=endpoint._prefix).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

      def log_message(self, *args):  # scrapes stay out of stderr
        del args

    self._prefix = prefix
    self._server = http.server.ThreadingHTTPServer((host, port),
                                                   Handler)
    self.port = self._server.server_address[1]
    self._thread = threading.Thread(
        target=self._server.serve_forever, name="prometheus-scrape",
        daemon=True)
    self._thread.start()

  def close(self) -> None:
    self._server.shutdown()
    self._server.server_close()
    self._thread.join(timeout=5.0)


def serve(port: int = 0, host: str = "127.0.0.1",
          prefix: str = "t2r_") -> PrometheusEndpoint:
  """Starts (and returns) the scrape endpoint; `port=0` picks a free
  one (read it back from ``.port``)."""
  return PrometheusEndpoint(port=port, host=host, prefix=prefix)
