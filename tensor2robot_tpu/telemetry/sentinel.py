"""Alert sentinel: rule evaluation over the metrics stream (ISSUE 15).

Nothing watched the registry for regressions before this module: a
mid-run recompile or a slowly degrading actor surfaced only when a
human read `fleet_metrics.jsonl`. The sentinel closes that loop —
`Watch` rules (rolling-baseline EWMA + absolute bounds,
gin-configurable) are evaluated at the trainers' log cadence and the
orchestrator's poll cadence over the flat scalar view the registry
already produces, and a breach

  * emits an ``alert.<rule>`` telemetry event + bumps the shared
    ``alert.fired`` counter and a per-rule counter,
  * appends one JSON record to ``alerts.jsonl`` next to the run's
    other telemetry files (the report tool's alert log),
  * ESCALATES through the severity tiers (ISSUE 18):
    ``log`` → record only; ``warn`` → the warning log; ``act`` →
    the caller's act hook (the control plane's remediation entry);
    ``page`` → the act hook FIRST — a remediation that reports the
    alert handled DEMOTES the page to the act tier — and only an
    unremediated breach invokes the page hook. Flight records stay
    the TERMINAL tier: the trainers dump a flight record; the fleet
    orchestrator dumps its own view AND requests a host dump, naming
    the offending role exactly as the hang path does — so an
    unremediated regression self-documents with the same artifact a
    crash gets. The record's ``escalation`` field names the tier
    actually reached.

Rule grammar (docs/OBSERVABILITY.md §"Sentinel"):

  kind        breach condition
  ----------  ----------------------------------------------------
  above       value > threshold (absolute bound)
  below       value < threshold
  increase    value > last_value + threshold (counters: any warm-path
              increment with threshold 0)
  ewma_drop   value < ewma · (1 − threshold)  (threshold = fraction)
  ewma_spike  value > ewma · (1 + threshold)

`warmup` evaluations establish the baseline and can never fire;
`sustain` consecutive breaching evaluations are required to fire; a
fired rule holds (hysteresis — no re-fire) until one non-breaching
evaluation re-arms it, so a sustained regression fires exactly once.
The EWMA baseline only absorbs NON-breaching values — a sustained
drop cannot drag its own baseline down and silence itself.

In the fleet's aggregated view metrics arrive role-prefixed
(``actor-0/fleet.rpc.timeouts``); a watch matches the bare metric in
every role, keeps per-role state, and the alert names the role.

jax-free (IMP401 worker-safe set) like the rest of the package.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.telemetry import core
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import perf as perf_lib

log = logging.getLogger(__name__)

ALERTS_FILENAME = "alerts.jsonl"
KINDS = ("above", "below", "increase", "ewma_drop", "ewma_spike")
# Escalation tiers, mildest first. "act" asks the act hook (the
# control plane) to remediate and never pages; "page" tries the act
# hook first and pages only when unremediated (ISSUE 18).
SEVERITIES = ("log", "warn", "act", "page")


@gin.configurable
@dataclasses.dataclass(frozen=True)
class Watch:
  """One sentinel rule (see the module-docstring grammar)."""

  name: str = gin.REQUIRED        # -> alert.<name>
  metric: str = gin.REQUIRED      # flat scalar key (histograms: _p50/_p95)
  kind: str = "above"
  threshold: float = 0.0
  warmup: int = 1                 # evaluations before the rule can fire
  sustain: int = 1                # consecutive breaches required
  alpha: float = 0.2              # EWMA smoothing factor
  severity: str = "warn"

  def __post_init__(self):
    if self.kind not in KINDS:
      raise ValueError(f"watch {self.name!r}: kind must be one of "
                       f"{KINDS}, got {self.kind!r}")
    if self.severity not in SEVERITIES:
      raise ValueError(f"watch {self.name!r}: severity must be one of "
                       f"{SEVERITIES}, got {self.severity!r}")
    if not 0.0 < self.alpha <= 1.0:
      raise ValueError(f"watch {self.name!r}: alpha must be in (0, 1]")


class _WatchState:
  """Per-(watch, metric-key) evaluation state."""

  __slots__ = ("seen", "ewma", "last", "streak", "fired")

  def __init__(self):
    self.seen = 0
    self.ewma: Optional[float] = None
    self.last: Optional[float] = None
    self.streak = 0
    self.fired = False


class Sentinel:
  """Evaluates watches over flat scalar views at log cadence.

  `on_act(record) -> bool` is the remediation hook (the control
  plane's `Controller.handle_alert`): it runs for ``act`` and
  ``page`` severities, and returning True on a page DEMOTES it — the
  remediation acted, so no flight records. `on_page(record)` runs
  only for alerts that ESCALATE to the page tier — the
  flight-recorder trigger stays terminal. Evaluation is cheap (a
  dict scan per watch) and never raises: a broken rule must not take
  down the train loop it instruments.
  """

  def __init__(self,
               watches: Sequence[Watch],
               alerts_path: Optional[str] = None,
               on_page: Optional[Callable[[Dict[str, Any]], None]] = None,
               registry: Optional[tmetrics.MetricsRegistry] = None,
               tracer: Optional[core.Tracer] = None,
               on_act: Optional[Callable[[Dict[str, Any]], bool]] = None):
    self.watches = list(watches)
    self._alerts_path = alerts_path
    self._on_page = on_page
    self._on_act = on_act
    # `tracer`: where alert.<rule> events land. None = the
    # process-global tracer; the fleet orchestrator passes its private
    # one (it may supervise from inside a process with its own
    # telemetry identity).
    self._tracer = tracer
    self._registry = registry or tmetrics.registry()
    self._states: Dict[tuple, _WatchState] = {}
    # One owner thread by design (the train loop / orchestrator poll
    # that calls evaluate()) — like RpcClient, no lock to hold across
    # the alert append's file I/O (the CON301 contract this package is
    # linted with).
    self._file: Optional[Any] = None
    self.alerts: List[Dict[str, Any]] = []

  # ---- evaluation ----

  def _keys_for(self, metric: str,
                scalars: Dict[str, float]) -> List[str]:
    """The bare metric plus every role-prefixed twin (`role/metric`,
    the orchestrator's aggregated view)."""
    suffix = "/" + metric
    return [key for key in scalars
            if key == metric or key.endswith(suffix)]

  def _breach(self, watch: Watch, state: _WatchState,
              value: float) -> tuple:
    """(breached, baseline) for one observation; updates state's
    baseline bookkeeping (EWMA absorbs only non-breaching values)."""
    warming = state.seen < watch.warmup
    baseline: Optional[float] = None
    breached = False
    if watch.kind == "above":
      breached = value > watch.threshold
    elif watch.kind == "below":
      breached = value < watch.threshold
    elif watch.kind == "increase":
      baseline = state.last
      breached = (state.last is not None
                  and value > state.last + watch.threshold)
      state.last = value
    else:  # ewma_drop / ewma_spike
      baseline = state.ewma
      if state.ewma is not None:
        if watch.kind == "ewma_drop":
          breached = value < state.ewma * (1.0 - watch.threshold)
        else:
          breached = value > state.ewma * (1.0 + watch.threshold)
      if state.ewma is None:
        state.ewma = value
      elif warming or not breached:
        # The baseline only absorbs healthy values: a sustained
        # breach cannot normalize itself away.
        state.ewma += watch.alpha * (value - state.ewma)
    state.seen += 1
    if warming:
      return False, baseline  # warmup can never fire
    return breached, baseline

  def evaluate(self, scalars: Optional[Dict[str, float]] = None,
               step: Optional[int] = None) -> List[Dict[str, Any]]:
    """One evaluation pass; returns the alerts fired THIS pass.

    ``scalars`` defaults to this process's registry flat view; the
    orchestrator passes its aggregated role-prefixed payload instead.
    """
    if scalars is None:
      scalars = self._registry.scalars()
    fired: List[Dict[str, Any]] = []
    for watch in self.watches:
      for key in self._keys_for(watch.metric, scalars):
        try:
          value = float(scalars[key])
        except (TypeError, ValueError):
          continue
        state = self._states.setdefault((watch.name, key),
                                        _WatchState())
        breached, baseline = self._breach(watch, state, value)
        if not breached:
          state.streak = 0
          state.fired = False  # recovery re-arms the rule
          continue
        state.streak += 1
        if state.streak < watch.sustain or state.fired:
          continue  # not sustained yet / hysteresis hold
        state.fired = True
        fired.append(self._fire(watch, key, value, baseline, step))
    return fired

  # ---- firing ----

  def _fire(self, watch: Watch, key: str, value: float,
            baseline: Optional[float],
            step: Optional[int]) -> Dict[str, Any]:
    role = key.rsplit("/", 1)[0] if "/" in key else core.current_role()
    record: Dict[str, Any] = {
        "rule": watch.name,
        "metric": key,
        "role": role,
        "value": value,
        "baseline": baseline,
        "threshold": watch.threshold,
        "kind": watch.kind,
        "severity": watch.severity,
        "wall": time.time(),
    }
    if step is not None:
      record["step"] = int(step)
    log.log(logging.INFO if watch.severity == "log" else logging.WARNING,
            "sentinel alert.%s: %s=%.6g (baseline %s, %s %s) "
            "severity=%s", watch.name, key, value, baseline,
            watch.kind, watch.threshold, watch.severity)
    (self._tracer.event if self._tracer is not None else core.event)(
        f"alert.{watch.name}", metric=key,
        value=round(value, 6), severity=watch.severity)
    self._registry.counter("alert.fired").inc()
    self._registry.counter(f"alert.{watch.name}").inc()
    # Escalation (ISSUE 18): act/page severities offer the alert to
    # the remediation hook first; a handled page DEMOTES to the act
    # tier and flight records stay terminal.
    escalation = watch.severity
    if watch.severity in ("act", "page") and self._on_act is not None:
      handled = False
      try:
        handled = bool(self._on_act(record))
      except Exception:  # noqa: BLE001 — a broken remediation must
        # not mask the alert (nor block the page below).
        log.warning("sentinel act hook failed", exc_info=True)
      record["handled"] = handled
      if handled:
        self._registry.counter("alert.remediated").inc()
        if watch.severity == "page":
          escalation = "act"
    record["escalation"] = escalation
    self.alerts.append(record)
    self._append(record)
    if escalation == "page":
      self._registry.counter("alert.paged").inc()
      if self._on_page is not None:
        try:
          self._on_page(record)
        except Exception:  # noqa: BLE001 — forensics must not mask
          log.warning("sentinel page hook failed", exc_info=True)
    return record

  def _append(self, record: Dict[str, Any]) -> None:
    if not self._alerts_path:
      return
    try:
      if self._file is None:
        os.makedirs(os.path.dirname(self._alerts_path) or ".",
                    exist_ok=True)
        self._file = open(self._alerts_path, "a")
      self._file.write(json.dumps(record) + "\n")
      self._file.flush()
    except OSError:
      log.warning("could not append to %s; alert kept in memory only",
                  self._alerts_path, exc_info=True)

  def close(self) -> None:
    if self._file is not None:
      self._file.close()
      self._file = None


def read_alerts(path: str) -> List[Dict[str, Any]]:
  """All alert records of one ``alerts.jsonl`` (the report tool's
  reader; [] for a missing file — a quiet run writes none)."""
  alerts: List[Dict[str, Any]] = []
  if not os.path.exists(path):
    return alerts
  with open(path) as f:
    for line in f:
      line = line.strip()
      if not line:
        continue
      try:
        alerts.append(json.loads(line))
      except ValueError:
        continue  # a torn line from a dying writer
  return alerts


@gin.configurable
def default_watches(
    mfu_drop_fraction: float = 0.25,
    mfu_warmup: int = 4,
    mfu_sustain: int = 3,
    stall_fraction_max: float = 0.5,
    stall_sustain: int = 3,
    host_rss_budget_bytes: float = 0.0,
    recompile_severity: str = "warn",
) -> List[Watch]:
  """The trainers' standing rule set (gin-tunable thresholds).

  ``host_rss_budget_bytes=0`` disables the RSS budget watch (there is
  no universal default budget); set it per deployment.
  """
  watches = [
      # Sustained live-MFU drop vs the run's own rolling baseline.
      Watch(name="mfu_drop", metric="perf.mfu", kind="ewma_drop",
            threshold=mfu_drop_fraction, warmup=mfu_warmup,
            sustain=mfu_sustain),
      # Stall spike: the loop is losing most of its wall to
      # save/eval/log stalls.
      Watch(name="stall_spike", metric="train.stall_fraction",
            kind="above", threshold=stall_fraction_max,
            sustain=stall_sustain),
      # Any warm-path recompile: compile_cache.misses moved after the
      # first log interval (the CompileWatch tap, docs/OBSERVABILITY.md).
      Watch(name="warm_recompile", metric="compile_cache.misses",
            kind="increase", threshold=0.0, warmup=1, sustain=1,
            severity=recompile_severity),
  ]
  if host_rss_budget_bytes:
    watches.append(
        Watch(name="rss_over_budget", metric="rsrc.host_rss_bytes",
              kind="above", threshold=host_rss_budget_bytes,
              sustain=1, severity="page"))
  return watches


@gin.configurable
def fleet_watches(
    recovery_p95_ms_max: float = 60000.0,
    rpc_timeout_severity: str = "warn",
    replay_fill_max: float = 1.01,
) -> List[Watch]:
  """The orchestrator's standing rules over the aggregated view.

  ``rpc_timeout_severity`` defaults to ``warn`` so routine chaos
  rehearsal (bench --chaos injects RPC faults on purpose) does not
  page; the bench --telemetry sentinel leg and deployments that want
  the flight record set it to ``page``.
  """
  return [
      # `above 0`, not `increase`: the timeouts counter is CREATED
      # lazily by the first timeout, so the first value a poll ever
      # sees is already nonzero — an increase rule would baseline on
      # it and stay silent forever. Above-zero fires once (hysteresis
      # holds while the counter stays breached) — exactly one alert
      # per run with timeouts.
      Watch(name="rpc_timeouts", metric="fleet.rpc.timeouts",
            kind="above", threshold=0.0, warmup=0, sustain=1,
            severity=rpc_timeout_severity),
      Watch(name="recovery_p95", metric="fleet.recovery_ms_p95",
            kind="above", threshold=recovery_p95_ms_max, sustain=1),
      Watch(name="replay_overflow", metric="replay.fill",
            kind="above", threshold=replay_fill_max, sustain=2),
  ]


@gin.configurable(denylist=("model_dir",))
def build_for_run(model_dir: str,
                  enabled: bool = True,
                  watches: Optional[Sequence[Watch]] = None,
                  on_page: Optional[Callable] = None
                  ) -> Optional[Sentinel]:
  """The trainers' sentinel factory: default watches, alerts.jsonl
  under ``<model_dir>/telemetry/``, and a page hook that dumps this
  process's flight record to ``<model_dir>/flightrec/`` — the same
  artifact a crash gets. None when disabled (gin) or when the perf
  plane is off (`perf.plane_enabled`)."""
  if not enabled or not perf_lib.plane_enabled():
    return None
  if on_page is None:
    from tensor2robot_tpu.telemetry import flightrec

    def on_page(record: Dict[str, Any]) -> None:
      flightrec.dump(
          flightrec.flightrec_dir(model_dir),
          f"sentinel page: alert.{record['rule']} on "
          f"{record['metric']} = {record['value']:.6g} "
          f"(role {record['role']})")

  return Sentinel(
      watches if watches is not None else default_watches(),
      alerts_path=os.path.join(model_dir, "telemetry",
                               ALERTS_FILENAME),
      on_page=on_page)
