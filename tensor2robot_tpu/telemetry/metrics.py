"""Process-wide metrics registry: counters, gauges, histograms.

One registry per process, a fixed snapshot schema, and every subsystem
publishing into it at its event sites — replay fill/add/sample/drop,
serving bucket latency + micro-batcher queue depth, param-refresh lag,
staleness, stall/input-wait fractions, compile-cache hits/misses —
so the fleet host can answer a ``telemetry`` RPC with ONE dict and the
orchestrator can log one aggregated fleet-wide view (docs/OBSERVABILITY.md
catalogs the metric names and definitions).

Snapshot schema (fixed — the schema-validation tests pin it)::

    {"counters":   {name: float},          # monotonic totals
     "gauges":     {name: float},          # last-set values
     "histograms": {name: {"bounds": [...], "counts": [...],
                           "count": n, "sum": s, "min": lo,
                           "max": hi, "p50": ..., "p95": ...}}}

Thread-safety: each metric guards its few arithmetic ops with its own
lock — nothing blocking ever runs under one (the CON301 contract this
package is linted with). Updates are nanoseconds; these sit on replay
adds and serving dispatches.

jax-free by design: data-plane workers and fleet actors publish too
(IMP401 worker-safe set).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Default histogram bounds: latency in MILLISECONDS, log-spaced from
# sub-bucket dispatches to multi-second stalls. Values above the last
# bound land in the overflow bucket.
DEFAULT_MS_BOUNDS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                     50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
                     5000.0, 10000.0)
# For step-denominated distributions (lag, staleness) — the fleet
# host's LAG_BUCKETS family.
DEFAULT_STEP_BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                       128.0, 256.0, 512.0, 1024.0, 4096.0)


class Counter:
  """Monotonic total. `inc` only — resets happen by registry reset."""

  __slots__ = ("_lock", "value")

  def __init__(self):
    self._lock = threading.Lock()
    self.value = 0.0

  def inc(self, n: float = 1.0) -> None:
    with self._lock:
      self.value += n


class Gauge:
  """Last-set value (fill fractions, queue depths, rates)."""

  __slots__ = ("_lock", "value")

  def __init__(self):
    self._lock = threading.Lock()
    self.value = 0.0

  def set(self, value: float) -> None:
    with self._lock:
      self.value = float(value)


class Histogram:
  """Fixed-bound histogram with running count/sum/min/max.

  ``bounds`` are inclusive upper edges; one overflow bucket catches
  everything above the last bound. Quantiles are estimated from the
  bucket counts (linear interpolation inside the winning bucket), the
  standard Prometheus-style read: exact enough for p50/p95 dashboards
  at these bucket densities.
  """

  __slots__ = ("_lock", "bounds", "counts", "count", "sum",
               "min", "max")

  def __init__(self, bounds: Sequence[float] = DEFAULT_MS_BOUNDS):
    self._lock = threading.Lock()
    self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
    self.counts = [0] * (len(self.bounds) + 1)
    self.count = 0
    self.sum = 0.0
    self.min: Optional[float] = None
    self.max: Optional[float] = None

  def observe(self, value: float, n: int = 1) -> None:
    """Records `value` with weight `n` (e.g. a per-commit lag applies
    to every row of the commit — n=rows keeps the distribution
    row-weighted without n bisects)."""
    value = float(value)
    index = bisect.bisect_left(self.bounds, value)
    with self._lock:
      self.counts[index] += n
      self.count += n
      self.sum += value * n
      if self.min is None or value < self.min:
        self.min = value
      if self.max is None or value > self.max:
        self.max = value

  def quantile(self, q: float) -> float:
    """Bucket-interpolated quantile; 0.0 on an empty histogram."""
    with self._lock:
      counts = list(self.counts)
      total = self.count
      hi = self.max
    if not total:
      return 0.0
    rank = q * total
    seen = 0
    for index, bucket_count in enumerate(counts):
      if seen + bucket_count >= rank:
        if index == len(self.bounds):  # overflow bucket
          return float(hi)
        lo = self.bounds[index - 1] if index else 0.0
        up = self.bounds[index]
        if not bucket_count:
          return up
        frac = (rank - seen) / bucket_count
        return lo + (up - lo) * min(max(frac, 0.0), 1.0)
      seen += bucket_count
    return float(hi)

  def snapshot(self) -> Dict[str, object]:
    with self._lock:
      snap = {
          "bounds": list(self.bounds),
          "counts": list(self.counts),
          "count": int(self.count),
          "sum": float(self.sum),
          "min": self.min,
          "max": self.max,
      }
    snap["p50"] = self.quantile(0.5)
    snap["p95"] = self.quantile(0.95)
    return snap


class MetricsRegistry:
  """Name → metric table with get-or-create accessors and the fixed
  snapshot schema. The registry lock guards only dict lookups; metric
  updates take the metric's own lock."""

  def __init__(self):
    self._lock = threading.Lock()
    self._counters: Dict[str, Counter] = {}
    self._gauges: Dict[str, Gauge] = {}
    self._histograms: Dict[str, Histogram] = {}

  def counter(self, name: str) -> Counter:
    with self._lock:
      metric = self._counters.get(name)
      if metric is None:
        metric = self._counters[name] = Counter()
    return metric

  def gauge(self, name: str) -> Gauge:
    with self._lock:
      metric = self._gauges.get(name)
      if metric is None:
        metric = self._gauges[name] = Gauge()
    return metric

  def histogram(self, name: str,
                bounds: Sequence[float] = DEFAULT_MS_BOUNDS
                ) -> Histogram:
    with self._lock:
      metric = self._histograms.get(name)
      if metric is None:
        metric = self._histograms[name] = Histogram(bounds)
    return metric

  def snapshot(self) -> Dict[str, Dict[str, object]]:
    """The full registry in the fixed schema (see module docstring)."""
    with self._lock:
      counters = dict(self._counters)
      gauges = dict(self._gauges)
      histograms = dict(self._histograms)
    return {
        "counters": {n: float(c.value) for n, c in counters.items()},
        "gauges": {n: float(g.value) for n, g in gauges.items()},
        "histograms": {n: h.snapshot() for n, h in histograms.items()},
    }

  def scalars(self, prefix: str = "") -> Dict[str, float]:
    """The flat-scalar cut, shaped for `metrics_<tag>.jsonl` payloads:
    counters/gauges as-is, histograms as `<name>_{p50,p95,count}`.
    ``prefix`` filters by metric-name prefix."""
    return scalars_from_snapshot(self.snapshot(), name_filter=prefix)

  def reset(self) -> None:
    with self._lock:
      self._counters.clear()
      self._gauges.clear()
      self._histograms.clear()


def scalars_from_snapshot(snapshot: Dict[str, Dict[str, object]],
                          prefix: str = "",
                          name_filter: str = "") -> Dict[str, float]:
  """Flattens a registry `snapshot()` (this process's or one shipped
  over the fleet's ``telemetry_push`` RPC) to scalars, optionally
  prepending ``prefix`` to every key (the orchestrator's per-role
  aggregation) and keeping only names starting with ``name_filter``."""
  out: Dict[str, float] = {}
  for name, value in snapshot.get("counters", {}).items():
    if name.startswith(name_filter):
      out[prefix + name] = float(value)
  for name, value in snapshot.get("gauges", {}).items():
    if name.startswith(name_filter):
      out[prefix + name] = float(value)
  for name, hist in snapshot.get("histograms", {}).items():
    if name.startswith(name_filter) and hist.get("count"):
      out[f"{prefix}{name}_p50"] = float(hist["p50"])
      out[f"{prefix}{name}_p95"] = float(hist["p95"])
      out[f"{prefix}{name}_count"] = float(hist["count"])
  return out


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
  """The process-wide registry every subsystem publishes into."""
  return _REGISTRY


def counter(name: str) -> Counter:
  return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
  return _REGISTRY.gauge(name)


def histogram(name: str,
              bounds: Sequence[float] = DEFAULT_MS_BOUNDS) -> Histogram:
  return _REGISTRY.histogram(name, bounds)


def reset_for_tests() -> None:
  _REGISTRY.reset()
