"""Trace merge tool: per-process JSONL traces → one Chrome/Perfetto
timeline.

    python -m tensor2robot_tpu.telemetry.merge --trace-dir DIR \
        [--out merged_trace.json]

Reads every ``trace_<role>.jsonl`` a run's processes wrote
(`telemetry.core`), reconciles clocks via the per-file
``clock_offset`` meta lines (learned from the fleet RPC handshake —
every process's spans land on the HOST's monotonic clock), and emits
one Chrome-trace JSON (the `chrome://tracing` / Perfetto `traceEvents`
array format, `ts`/`dur` in microseconds relative to the earliest
span). Each process appears as its role (`process_name` metadata
events), so the merged view answers the fleet-scale bottleneck
question — learner input-starved vs host coalescing poorly vs an
actor wedged — from one screen.

jax-free (runs as a standalone post-mortem tool).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

TRACE_GLOB = "trace_*.jsonl"


def load_trace_file(path: str) -> Tuple[Dict[str, Any],
                                        List[Dict[str, Any]]]:
  """(meta, spans) of one per-process trace file.

  Multiple meta lines may exist (reconfigures, restarts of the same
  role appending to one file, a late clock-offset stamp): the LAST
  clock_offset before each span applies — offsets are applied per
  span, not per file, so a restarted actor's second incarnation keeps
  its own offset.
  """
  meta: Dict[str, Any] = {}
  spans: List[Dict[str, Any]] = []
  offset = 0.0
  with open(path) as f:
    for line in f:
      line = line.strip()
      if not line:
        continue
      try:
        record = json.loads(line)
      except ValueError:
        continue  # a torn line from a crashed writer
      if record.get("ph") == "M":
        meta = record
        offset = float(record.get("clock_offset", 0.0))
        continue
      record["_offset"] = offset
      spans.append(record)
  return meta, spans


def merge_traces(trace_dir: str,
                 out_path: Optional[str] = None) -> Dict[str, Any]:
  """Merges every trace file under ``trace_dir``; returns (and
  optionally writes) the Chrome-trace dict."""
  paths = sorted(glob.glob(os.path.join(trace_dir, TRACE_GLOB)))
  raw: List[Dict[str, Any]] = []
  roles: Dict[int, str] = {}
  role_names = set()
  for path in paths:
    meta, spans = load_trace_file(path)
    if meta.get("role"):
      role_names.add(meta["role"])
    for span in spans:
      if span.get("role"):
        role_names.add(span["role"])
        roles[int(span.get("pid", 0))] = span["role"]
      raw.append(span)
  corrected = [
      (float(span["ts"]) - span.pop("_offset", 0.0), span)
      for span in raw]
  t0 = min((ts for ts, _ in corrected), default=0.0)
  events: List[Dict[str, Any]] = []
  for pid, role in sorted(roles.items()):
    events.append({"name": "process_name", "ph": "M", "pid": pid,
                   "tid": 0, "args": {"name": role}})
  timed = []
  for ts, span in corrected:
    event = {
        "name": span.get("name", "?"),
        "ph": "X",
        "ts": (ts - t0) * 1e6,
        "dur": float(span.get("dur", 0.0)) * 1e6,
        "pid": int(span.get("pid", 0)),
        "tid": int(span.get("tid", 0)),
        "cat": span.get("role", "?"),
    }
    if span.get("args"):
      event["args"] = span["args"]
    timed.append(event)
  # MERGED ORDER: one timeline, host-clock sorted — the property the
  # cross-process ordering test pins.
  timed.sort(key=lambda e: e["ts"])
  # RPC flow synthesis (ISSUE 15): an rpc_call.<m> span and the
  # rpc.<m> handler span sharing a client-stamped `req` id become one
  # Perfetto flow — the arrow from the caller's wait to the host's
  # handler work. Offsets were already applied per meta-line above, so
  # flows inherit the same per-file-offset awareness.
  flows = _rpc_flow_events(timed)
  events.extend(timed)
  events.extend(flows)
  span_counts: Dict[str, int] = {}
  for event in timed:
    span_counts[event["cat"]] = span_counts.get(event["cat"], 0) + 1
  trace = {
      "traceEvents": events,
      "displayTimeUnit": "ms",
      "metadata": {
          "rpc_flows": len(flows) // 2,
          # `roles` = every role SEEN (a meta line counts: the process
          # configured tracing); `span_counts_by_role` is the stronger
          # fact — a role that configured but never recorded shows 0,
          # which is what coverage gates must check.
          "roles": sorted(role_names),
          "span_counts_by_role": span_counts,
          "trace_files": [os.path.basename(p) for p in paths],
          "span_count": len(timed),
      },
  }
  if out_path:
    if out_path.endswith(".gz"):
      # Perfetto / chrome://tracing load gzipped traces natively; the
      # committed-artifact path uses this (a full fleet timeline is
      # ~2 MB raw, ~10× smaller gzipped).
      import gzip
      with gzip.open(out_path, "wt") as f:
        json.dump(trace, f)
    else:
      with open(out_path, "w") as f:
        json.dump(trace, f)
  return trace


def _rpc_flow_events(timed: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
  """Chrome-trace flow event pairs linking rpc_call.<m> (client span,
  flow start) to rpc.<m> (server handler span, flow end) by the
  client-stamped ``args.req`` id (fleet/rpc.py). A retried call whose
  first send was dropped has a client span with no handler twin (or
  vice versa after a crash) — unpaired ids emit nothing."""
  starts: Dict[str, Dict[str, Any]] = {}
  ends: Dict[str, Dict[str, Any]] = {}
  for event in timed:
    req = (event.get("args") or {}).get("req")
    if not req:
      continue
    name = event.get("name", "")
    if name.startswith("rpc_call.") and req not in starts:
      starts[req] = event
    elif name.startswith("rpc.") and req not in ends:
      ends[req] = event
  flows: List[Dict[str, Any]] = []
  for index, (req, start) in enumerate(sorted(starts.items())):
    end = ends.get(req)
    if end is None:
      continue
    method = start["name"][len("rpc_call."):]
    base = {"name": f"rpc:{method}", "cat": "rpc_flow",
            "id": index + 1}
    flows.append({**base, "ph": "s", "ts": start["ts"],
                  "pid": start["pid"], "tid": start["tid"]})
    flows.append({**base, "ph": "f", "bp": "e", "ts": end["ts"],
                  "pid": end["pid"], "tid": end["tid"]})
  return flows


def roles_in(trace: Dict[str, Any]) -> List[str]:
  """Every role seen in the merge (meta lines included)."""
  return list(trace.get("metadata", {}).get("roles", []))


def roles_with_spans(trace: Dict[str, Any]) -> List[str]:
  """Roles that contributed at least one actual span — the set
  coverage assertions ("the timeline contains spans from every role")
  must check; `roles_in` also counts a process that merely configured
  tracing and then wedged before recording."""
  counts = trace.get("metadata", {}).get("span_counts_by_role", {})
  return sorted(role for role, n in counts.items() if n > 0)


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      description="Merge per-process telemetry traces into one "
      "Chrome-trace timeline.")
  parser.add_argument("--trace-dir", required=True,
                      help="directory holding trace_<role>.jsonl files")
  parser.add_argument("--out", default=None,
                      help="merged Chrome-trace JSON output path "
                      "(default: <trace-dir>/merged_trace.json)")
  args = parser.parse_args(argv)
  out = args.out or os.path.join(args.trace_dir, "merged_trace.json")
  trace = merge_traces(args.trace_dir, out_path=out)
  summary = {
      "out": out,
      "roles": roles_in(trace),
      "span_count": trace["metadata"]["span_count"],
  }
  print(json.dumps(summary))
  return 0 if trace["metadata"]["span_count"] else 1


if __name__ == "__main__":
  sys.exit(main())
