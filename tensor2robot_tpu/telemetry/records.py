"""The unified `metrics_<tag>.jsonl` record envelope.

Every metrics sink in the repo — the supervised trainer, the anakin
trainer, the fleet learner's train_qtopt, the success-eval hooks —
writes through `train_eval.MetricLogger`, and as of ISSUE 11 every
record it emits is ONE envelope::

    {"step": int, "wall": float, "role": str, "payload": {name: float}}

``step`` is the training step the record describes, ``wall`` is
`time.time()` at write, ``role`` is the process's telemetry role
(`telemetry.core.current_role()` — ``trainer`` by default, ``learner``
in a fleet learner process, ``anakin`` under `--trainer=anakin`), and
``payload`` holds the actual scalars. Before this the four producers
emitted four ad-hoc flat shapes; merged-timeline tooling (and the
fleet's aggregated view) needs one.

`read_records` is the ONE reader the repo's tests/benches/scripts use:
it normalizes both the envelope and the legacy flat shape
(``{"step": ..., **scalars}``) to flat dicts, so analysis code indexes
scalars directly and old run directories stay readable.

jax-free (IMP401 worker-safe set).
"""

from __future__ import annotations

import json
import numbers
import time
from typing import Any, Dict, List, Optional

from tensor2robot_tpu.telemetry import core

SCHEMA_VERSION = 1
ENVELOPE_KEYS = ("step", "wall", "role", "payload")


def make_record(step: int, payload: Dict[str, float],
                role: Optional[str] = None,
                wall: Optional[float] = None) -> Dict[str, Any]:
  """Builds one envelope record (role defaults to the process role)."""
  return {
      "step": int(step),
      "wall": float(time.time() if wall is None else wall),
      "role": str(role if role is not None else core.current_role()),
      "payload": dict(payload),
  }


def validate_record(record: Any) -> List[str]:
  """Schema problems with one parsed record ([] = valid envelope)."""
  problems: List[str] = []
  if not isinstance(record, dict):
    return [f"record is {type(record).__name__}, not dict"]
  extra = sorted(set(record) - set(ENVELOPE_KEYS))
  missing = sorted(set(ENVELOPE_KEYS) - set(record))
  if missing:
    problems.append(f"missing keys {missing}")
  if extra:
    problems.append(f"unexpected keys {extra}")
  if "step" in record and not (
      isinstance(record["step"], int)
      and not isinstance(record["step"], bool)):
    problems.append(f"step is {type(record['step']).__name__}, not int")
  if "wall" in record and not isinstance(
      record["wall"], numbers.Real):
    problems.append("wall is not a number")
  if "role" in record and not (
      isinstance(record["role"], str) and record["role"]):
    problems.append("role is not a non-empty string")
  payload = record.get("payload")
  if payload is not None:
    if not isinstance(payload, dict):
      problems.append("payload is not a dict")
    else:
      for key, value in payload.items():
        if not isinstance(key, str):
          problems.append(f"payload key {key!r} is not a string")
        if not isinstance(value, numbers.Real) or isinstance(
            value, bool):
          problems.append(
              f"payload[{key!r}] is {type(value).__name__}, "
              "not a number")
  return problems


def normalize_record(record: Dict[str, Any]) -> Dict[str, Any]:
  """Envelope or legacy-flat record → flat dict with the payload
  scalars at top level (plus step/wall/role where present)."""
  if "payload" in record:
    flat = {k: record[k] for k in ("step", "wall", "role")
            if k in record}
    flat.update(record["payload"])
    return flat
  return dict(record)


def read_records(path: str) -> List[Dict[str, Any]]:
  """All records of one `metrics_<tag>.jsonl`, normalized flat."""
  records = []
  with open(path) as f:
    for line in f:
      line = line.strip()
      if line:
        records.append(normalize_record(json.loads(line)))
  return records
