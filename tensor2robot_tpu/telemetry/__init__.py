"""Unified telemetry plane: spans, metrics registry, flight recorder.

Three legs (ISSUE 11, docs/OBSERVABILITY.md):

  * `core` — the cross-process span tracer: monotonic-clock spans
    tagged pid/role/actor_id in a lock-free bounded ring, flushed to
    per-process ``trace_<role>.jsonl``; `merge`
    (``python -m tensor2robot_tpu.telemetry.merge``) folds every
    process of a run into one Chrome-trace/Perfetto timeline with
    clock offsets reconciled via the fleet RPC handshake.
  * `metrics` — the process-wide counter/gauge/histogram registry the
    existing subsystems publish into (replay, serving, data plane,
    trainers, compile cache), snapshotted on the trainers' log cadence
    and pollable over the fleet's ``telemetry`` RPC.
  * `flightrec` — on a latched error / crash-policy trigger / hang
    detection, every process dumps its span ring + metrics snapshot to
    ``<model_dir>/flightrec/``.

`records` defines the unified `metrics_<tag>.jsonl` envelope
(``{step, wall, role, payload}``) and its one reader.

The always-on performance plane (ISSUE 15) rides the same three legs:
`perf` (live MFU attribution on bench's analytic denominator +
`rsrc.*` resource watermarks from a per-role sampler thread),
`sentinel` (gin-configurable watch rules over the registry's scalar
view, alert events/counters/`alerts.jsonl`, page severity → flight
records), and `report` (``python -m tensor2robot_tpu.telemetry.report``
— one markdown page per run dir).

The whole package is jax-free BY CONTRACT: fleet actors and data-plane
workers import it at spawn (IMP401 worker-safe set; subprocess-pinned
by tests/test_telemetry.py).
"""

from tensor2robot_tpu.telemetry import core
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import merge
from tensor2robot_tpu.telemetry import metrics
from tensor2robot_tpu.telemetry import perf
from tensor2robot_tpu.telemetry import prometheus
from tensor2robot_tpu.telemetry import records
from tensor2robot_tpu.telemetry import report
from tensor2robot_tpu.telemetry import sentinel
from tensor2robot_tpu.telemetry.core import (
    clock_offset_from_handshake,
    configure,
    current_role,
    event,
    get_tracer,
    span,
)
from tensor2robot_tpu.telemetry.metrics import registry

__all__ = [
    "clock_offset_from_handshake",
    "configure",
    "core",
    "current_role",
    "event",
    "flightrec",
    "get_tracer",
    "merge",
    "metrics",
    "perf",
    "prometheus",
    "records",
    "registry",
    "report",
    "sentinel",
    "span",
]
