"""Span/event tracing core: the per-process half of the telemetry plane.

The system spans five process roles (learner, actors, replay/serving
host, data-plane workers, pod-Anakin programs) and the bottleneck
question at fleet scale — is the learner input-starved, the host
coalescing poorly, or an actor wedged? — is only answerable from ONE
merged timeline (PAPERS.md, "Podracer architectures for scalable RL").
This module is the recording side of that timeline:

  * one process-global `Tracer`, configured once per process with its
    ROLE (``host`` / ``learner`` / ``actor-3`` / ``trainer`` / ...);
  * `span(name)` context managers stamping CLOCK_MONOTONIC start +
    duration, pid, thread id, and role;
  * a BOUNDED ring of recent spans, appended LOCK-FREE (a
    `collections.deque(maxlen=...)` — GIL-atomic appends, oldest spans
    drop when nothing flushes them) so a wedged or crashing process
    always has its last moments available to the flight recorder;
  * flushing to a per-process ``trace_<role>.jsonl`` via single
    `os.write` calls on an ``O_APPEND`` fd — atomic whole-line appends
    with NO lock anywhere on the recording path, so tracing can sit on
    RPC handlers and train loops without serializing them.

Clock model: `time.monotonic` is CLOCK_MONOTONIC, system-wide on Linux
(`fleet.proc.beat` already relies on this), so same-host processes
share a timeline natively. Across hosts each process learns its offset
to the fleet host's clock from the RPC ``hello`` handshake
(`clock_offset_from_handshake`) and stamps it into the trace file; the
merge tool (`telemetry.merge`) subtracts it, putting every process on
the host's clock.

This module must stay importable WITHOUT jax: actor and data-plane
worker processes record spans too (IMP401 worker-safe set; the dynamic
twin is tests/test_telemetry.py's subprocess import pin).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# Ring capacity: enough for the last ~seconds of a busy process (RPC
# handlers run ~kHz at most) without holding more than a few MB.
DEFAULT_RING_CAPACITY = 8192
# Flush when this many spans are pending (only when a trace file is
# configured): one os.write per batch amortizes the I/O to ~nothing.
FLUSH_BATCH = 512

DEFAULT_ROLE = "trainer"


class _NullSpan:
  """Shared no-op context manager: the disabled-tracer fast path costs
  one attribute check + returning this singleton."""

  __slots__ = ()

  def __enter__(self) -> "_NullSpan":
    return self

  def __exit__(self, *exc) -> bool:
    return False


_NULL_SPAN = _NullSpan()


class _Span:
  """One live span: records (name, t0, dur) into the tracer on exit."""

  __slots__ = ("_tracer", "_name", "_args", "_t0")

  def __init__(self, tracer: "Tracer", name: str,
               args: Optional[Dict[str, Any]]):
    self._tracer = tracer
    self._name = name
    self._args = args

  def __enter__(self) -> "_Span":
    self._t0 = time.monotonic()
    return self

  def __exit__(self, exc_type, exc, tb) -> bool:
    dur = time.monotonic() - self._t0
    args = self._args
    if exc_type is not None:
      args = dict(args or ())
      args["error"] = exc_type.__name__
    self._tracer._record(self._name, self._t0, dur, args)
    return False


class Tracer:
  """Process-global span recorder (see module docstring).

  Thread-safety: `_record` appends to a `deque(maxlen=...)` (GIL-atomic)
  and `flush` drains via `popleft` (also atomic), appending whole lines
  with one `os.write` on an O_APPEND fd — concurrent flushers pop
  disjoint spans and interleave whole lines. The SPAN path holds no
  lock (this code sits inside RPC handlers and train loops); only the
  recorded/flushed statistics counters take a nanosecond mutex (a bare
  `+=` is a read-modify-write that drops updates under preemption, and
  `spans_dropped` is derived from them).
  """

  def __init__(self):
    self._ring: collections.deque = collections.deque(
        maxlen=DEFAULT_RING_CAPACITY)
    self.enabled = False
    self.role: Optional[str] = None
    self.actor_id: Optional[str] = None
    self.clock_offset = 0.0
    self.spans_recorded = 0
    self.spans_flushed = 0
    self._count_lock = threading.Lock()
    self._fd: Optional[int] = None
    self.trace_path: Optional[str] = None

  # ---- configuration ----

  def configure(self, role: str,
                trace_dir: Optional[str] = None,
                actor_id: Optional[str] = None,
                capacity: Optional[int] = None,
                enabled: bool = True) -> "Tracer":
    """Sets this process's role and (optionally) its trace file.

    With ``trace_dir`` the tracer appends to
    ``<trace_dir>/trace_<role>.jsonl`` (created if needed; restarts of
    the same role append to the same file — O_APPEND keeps concurrent
    incarnations' lines whole). Without it spans stay in the bounded
    ring only (memory-mode: the flight recorder still sees them).
    Reconfiguration closes any previous file. Idempotent per
    (role, trace_dir).
    """
    self.close()
    self.role = str(role)
    self.actor_id = actor_id
    if capacity:
      self._ring = collections.deque(maxlen=int(capacity))
    self.enabled = bool(enabled)
    if trace_dir:
      os.makedirs(trace_dir, exist_ok=True)
      path = os.path.join(trace_dir, f"trace_{self.role}.jsonl")
      self._fd = os.open(path,
                         os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                         0o644)
      self.trace_path = path
      self._write_meta()
    return self

  @property
  def capacity(self) -> int:
    return self._ring.maxlen or 0

  def set_clock_offset(self, offset_secs: float) -> None:
    """Records this process's monotonic-clock offset to the fleet
    host's clock (local_monotonic − host_monotonic); the merge tool
    subtracts it. Stamped into the trace file so merging needs no
    side channel."""
    self.clock_offset = float(offset_secs)
    if self._fd is not None:
      self._write_meta()

  def _write_meta(self) -> None:
    meta = {
        "ph": "M",
        "role": self.role,
        "pid": os.getpid(),
        "actor_id": self.actor_id,
        "wall0": time.time(),
        "mono0": time.monotonic(),
        "clock_offset": self.clock_offset,
    }
    self._write((json.dumps(meta) + "\n").encode())

  def _write(self, payload: bytes) -> bool:
    """One O_APPEND write; on failure (ENOSPC, a yanked volume) the
    tracer DEGRADES to memory-mode instead of raising — flushes run
    inline on instrumented paths (RPC handlers, train loops), and
    telemetry must never take those down. Returns success."""
    try:
      os.write(self._fd, payload)
      return True
    except OSError:
      import logging
      logging.getLogger(__name__).warning(
          "trace write to %s failed; tracing degrades to memory-mode",
          self.trace_path, exc_info=True)
      fd, self._fd = self._fd, None
      try:
        os.close(fd)
      except OSError:
        pass
      self.trace_path = None
      return False

  # ---- recording ----

  def span(self, name: str, **args) -> Any:
    """Context manager timing one operation; no-op when disabled."""
    if not self.enabled:
      return _NULL_SPAN
    return _Span(self, name, args or None)

  def event(self, name: str, **args) -> None:
    """One instant (zero-duration) event."""
    if not self.enabled:
      return
    self._record(name, time.monotonic(), 0.0, args or None)

  def _record(self, name: str, t0: float, dur: float,
              args: Optional[Dict[str, Any]]) -> None:
    if not self.enabled:
      return
    self._ring.append(
        (name, t0, dur, threading.get_ident(), args))
    with self._count_lock:
      self.spans_recorded += 1
    if self._fd is not None and len(self._ring) >= FLUSH_BATCH:
      self.flush()

  # ---- draining ----

  @property
  def pending(self) -> int:
    return len(self._ring)

  @property
  def spans_dropped(self) -> int:
    """Spans that aged out of the ring unflushed (memory-mode churn)."""
    return max(
        self.spans_recorded - self.spans_flushed - len(self._ring), 0)

  def _drain(self) -> List[tuple]:
    spans = []
    while True:
      try:
        spans.append(self._ring.popleft())
      except IndexError:
        return spans

  def _encode(self, span: tuple) -> Dict[str, Any]:
    name, t0, dur, tid, args = span
    record = {"ph": "X", "name": name, "ts": t0, "dur": dur,
              "pid": os.getpid(), "tid": tid, "role": self.role}
    if args:
      record["args"] = args
    return record

  def snapshot_spans(self) -> List[Dict[str, Any]]:
    """A copy of the ring (most recent spans), without draining it —
    the flight recorder's view; the trace file keeps its own copy via
    the normal flush path."""
    return [self._encode(span) for span in list(self._ring)]

  def flush(self) -> int:
    """Drains the ring to the trace file; returns spans written.
    Without a file the ring is left alone (it IS the retention)."""
    if self._fd is None:
      return 0
    spans = self._drain()
    if not spans:
      return 0
    payload = "".join(
        json.dumps(self._encode(span)) + "\n" for span in spans)
    if not self._write(payload.encode()):
      return 0  # degraded to memory-mode; the drained spans are lost
    with self._count_lock:
      self.spans_flushed += len(spans)
    return len(spans)

  def close(self) -> None:
    """Teardown: flush the tail and release the fd. Never raises
    (`_write` degrades instead) — close() sits in finally blocks next
    to resource closes a failed trace write must not mask or skip."""
    if self._fd is not None:
      self.flush()
    if self._fd is not None:
      fd, self._fd = self._fd, None
      try:
        os.close(fd)
      except OSError:
        pass
    self.trace_path = None


_TRACER = Tracer()


def get_tracer() -> Tracer:
  return _TRACER


def configure(role: str, trace_dir: Optional[str] = None,
              **kwargs) -> Tracer:
  """Configures the process-global tracer (see `Tracer.configure`)."""
  return _TRACER.configure(role, trace_dir=trace_dir, **kwargs)


def span(name: str, **args) -> Any:
  """A span on the process-global tracer (no-op until configured)."""
  return _TRACER.span(name, **args)


def event(name: str, **args) -> None:
  _TRACER.event(name, **args)


def current_role() -> str:
  """The configured process role, or the default ``trainer`` — the
  `role` field of every metrics-record envelope (telemetry.records)."""
  return _TRACER.role or DEFAULT_ROLE


def clock_offset_from_handshake(host_monotonic: float,
                                t_before: float,
                                t_after: float) -> float:
  """Offset of THIS clock to the fleet host's, from one RPC roundtrip.

  The host stamped ``host_monotonic`` while handling the request; the
  caller read its own clock just before (``t_before``) and after
  (``t_after``) the call. Midpoint estimate: the host's stamp
  corresponds to the local midpoint, so
  ``offset = (t_before + t_after) / 2 - host_monotonic`` (error ≤
  rtt/2 — microseconds on loopback, and exactly the quantity the merge
  tool needs to subtract). Same-host processes share CLOCK_MONOTONIC,
  so the estimate lands at ~0 there by construction.
  """
  return (t_before + t_after) / 2.0 - float(host_monotonic)


def reset_for_tests() -> None:
  """Fresh process-global tracer (test isolation)."""
  global _TRACER
  _TRACER.close()
  _TRACER = Tracer()
