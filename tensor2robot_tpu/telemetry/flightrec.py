"""Crash flight recorder: post-mortem forensics for fleet failures.

The bug class this repo keeps fixing — stranded producers, dead
actors, wedged hosts — is exactly the class where the interesting
state is gone by the time a human looks: the latched error says WHO
died, not what the process was doing in its last seconds. The flight
recorder closes that gap: on a latched error, a crash-policy trigger,
or hang detection (heartbeat timeout), every process dumps

  * its span ring (the tracer's last `capacity` spans — kept in
    memory precisely so a crash always has them),
  * its latest metrics-registry snapshot,
  * the trigger reason + wall/monotonic stamps + clock offset

to ``<model_dir>/flightrec/<role>-<pid>.json``. The fleet wiring
(docs/OBSERVABILITY.md): learner/actor mains dump in their own
except paths; the orchestrator dumps its own view (latched error +
per-child heartbeat ages) and asks a still-live host to dump over the
``flight_record`` RPC. A hung process cannot dump itself — the
orchestrator's dump records which heartbeat went stale instead.

jax-free (actors dump too; IMP401 worker-safe set).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from tensor2robot_tpu.telemetry import core
from tensor2robot_tpu.telemetry import metrics

DIRNAME = "flightrec"


def flightrec_dir(model_dir: str) -> str:
  """The canonical dump directory of a run (`<model_dir>/flightrec`)."""
  return os.path.join(model_dir, DIRNAME)


def dump(out_dir: str, reason: str,
         extra: Optional[Dict[str, Any]] = None,
         role: Optional[str] = None) -> str:
  """Writes this process's flight record; returns its path.

  Never raises (a failing dump must not mask the error that triggered
  it); returns "" when the write failed. The tracer's file (if any) is
  flushed too, so the merged timeline covers the final spans. ``role``
  overrides the process role (the orchestrator dumps as
  ``orchestrator`` from whatever process supervises the fleet).
  """
  tracer = core.get_tracer()
  role = role or core.current_role()
  record = {
      "reason": str(reason)[:4000],
      "role": role,
      "pid": os.getpid(),
      "wall": time.time(),
      "monotonic": time.monotonic(),
      "clock_offset": tracer.clock_offset,
      "spans": tracer.snapshot_spans(),
      "spans_recorded": tracer.spans_recorded,
      "spans_dropped": tracer.spans_dropped,
      "metrics": metrics.registry().snapshot(),
  }
  if extra:
    record["extra"] = extra
  try:
    tracer.flush()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{role}-{os.getpid()}.json")
    with open(path, "w") as f:
      json.dump(record, f)
    return path
  except OSError:
    return ""


def read_dumps(out_dir: str) -> List[Dict[str, Any]]:
  """All flight records in a dump dir, sorted by wall time."""
  dumps = []
  if not os.path.isdir(out_dir):
    return dumps
  for name in sorted(os.listdir(out_dir)):
    if not name.endswith(".json"):
      continue
    try:
      with open(os.path.join(out_dir, name)) as f:
        dumps.append(json.load(f))
    except (OSError, ValueError):
      continue
  return sorted(dumps, key=lambda d: d.get("wall", 0.0))
