"""Live performance attribution + resource watermarks (ISSUE 15).

PR 11's plane answers "what happened"; this module answers "is the run
healthy and how fast should it be", always on:

  * `mfu_value` — THE one MFU formula. `bench.py` (via
    `utils.profiling.mfu`) and the trainers' live gauges both call it,
    and the FLOPs denominator both sides pass comes from the one
    `utils.profiling.analytic_flops` model — bench MFU and live
    ``perf.mfu`` agree by construction (the shared-code-path pin in
    tests/test_perf_plane.py).
  * `PerfMeter` — per-process live attribution: wraps each train
    dispatch in the standard telemetry span while accumulating its
    wall time, and at log cadence publishes ``perf.mfu``,
    ``perf.flops_per_sec`` and ``perf.device_time_fraction`` gauges
    into the registry (so every ``metrics_<tag>.jsonl`` envelope and
    the Prometheus endpoint carry utilization for free). Device-count
    aware: the pod trainers pass their device count so MFU stays the
    per-chip fraction-of-peak at any scale.
  * `ResourceSampler` — a daemon sampler thread per process role:
    host RSS (``/proc/self/status``), optional device-memory sources
    (`utils.profiling.device_memory_source` — jax stays out of THIS
    package), and peak watermarks over selected registry fill gauges
    (replay ring, ingestion queue, arena residency), published as
    ``rsrc.*`` gauges with ``_peak`` watermark twins. Because they
    live in the ordinary registry they ride the fleet's existing
    ``telemetry_push`` RPC — the orchestrator's poll aggregates them
    fleet-wide with zero new transport.

The whole plane honors one switch: `set_plane_enabled(False)` (or env
``T2R_PERF_PLANE=0``) turns publication, sampling, and the sentinel
off — the A/B arm of the bench overhead gate.

jax-free BY CONTRACT like the rest of the package (IMP401 worker-safe
set): actors run the sampler too; anything device-specific arrives as
an injected source callable.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence

from tensor2robot_tpu.telemetry import core
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Registry gauges the sampler tracks peak watermarks for (fill/queue
# depths whose PEAK is the capacity-planning signal; the live values
# are already published at their event sites).
DEFAULT_WATCHED_GAUGES = (
    "replay.fill",
    "replay.ingest_queue_depth",
    "serving.arena.resident_bytes",
    "serving.microbatch_queue_depth",
)

_PLANE_ENV = "T2R_PERF_PLANE"
_plane_enabled: Optional[bool] = None
_plane_lock = threading.Lock()


def plane_enabled() -> bool:
  """Whether the always-on perf plane (live gauges, resource sampler,
  sentinel) is active in this process. Default on; ``T2R_PERF_PLANE=0``
  or `set_plane_enabled(False)` disables (the bench A/B off-arm)."""
  global _plane_enabled
  if _plane_enabled is None:
    _plane_enabled = os.environ.get(_PLANE_ENV, "1") not in (
        "0", "false", "off")
  return _plane_enabled


def set_plane_enabled(enabled: Optional[bool]) -> None:
  """Overrides the plane switch (None = re-read the environment)."""
  global _plane_enabled
  _plane_enabled = enabled


def mfu_value(steps_per_sec: float,
              flops_per_step: Optional[float],
              peak_flops: Optional[float],
              devices: int = 1) -> Optional[float]:
  """Model FLOPs utilization: achieved / (per-chip peak × devices).

  THE one MFU formula — `utils.profiling.mfu` (bench.py's path) and
  `PerfMeter.publish` (the live gauges) both call it, so the two can
  never drift. None when the peak or the denominator is unknowable
  (e.g. XLA:CPU with no `T2R_PEAK_FLOPS_OVERRIDE`).
  """
  if not peak_flops or not flops_per_step:
    return None
  return steps_per_sec * flops_per_step / (peak_flops * max(devices, 1))


class PerfMeter:
  """Per-process live performance attribution (one per train loop).

  Usage (the three trainers):

      meter = perf.PerfMeter(flops_per_step=..., peak_flops=...,
                             devices=D)
      ...
      with meter.dispatch("qtopt.dispatch", step=step):  # = span + timer
        state, metrics = train_step(...)
      ...
      scalars.update(meter.publish(grad_steps_per_sec, interval_secs))

  ``flops_per_step`` is the analytic MODEL flops of one GLOBAL train
  step (`utils.profiling.analytic_flops`; pod trainers multiply their
  per-device count by D); ``devices`` scales the peak so ``perf.mfu``
  stays the per-chip fraction-of-peak. ``perf.device_time_fraction``
  is the share of the log interval spent inside dispatch spans — the
  dispatch-span-derived busy fraction (host-side wall including the
  device program; the stall/input-wait gauges decompose the rest).
  """

  def __init__(self,
               flops_per_step: Optional[float] = None,
               peak_flops: Optional[float] = None,
               devices: int = 1,
               registry: Optional[tmetrics.MetricsRegistry] = None,
               enabled: Optional[bool] = None):
    self.flops_per_step = flops_per_step
    self.peak_flops = peak_flops
    self.devices = max(int(devices), 1)
    self._registry = registry or tmetrics.registry()
    self.enabled = plane_enabled() if enabled is None else bool(enabled)
    self._busy_secs = 0.0
    self._busy_lock = threading.Lock()

  def dispatch(self, name: str, **args):
    """The standard dispatch span + busy-time accumulation in one
    context manager (replaces the bare `telemetry.span` at the train
    loops' dispatch sites)."""
    return _DispatchSpan(self, core.span(name, **args))

  def _add_busy(self, secs: float) -> None:
    with self._busy_lock:
      self._busy_secs += secs

  def publish(self, steps_per_sec: float,
              interval_secs: float) -> Dict[str, float]:
    """Publishes the interval's perf gauges; returns them as scalars
    for the trainer's `metrics_<tag>.jsonl` record. Resets the busy
    accumulator (one call per log interval)."""
    with self._busy_lock:
      busy, self._busy_secs = self._busy_secs, 0.0
    if not self.enabled:
      return {}
    out: Dict[str, float] = {}
    out["perf.device_time_fraction"] = min(
        max(busy / max(interval_secs, 1e-9), 0.0), 1.0)
    if self.flops_per_step:
      out["perf.flops_per_sec"] = steps_per_sec * self.flops_per_step
    util = mfu_value(steps_per_sec, self.flops_per_step,
                     self.peak_flops, devices=self.devices)
    if util is not None:
      out["perf.mfu"] = util
    self._registry.gauge("perf.device_time_fraction").set(
        out["perf.device_time_fraction"])
    if "perf.flops_per_sec" in out:
      self._registry.gauge("perf.flops_per_sec").set(
          out["perf.flops_per_sec"])
    if "perf.mfu" in out:
      self._registry.gauge("perf.mfu").set(out["perf.mfu"])
    return out


class _DispatchSpan:
  """Context manager pairing a telemetry span with busy accounting."""

  __slots__ = ("_meter", "_span", "_t0")

  def __init__(self, meter: PerfMeter, span: Any):
    self._meter = meter
    self._span = span

  def __enter__(self) -> "_DispatchSpan":
    self._t0 = time.monotonic()
    self._span.__enter__()
    return self

  def __exit__(self, exc_type, exc, tb) -> bool:
    self._span.__exit__(exc_type, exc, tb)
    self._meter._add_busy(time.monotonic() - self._t0)
    return False


def host_rss_source() -> Callable[[], Dict[str, float]]:
  """Resident-set-size source from ``/proc/self/status`` (jax-free,
  no psutil dependency; yields nothing on hosts without procfs)."""

  def sample() -> Dict[str, float]:
    try:
      with open("/proc/self/status") as f:
        for line in f:
          if line.startswith("VmRSS:"):
            kb = float(line.split()[1])
            return {"host_rss_bytes": kb * 1024.0}
    except (OSError, ValueError, IndexError):
      pass
    return {}

  return sample


class ResourceSampler:
  """Daemon sampler thread publishing ``rsrc.*`` gauges + watermarks.

  Every period it runs each source callable (dict name → value; a
  failing source is logged once and skipped, never raises out), sets
  ``rsrc.<name>`` and the peak watermark ``rsrc.<name>_peak``, and
  mirrors the peak of each watched registry gauge as
  ``rsrc.<gauge>_peak``. Lock-free on the hot paths it observes: it
  only READS registry gauges and sets its own (per-metric
  arithmetic-only locks — the CON301 contract).
  """

  def __init__(self,
               sources: Sequence[Callable[[], Dict[str, float]]] = (),
               watched_gauges: Iterable[str] = DEFAULT_WATCHED_GAUGES,
               period_secs: float = 1.0,
               registry: Optional[tmetrics.MetricsRegistry] = None):
    self._sources = list(sources) or [host_rss_source()]
    self._watched = tuple(watched_gauges)
    self._period = max(float(period_secs), 0.05)
    self._registry = registry or tmetrics.registry()
    self._peaks: Dict[str, float] = {}
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self.samples = 0

  def _publish(self, name: str, value: float) -> None:
    self._registry.gauge(f"rsrc.{name}").set(value)
    peak = self._peaks.get(name)
    if peak is None or value > peak:
      self._peaks[name] = value
      self._registry.gauge(f"rsrc.{name}_peak").set(value)

  def sample_once(self) -> None:
    """One sampling pass (also the test seam)."""
    for source in self._sources:
      try:
        values = source()
      except Exception:  # noqa: BLE001 — sampling must never raise
        log.warning("resource source %r failed; skipping", source,
                    exc_info=True)
        continue
      for name, value in (values or {}).items():
        self._publish(str(name), float(value))
    if self._watched:
      gauges = self._registry.snapshot().get("gauges", {})
      for name in self._watched:
        if name in gauges:
          value = float(gauges[name])
          peak = self._peaks.get(name)
          if peak is None or value > peak:
            self._peaks[name] = value
            self._registry.gauge(f"rsrc.{name}_peak").set(value)
    self.samples += 1

  def _run(self) -> None:
    while not self._stop.is_set():
      try:
        self.sample_once()
      except Exception:  # noqa: BLE001 — the thread must outlive bugs
        log.warning("resource sampling pass failed", exc_info=True)
      self._stop.wait(self._period)

  def start(self) -> "ResourceSampler":
    if self._thread is None:
      self._thread = threading.Thread(
          target=self._run, name="t2r-rsrc-sampler", daemon=True)
      self._thread.start()
    return self

  def close(self, timeout_secs: float = 2.0) -> None:
    self._stop.set()
    thread, self._thread = self._thread, None
    if thread is not None:
      thread.join(timeout=timeout_secs)


_SAMPLER: Optional[ResourceSampler] = None


def start_resource_sampler(
    sources: Sequence[Callable[[], Dict[str, float]]] = (),
    period_secs: float = 1.0) -> Optional[ResourceSampler]:
  """Starts (or returns) the process-wide resource sampler. Idempotent
  per process — the first caller's sources win (one sampler per
  process role, the ISSUE-15 contract). No-op returning None while the
  plane is disabled."""
  global _SAMPLER
  if not plane_enabled():
    return None
  with _plane_lock:
    if _SAMPLER is None:
      _SAMPLER = ResourceSampler(
          sources=list(sources) + [host_rss_source()],
          period_secs=period_secs).start()
      # Joined at interpreter exit, BEFORE teardown: a device-memory
      # source mid-call into jax's C++ while the main thread tears
      # down XLA aborts the process ("terminate called without an
      # active exception" — found by the fleet learner, which exits
      # right after training). atexit runs with the interpreter still
      # whole, so the thread stops cleanly first.
      atexit.register(stop_resource_sampler)
  return _SAMPLER


def stop_resource_sampler() -> None:
  """Stops the process-wide sampler (tests / clean teardown)."""
  global _SAMPLER
  with _plane_lock:
    sampler, _SAMPLER = _SAMPLER, None
  if sampler is not None:
    sampler.close()
