"""MAML: model-agnostic meta-learning over any base T2R model.

Reference parity: tensor2robot `meta_learning/maml_model.py` +
`meta_tfdata.py` — condition/inference episode split, K inner gradient
steps on condition data, outer loss on inference data, second-order
gradients unless `first_order` (SURVEY.md §3 "MAML wrapper", §4.5;
file:line unavailable — empty reference mount).

TPU-native redesign: the reference built the inner loop by manually
constructing TF graph ops over variable copies. In JAX the inner loop
is literally `jax.grad` inside the outer loss, `lax.scan`ned over K
steps and `vmap`ped over the task batch — one XLA program, second-order
gradients for free, no variable bookkeeping. Meta-batch layout:

  features.condition.<base feature keys>  [B_tasks, N_cond, ...]
  features.inference.<base feature keys>  [B_tasks, N_inf, ...]
  labels.condition.<base label keys>      [B_tasks, N_cond, ...]
  labels.inference.<base label keys>      [B_tasks, N_inf, ...]

which is the reference's meta-example structure expressed as a spec
tree — so random meta-batches, parsers, and validation all come
mechanically from the spec system, like everything else.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.specs import TensorSpecStruct

CONDITION = "condition"
INFERENCE = "inference"


CONDITION_LABELS = "condition_labels"


def _nest_spec(base_spec: Optional[TensorSpecStruct],
               splits: Tuple[Tuple[str, int], ...],
               optional: bool = False) -> Optional[TensorSpecStruct]:
  """Wraps a base spec under per-split prefixes with per-task sample dims.

  Wire names are prefixed too: condition/x and inference/x must be
  DISTINCT tf.Example keys (same-named specs would silently collide in
  every spec-name-keyed consumer, e.g. the TFExample feature map).
  """
  if base_spec is None:
    return None
  flat = base_spec.to_flat_dict() if isinstance(base_spec,
                                                TensorSpecStruct) \
      else dict(base_spec)
  out = {}
  for split, n in splits:
    for key, spec in flat.items():
      nested = spec.replace(
          shape=(n,) + tuple(spec.shape),
          name=f"{split}_{spec.name or key}")
      if nested.data_format is not None:
        # A jpeg/png wire encoding holds ONE image; the nested
        # (N, H, W, C) sample set must travel as raw numeric data or
        # the tf.Example feature map cannot represent it.
        nested = nested.replace(data_format=None)
      if optional:
        nested = nested.replace(is_optional=True)
      out[f"{split}/{key}"] = nested
  return TensorSpecStruct.from_flat_dict(out)


def _split(struct: TensorSpecStruct, split: str) -> TensorSpecStruct:
  """Extracts a split substructure (delegates to the container's paths)."""
  return struct[split]


class MAMLPreprocessor:
  """Runs the BASE model's preprocessor on each meta split.

  Reference parity: the meta_learning preprocessor wrapper
  (SURVEY.md §3 "MAML wrapper" — `meta_learning/preprocessors.py`): the
  base model's wire↔model spec contract (image crop/distort, dtype
  casts) must survive meta-wrapping. Per split, the task dim folds into
  the batch dim, the base preprocess runs, and the result unfolds back.
  """

  def __init__(self, base_preprocessor, num_condition: int,
               num_inference: int, base_label_spec_fn):
    self._base = base_preprocessor
    self._num_condition = num_condition
    self._num_inference = num_inference
    self._base_label_spec_fn = base_label_spec_fn

  def _splits(self):
    return ((CONDITION, self._num_condition),
            (INFERENCE, self._num_inference))

  def get_in_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    spec = _nest_spec(self._base.get_in_feature_specification(mode),
                      self._splits())
    if mode == Mode.PREDICT:
      demo = _nest_spec(self._base.get_in_label_specification(mode),
                        ((CONDITION_LABELS, self._num_condition),),
                        optional=True)
      if demo is not None:
        flat = spec.to_flat_dict()
        flat.update(demo.to_flat_dict())
        spec = TensorSpecStruct.from_flat_dict(flat)
    return spec

  def get_in_label_specification(self, mode: Mode):
    return _nest_spec(self._base.get_in_label_specification(mode),
                      self._splits())

  def get_out_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    spec = _nest_spec(self._base.get_out_feature_specification(mode),
                      self._splits())
    if mode == Mode.PREDICT:
      demo = _nest_spec(self._base.get_out_label_specification(mode),
                        ((CONDITION_LABELS, self._num_condition),),
                        optional=True)
      if demo is not None:
        flat = spec.to_flat_dict()
        flat.update(demo.to_flat_dict())
        spec = TensorSpecStruct.from_flat_dict(flat)
    return spec

  def get_out_label_specification(self, mode: Mode):
    return _nest_spec(self._base.get_out_label_specification(mode),
                      self._splits())

  def preprocess(self, features, labels, mode: Mode, rng=None):
    import jax as _jax

    out_f, out_l = {}, {}
    flat_features = features.to_flat_dict()
    has_labels = labels is not None
    demo_prefix = CONDITION_LABELS + "/"
    demo_keys = [k for k in flat_features if k.startswith(demo_prefix)]
    rngs = (_jax.random.split(rng, 2) if rng is not None
            else (None, None))
    for i, (split, n) in enumerate(self._splits()):
      f = _split(features, split)
      l = _split(labels, split) if has_labels else None
      # Predict-time demonstration labels must ride the SAME base label
      # path as training labels (dtype casts, scaling): _adapt compares
      # preprocessed network outputs against them, so feeding them raw
      # would skew adaptation whenever the base preprocessor transforms
      # labels.
      demo_as_labels = (split == CONDITION and demo_keys and l is None)
      if demo_as_labels:
        l = TensorSpecStruct.from_flat_dict(
            {k[len(demo_prefix):]: flat_features[k] for k in demo_keys})

      num_tasks = _jax.tree_util.tree_leaves(f)[0].shape[0]

      def fold(x):
        return x.reshape((num_tasks * n,) + x.shape[2:])

      def unfold(x):
        return x.reshape((num_tasks, n) + x.shape[1:])

      f2, l2 = self._base.preprocess(
          _jax.tree_util.tree_map(fold, f),
          _jax.tree_util.tree_map(fold, l) if l is not None else None,
          mode, rngs[i])
      for key, value in f2.to_flat_dict().items():
        out_f[f"{split}/{key}"] = unfold(value)
      if l2 is not None:
        if demo_as_labels:
          for key, value in l2.to_flat_dict().items():
            out_f[f"{CONDITION_LABELS}/{key}"] = unfold(value)
        else:
          for key, value in l2.to_flat_dict().items():
            out_l[f"{split}/{key}"] = unfold(value)
    # Anything not handled above (labels already supplied alongside
    # demonstrations) passes through unchanged.
    for key, value in flat_features.items():
      if key.startswith(demo_prefix) and key not in out_f:
        out_f[key] = value
    features_out = TensorSpecStruct.from_flat_dict(out_f)
    labels_out = TensorSpecStruct.from_flat_dict(out_l) if out_l else \
        (labels if has_labels else None)
    return features_out, labels_out


@gin.configurable
class MAMLModel(AbstractT2RModel):
  """Meta-trains `base_model` with inner-loop adaptation.

  Works with any base model whose network carries no mutable
  batch-norm state (the reference's MAML models used BN-free nets for
  the same reason: per-task adapted stats are ill-defined).
  """

  def __init__(self,
               base_model: AbstractT2RModel,
               num_inner_steps: int = 1,
               inner_lr: float = 0.01,
               first_order: bool = False,
               learn_inner_lr: bool = False,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               report_pre_adaptation_loss: bool = False,
               **kwargs):
    kwargs.setdefault("device_dtype", base_model.device_dtype)
    super().__init__(**kwargs)
    self._base = base_model
    self._num_inner_steps = num_inner_steps
    self._inner_lr = inner_lr
    self._first_order = first_order
    self._learn_inner_lr = learn_inner_lr
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task
    self._report_pre_adaptation_loss = report_pre_adaptation_loss

  @property
  def base_model(self) -> AbstractT2RModel:
    return self._base

  @property
  def preprocessor(self):
    """The base model's preprocessor, lifted over the meta splits."""
    if self._preprocessor is None:
      self._preprocessor = MAMLPreprocessor(
          self._base.preprocessor, self._num_condition,
          self._num_inference, self._base.get_label_specification)
    return self._preprocessor

  # ---- specs: base specs nested under condition/inference ----

  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    spec = _nest_spec(
        self._base.get_feature_specification(mode),
        ((CONDITION, self._num_condition),
         (INFERENCE, self._num_inference)))
    if mode == Mode.PREDICT:
      # Serving carries demonstration labels INSIDE the feature struct
      # (optional: absent ⇒ zero-shot) so exported models and
      # predictors have a real input for adaptation data.
      base_labels = self._base.get_label_specification(mode)
      demo = _nest_spec(base_labels,
                        ((CONDITION_LABELS, self._num_condition),),
                        optional=True)
      if demo is not None:
        flat = spec.to_flat_dict()
        flat.update(demo.to_flat_dict())
        spec = TensorSpecStruct.from_flat_dict(flat)
    return spec

  def get_label_specification(self, mode: Mode):
    return _nest_spec(
        self._base.get_label_specification(mode),
        ((CONDITION, self._num_condition),
         (INFERENCE, self._num_inference)))

  # ---- network: the base network, with an optional inner-lr param ----

  class _MetaNetwork(nn.Module):
    base_net: nn.Module
    learn_inner_lr: bool
    init_inner_lr: float

    @nn.compact
    def __call__(self, features, train: bool = False):
      if self.learn_inner_lr:
        # Meta-SGD-style scalar learnable inner rate (participates in
        # outer optimization; read via params during adaptation).
        self.param("inner_lr_log",
                   nn.initializers.constant(jnp.log(self.init_inner_lr)),
                   ())
      # Init path: run base net on the condition split so params exist.
      cond = _split(features, CONDITION)
      squeezed = jax.tree_util.tree_map(
          lambda x: x.reshape((-1,) + x.shape[2:]), cond)
      return self.base_net(squeezed, train=train)

  def create_network(self) -> nn.Module:
    return self._MetaNetwork(
        base_net=self._base.network,
        learn_inner_lr=self._learn_inner_lr,
        init_inner_lr=self._inner_lr,
    )

  # ---- the meta loss ----

  def model_train_fn(self, features, labels, outputs, mode):
    """Unused: MAML overrides loss_fn wholesale (kept for the ABC)."""
    raise NotImplementedError(
        "MAMLModel computes its loss in loss_fn; model_train_fn is the "
        "base model's.")

  def _task_loss(self, base_params, features, labels, mode, rng,
                 train: bool):
    """Loss of the base model on ONE task's [N, ...] sample set."""
    rngs = {"dropout": rng} if (train and rng is not None) else None
    outputs = self._base.network.apply(
        {"params": base_params}, features, train=train, rngs=rngs)
    loss, scalars = self._base.model_train_fn(
        features, labels, outputs, mode)
    return loss, scalars

  def _adapt(self, base_params, inner_lr, cond_f, cond_l, mode, rng,
             train: bool = True):
    """K inner SGD steps on the condition set; scanned, not unrolled."""

    def one_step(params, step_rng):
      grads = jax.grad(
          lambda p: self._task_loss(p, cond_f, cond_l, mode,
                                    step_rng if train else None,
                                    train=train)[0])(params)
      if self._first_order:
        grads = jax.lax.stop_gradient(grads)
      params = jax.tree_util.tree_map(
          lambda p, g: p - inner_lr * g.astype(p.dtype), params, grads)
      return params, ()

    step_rngs = (jax.random.split(rng, self._num_inner_steps)
                 if rng is not None else
                 jnp.zeros((self._num_inner_steps, 2), jnp.uint32))
    adapted, _ = jax.lax.scan(one_step, base_params, step_rngs)
    return adapted

  def loss_fn(self, params, batch_stats, features, labels, rng,
              mode: Mode):
    if batch_stats:
      raise ValueError(
          "MAMLModel requires a batch-stats-free base network "
          "(use GroupNorm/LayerNorm instead of BatchNorm).")
    train = mode == Mode.TRAIN
    rng_pre, rng_net = (jax.random.split(rng) if rng is not None
                        else (None, None))
    features, labels = self.preprocessor.preprocess(
        features, labels, mode, rng_pre)

    # _MetaNetwork nests the base net's params under 'base_net'.
    base_params = params["base_net"]
    inner_lr = self._inner_lr
    if self._learn_inner_lr:
      inner_lr = jnp.exp(params["inner_lr_log"])

    cond_f, inf_f = _split(features, CONDITION), _split(features,
                                                        INFERENCE)
    cond_l = _split(labels, CONDITION) if labels is not None else None
    inf_l = _split(labels, INFERENCE) if labels is not None else None

    num_tasks = jax.tree_util.tree_leaves(cond_f)[0].shape[0]
    task_rngs = (jax.random.split(rng_net, num_tasks)
                 if rng_net is not None else
                 jnp.zeros((num_tasks, 2), jnp.uint32))

    # The pre-adaptation diagnostic costs a third forward pass per task;
    # only pay for it in eval (or when explicitly requested).
    report_pre = self._report_pre_adaptation_loss or not train

    def per_task(cond_f, cond_l, inf_f, inf_l, task_rng):
      rng_adapt, rng_outer = jax.random.split(task_rng)
      adapted = self._adapt(base_params, inner_lr, cond_f, cond_l, mode,
                            rng_adapt, train=train)
      outer_loss, outer_scalars = self._task_loss(
          adapted, inf_f, inf_l, mode, rng_outer if train else None,
          train=train)
      if report_pre:
        pre_loss, _ = self._task_loss(
            base_params, inf_f, inf_l, mode, None, train=False)
      else:
        pre_loss = jnp.zeros(())
      return outer_loss, pre_loss, outer_scalars

    outer_losses, pre_losses, scalars = jax.vmap(per_task)(
        cond_f, cond_l, inf_f, inf_l, task_rngs)
    loss = jnp.mean(outer_losses)
    metrics = {k: jnp.mean(v) for k, v in scalars.items()}
    if report_pre:
      metrics["pre_adaptation_loss"] = jnp.mean(pre_losses)
    metrics["post_adaptation_loss"] = loss
    return loss, (metrics, batch_stats)

  def eval_step(self, state, features, labels) -> Dict[str, jax.Array]:
    """Eval = the meta loss without gradients (adaptation still runs)."""
    loss, (metrics, _) = self.loss_fn(
        state.params, state.batch_stats, features, labels, None,
        Mode.EVAL)
    return {"loss": loss, **metrics}

  # ---- serving: adapt on condition, answer on inference ----

  def predict_step(self, state, features) -> Any:
    features, _ = self.preprocessor.preprocess(
        features, None, Mode.PREDICT, None)
    base_params = state.params["base_net"]
    inner_lr = self._inner_lr
    if self._learn_inner_lr:
      inner_lr = jnp.exp(state.params["inner_lr_log"])
    cond_f = _split(features, CONDITION)
    inf_f = _split(features, INFERENCE)
    # At predict time the condition labels ride along INSIDE the feature
    # struct when the task supplies demonstrations; reference meta
    # policies conditioned the same way. Without labels in features,
    # adaptation is skipped (zero-shot).
    cond_l = None
    flat = features.to_flat_dict()
    prefix = CONDITION_LABELS + "/"
    label_keys = [k for k in flat if k.startswith(prefix)]
    if label_keys:
      cond_l = TensorSpecStruct.from_flat_dict(
          {k[len(prefix):]: flat[k] for k in label_keys})

    def per_task(cond_f, cond_l, inf_f):
      if cond_l is not None:
        adapted = self._adapt(base_params, inner_lr, cond_f, cond_l,
                              Mode.PREDICT,
                              jax.random.PRNGKey(0), train=False)
      else:
        adapted = base_params
      return self._base.network.apply({"params": adapted}, inf_f,
                                      train=False)

    if cond_l is not None:
      return jax.vmap(lambda cf, cl, inf: per_task(cf, cl, inf))(
          cond_f, cond_l, inf_f)
    return jax.vmap(lambda cf, inf: per_task(cf, None, inf))(cond_f,
                                                             inf_f)
