"""Demonstration-conditioned serving policies over exported meta models.

Reference parity: tensor2robot `meta_learning/meta_policies.py` — the
on-robot wrapper around an exported meta model: hold the current task's
demonstration(s), assemble each control step's meta feature batch
(condition split = demos, inference split = live observation), call the
predictor, hand back the adapted prediction (SURVEY.md §3 "MAML
wrapper" row, §4.4 serving handoff; file:line unavailable — empty
reference mount).

Works identically over `CheckpointPredictor` and `SavedModelPredictor`
(the exported jax2tf artifact), and over both adaptation mechanisms the
framework ships: gradient adaptation (MAML — demonstrations drive inner
SGD steps inside predict) and in-context conditioning (SNAIL —
demonstrations enter the trunk through attention). Both consume the
same flat serving layout the MAML preprocessor defines:

  condition/<feature keys>        [B_tasks, N_cond, ...]
  inference/<feature keys>        [B_tasks, N_inf, ...]
  condition_labels/<label keys>   [B_tasks, N_cond, ...]   (demos)
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import numpy as np

from tensor2robot_tpu.meta_learning.maml_model import (
    CONDITION,
    CONDITION_LABELS,
    INFERENCE,
)

log = logging.getLogger(__name__)


def _fit_to(array: np.ndarray, n: int) -> np.ndarray:
  """Cycles/truncates the leading (sample) dim to exactly n entries.

  Robots rarely record exactly the meta-trained demos-per-task count;
  cycling preserves every demonstration's influence, truncation keeps
  the earliest n (deterministic either way).
  """
  array = np.asarray(array)
  if array.shape[0] == n:
    return array
  if array.shape[0] > n:
    return array[:n]
  reps = -(-n // array.shape[0])  # ceil
  return np.concatenate([array] * reps, axis=0)[:n]


class MetaPolicy:
  """Holds a task's demonstrations; serves adapted predictions.

  Usage (one task episode):
      policy = MetaPolicy(predictor)
      policy.set_task(demo_features, demo_labels)   # condition data
      out = policy.predict(observation)             # adapted
      policy.reset_task()                           # back to zero-shot

  `demo_features` / `demo_labels`: flat dicts of [N_demos, ...] arrays
  keyed by the BASE model's feature/label keys. `observation`: a flat
  dict of single (unbatched) base feature arrays.

  Zero-shot (no demonstrations) requires a predictor whose serving path
  treats condition labels as optional — the checkpoint predictor does;
  an exported SavedModel signature takes fixed inputs, so exported
  serving always conditions (`set_task` first).
  """

  def __init__(self, predictor):
    self._predictor = predictor
    flat = predictor.get_feature_specification().to_flat_dict()
    self._condition_keys = sorted(
        k[len(CONDITION) + 1:] for k in flat
        if k.startswith(CONDITION + "/"))
    self._inference_keys = sorted(
        k[len(INFERENCE) + 1:] for k in flat
        if k.startswith(INFERENCE + "/"))
    self._label_keys = sorted(
        k[len(CONDITION_LABELS) + 1:] for k in flat
        if k.startswith(CONDITION_LABELS + "/"))
    if not self._condition_keys or not self._inference_keys:
      raise ValueError(
          "Predictor does not serve a meta model: feature spec has no "
          f"{CONDITION}/ + {INFERENCE}/ splits: {sorted(flat)}")
    self._num_condition = flat[
        f"{CONDITION}/{self._condition_keys[0]}"].shape[0]
    self._num_inference = flat[
        f"{INFERENCE}/{self._inference_keys[0]}"].shape[0]
    self._demo_features: Optional[Dict[str, np.ndarray]] = None
    self._demo_labels: Optional[Dict[str, np.ndarray]] = None

  @property
  def num_condition(self) -> int:
    return self._num_condition

  @property
  def num_inference(self) -> int:
    return self._num_inference

  @property
  def task_is_set(self) -> bool:
    return self._demo_features is not None

  def set_task(self,
               demo_features: Dict[str, np.ndarray],
               demo_labels: Optional[Dict[str, np.ndarray]] = None
               ) -> None:
    """Stores the current task's demonstrations (condition data)."""
    missing = set(self._condition_keys) - set(demo_features)
    if missing:
      raise ValueError(f"demo_features missing keys: {sorted(missing)}")
    self._demo_features = {
        k: _fit_to(demo_features[k], self._num_condition)
        for k in self._condition_keys}
    if demo_labels is not None:
      missing = set(self._label_keys) - set(demo_labels)
      if missing:
        raise ValueError(f"demo_labels missing keys: {sorted(missing)}")
      self._demo_labels = {
          k: _fit_to(demo_labels[k], self._num_condition)
          for k in self._label_keys}
    else:
      self._demo_labels = None

  def reset_task(self) -> None:
    """Clears demonstrations: subsequent predictions are zero-shot."""
    self._demo_features = None
    self._demo_labels = None

  def predict(self, observation: Dict[str, np.ndarray]
              ) -> Dict[str, Any]:
    """One adapted prediction for a single observation.

    Assembles the meta feature batch (task dim 1), runs the predictor,
    and returns the LAST inference slot of every output, unbatched —
    every slot holds the same live observation, and for causal
    in-context models the last slot attends to the most context.
    """
    missing = set(self._inference_keys) - set(observation)
    if missing:
      raise ValueError(f"observation missing keys: {sorted(missing)}")
    obs = {k: np.asarray(observation[k]) for k in self._inference_keys}

    features: Dict[str, np.ndarray] = {}
    for key in self._inference_keys:
      tiled = np.broadcast_to(
          obs[key][None], (self._num_inference,) + obs[key].shape)
      features[f"{INFERENCE}/{key}"] = np.ascontiguousarray(
          tiled)[None]
    if self.task_is_set:
      for key in self._condition_keys:
        features[f"{CONDITION}/{key}"] = self._demo_features[key][None]
      if self._demo_labels is not None:
        for key in self._label_keys:
          features[f"{CONDITION_LABELS}/{key}"] = \
              self._demo_labels[key][None]
    else:
      # Zero-shot: the condition slots still need tensors (the specs
      # are required); the live observation stands in, and with no
      # condition_labels the model skips adaptation.
      log.debug("MetaPolicy.predict with no task set: zero-shot.")
      for key in self._condition_keys:
        tiled = np.broadcast_to(
            obs[key][None], (self._num_condition,) + obs[key].shape)
        features[f"{CONDITION}/{key}"] = np.ascontiguousarray(
            tiled)[None]

    outputs = self._predictor.predict(features)
    result: Dict[str, Any] = {}
    for key, value in outputs.items():
      value = np.asarray(value)
      # [1 task, N_inf, ...] -> last inference slot; anything else
      # (per-task scalars etc.) just drops the task dim.
      if value.ndim >= 2 and value.shape[:1] == (1,):
        value = value[0]
        if value.ndim >= 1 and value.shape[0] == self._num_inference:
          value = value[-1]
      result[key] = value
    return result

  __call__ = predict
