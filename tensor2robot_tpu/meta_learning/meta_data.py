"""Meta-batch construction utilities.

Reference parity: tensor2robot `meta_learning/meta_tfdata.py` — turning
flat example streams into meta-example batches of (condition, inference)
sample sets per task (SURVEY.md §3 "MAML wrapper"; file:line unavailable
— empty reference mount).

Host-side numpy transforms: the meta-batch layout is just a reshape of
a flat batch, so any existing input generator becomes a meta generator
by wrapping it.
"""

from __future__ import annotations

import logging
from typing import Iterator, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.data.tfexample import SEQUENCE_LENGTH_KEY
from tensor2robot_tpu.meta_learning.maml_model import (
    CONDITION,
    INFERENCE,
)
from tensor2robot_tpu.specs import TensorSpecStruct, as_sequence_specs

log = logging.getLogger(__name__)


def make_meta_batch(features: TensorSpecStruct,
                    labels: Optional[TensorSpecStruct],
                    num_condition: int,
                    num_inference: int
                    ) -> Tuple[TensorSpecStruct,
                               Optional[TensorSpecStruct]]:
  """Reshapes a flat batch [B, ...] into a meta batch.

  B must be divisible by (num_condition + num_inference); the result has
  B / (num_condition + num_inference) tasks. Consecutive samples are
  assigned to the same task (callers wanting task coherence should feed
  episode-grouped batches, as the reference's episode_to_transitions
  pipelines did).
  """
  per_task = num_condition + num_inference

  def nest(struct):
    if struct is None:
      return None
    flat = struct.to_flat_dict()
    out = {}
    for key, value in flat.items():
      batch = value.shape[0]
      if batch % per_task:
        raise ValueError(
            f"Batch {batch} not divisible by condition+inference = "
            f"{per_task} (key {key!r}).")
      tasks = value.reshape((batch // per_task, per_task) +
                            value.shape[1:])
      out[f"{CONDITION}/{key}"] = tasks[:, :num_condition]
      out[f"{INFERENCE}/{key}"] = tasks[:, num_condition:]
    return TensorSpecStruct.from_flat_dict(out)

  return nest(features), nest(labels)


def meta_batch_from_episodes(features: TensorSpecStruct,
                             labels: Optional[TensorSpecStruct],
                             num_condition: int,
                             num_inference: int,
                             context_keys: Tuple[str, ...] = (),
                             ) -> Tuple[TensorSpecStruct,
                                        Optional[TensorSpecStruct]]:
  """Episode batch [B, T, ...] → meta batch; each episode is one task.

  The first `num_condition` timesteps become the condition set, the
  next `num_inference` the inference set — the reference's episode
  semantics (demonstration prefix conditions, later steps evaluate).
  Episodes whose TRUE length (the parser's `sequence_length` feature,
  when present) is < num_condition + num_inference are DROPPED with a
  logged warning — zero-padded timesteps must never masquerade as data,
  and real ragged datasets shouldn't abort the iterator over one short
  episode. If every episode in the batch is too short, raises (that is
  a config error, not raggedness). Keys in `context_keys` are
  per-episode (no time axis); they are tiled across the per-task sample
  dim of both splits. The `sequence_length` key itself is consumed
  here, not forwarded.
  """
  need = num_condition + num_inference
  flat_f = features.to_flat_dict()
  true_lengths = flat_f.get(SEQUENCE_LENGTH_KEY)
  keep = None
  if true_lengths is not None:
    short = np.asarray(true_lengths) < need
    if np.all(short):
      raise ValueError(
          f"Every episode in the batch is shorter than condition+"
          f"inference = {need} (true lengths "
          f"{np.asarray(true_lengths).tolist()}); splitting them would "
          f"train on zero padding. Lower num_condition/num_inference or "
          f"collect longer episodes.")
    if np.any(short):
      log.warning(
          "Dropping %d/%d episode(s) shorter than condition+inference "
          "= %d (true lengths %s).", int(short.sum()), short.size, need,
          np.asarray(true_lengths)[short].tolist())
      keep = ~short

  def nest(struct):
    if struct is None:
      return None
    out = {}
    for key, value in struct.to_flat_dict().items():
      if key == SEQUENCE_LENGTH_KEY:
        continue
      if keep is not None:
        value = value[keep]
      if key in context_keys:
        cond = np.repeat(value[:, None], num_condition, axis=1)
        inf = np.repeat(value[:, None], num_inference, axis=1)
        out[f"{CONDITION}/{key}"] = cond
        out[f"{INFERENCE}/{key}"] = inf
        continue
      if value.ndim < 2 or value.shape[1] < need:
        raise ValueError(
            f"Episode key {key!r} has shape {value.shape}; need a time "
            f"axis of at least condition+inference = {need}. Per-episode "
            f"(non-sequence) keys must be listed in context_keys.")
      out[f"{CONDITION}/{key}"] = value[:, :num_condition]
      out[f"{INFERENCE}/{key}"] = value[:, num_condition:need]
    return TensorSpecStruct.from_flat_dict(out)

  return nest(features), nest(labels)


@gin.configurable
class EpisodeMetaInputGenerator(AbstractInputGenerator):
  """Turns an episode generator's [B, T, ...] batches into meta batches.

  Reference parity: `meta_tfdata`'s episode→meta-example path — each
  episode is a task; its timestep prefix conditions the inner loop.
  `batch_size` counts TASKS (= episodes).
  """

  def __init__(self,
               episode_generator: AbstractInputGenerator,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               batch_size: int = 8):
    super().__init__(batch_size=batch_size)
    self._episodes = episode_generator
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task

  def set_specification_from_model(self, model, mode: Mode) -> None:
    base_model = getattr(model, "base_model", None)
    if base_model is None:
      raise ValueError(
          "EpisodeMetaInputGenerator requires a meta model exposing "
          "`base_model` (e.g. MAMLModel).")
    # The episode wire carries the BASE specs per timestep.
    base_feat = base_model.get_feature_specification(mode)
    base_label = base_model.get_label_specification(mode)
    self._episodes.set_specification(
        as_sequence_specs(base_feat),
        as_sequence_specs(base_label)
        if base_label is not None else None)
    self.set_specification(
        model.preprocessor.get_in_feature_specification(mode),
        model.preprocessor.get_in_label_specification(mode))

  def _create_dataset(self, mode: Mode, batch_size: int
                      ) -> Iterator[Tuple[TensorSpecStruct,
                                          Optional[TensorSpecStruct]]]:
    # Per-episode (non-sequence) keys carry no time axis and must be
    # tiled, not sliced.
    context_keys = tuple(
        k for k, s in self._episodes.feature_spec.to_flat_dict().items()
        if not s.is_sequence)
    # Short episodes are filtered HERE, buffering survivors across
    # episode batches, so every emitted meta batch carries exactly
    # `batch_size` tasks: a ragged dataset must neither abort the
    # iterator (all-short batch) nor shrink the task dim (each distinct
    # task count would retrace the jitted train step).
    need = self._num_condition + self._num_inference
    buf_f: dict = {}
    buf_l: Optional[dict] = None
    dropped = 0

    def emit_from(joined_f, joined_l):
      feats = TensorSpecStruct.from_flat_dict(joined_f)
      labs = (TensorSpecStruct.from_flat_dict(joined_l)
              if joined_l is not None else None)
      return meta_batch_from_episodes(
          feats, labs, self._num_condition, self._num_inference,
          context_keys=context_keys)

    for features, labels in self._episodes.create_dataset(
        mode, batch_size=batch_size):
      flat_f = features.to_flat_dict()
      lengths = flat_f.get(SEQUENCE_LENGTH_KEY)
      if lengths is not None:
        keep = np.asarray(lengths) >= need
        if not np.all(keep):
          dropped += int((~keep).sum())
          log.warning(
              "Dropped %d episode(s) shorter than condition+inference "
              "= %d (%d dropped so far).", int((~keep).sum()), need,
              dropped)
          flat_f = {k: v[keep] for k, v in flat_f.items()}
          if labels is not None:
            labels = TensorSpecStruct.from_flat_dict(
                {k: v[keep] for k, v in labels.to_flat_dict().items()})
          if not int(keep.sum()):
            continue
      for k, v in flat_f.items():
        buf_f.setdefault(k, []).append(v)
      if labels is not None:
        buf_l = buf_l or {}
        for k, v in labels.to_flat_dict().items():
          buf_l.setdefault(k, []).append(v)
      count = sum(a.shape[0] for a in buf_f[next(iter(buf_f))])
      while count >= batch_size:
        joined_f = {k: np.concatenate(v) for k, v in buf_f.items()}
        joined_l = ({k: np.concatenate(v) for k, v in buf_l.items()}
                    if buf_l else None)
        out_f = {k: v[:batch_size] for k, v in joined_f.items()}
        out_l = ({k: v[:batch_size] for k, v in joined_l.items()}
                 if joined_l is not None else None)
        buf_f = {k: [v[batch_size:]] for k, v in joined_f.items()}
        if joined_l is not None:
          buf_l = {k: [v[batch_size:]] for k, v in joined_l.items()}
        count -= batch_size
        yield emit_from(out_f, out_l)


@gin.configurable
class MetaExampleInputGenerator(AbstractInputGenerator):
  """Wraps a flat generator into meta-example batches.

  `batch_size` counts TASKS; the inner generator is driven at
  tasks × (num_condition + num_inference) samples per step.
  """

  def __init__(self,
               base_generator: AbstractInputGenerator,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               batch_size: int = 8):
    super().__init__(batch_size=batch_size)
    self._base = base_generator
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task

  def set_specification_from_model(self, model, mode: Mode) -> None:
    # The model is a MAMLModel: its specs are the nested meta specs;
    # the BASE generator needs the base model's flat specs.
    base_model = getattr(model, "base_model", None)
    if base_model is not None:
      self._base.set_specification_from_model(base_model, mode)
      self.set_specification(
          model.preprocessor.get_in_feature_specification(mode),
          model.preprocessor.get_in_label_specification(mode))
    else:
      raise ValueError(
          "MetaExampleInputGenerator requires a meta model exposing "
          "`base_model` (e.g. MAMLModel); a flat model would declare "
          "flat specs while this generator yields nested meta batches.")

  def _create_dataset(self, mode: Mode, batch_size: int
                      ) -> Iterator[Tuple[TensorSpecStruct,
                                          Optional[TensorSpecStruct]]]:
    per_task = self._num_condition + self._num_inference
    flat_batch = batch_size * per_task
    for features, labels in self._base.create_dataset(
        mode, batch_size=flat_batch):
      yield make_meta_batch(features, labels, self._num_condition,
                            self._num_inference)
