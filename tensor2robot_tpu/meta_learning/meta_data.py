"""Meta-batch construction utilities.

Reference parity: tensor2robot `meta_learning/meta_tfdata.py` — turning
flat example streams into meta-example batches of (condition, inference)
sample sets per task (SURVEY.md §3 "MAML wrapper"; file:line unavailable
— empty reference mount).

Host-side numpy transforms: the meta-batch layout is just a reshape of
a flat batch, so any existing input generator becomes a meta generator
by wrapping it.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.meta_learning.maml_model import (
    CONDITION,
    INFERENCE,
)
from tensor2robot_tpu.specs import TensorSpecStruct


def make_meta_batch(features: TensorSpecStruct,
                    labels: Optional[TensorSpecStruct],
                    num_condition: int,
                    num_inference: int
                    ) -> Tuple[TensorSpecStruct,
                               Optional[TensorSpecStruct]]:
  """Reshapes a flat batch [B, ...] into a meta batch.

  B must be divisible by (num_condition + num_inference); the result has
  B / (num_condition + num_inference) tasks. Consecutive samples are
  assigned to the same task (callers wanting task coherence should feed
  episode-grouped batches, as the reference's episode_to_transitions
  pipelines did).
  """
  per_task = num_condition + num_inference

  def nest(struct):
    if struct is None:
      return None
    flat = struct.to_flat_dict()
    out = {}
    for key, value in flat.items():
      batch = value.shape[0]
      if batch % per_task:
        raise ValueError(
            f"Batch {batch} not divisible by condition+inference = "
            f"{per_task} (key {key!r}).")
      tasks = value.reshape((batch // per_task, per_task) +
                            value.shape[1:])
      out[f"{CONDITION}/{key}"] = tasks[:, :num_condition]
      out[f"{INFERENCE}/{key}"] = tasks[:, num_condition:]
    return TensorSpecStruct.from_flat_dict(out)

  return nest(features), nest(labels)


@gin.configurable
class MetaExampleInputGenerator(AbstractInputGenerator):
  """Wraps a flat generator into meta-example batches.

  `batch_size` counts TASKS; the inner generator is driven at
  tasks × (num_condition + num_inference) samples per step.
  """

  def __init__(self,
               base_generator: AbstractInputGenerator,
               num_condition_samples_per_task: int = 4,
               num_inference_samples_per_task: int = 4,
               batch_size: int = 8):
    super().__init__(batch_size=batch_size)
    self._base = base_generator
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task

  def set_specification_from_model(self, model, mode: Mode) -> None:
    # The model is a MAMLModel: its specs are the nested meta specs;
    # the BASE generator needs the base model's flat specs.
    base_model = getattr(model, "base_model", None)
    if base_model is not None:
      self._base.set_specification_from_model(base_model, mode)
      self.set_specification(
          model.preprocessor.get_in_feature_specification(mode),
          model.preprocessor.get_in_label_specification(mode))
    else:
      raise ValueError(
          "MetaExampleInputGenerator requires a meta model exposing "
          "`base_model` (e.g. MAMLModel); a flat model would declare "
          "flat specs while this generator yields nested meta batches.")

  def _create_dataset(self, mode: Mode, batch_size: int
                      ) -> Iterator[Tuple[TensorSpecStruct,
                                          Optional[TensorSpecStruct]]]:
    per_task = self._num_condition + self._num_inference
    flat_batch = batch_size * per_task
    for features, labels in self._base.create_dataset(
        mode, batch_size=flat_batch):
      yield make_meta_batch(features, labels, self._num_condition,
                            self._num_inference)
