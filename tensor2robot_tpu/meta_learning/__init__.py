"""Meta-learning (reference: tensor2robot meta_learning/)."""

from tensor2robot_tpu.meta_learning.maml_model import (
    CONDITION,
    CONDITION_LABELS,
    INFERENCE,
    MAMLModel,
)
from tensor2robot_tpu.meta_learning.meta_policies import MetaPolicy
from tensor2robot_tpu.meta_learning.meta_data import (
    EpisodeMetaInputGenerator,
    MetaExampleInputGenerator,
    make_meta_batch,
    meta_batch_from_episodes,
)
