"""SavedModel exporter: jax2tf predict path + t2r spec assets.

Reference parity: tensor2robot `export_generators/
default_export_generator.py` — SavedModel export with raw-numpy and
tf.Example serving signatures, plus `assets.extra/t2r_assets` so
robot-side predictors can rebuild the serving specs (SURVEY.md §3, §4.4;
file:line unavailable — empty reference mount).

TPU-native redesign: the model's pure `predict_step` (preprocess +
network, already one XLA program) is closed over the trained params and
staged to TF with `jax2tf.convert`. Two signatures:
  * `serving_default` — one named tf tensor per flat feature-spec key
    (the reference's numpy receiver).
  * `parse_tf_example` — a batch of serialized tf.Example protos; the
    spec-derived parse graph (same derivation as the training-side
    TFExampleDecoder) runs in TF, then feeds the converted XLA fn.
Spec assets land in `assets.extra/t2r_assets.json` inside the
SavedModel, exactly where reference consumers look for them.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data import tfexample
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.export.abstract_export_generator import (
    AbstractExportGenerator,
    check_signature_keys,
    claim_timestamped_export_dir,
    sanitize_signature_key,
)


def _tf():
  import tensorflow as tf  # lazy, host-side only
  return tf


@gin.configurable
class SavedModelExportGenerator(AbstractExportGenerator):
  """Exports predict_step as a TF SavedModel with spec assets."""

  def __init__(self,
               export_dir_base: Optional[str] = None,
               include_tf_example_signature: bool = True,
               batch_polymorphic: bool = True,
               sequence_example_length: Optional[int] = None,
               serving_max_batch: Optional[int] = None,
               serving_max_wait_us: int = 200):
    """Args:
      export_dir_base: where timestamped exports land.
      include_tf_example_signature: also emit a serialized-proto
        signature. For flat specs that is `parse_tf_example`
        (tf.Example wire); sequence specs cannot ride tf.Example, so
        episode models emit `parse_tf_sequence_example` instead —
        which needs `sequence_example_length` — or, when no length is
        given, skip the proto signature with a warning (the
        `serving_default` numpy signature always works).
      batch_polymorphic: symbolic batch dim in the exported graph.
      sequence_example_length: static time-axis length the
        tf.SequenceExample parse signature pads/truncates episodes to.
      serving_max_batch: when set, the recommended low-latency serving
        config (powers-of-two bucket table up to this max, plus the
        micro-batch deadline below) ships in the asset payload under
        `extra["serving"]` — fleet consumers configure their bucketed
        engines from the export alone (docs/SERVING.md).
      serving_max_wait_us: recommended micro-batch coalescing deadline
        recorded alongside the bucket table.
    """
    super().__init__(export_dir_base)
    self._include_tf_example_signature = include_tf_example_signature
    self._batch_polymorphic = batch_polymorphic
    self._sequence_example_length = sequence_example_length
    self._serving_max_batch = serving_max_batch
    self._serving_max_wait_us = serving_max_wait_us

  def export(self, model: Any, state: Any, model_dir: str) -> str:
    from jax.experimental import jax2tf  # lazy: TF import is slow
    tf = _tf()

    feature_spec = specs_lib.flatten_spec_structure(
        model.preprocessor.get_in_feature_specification(Mode.PREDICT))
    flat_specs = feature_spec.to_flat_dict()
    # Serving state must be host-local numpy: the SavedModel must not
    # capture device buffers (the trainer's state lives on the mesh).
    variables = jax.device_get(state.variables)
    state_step = int(np.asarray(jax.device_get(state.step)))

    def predict_flat(flat_features: Dict[str, Any]):
      features = specs_lib.TensorSpecStruct.from_flat_dict(
          dict(flat_features))
      frozen = type(state)(
          step=state_step, params=variables["params"],
          batch_stats=variables.get("batch_stats", {}),
          opt_state=None)
      outputs = model.predict_step(frozen, features)
      if not isinstance(outputs, (dict, specs_lib.TensorSpecStruct)):
        outputs = {"output": outputs}
      if isinstance(outputs, specs_lib.TensorSpecStruct):
        outputs = outputs.to_flat_dict()
      return dict(outputs)

    batch_dim = None if self._batch_polymorphic else 1
    # Sequence specs (is_sequence) carry a time axis between batch and
    # the per-step shape — episode-consuming models (the long-context
    # transformer family) serve [B, T, ...] batches, so the time dim
    # is always polymorphic in the export.
    seq_keys = {k for k, s in flat_specs.items()
                if getattr(s, "is_sequence", False)}
    b_sym = "b" if self._batch_polymorphic else "1"
    use_poly = self._batch_polymorphic or bool(seq_keys)
    poly_map = {
        k: f"({b_sym}, t, ...)" if k in seq_keys else f"({b_sym}, ...)"
        for k in flat_specs
    }
    converted = jax2tf.convert(
        predict_flat,
        polymorphic_shapes=[poly_map] if use_poly else None,
        # Robots deserve a model that runs wherever they are: lower for
        # CPU and TPU regardless of which backend the trainer ran on.
        native_serialization_platforms=("cpu", "tpu"),
        with_gradient=False)

    tf_module = tf.Module()

    # Signature tensor names cannot contain '/', so nested flat keys
    # (a/b/c) are sanitized; predictors apply the same mapping.
    check_signature_keys(flat_specs)
    input_sigs = {
        key: tf.TensorSpec(
            [batch_dim] + ([None] if key in seq_keys else [])
            + list(spec.shape),
            _tf_dtype(tf, spec), name=sanitize_signature_key(key))
        for key, spec in flat_specs.items()
    }

    @tf.function(input_signature=[input_sigs])
    def serving_default(flat_features):
      return converted(flat_features)

    signatures = {"serving_default": serving_default}

    if self._include_tf_example_signature and not seq_keys:

      @tf.function(input_signature=[
          tf.TensorSpec([batch_dim], tf.string, name="examples")])
      def parse_tf_example(serialized):
        # Same graph parser the training-side tf.data pipeline maps —
        # ONE implementation of the wire contract (decode, varlen
        # pad/truncate, static shapes) for train and serve.
        flat = tfexample.graph_parse_example(serialized, feature_spec)
        return converted(flat)

      signatures["parse_tf_example"] = parse_tf_example
    elif (self._include_tf_example_signature
          and self._sequence_example_length is not None):
      seq_len = int(self._sequence_example_length)

      @tf.function(input_signature=[
          tf.TensorSpec([batch_dim], tf.string, name="examples")])
      def parse_tf_sequence_example(serialized):
        # Episodes travel as tf.SequenceExample; same graph parser as
        # the training-side episode pipeline, padded/truncated to the
        # declared static length.
        flat = tfexample.graph_parse_sequence_example(
            serialized, feature_spec, seq_len)
        # The parser's true-lengths output is not a model feature.
        flat.pop(tfexample.SEQUENCE_LENGTH_KEY, None)
        return converted(flat)

      signatures["parse_tf_sequence_example"] = parse_tf_sequence_example
    elif self._include_tf_example_signature:
      # Sequence specs cannot be bound to the tf.Example wire
      # (data/tfexample.py build_feature_map raises); without a
      # declared static episode length there is no proto signature to
      # build. serving_default still serves [B, T, ...] batches.
      warnings.warn(
          f"Skipping the serialized-proto serving signature: feature "
          f"specs {sorted(seq_keys)} are sequences, which travel as "
          f"tf.SequenceExample, and no sequence_example_length was "
          f"configured. Pass "
          f"SavedModelExportGenerator.sequence_example_length to emit "
          f"parse_tf_sequence_example, or serve via serving_default.",
          RuntimeWarning, stacklevel=2)

    export_base = self.export_dir_base(model_dir)
    export_dir, tmp_dir = claim_timestamped_export_dir(export_base)
    tf.saved_model.save(tf_module, tmp_dir, signatures=signatures)

    assets_dir = os.path.join(tmp_dir, "assets.extra")
    os.makedirs(assets_dir, exist_ok=True)
    extra = None
    if self._serving_max_batch is not None:
      from tensor2robot_tpu.serving.bucketing import bucket_table
      extra = {"serving": {
          "max_batch": int(self._serving_max_batch),
          "bucket_sizes": list(bucket_table(self._serving_max_batch)),
          "max_wait_us": int(self._serving_max_wait_us),
      }}
    specs_lib.write_assets(
        os.path.join(assets_dir, specs_lib.ASSET_FILENAME),
        feature_spec,
        label_spec=model.preprocessor.get_in_label_specification(
            Mode.PREDICT),
        global_step=state_step,
        extra=extra)
    # Atomic publish: pollers never observe a half-written SavedModel.
    os.rename(tmp_dir, export_dir)
    return export_dir


def _tf_dtype(tf, spec):
  name = ("bfloat16" if str(spec.dtype) == "bfloat16"
          else np.dtype(spec.dtype).name)
  return getattr(tf, name)


@gin.configurable
def create_default_exporters(model,
                             export_dir_base: Optional[str] = None,
                             **kwargs):
  """Reference-parity factory for train_eval's create_exporters_fn."""
  del model
  return [SavedModelExportGenerator(export_dir_base=export_dir_base,
                                    **kwargs)]
