"""Export generator protocol: trained state → serving artifact.

Reference parity: tensor2robot `export_generators/
abstract_export_generator.py` — `AbstractExportGenerator` building
serving_input_receiver_fns from specs and exporting SavedModels with t2r
assets (SURVEY.md §3 "Export generators"; file:line unavailable — empty
reference mount).

TPU-native redesign: no receiver fns / sessions. An exporter takes the
model and its on-device TrainState and writes a self-describing artifact
(SavedModel via jax2tf, or a raw orbax params dir) whose spec assets let
predictors rebuild the serving contract without the model class.
"""

from __future__ import annotations

import abc
import os
import time
from typing import Any, Optional


def sanitize_signature_key(key: str) -> str:
  """Flat spec key → TF signature tensor name (no '/' allowed).

  This is a WIRE CONTRACT between exporters and SavedModel predictors;
  both sides must use this one helper. The mapping is not injective
  ('a/b' and 'a_b' collide) — exporters must call
  `check_signature_keys` over the full key set so a colliding spec
  fails loudly at export time instead of producing an ambiguous feed.
  """
  return key.replace("/", "_")


def check_signature_keys(keys) -> None:
  """Raises if two flat spec keys sanitize to the same tensor name."""
  seen = {}
  for key in keys:
    name = sanitize_signature_key(key)
    if name in seen and seen[name] != key:
      raise ValueError(
          f"Flat spec keys {seen[name]!r} and {key!r} both sanitize to "
          f"signature name {name!r}; rename one — the SavedModel feed "
          "would be ambiguous.")
    seen[name] = key


def claim_timestamped_export_dir(export_dir_base: str) -> tuple:
  """Atomically claims `<base>/<unix_ts>`; returns (final_dir, tmp_dir).

  Estimator-style monotonic timestamp dirs so pollers pick `max()`.
  The claim is the mkdir of `<ts>.tmp` (atomic on POSIX): concurrent
  exporters — e.g. the async-export hook's thread racing the end-of-
  training exporter within the same second — get distinct timestamps
  instead of colliding inside one half-written artifact. The caller
  writes into tmp_dir and publishes with os.rename(tmp_dir, final_dir).
  """
  os.makedirs(export_dir_base, exist_ok=True)
  ts = int(time.time())
  while True:
    path = os.path.join(export_dir_base, str(ts))
    tmp = path + ".tmp"
    if not os.path.exists(path):
      try:
        os.mkdir(tmp)
        return path, tmp
      except FileExistsError:
        pass
    ts += 1


def latest_export_dir(export_dir_base: str) -> Optional[str]:
  """Largest finalized timestamped subdir, or None."""
  if not os.path.isdir(export_dir_base):
    return None
  candidates = [d for d in os.listdir(export_dir_base)
                if d.isdigit()
                and not d.endswith(".tmp")
                and os.path.isdir(os.path.join(export_dir_base, d))]
  if not candidates:
    return None
  return os.path.join(export_dir_base, max(candidates, key=int))


class AbstractExportGenerator(abc.ABC):
  """Builds serving artifacts from a model + TrainState."""

  def __init__(self, export_dir_base: Optional[str] = None):
    self._export_dir_base = export_dir_base

  def export_dir_base(self, model_dir: str) -> str:
    return self._export_dir_base or os.path.join(model_dir, "export")

  def set_export_dir_base(self, export_dir_base: str) -> None:
    """Public override point (used by e.g. AsyncExportHook)."""
    self._export_dir_base = export_dir_base

  @abc.abstractmethod
  def export(self, model: Any, state: Any, model_dir: str) -> str:
    """Writes one serving artifact; returns its path."""
