"""Export generators (reference: tensor2robot export_generators/)."""

from tensor2robot_tpu.export.abstract_export_generator import (
    AbstractExportGenerator,
    claim_timestamped_export_dir,
    latest_export_dir,
)
from tensor2robot_tpu.export.savedmodel_export_generator import (
    SavedModelExportGenerator,
    create_default_exporters,
)
