"""Declarative tensor specifications — the core of the framework.

This is the TPU-native re-design of the reference's spec system
(reference: tensor2robot `utils/tensorspec_utils.py` — `ExtendedTensorSpec`
and `TensorSpecStruct`; exact file:line cites unavailable, see SURVEY.md
provenance note). Models declare their input/output feature and label
specs declaratively; the framework mechanically derives record parsers,
random test-data generators, serving signatures, sharding layouts, and
validation from those declarations.

TPU-first design choices (vs. the reference's TF1 `tf.TensorSpec` subclass):

* Specs are immutable, hashable dataclasses built around *logical* shapes
  (no batch dim).  They convert directly to `jax.ShapeDtypeStruct` for
  `jax.eval_shape` / AOT compilation, and carry an optional
  `jax.sharding.PartitionSpec` so the same declaration that derives the
  parser also derives the pjit sharding of the batch.
* `TensorSpecStruct` is registered as a JAX pytree, so entire spec
  structures (and the batches packed against them) flow through `jax.jit`,
  `jax.tree_util`, and `pjit` without adapter code.
* dtypes are numpy/jax dtypes; `bfloat16` is first-class (the reference
  had to special-case it for TPU).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Iterator, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

# Path separator for nested spec structures, matching the reference's
# convention of '/'-joined keys in flattened spec structures.
PATH_SEP = "/"

_VALID_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")


def _normalize_dtype(dtype: Any) -> np.dtype:
  """Normalizes dtypes, keeping bfloat16 (a numpy extension type) intact."""
  if dtype is None:
    raise ValueError("TensorSpec dtype must not be None.")
  if isinstance(dtype, str) and dtype == "bfloat16":
    return jnp.bfloat16.dtype
  if dtype in (jnp.bfloat16, jnp.bfloat16.dtype):
    return jnp.bfloat16.dtype
  return np.dtype(dtype)


@dataclasses.dataclass(frozen=True)
class ExtendedTensorSpec:
  """An immutable tensor declaration with data-pipeline metadata.

  Attributes:
    shape: Logical (unbatched) shape. Entries must be positive ints.
    dtype: numpy/jax dtype of the tensor *as seen by the model*.
    name: Wire name; used as the record feature key unless `dataset_key`
      overrides it and as the serving-signature name.
    is_optional: Optional tensors may be absent from a dataset; required
      tensors fail validation when missing.
    is_sequence: Marks per-timestep tensors (episode data). Sequence
      tensors get a leading time axis after the batch axis.
    data_format: The on-disk encoding: 'jpeg'/'png' (image codecs —
      stored as encoded strings, decoded host-side before infeed),
      'raw' (one little-endian C-order byte string per tensor — a
      near-memcpy `decode_raw` at parse time, trading disk for the
      decode CPU that bounds host feed rate), or None for numeric
      int64/float lists.
    dataset_key: For multi-dataset input pipelines, the name of the source
      dataset this tensor is read from ('' = default dataset).
    varlen: Variable-length feature (ragged on disk); padded/truncated to
      `shape` at parse time.
    sharding: Optional `jax.sharding.PartitionSpec` for the *batched*
      tensor; derived pipelines use it to place the batch on the mesh.
      None means "replicate / let the trainer's data axis rule apply".
  """

  shape: Tuple[int, ...]
  dtype: np.dtype
  name: Optional[str] = None
  is_optional: bool = False
  is_sequence: bool = False
  data_format: Optional[str] = None
  dataset_key: str = ""
  varlen: bool = False
  sharding: Optional[Any] = None  # jax.sharding.PartitionSpec

  def __post_init__(self):
    shape = tuple(int(d) for d in self.shape)
    if any(d <= 0 for d in shape):
      raise ValueError(
          f"ExtendedTensorSpec shapes must be fully-defined and positive, "
          f"got {shape} for name={self.name!r}. Use varlen=True for "
          f"ragged wire data; logical shapes are static (XLA requirement).")
    object.__setattr__(self, "shape", shape)
    object.__setattr__(self, "dtype", _normalize_dtype(self.dtype))
    if self.name is not None and not _VALID_NAME_RE.match(self.name):
      raise ValueError(f"Invalid spec name: {self.name!r}")
    if self.data_format is not None and self.data_format not in (
        "jpeg", "png", "raw"):
      raise ValueError(f"Unsupported data_format: {self.data_format!r}")

  # ---- constructors ----

  @classmethod
  def from_spec(cls, spec: "ExtendedTensorSpec", **overrides) -> (
      "ExtendedTensorSpec"):
    """Copy-with-overrides, mirroring the reference's from_spec API."""
    kwargs = dict(
        shape=spec.shape,
        dtype=spec.dtype,
        name=spec.name,
        is_optional=spec.is_optional,
        is_sequence=spec.is_sequence,
        data_format=spec.data_format,
        dataset_key=spec.dataset_key,
        varlen=spec.varlen,
        sharding=spec.sharding,
    )
    kwargs.update(overrides)
    return cls(**kwargs)

  @classmethod
  def from_array(cls, array: Any, name: Optional[str] = None) -> (
      "ExtendedTensorSpec"):
    """Builds a spec describing a concrete (unbatched) array."""
    arr = np.asarray(array) if not hasattr(array, "dtype") else array
    return cls(shape=tuple(arr.shape), dtype=arr.dtype, name=name)

  # ---- conversions ----

  def to_shape_dtype_struct(
      self, batch_size: Optional[int] = None,
      sequence_length: Optional[int] = None) -> jax.ShapeDtypeStruct:
    """The jax-native view of this spec, optionally batched.

    This is what feeds `jax.eval_shape` and AOT lowering: the same
    declaration the data layer parses against also drives compilation.
    """
    shape = self.shape
    if self.is_sequence and sequence_length is not None:
      shape = (sequence_length,) + shape
    if batch_size is not None:
      shape = (batch_size,) + shape
    return jax.ShapeDtypeStruct(shape, self.dtype)

  @property
  def is_image(self) -> bool:
    return self.data_format in ("jpeg", "png")

  def replace(self, **overrides) -> "ExtendedTensorSpec":
    return self.from_spec(self, **overrides)

  def __repr__(self):
    parts = [f"shape={self.shape}", f"dtype={np.dtype(self.dtype).name}"]
    if self.name:
      parts.append(f"name={self.name!r}")
    for attr in ("is_optional", "is_sequence", "varlen"):
      if getattr(self, attr):
        parts.append(f"{attr}=True")
    if self.data_format:
      parts.append(f"data_format={self.data_format!r}")
    if self.dataset_key:
      parts.append(f"dataset_key={self.dataset_key!r}")
    return f"ExtendedTensorSpec({', '.join(parts)})"


# Short alias used throughout the codebase.
TensorSpec = ExtendedTensorSpec


class TensorSpecStruct(Mapping[str, Any]):
  """An ordered, nested attribute/dict hybrid container for specs & tensors.

  Reference parity: tensor2robot's `TensorSpecStruct` (utils/
  tensorspec_utils.py [U]) — an OrderedDict subclass allowing both
  `struct.key` attribute access and `struct['a/b']` flat path access over
  a nested structure.

  TPU-native twist: instances are registered as a JAX pytree node
  (see `register_pytree_node` below), so the same container type holds
  spec trees, `ShapeDtypeStruct` trees, and concrete batch trees, and can
  be passed straight into jitted/pjitted functions.

  Internally the structure is stored FLAT: an insertion-ordered dict from
  '/'-joined paths to leaves. Nested access materializes sub-structs
  lazily. This makes flatten/pack trivial and guarantees a stable leaf
  order for pytree flattening (insertion order, like the reference's
  OrderedDict semantics).
  """

  __slots__ = ("_flat",)

  def __init__(self, *args, **kwargs):
    object.__setattr__(self, "_flat", {})
    init = {}
    if args:
      if len(args) > 1:
        raise TypeError("TensorSpecStruct takes at most one positional arg")
      src = args[0]
      if isinstance(src, TensorSpecStruct):
        init.update(src._flat)
      elif isinstance(src, Mapping):
        init.update(src)
      else:
        init.update(dict(src))
    init.update(kwargs)
    for key, value in init.items():
      self[key] = value

  # ---- core mapping protocol over flat '/' paths and nested prefixes ----

  def _subkeys(self, prefix: str):
    prefix_sep = prefix + PATH_SEP
    return [k for k in self._flat if k.startswith(prefix_sep)]

  def __getitem__(self, key: str):
    if not isinstance(key, str):
      raise TypeError(f"Keys must be str, got {type(key)}")
    if key in self._flat:
      return self._flat[key]
    sub = self._subkeys(key)
    if sub:
      prefix_sep = key + PATH_SEP
      return TensorSpecStruct(
          {k[len(prefix_sep):]: self._flat[k] for k in sub})
    raise KeyError(key)

  def __setitem__(self, key: str, value: Any):
    if not isinstance(key, str) or not key or key.startswith(PATH_SEP):
      raise KeyError(f"Invalid key: {key!r}")
    if isinstance(value, (TensorSpecStruct, dict)):
      items = (value._flat.items() if isinstance(value, TensorSpecStruct)
               else TensorSpecStruct(value)._flat.items())
      # Clear any existing leaf/subtree at this key first.
      self._delete_prefix(key, missing_ok=True)
      for sub_key, leaf in items:
        self._flat[f"{key}{PATH_SEP}{sub_key}"] = leaf
    else:
      # A leaf overwrite shadows any subtree previously at this path.
      self._delete_prefix(key, missing_ok=True)
      self._flat[key] = value

  def _delete_prefix(self, key: str, missing_ok: bool = False):
    found = False
    if key in self._flat:
      del self._flat[key]
      found = True
    for k in self._subkeys(key):
      del self._flat[k]
      found = True
    if not found and not missing_ok:
      raise KeyError(key)

  def __delitem__(self, key: str):
    self._delete_prefix(key)

  def __contains__(self, key) -> bool:
    return key in self._flat or bool(self._subkeys(key))

  def __iter__(self) -> Iterator[str]:
    # Iterates top-level keys, preserving first-insertion order.
    seen = []
    for k in self._flat:
      top = k.split(PATH_SEP, 1)[0]
      if top not in seen:
        seen.append(top)
    return iter(seen)

  def __len__(self) -> int:
    return sum(1 for _ in self)

  # ---- attribute access ----

  def __getattr__(self, name: str):
    if name.startswith("_"):
      raise AttributeError(name)
    try:
      return self[name]
    except KeyError as e:
      raise AttributeError(name) from e

  def __setattr__(self, name: str, value: Any):
    if name.startswith("_"):
      object.__setattr__(self, name, value)
    else:
      self[name] = value

  def __delattr__(self, name: str):
    try:
      del self[name]
    except KeyError as e:
      raise AttributeError(name) from e

  # ---- flat views ----

  def to_flat_dict(self) -> dict:
    """Flat '/'-path → leaf dict (insertion-ordered copy)."""
    return dict(self._flat)

  @classmethod
  def from_flat_dict(cls, flat: Mapping[str, Any]) -> "TensorSpecStruct":
    out = cls()
    for k, v in flat.items():
      out._flat[k] = v
    return out

  def to_nested_dict(self) -> dict:
    out: dict = {}
    for path, leaf in self._flat.items():
      parts = path.split(PATH_SEP)
      node = out
      for p in parts[:-1]:
        node = node.setdefault(p, {})
      node[parts[-1]] = leaf
    return out

  # ---- niceties ----

  def keys(self):
    return list(iter(self))

  def values(self):
    return [self[k] for k in self]

  def items(self):
    return [(k, self[k]) for k in self]

  def __eq__(self, other):
    if isinstance(other, TensorSpecStruct):
      return self._flat == other._flat
    if isinstance(other, Mapping):
      try:
        return self._flat == TensorSpecStruct(other)._flat
      except (KeyError, TypeError):
        return False
    return NotImplemented

  def __repr__(self):
    inner = ", ".join(f"{k}: {v!r}" for k, v in self._flat.items())
    return f"TensorSpecStruct({{{inner}}})"


def _struct_flatten(struct: TensorSpecStruct):
  flat = struct.to_flat_dict()
  keys = tuple(flat.keys())
  return tuple(flat.values()), keys


def _struct_unflatten(keys, leaves):
  out = TensorSpecStruct()
  for k, v in zip(keys, leaves):
    out._flat[k] = v  # bypass subtree logic: keys are known-flat
  return out


jax.tree_util.register_pytree_node(
    TensorSpecStruct, _struct_flatten, _struct_unflatten)


SpecOrStruct = Union[ExtendedTensorSpec, TensorSpecStruct, Mapping]
