"""Spec-driven random data generation — the test backbone.

Reference parity: tensor2robot's `DefaultRandomInputGenerator` /
`make_random_numpy`-style helpers (input_generators/ and utils/
tensorspec_utils.py [U]; SURVEY.md §5): every framework integration test
runs on random spec-conforming data, so no datasets are needed to exercise
the full train/eval/export path. We reproduce that contract with numpy
RNG (host-side; feeding real pipelines) and keep it deterministic via an
explicit seed.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from tensor2robot_tpu.specs import packing
from tensor2robot_tpu.specs.tensorspec import (
    ExtendedTensorSpec,
    TensorSpecStruct,
)


def random_array_for_spec(
    spec: ExtendedTensorSpec,
    rng: np.random.Generator,
    batch_size: Optional[int] = None,
    sequence_length: Optional[int] = None,
) -> np.ndarray:
  """Draws one random array conforming to `spec`.

  Images (uint8 / image-format specs) are uniform in [0, 255]; floats are
  standard normal; ints uniform in [0, 10); bools fair coin flips.
  """
  shape = tuple(spec.shape)
  if spec.is_sequence:
    shape = (sequence_length or 3,) + shape
  if batch_size is not None:
    shape = (batch_size,) + shape
  dtype = np.dtype(spec.dtype) if spec.dtype.kind != "V" else spec.dtype
  if spec.is_image or dtype == np.uint8:
    return rng.integers(0, 256, size=shape, dtype=np.uint8).astype(spec.dtype)
  if dtype.kind == "f" or spec.dtype.name == "bfloat16":
    return rng.standard_normal(size=shape).astype(spec.dtype)
  if dtype.kind in ("i", "u"):
    return rng.integers(0, 10, size=shape).astype(dtype)
  if dtype.kind == "b":
    return (rng.random(size=shape) > 0.5)
  raise ValueError(f"Cannot generate random data for dtype {dtype}")


def make_random_tensors(
    spec_structure: Any,
    batch_size: Optional[int] = None,
    sequence_length: Optional[int] = None,
    seed: int = 0,
    include_optional: bool = True,
) -> TensorSpecStruct:
  """Generates a full random batch conforming to a spec structure."""
  rng = np.random.default_rng(seed)
  flat = packing.flatten_spec_structure(spec_structure).to_flat_dict()
  out = {}
  for key, spec in flat.items():
    if spec.is_optional and not include_optional:
      continue
    out[key] = random_array_for_spec(
        spec, rng, batch_size=batch_size, sequence_length=sequence_length)
  return TensorSpecStruct.from_flat_dict(out)
