"""Validation and packing of tensors against spec structures.

Reference parity: tensor2robot `utils/tensorspec_utils.py` —
`flatten_spec_structure`, `validate_and_pack`, `validate_and_flatten`,
`filter_required_flat_tensor_spec_structure`,
`pack_flat_sequence_to_spec_structure` (file:line cites unavailable; see
SURVEY.md provenance note).

The contract these functions enforce is the framework's backbone: a model
declares specs; data pipelines produce flat dicts of arrays; before any
array reaches a jitted step it is validated (shape/dtype, modulo batch and
time prefixes) and packed into a `TensorSpecStruct` whose layout matches
the declaration. Optional specs may be absent; required specs must match.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np

from tensor2robot_tpu.specs.tensorspec import (
    PATH_SEP,
    ExtendedTensorSpec,
    TensorSpecStruct,
)


class SpecValidationError(ValueError):
  """Raised when tensors do not conform to their declared specs."""


def is_leaf_spec(value: Any) -> bool:
  return isinstance(value, ExtendedTensorSpec)


def flatten_spec_structure(spec_structure: Any) -> TensorSpecStruct:
  """Flattens an arbitrarily nested structure into a TensorSpecStruct.

  Accepts TensorSpecStruct, mappings, named tuples, and (nested) lists /
  tuples; list positions become string indices, matching the reference's
  behavior of admitting arbitrary nests.
  """
  flat: dict = {}

  def visit(prefix: str, node: Any):
    if isinstance(node, TensorSpecStruct):
      for k, v in node.to_flat_dict().items():
        flat_key = f"{prefix}{PATH_SEP}{k}" if prefix else k
        flat[flat_key] = v
    elif isinstance(node, Mapping):
      for k, v in node.items():
        flat_key = f"{prefix}{PATH_SEP}{k}" if prefix else str(k)
        visit(flat_key, v)
    elif hasattr(node, "_asdict"):  # namedtuple
      visit(prefix, node._asdict())
    elif isinstance(node, (list, tuple)):
      for i, v in enumerate(node):
        flat_key = f"{prefix}{PATH_SEP}{i}" if prefix else str(i)
        visit(flat_key, v)
    else:
      if not prefix:
        raise SpecValidationError(
            "Cannot flatten a bare leaf without a key.")
      flat[prefix] = node

  visit("", spec_structure)
  return TensorSpecStruct.from_flat_dict(flat)


def assert_valid_spec_structure(spec_structure: Any) -> None:
  """Asserts every leaf is an ExtendedTensorSpec."""
  flat = flatten_spec_structure(spec_structure)
  for key, leaf in flat.to_flat_dict().items():
    if not is_leaf_spec(leaf):
      raise SpecValidationError(
          f"Spec structure leaf {key!r} is not an ExtendedTensorSpec: "
          f"{type(leaf)}")


def filter_required_flat_tensor_spec_structure(
    spec_structure: Any) -> TensorSpecStruct:
  """Returns only the non-optional specs, flattened."""
  flat = flatten_spec_structure(spec_structure)
  return TensorSpecStruct.from_flat_dict({
      k: v for k, v in flat.to_flat_dict().items() if not v.is_optional
  })


def _check_leaf(
    key: str,
    spec: ExtendedTensorSpec,
    array: Any,
    batch_prefix_dims: int,
) -> None:
  """Validates one array against one spec, ignoring leading prefix dims."""
  shape = tuple(array.shape)
  expected = tuple(spec.shape)
  # Sequence tensors carry one extra (time) axis inside the prefix.
  prefix = batch_prefix_dims + (1 if spec.is_sequence else 0)
  if len(shape) != prefix + len(expected):
    raise SpecValidationError(
        f"{key!r}: rank mismatch — got shape {shape}, expected "
        f"{prefix} prefix dim(s) + {expected} (spec {spec!r}).")
  if shape[prefix:] != expected:
    raise SpecValidationError(
        f"{key!r}: shape mismatch — got {shape}, expected trailing dims "
        f"{expected} (spec {spec!r}).")
  got_dtype = np.dtype(array.dtype) if array.dtype != jax.numpy.bfloat16 \
      else jax.numpy.bfloat16.dtype
  if spec.is_image:
    # Encoded images arrive as uint8 bytes or already-decoded uint8/float.
    return
  if got_dtype != spec.dtype:
    raise SpecValidationError(
        f"{key!r}: dtype mismatch — got {got_dtype}, expected "
        f"{np.dtype(spec.dtype)}.")


def validate_and_flatten(
    spec_structure: Any,
    tensors: Any,
    ignore_batch: bool = True,
) -> TensorSpecStruct:
  """Validates tensors against specs; returns them flat, spec-ordered.

  Optional specs may be missing from `tensors`; required specs must be
  present and conforming. Extra tensors not covered by any spec are
  dropped (reference semantics: the spec is the contract, the data may be
  a superset).

  Args:
    spec_structure: nested structure of ExtendedTensorSpec.
    tensors: nested structure of arrays with matching keys.
    ignore_batch: if True, arrays have one leading batch dim not present
      in the (logical, unbatched) specs.
  """
  flat_specs = flatten_spec_structure(spec_structure)
  flat_tensors = flatten_spec_structure(tensors)
  spec_dict = flat_specs.to_flat_dict()
  tensor_dict = flat_tensors.to_flat_dict()
  prefix = 1 if ignore_batch else 0

  out: dict = {}
  missing = []
  for key, spec in spec_dict.items():
    if not is_leaf_spec(spec):
      raise SpecValidationError(
          f"Spec leaf {key!r} is not an ExtendedTensorSpec.")
    if key in tensor_dict:
      _check_leaf(key, spec, tensor_dict[key], prefix)
      out[key] = tensor_dict[key]
    elif spec.is_optional:
      continue
    else:
      missing.append(key)
  if missing:
    raise SpecValidationError(
        f"Required specs missing from tensors: {missing}. "
        f"Available keys: {list(tensor_dict)}")
  return TensorSpecStruct.from_flat_dict(out)


def validate_and_pack(
    spec_structure: Any,
    tensors: Any,
    ignore_batch: bool = True,
) -> TensorSpecStruct:
  """Validates and returns tensors packed in the spec structure's layout."""
  flat = validate_and_flatten(spec_structure, tensors, ignore_batch)
  packed = TensorSpecStruct()
  for key, value in flat.to_flat_dict().items():
    packed[key] = value
  return packed


def pack_flat_sequence_to_spec_structure(
    spec_structure: Any,
    flat_sequence: Sequence[Any],
) -> TensorSpecStruct:
  """Packs a flat sequence of leaves against the spec's leaf order."""
  flat_specs = flatten_spec_structure(spec_structure).to_flat_dict()
  if len(flat_specs) != len(flat_sequence):
    raise SpecValidationError(
        f"Leaf count mismatch: {len(flat_specs)} specs vs "
        f"{len(flat_sequence)} tensors.")
  out = TensorSpecStruct()
  for key, value in zip(flat_specs.keys(), flat_sequence):
    out[key] = value
  return out


def replace_dtype(
    spec_structure: Any,
    from_dtype: Any,
    to_dtype: Any,
) -> TensorSpecStruct:
  """Returns a copy of the spec structure with dtypes swapped.

  Used by the TPU-compat preprocessor wrapper to declare uint8 wire specs
  with bfloat16/float32 model-side specs.
  """
  flat = flatten_spec_structure(spec_structure).to_flat_dict()
  from_dtype = np.dtype(from_dtype) if from_dtype != jax.numpy.bfloat16 \
      else jax.numpy.bfloat16.dtype
  out = {}
  for key, spec in flat.items():
    if spec.dtype == from_dtype:
      out[key] = spec.replace(dtype=to_dtype)
    else:
      out[key] = spec
  return TensorSpecStruct.from_flat_dict(out)


def to_shape_dtype_structs(
    spec_structure: Any,
    batch_size: Optional[int] = None,
    sequence_length: Optional[int] = None,
) -> TensorSpecStruct:
  """Maps a spec structure to jax.ShapeDtypeStruct leaves (for eval_shape)."""
  flat = flatten_spec_structure(spec_structure).to_flat_dict()
  return TensorSpecStruct.from_flat_dict({
      k: v.to_shape_dtype_struct(batch_size, sequence_length)
      for k, v in flat.items()
  })


def as_sequence_specs(spec_structure: Any) -> TensorSpecStruct:
  """Lifts every spec in a structure to a per-timestep sequence spec.

  Episode pipelines record a model's (per-step) feature/label specs once
  per timestep on the wire; this helper marks every leaf `is_sequence`
  so SequenceExample codecs and episode generators treat the data as
  [time, ...] feature_lists (reference: tensor2robot `meta_tfdata.py`
  episode batching — file:line unavailable, see SURVEY.md provenance).
  """
  flat = flatten_spec_structure(spec_structure).to_flat_dict()
  return TensorSpecStruct.from_flat_dict(
      {k: v.replace(is_sequence=True) for k, v in flat.items()})


def add_sequence_length(
    spec_structure: Any, sequence_length: int) -> TensorSpecStruct:
  """Materializes sequence specs to fixed-length specs (time-major-after-batch).

  XLA requires static shapes; episode pipelines pad/truncate to a fixed
  `sequence_length` and this helper rewrites `is_sequence` specs to their
  padded concrete shapes.
  """
  flat = flatten_spec_structure(spec_structure).to_flat_dict()
  out = {}
  for key, spec in flat.items():
    if spec.is_sequence:
      out[key] = spec.replace(
          shape=(sequence_length,) + tuple(spec.shape), is_sequence=False)
    else:
      out[key] = spec
  return TensorSpecStruct.from_flat_dict(out)
