"""Declarative tensor-spec system (reference: tensor2robot utils/tensorspec_utils.py)."""

from tensor2robot_tpu.specs.tensorspec import (
    ExtendedTensorSpec,
    TensorSpec,
    TensorSpecStruct,
    PATH_SEP,
)
from tensor2robot_tpu.specs.packing import (
    SpecValidationError,
    add_sequence_length,
    as_sequence_specs,
    assert_valid_spec_structure,
    filter_required_flat_tensor_spec_structure,
    flatten_spec_structure,
    pack_flat_sequence_to_spec_structure,
    replace_dtype,
    to_shape_dtype_structs,
    validate_and_flatten,
    validate_and_pack,
)
from tensor2robot_tpu.specs.serialization import (
    ASSET_FILENAME,
    deserialize_assets,
    read_assets,
    serialize_assets,
    spec_from_dict,
    spec_to_dict,
    struct_from_dict,
    struct_to_dict,
    write_assets,
)
from tensor2robot_tpu.specs.random_data import (
    make_random_tensors,
    random_array_for_spec,
)
