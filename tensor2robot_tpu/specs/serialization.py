"""Spec (de)serialization for serving assets.

Reference parity: tensor2robot shipped a `t2r.proto` (`TensorSpecProto`,
`T2RAssets`) and wrote `assets.extra/t2r_assets.pbtxt` into exported
SavedModels so predictors could rebuild the feature/label specs without
the model class (SURVEY.md §3; file:line unavailable — empty reference
mount). We keep the same capability with a JSON wire format: it round-trips
every ExtendedTensorSpec field, needs no generated code, and is readable
in the export directory. The asset file name is `t2r_assets.json`.
"""

from __future__ import annotations

import json
from typing import Any, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from tensor2robot_tpu.specs.tensorspec import (
    ExtendedTensorSpec,
    TensorSpecStruct,
)
from tensor2robot_tpu.specs import packing

ASSET_FILENAME = "t2r_assets.json"
_FORMAT_VERSION = 1


def spec_to_dict(spec: ExtendedTensorSpec) -> dict:
  dtype_name = ("bfloat16" if spec.dtype == jnp.bfloat16.dtype
                else np.dtype(spec.dtype).name)
  out = {
      "shape": list(spec.shape),
      "dtype": dtype_name,
  }
  if spec.name is not None:
    out["name"] = spec.name
  for field in ("is_optional", "is_sequence", "varlen"):
    if getattr(spec, field):
      out[field] = True
  if spec.data_format is not None:
    out["data_format"] = spec.data_format
  if spec.dataset_key:
    out["dataset_key"] = spec.dataset_key
  return out


def spec_from_dict(data: dict) -> ExtendedTensorSpec:
  return ExtendedTensorSpec(
      shape=tuple(data["shape"]),
      dtype=data["dtype"],
      name=data.get("name"),
      is_optional=data.get("is_optional", False),
      is_sequence=data.get("is_sequence", False),
      data_format=data.get("data_format"),
      dataset_key=data.get("dataset_key", ""),
      varlen=data.get("varlen", False),
  )


def struct_to_dict(spec_structure: Any) -> dict:
  flat = packing.flatten_spec_structure(spec_structure).to_flat_dict()
  return {k: spec_to_dict(v) for k, v in flat.items()}


def struct_from_dict(data: dict) -> TensorSpecStruct:
  return TensorSpecStruct.from_flat_dict(
      {k: spec_from_dict(v) for k, v in data.items()})


def serialize_assets(
    feature_spec: Any,
    label_spec: Optional[Any] = None,
    global_step: Optional[int] = None,
    extra: Optional[dict] = None,
) -> str:
  """Serializes the serving contract to a JSON string."""
  payload = {
      "format_version": _FORMAT_VERSION,
      "feature_spec": struct_to_dict(feature_spec),
  }
  if label_spec is not None:
    payload["label_spec"] = struct_to_dict(label_spec)
  if global_step is not None:
    payload["global_step"] = int(global_step)
  if extra:
    payload["extra"] = extra
  return json.dumps(payload, indent=2, sort_keys=False)


def deserialize_assets(serialized: str) -> dict:
  """Inverse of serialize_assets; spec dicts become TensorSpecStructs."""
  payload = json.loads(serialized)
  version = payload.get("format_version")
  if version != _FORMAT_VERSION:
    raise ValueError(f"Unsupported t2r asset format version: {version}")
  out = {
      "feature_spec": struct_from_dict(payload["feature_spec"]),
  }
  if "label_spec" in payload:
    out["label_spec"] = struct_from_dict(payload["label_spec"])
  if "global_step" in payload:
    out["global_step"] = payload["global_step"]
  if "extra" in payload:
    out["extra"] = payload["extra"]
  return out


def write_assets(path: str, feature_spec: Any, **kwargs) -> None:
  with open(path, "w") as f:
    f.write(serialize_assets(feature_spec, **kwargs))


def read_assets(path: str) -> dict:
  with open(path) as f:
    return deserialize_assets(f.read())
