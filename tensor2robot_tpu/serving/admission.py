"""Per-tenant admission control: token-bucket rate + bounded queues.

A multi-tenant front dies by its worst tenant unless admission is
enforced per tenant at the door: one runaway client (a retry storm, a
misconfigured fleet) must shed ITS OWN load while every other tenant
keeps its SLO. This module is that door, reusing the replay service's
overflow contract (docs/REPLAY.md) verbatim:

  * ``"drop"`` — an over-rate or queue-full request is rejected
    immediately, counted (``serving.<tenant>.admission.dropped``), and
    the caller never blocks;
  * ``"block"`` — the caller waits for capacity (backpressure), with
    ``block_timeout_secs`` capping the wait; on expiry the request is
    dropped and counted, exactly like a replay producer's timed put.

Two gates, both per tenant:

  * TOKEN BUCKET — ``rate_rps`` sustained requests/s with ``burst``
    headroom. Tokens refill continuously; a request needs one token
    per ROW (a batch-8 request spends 8), so row-weighted fairness
    falls out of the same accounting.
  * BOUNDED QUEUE — ``max_queue`` rows may wait in the tenant's front
    queue; beyond that the overflow policy applies. The bound is what
    keeps an admitted-but-slow tenant's latency finite instead of
    letting its queue grow without limit.

SLO accounting keys on the ``serving.<tenant>.bucket_<n>_ms``
dispatch-latency histograms the telemetry registry already publishes
(the engine records them; ISSUE 11/12): `slo_report()` merges a
tenant's per-bucket histograms and interpolates the in-SLO fraction
and p50/p95/p99 from the bucket counts — no new instrumentation on
the hot path.

Locking: the token bucket guards a few floats with its own lock
(arithmetic only — the CON301 contract); every wait (block policy)
happens OUTSIDE any lock, in timed slices that re-check the deadline.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.telemetry import metrics as tmetrics

OVERFLOW_POLICIES = ("drop", "block")

# `retune(rate_rps=None)` means UNLIMITED, so "not provided" needs
# its own sentinel.
_UNSET = object()


class RequestRejected(RuntimeError):
  """An admission gate shed this request (rate, queue bound, or block
  deadline). `tenant` and `reason` ("rate" | "queue_full") say which."""

  def __init__(self, tenant: str, reason: str, message: str):
    super().__init__(message)
    self.tenant = tenant
    self.reason = reason


class TenantPolicy:
  """One tenant's admission envelope. Policy OBJECTS are immutable;
  a live retune (`AdmissionController.retune`, the control plane's
  lever) swaps the whole policy atomically rather than mutating."""

  __slots__ = ("rate_rps", "burst", "max_queue", "overflow",
               "block_timeout_secs", "slo_ms")

  def __init__(self,
               rate_rps: Optional[float] = None,
               burst: int = 32,
               max_queue: int = 256,
               overflow: str = "drop",
               block_timeout_secs: Optional[float] = None,
               slo_ms: float = 100.0):
    """Args:
      rate_rps: sustained admitted rows/s (None = unlimited — the
        queue bound still applies).
      burst: token-bucket depth: rows admitted instantaneously above
        the sustained rate.
      max_queue: rows that may wait in the tenant's front queue.
      overflow: "drop" (reject + count, never block) or "block"
        (backpressure; `block_timeout_secs` caps the wait, expiry =
        counted drop) — the replay service's contract.
      block_timeout_secs: cap on a "block" wait (None = wait forever,
        which is only safe when the dispatcher is known alive).
      slo_ms: the tenant's latency objective; `slo_report()` scores
        the dispatch histograms against it and the bench counts a
        completion under it as GOODPUT.
    """
    if overflow not in OVERFLOW_POLICIES:
      raise ValueError(
          f"overflow must be one of {OVERFLOW_POLICIES}, got {overflow!r}")
    if rate_rps is not None and rate_rps <= 0:
      raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    if burst < 1 or max_queue < 1:
      raise ValueError("burst and max_queue must be >= 1")
    self.rate_rps = None if rate_rps is None else float(rate_rps)
    self.burst = int(burst)
    self.max_queue = int(max_queue)
    self.overflow = overflow
    self.block_timeout_secs = block_timeout_secs
    self.slo_ms = float(slo_ms)


def deadline_slices(block_timeout_secs: Optional[float],
                    stop: Optional[threading.Event] = None,
                    slice_secs: float = 0.05):
  """Yields sleep-slice durations for a "block" overflow wait.

  Ends (StopIteration) when the deadline expires or `stop` is set —
  the caller then counts its drop. THE one timed-slice loop both the
  rate gate (`admit`) and the front's queue gate drive, so the
  replay-service overflow contract the two docstrings cite can never
  drift between them. `block_timeout_secs=None` yields forever (wait
  until `stop`).
  """
  deadline = (time.monotonic() + block_timeout_secs
              if block_timeout_secs is not None else None)
  while True:
    if stop is not None and stop.is_set():
      return
    duration = slice_secs
    if deadline is not None:
      remaining = deadline - time.monotonic()
      if remaining <= 0:
        return
      duration = min(duration, remaining)
    yield duration


class _TokenBucket:
  """Continuous-refill token bucket; arithmetic-only under its lock."""

  __slots__ = ("_lock", "_rate", "_burst", "_tokens", "_last")

  def __init__(self, rate_rps: float, burst: int):
    self._lock = threading.Lock()
    self._rate = float(rate_rps)
    self._burst = float(burst)
    self._tokens = float(burst)
    self._last = time.monotonic()

  def try_take(self, n: int) -> bool:
    now = time.monotonic()
    with self._lock:
      self._tokens = min(self._burst,
                         self._tokens + (now - self._last) * self._rate)
      self._last = now
      if self._tokens >= n:
        self._tokens -= n
        return True
      return False

  def seconds_until(self, n: int) -> float:
    """Time until `n` tokens accumulate (0.0 if available now)."""
    now = time.monotonic()
    with self._lock:
      tokens = min(self._burst,
                   self._tokens + (now - self._last) * self._rate)
      if tokens >= n:
        return 0.0
      return (n - tokens) / self._rate

  def refund(self, n: int) -> None:
    """Returns `n` spent tokens (a request shed AFTER the rate gate —
    unserved rows must not charge the tenant's future budget)."""
    with self._lock:
      self._tokens = min(self._burst, self._tokens + n)


@gin.configurable
class AdmissionController:
  """Per-tenant token buckets + drop/block overflow + SLO reports.

  One controller fronts one `ServingFront`; tenants register with a
  `TenantPolicy` (or inherit the gin-configured defaults). The front
  calls `admit()` BEFORE enqueueing and `queue_full()` when the
  tenant's bounded queue rejects the put — admission owns every shed
  counter so the telemetry story lives in one place:

    serving.<tenant>.admission.admitted    (counter, rows)
    serving.<tenant>.admission.dropped     (counter, rows)
    serving.<tenant>.admission.shed_rate   (counter, rows — over-rate)
    serving.<tenant>.admission.shed_queue  (counter, rows — queue full)
  """

  def __init__(self,
               rate_rps: Optional[float] = None,
               burst: int = 32,
               max_queue: int = 256,
               overflow: str = "drop",
               block_timeout_secs: Optional[float] = None,
               slo_ms: float = 100.0):
    """The args are the DEFAULT `TenantPolicy` (gin-bindable —
    serving_multitenant.gin); `register()` may override per tenant."""
    self._default = TenantPolicy(
        rate_rps=rate_rps, burst=burst, max_queue=max_queue,
        overflow=overflow, block_timeout_secs=block_timeout_secs,
        slo_ms=slo_ms)
    self._lock = threading.Lock()
    self._policies: Dict[str, TenantPolicy] = {}
    self._buckets: Dict[str, _TokenBucket] = {}
    self._tm: Dict[str, tmetrics.Counter] = {}

  @property
  def default_policy(self) -> TenantPolicy:
    return self._default

  def register(self, tenant: str,
               policy: Optional[TenantPolicy] = None) -> TenantPolicy:
    """Installs (or returns the existing) policy for `tenant`."""
    with self._lock:
      existing = self._policies.get(tenant)
      if existing is not None:
        return existing
      policy = policy or self._default
      self._policies[tenant] = policy
      if policy.rate_rps is not None:
        self._buckets[tenant] = _TokenBucket(policy.rate_rps,
                                             policy.burst)
      return policy

  def policy(self, tenant: str) -> TenantPolicy:
    with self._lock:
      found = self._policies.get(tenant)
    return found if found is not None else self._default

  def _count(self, tenant: str, leaf: str, rows: int) -> None:
    name = f"serving.{tenant}.admission.{leaf}"
    with self._lock:
      handle = self._tm.get(name)
      if handle is None:
        handle = self._tm[name] = tmetrics.counter(name)
    handle.inc(rows)

  # ---- the gates (called by the front's submit path) ----

  def admit(self, tenant: str, rows: int,
            stop: Optional[threading.Event] = None) -> bool:
    """The RATE gate. True = tokens granted (NOT yet counted admitted
    — the caller counts via `count_admitted` only after the request
    clears the queue gate too, so `admitted` and `dropped` partition
    offered load with no overlap).

    "drop": an over-rate request returns False immediately (counted).
    "block": waits in timed slices for tokens, re-checking `stop`
    (the front's closed flag — a shutdown must not strand callers)
    and the policy's block deadline; expiry/shutdown = counted drop.
    Never called under a lock.
    """
    policy = self.policy(tenant)
    bucket = self._bucket(tenant, policy)
    if bucket is None or bucket.try_take(rows):
      return True
    if policy.overflow == "block":
      for slice_secs in deadline_slices(policy.block_timeout_secs,
                                        stop):
        wait = bucket.seconds_until(rows)
        if wait <= 0.0 and bucket.try_take(rows):
          return True
        time.sleep(min(slice_secs, max(wait, 0.001)))
    self._count(tenant, "dropped", rows)
    self._count(tenant, "shed_rate", rows)
    return False

  def count_admitted(self, tenant: str, rows: int) -> None:
    """Counts rows that cleared BOTH gates (rate + queue). The front
    calls this after a successful enqueue."""
    self._count(tenant, "admitted", rows)

  def queue_full(self, tenant: str, rows: int) -> None:
    """The QUEUE gate's shed accounting (the front detected the full
    queue — bounded puts live with the queue, counters live here).
    Refunds the rate tokens the request already spent: a shed request
    served nothing, so it must not charge the tenant's budget."""
    policy = self.policy(tenant)
    bucket = self._bucket(tenant, policy)
    if bucket is not None:
      bucket.refund(rows)
    self._count(tenant, "dropped", rows)
    self._count(tenant, "shed_queue", rows)

  def retune(self, tenant: str,
             rate_rps: object = _UNSET,
             factor: Optional[float] = None,
             burst: Optional[int] = None,
             min_rate_rps: float = 1.0,
             max_rate_rps: Optional[float] = None) -> TenantPolicy:
    """Live-retunes a REGISTERED tenant's token rate (ISSUE 18 — the
    control plane's `retune_admission` actuator and the degradation
    ladder both land here).

    Either an absolute ``rate_rps`` (None = unlimited — the restore
    path) or a multiplicative ``factor`` over the current rate; the
    result clamps to ``[min_rate_rps, max_rate_rps]``. A ``factor``
    on an unlimited tenant grants ``max_rate_rps`` (you cannot scale
    infinity down; the cap is the starting point) and is a no-op when
    no cap is given. The policy swap is atomic under the controller
    lock and the bucket is REBUILT at the new rate — a shed tenant's
    hoarded burst tokens must not outlive the retune. Raises
    `KeyError` for an unregistered tenant (retuning a tenant that
    never registered would silently create policy out of thin air).
    """
    with self._lock:
      current = self._policies.get(tenant)
      if current is None:
        raise KeyError(f"unknown tenant {tenant!r}: retune needs a "
                       f"registered policy")
      new_rate = current.rate_rps
      if factor is not None:
        if factor <= 0:
          raise ValueError(f"factor must be positive, got {factor}")
        if new_rate is None:
          new_rate = max_rate_rps  # may stay None: no cap, no-op
        else:
          new_rate = new_rate * factor
      elif rate_rps is not _UNSET:
        new_rate = None if rate_rps is None else float(rate_rps)
      if new_rate is not None:
        new_rate = max(new_rate, float(min_rate_rps))
        if max_rate_rps is not None:
          new_rate = min(new_rate, float(max_rate_rps))
      policy = TenantPolicy(
          rate_rps=new_rate,
          burst=int(burst) if burst is not None else current.burst,
          max_queue=current.max_queue,
          overflow=current.overflow,
          block_timeout_secs=current.block_timeout_secs,
          slo_ms=current.slo_ms)
      self._policies[tenant] = policy
      if policy.rate_rps is None:
        self._buckets.pop(tenant, None)
      else:
        self._buckets[tenant] = _TokenBucket(policy.rate_rps,
                                             policy.burst)
    self._count(tenant, "retunes", 1)
    return policy

  def _bucket(self, tenant: str,
              policy: TenantPolicy) -> Optional[_TokenBucket]:
    if policy.rate_rps is None:
      return None
    with self._lock:
      bucket = self._buckets.get(tenant)
      if bucket is None:
        bucket = self._buckets[tenant] = _TokenBucket(
            policy.rate_rps, policy.burst)
    return bucket

  # ---- SLO accounting over the published histograms ----

  def slo_report(self, snapshot: Optional[Dict] = None) -> Dict[str, Dict]:
    """Per-tenant SLO scorecard from the registry's histograms.

    Two views per tenant, both read off already-published histograms:

      * DISPATCH view (``in_slo_fraction``/``p50..p99_ms``): merges
        the ``serving.<tenant>.bucket_<n>_ms`` engine histograms —
        device-program latency, the "is the MODEL fast enough"
        question, stable under load;
      * END-TO-END view (``e2e_*``): the front's
        ``serving.<tenant>.request_ms`` histogram — submit→result
        including queueing, the latency a CALLER experiences. Past
        saturation these diverge (queue wait dominates while dispatch
        stays flat); alert on the e2e view, diagnose with the
        dispatch view.

    Quantiles interpolate inside the straddling bucket (the registry's
    own read). A tenant with no recorded traffic reports ``count==0``.
    """
    if snapshot is None:
      snapshot = tmetrics.registry().snapshot()
    histograms = snapshot.get("histograms", {})
    with self._lock:
      tenants = list(self._policies)
    report = {}
    for tenant in tenants:
      prefix = f"serving.{tenant}.bucket_"
      merged_bounds = None
      merged_counts = None
      merged_max = None
      total = 0
      for name, hist in histograms.items():
        if not (name.startswith(prefix) and name.endswith("_ms")):
          continue
        bounds = tuple(hist["bounds"])
        if merged_bounds is None:
          merged_bounds = bounds
          merged_counts = [0] * (len(bounds) + 1)
        if bounds != merged_bounds:
          continue  # foreign bounds can't merge; skip rather than lie
        for index, count in enumerate(hist["counts"]):
          merged_counts[index] += count
        total += int(hist["count"])
        if hist.get("max") is not None:
          merged_max = (hist["max"] if merged_max is None
                        else max(merged_max, hist["max"]))
      policy = self.policy(tenant)
      entry = {"slo_ms": policy.slo_ms, "count": total}
      if total:
        entry["in_slo_fraction"] = round(_fraction_at_most(
            merged_bounds, merged_counts, total, policy.slo_ms,
            merged_max), 4)
        for q in (0.5, 0.95, 0.99):
          entry[f"p{int(q * 100)}_ms"] = round(_quantile(
              merged_bounds, merged_counts, total, q, merged_max), 3)
      e2e = histograms.get(f"serving.{tenant}.request_ms")
      if e2e is not None and e2e["count"]:
        e2e_bounds = tuple(e2e["bounds"])
        e2e_total = int(e2e["count"])
        e2e_max = e2e.get("max")
        entry["e2e_count"] = e2e_total
        entry["e2e_in_slo_fraction"] = round(_fraction_at_most(
            e2e_bounds, e2e["counts"], e2e_total, policy.slo_ms,
            e2e_max), 4)
        for q in (0.5, 0.95, 0.99):
          entry[f"e2e_p{int(q * 100)}_ms"] = round(_quantile(
              e2e_bounds, e2e["counts"], e2e_total, q, e2e_max), 3)
      report[tenant] = entry
    return report


def _fraction_at_most(bounds, counts, total, value,
                      observed_max=None) -> float:
  """Fraction of observations ≤ `value`, interpolated in its bucket.

  The OVERFLOW bucket (observations above the last bound) only counts
  as ≤ `value` when the observed max proves it — an SLO above the
  histogram's top bound must not silently bless multi-minute stalls
  as in-SLO (the pessimistic default when no max is known)."""
  seen = 0.0
  lo = 0.0
  for index, bound in enumerate(bounds):
    if value <= bound:
      width = bound - lo
      frac = (value - lo) / width if width > 0 else 1.0
      return (seen + counts[index] * min(max(frac, 0.0), 1.0)) / total
    seen += counts[index]
    lo = bound
  overflow = counts[len(bounds)]
  if overflow and observed_max is not None and observed_max <= value:
    seen += overflow
  return seen / total


def _quantile(bounds, counts, total, q, observed_max=None) -> float:
  """Bucket-interpolated quantile (the registry Histogram's read,
  reproduced over a MERGED count vector): the overflow bucket reports
  the observed max — clamping to the top bound would understate the
  tail exactly when it blows out."""
  rank = q * total
  seen = 0
  for index, count in enumerate(counts):
    if seen + count >= rank:
      if index == len(bounds):
        return float(observed_max if observed_max is not None
                     else bounds[-1])
      lo = bounds[index - 1] if index else 0.0
      up = bounds[index]
      if not count:
        return up
      frac = (rank - seen) / count
      return lo + (up - lo) * min(max(frac, 0.0), 1.0)
    seen += count
  return float(observed_max if observed_max is not None
               else bounds[-1])
