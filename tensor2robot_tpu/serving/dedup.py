"""Observation-dedup cache: identical frames short-circuit to a
cached action.

Fleets of robots produce DUPLICATE observations constantly — a parked
arm streams the same camera frame at 10 Hz, and N robots staring at
the same calibration target submit N bitwise-equal requests. Running
the CEM program again for a frame the tier just answered is pure
waste, so the router hashes a QUANTIZED copy of each observation and
serves repeats straight from a bounded cache.

Correctness contract (pinned by tests/test_serving_router.py):

  * A hit is BITWISE-EQUAL to the uncached path. The cached value is
    the action the real engine produced for that exact (quantized)
    key under the SAME param version; the engine is deterministic for
    identical input + identical params, so replaying its output is
    indistinguishable from recomputing it.
  * A cached action NEVER crosses a param hot-swap. Every entry is
    stamped with the param version it was computed under; `get` only
    returns an entry whose stamp matches the caller's current
    version, and `invalidate(version)` (called on publish) drops
    every stale entry eagerly so the cache never pins dead actions.

Quantization: float leaves are rounded to `quantize_scale` steps
before hashing (default 1/256 — camera frames are uint8 upstream, so
this is lossless for the deployment pixel path while absorbing
float32 jitter from preprocessing). Integer/bool leaves hash as-is.
Quantization affects only the KEY; the action returned is whatever
the engine computed for the first frame in the equivalence class.

The cache is a plain LRU over `capacity` entries with a lock around a
dict — arithmetic-only critical sections (the CON301 contract); the
expensive part (hashing a frame) happens OUTSIDE the lock.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from tensor2robot_tpu.telemetry import metrics as tmetrics


def observation_key(features: Any, quantize_scale: float = 256.0
                    ) -> str:
  """The dedup key: sha256 over every leaf's dtype/shape/quantized
  bytes, leaves visited in sorted-name order.

  `features` is anything with `.to_flat_dict()` (TensorSpecStruct) or
  a flat mapping of name → array.
  """
  flat = (features.to_flat_dict()
          if hasattr(features, "to_flat_dict") else dict(features))
  h = hashlib.sha256()
  for name in sorted(flat):
    leaf = np.asarray(flat[name])
    if np.issubdtype(leaf.dtype, np.floating):
      leaf = np.round(leaf * quantize_scale).astype(np.int64)
    h.update(name.encode())
    h.update(str(leaf.dtype).encode())
    h.update(str(leaf.shape).encode())
    h.update(np.ascontiguousarray(leaf).tobytes())
  return h.hexdigest()


class ObservationDedupCache:
  """Bounded, version-stamped LRU of observation-key → action."""

  def __init__(self, capacity: int = 1024,
               quantize_scale: float = 256.0,
               metric_prefix: str = "serving.dedup."):
    if capacity < 1:
      raise ValueError(f"capacity must be >= 1, got {capacity}")
    self.capacity = int(capacity)
    self.quantize_scale = float(quantize_scale)
    self._entries: "OrderedDict[str, Tuple[int, Any]]" = OrderedDict()
    self._lock = threading.Lock()
    self._hits = tmetrics.counter(f"{metric_prefix}hits")
    self._misses = tmetrics.counter(f"{metric_prefix}misses")
    self._evictions = tmetrics.counter(f"{metric_prefix}evictions")
    self._invalidated = tmetrics.counter(
        f"{metric_prefix}invalidated")
    self._size = tmetrics.gauge(f"{metric_prefix}size")
    # Telemetry counters are process-global (shared across every cache
    # with this prefix); stats() must describe THIS instance, so keep
    # local tallies beside them.
    self._n = {"hits": 0, "misses": 0, "evictions": 0,
               "invalidated": 0}

  def key(self, features: Any) -> str:
    return observation_key(features, self.quantize_scale)

  def get(self, key: str, version: int) -> Optional[Any]:
    """The cached action, iff one exists AND its param-version stamp
    matches `version` (else None — a stale entry is a miss)."""
    with self._lock:
      entry = self._entries.get(key)
      if entry is not None and entry[0] == version:
        self._entries.move_to_end(key)
        self._hits.inc()
        self._n["hits"] += 1
        return entry[1]
      self._misses.inc()
      self._n["misses"] += 1
      return None

  def put(self, key: str, version: int, action: Any) -> None:
    with self._lock:
      self._entries[key] = (int(version), action)
      self._entries.move_to_end(key)
      while len(self._entries) > self.capacity:
        self._entries.popitem(last=False)
        self._evictions.inc()
        self._n["evictions"] += 1
      self._size.set(len(self._entries))

  def invalidate(self, current_version: Optional[int] = None) -> int:
    """Drops every entry not stamped `current_version` (all entries
    when None). Called on publish; returns the drop count."""
    with self._lock:
      if current_version is None:
        dropped = len(self._entries)
        self._entries.clear()
      else:
        stale = [k for k, (v, _) in self._entries.items()
                 if v != current_version]
        for k in stale:
          del self._entries[k]
        dropped = len(stale)
      self._invalidated.inc(dropped)
      self._n["invalidated"] += dropped
      self._size.set(len(self._entries))
      return dropped

  def stats(self) -> Dict[str, int]:
    with self._lock:
      out = dict(self._n)
      out["size"] = len(self._entries)
      return out
