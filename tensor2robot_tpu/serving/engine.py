"""Bucketed AOT serving engine: pre-compiled programs, pinned params.

One `BucketedServingEngine` owns everything shape-dependent about the
hot path:

  * a per-bucket COMPILE CACHE of ahead-of-time compiled executables
    (`jax.jit(...).lower(...).compile()` at warmup) — the hot path
    calls finished executables, so it can never trace or recompile;
  * ONE device-resident state (params + batch stats) pytree shared by
    every bucket's program — buckets multiply compiled code, never
    parameter memory;
  * lock-free hot-swap: `swap_state` transfers the new tree, blocks
    until every buffer is materialized on device, then publishes it
    with a single reference assignment (atomic under the GIL). Calls
    in flight keep the tree they already read — a dispatch observes
    entirely-old or entirely-new params, never a mix;
  * donated request buffers: the padded features are donated into the
    program (`donate_argnums`), letting XLA alias their device memory
    for outputs instead of allocating per call.

The wrapped `fn(state, features[, rng])` must be pure and jittable with
a leading batch dim on every feature/output leaf (a model's
`predict_step`, or a CEM policy closure).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu.serving import bucketing

# Process-wide count of engine bucket compiles — tests pin "zero
# recompiles after warmup" against it alongside jax.monitoring events.
_COMPILE_COUNT = 0


def compile_count() -> int:
  return _COMPILE_COUNT


class BucketedServingEngine:
  """Serves `fn` over powers-of-two batch buckets, AOT-compiled."""

  def __init__(self,
               fn: Callable,
               state: Any,
               example_features: Any,
               max_batch: int = 8,
               takes_rng: bool = False,
               donate_features: bool = True):
    """Args:
      fn: pure `(state, features)` or `(state, features, rng)` callable.
      state: the params pytree `fn` closes over per call; transferred
        to device here and pinned (swaps must keep shapes/dtypes).
      example_features: a features pytree with ANY leading batch dim —
        only its per-row shapes/dtypes matter (bucket avals are derived
        from it).
      max_batch: largest servable request; the bucket table covers it.
      takes_rng: whether `fn` threads a PRNG key (CEM policies).
      donate_features: donate the padded request buffers into the
        program.
    """
    self._fn = fn
    self._takes_rng = takes_rng
    self._table = bucketing.bucket_table(max_batch)
    self._row_avals = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape[1:],
                                       np.asarray(a).dtype),
        example_features)
    placed = jax.device_put(state)
    jax.block_until_ready(placed)
    self._state = placed
    self._compiled: Dict[int, Any] = {}
    donate = (1,) if donate_features else ()
    self._jitted = jax.jit(fn, donate_argnums=donate)
    self._swap_lock = threading.Lock()
    self.dispatch_count = 0
    self.dispatches_per_bucket: Dict[int, int] = {}
    self.swap_count = 0

  @property
  def bucket_sizes(self):
    return self._table

  @property
  def max_batch(self) -> int:
    return self._table[-1]

  @property
  def compiled_buckets(self):
    return tuple(sorted(self._compiled))

  # ---- compilation ----

  def _feature_avals(self, bucket: int):
    return jax.tree_util.tree_map(
        lambda sd: jax.ShapeDtypeStruct((bucket,) + sd.shape, sd.dtype),
        self._row_avals)

  def _compile_bucket(self, bucket: int) -> None:
    global _COMPILE_COUNT
    import warnings

    args = [self._state, self._feature_avals(bucket)]
    if self._takes_rng:
      args.append(jax.ShapeDtypeStruct((2,), np.uint32))
    with warnings.catch_warnings():
      # Donation is best-effort: when no output matches a donated
      # input's shape/dtype XLA simply doesn't alias, which is fine —
      # the advisory warning would spam every warmup.
      warnings.filterwarnings(
          "ignore", message=".*donated buffers were not usable.*")
      self._compiled[bucket] = self._jitted.lower(*args).compile()
    _COMPILE_COUNT += 1

  def warmup(self) -> float:
    """AOT-compiles every bucket; returns wall seconds spent.

    Run at startup, BEFORE traffic: after it returns, every request
    size ≤ max_batch hits a finished executable and the control loop
    never absorbs a compile stall.
    """
    t0 = time.perf_counter()
    for bucket in self._table:
      if bucket not in self._compiled:
        self._compile_bucket(bucket)
    return time.perf_counter() - t0

  # ---- params hot-swap ----

  def swap_state(self, new_state: Any) -> None:
    """Publishes a fully-materialized new params tree (lock-free reads).

    The swap lock only serializes concurrent SWAPPERS (checkpoint
    poller vs. manual refresh); readers never take it — they grab the
    current reference once per dispatch.
    """
    with self._swap_lock:
      placed = jax.device_put(new_state)
      # Block BEFORE publishing: a dispatch must never race ahead of
      # a half-transferred restore.
      jax.block_until_ready(placed)
      self._state = placed
      self.swap_count += 1

  # ---- the hot path ----

  def predict(self, features: Any,
              rng: Optional[jax.Array] = None) -> Any:
    """One bucketed dispatch; returns host numpy outputs, unpadded."""
    leaves = jax.tree_util.tree_leaves(features)
    n = int(np.asarray(leaves[0]).shape[0])
    bucket = bucketing.bucket_for(n, self._table)
    if bucket not in self._compiled:
      # Cold bucket (warmup skipped): compile once, counted. Never
      # taken after warmup() — the table is fully populated there.
      self._compile_bucket(bucket)
    padded = bucketing.pad_batch(features, bucket)
    state = self._state  # one atomic read: old or new tree, never mixed
    if self._takes_rng:
      outputs = self._compiled[bucket](state, padded, rng)
    else:
      outputs = self._compiled[bucket](state, padded)
    self.dispatch_count += 1
    self.dispatches_per_bucket[bucket] = (
        self.dispatches_per_bucket.get(bucket, 0) + 1)
    outputs = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), outputs)
    return bucketing.unpad_batch(outputs, n)
