"""Bucketed AOT serving engine: pre-compiled programs, pinned params.

One `BucketedServingEngine` owns everything shape-dependent about the
hot path:

  * a per-bucket COMPILE CACHE of ahead-of-time compiled executables
    (`jax.jit(...).lower(...).compile()` at warmup) — the hot path
    calls finished executables, so it can never trace or recompile;
  * ONE device-resident state (params + batch stats) pytree shared by
    every bucket's program — buckets multiply compiled code, never
    parameter memory;
  * lock-free hot-swap: `swap_state` transfers the new tree, blocks
    until every buffer is materialized on device, then publishes it
    with a single reference assignment (atomic under the GIL). Calls
    in flight keep the tree they already read — a dispatch observes
    entirely-old or entirely-new params, never a mix;
  * donated request buffers: the padded features are donated into the
    program (`donate_argnums`), letting XLA alias their device memory
    for outputs instead of allocating per call.

The wrapped `fn(state, features[, rng])` must be pure and jittable with
a leading batch dim on every feature/output leaf (a model's
`predict_step`, or a CEM policy closure).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.serving import bucketing
from tensor2robot_tpu.telemetry import metrics as tmetrics


class _Published(NamedTuple):
  """One atomically-published params generation.

  The hot path reads this tuple with a single reference load, so the
  state, its monotonic version, and the learner step it was published
  at can never be observed mixed across a swap. `version` is the
  counter fleets log per episode; `learner_step` is the
  `param_refresh_lag` stamp (learner step the publisher trained to
  when it pushed this tree; 0 for the construction-time params).
  """

  state: Any
  version: int
  learner_step: int

# Process-wide count of engine bucket compiles — tests pin "zero
# recompiles after warmup" against it alongside jax.monitoring events.
_COMPILE_COUNT = 0


def compile_count() -> int:
  return _COMPILE_COUNT


class BucketedServingEngine:
  """Serves `fn` over powers-of-two batch buckets, AOT-compiled."""

  def __init__(self,
               fn: Callable,
               state: Any,
               example_features: Any,
               max_batch: int = 8,
               takes_rng: bool = False,
               donate_features: bool = True,
               metric_prefix: str = "serving."):
    """Args:
      fn: pure `(state, features)` or `(state, features, rng)` callable.
      state: the params pytree `fn` closes over per call; transferred
        to device here and pinned (swaps must keep shapes/dtypes).
      example_features: a features pytree with ANY leading batch dim —
        only its per-row shapes/dtypes matter (bucket avals are derived
        from it).
      max_batch: largest servable request; the bucket table covers it.
      takes_rng: whether `fn` threads a PRNG key (CEM policies).
      donate_features: donate the padded request buffers into the
        program.
      metric_prefix: namespace for this engine's registry metrics.
        The multi-tenant arena passes ``serving.<tenant>.`` so every
        tenant gets its own ``serving.<tenant>.bucket_<n>_ms``
        histograms (the SLO-accounting seam, docs/SERVING.md) and the
        Prometheus adapter renders the tenant as a label.
    """
    from tensor2robot_tpu.startup import compile_cache
    compile_cache.configure_compilation_cache()
    self._fn = fn
    self._takes_rng = takes_rng
    self._table = bucketing.bucket_table(max_batch)
    self._row_avals = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape[1:],
                                       np.asarray(a).dtype),
        example_features)
    placed = jax.device_put(state)
    jax.block_until_ready(placed)
    self._state = placed
    # Device bytes this engine pins (the arena's budget unit): params
    # only — compiled executables multiply code, never this.
    self._state_bytes = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(placed)
        if isinstance(leaf, jax.Array))
    self._released = False
    # The versioned publication record; `_state` is kept in sync for
    # introspection, but the hot path and the version/learner-step
    # readers all go through this one reference.
    self._published = _Published(placed, version=0, learner_step=0)
    # Buckets are LOWERED from these avals, never from the live state:
    # a concrete-state lower would key the (persistent) compile cache
    # on whatever tree `swap_state` last published, making a bucket
    # compiled after a checkpoint restore hash differently from the
    # same bucket compiled before it — nondeterministic cache keys
    # across restarts. Swaps keep shapes/dtypes/shardings, so the
    # avals stay valid for the engine's lifetime.
    self._state_avals = jax.tree_util.tree_map(
        compile_cache.aval_of, placed)
    self._compiled: Dict[int, Any] = {}
    # Donation is disabled when the persistent cache is live on CPU —
    # see compile_cache.donation_unsafe_with_cache (jaxlib heap bug).
    if compile_cache.donation_unsafe_with_cache():
      donate_features = False
    donate = (1,) if donate_features else ()
    self._jitted = jax.jit(fn, donate_argnums=donate)
    self._swap_lock = threading.Lock()
    # Serializes bucket compilation: an async warmup (compile-ahead
    # overlapped with a checkpoint restore) must never race a cold
    # `predict` into compiling the same bucket twice.
    self._compile_lock = threading.Lock()
    self._warmup_thread: Optional[threading.Thread] = None
    self._warmup_error: Optional[BaseException] = None
    self.warmup_seconds: float = 0.0
    self.dispatch_count = 0
    self.dispatches_per_bucket: Dict[int, int] = {}
    self.swap_count = 0
    # Telemetry handles cached per engine (per-bucket lazily): the
    # hot path calls .observe()/.inc() without a registry lookup.
    self._metric_prefix = metric_prefix
    self._tm_dispatches = tmetrics.counter(f"{metric_prefix}dispatches")
    self._tm_swaps = tmetrics.counter(f"{metric_prefix}swaps")
    self._tm_bucket_ms: Dict[int, Any] = {}

  @property
  def bucket_sizes(self):
    return self._table

  @property
  def max_batch(self) -> int:
    return self._table[-1]

  @property
  def compiled_buckets(self):
    return tuple(sorted(self._compiled))

  @property
  def state_bytes(self) -> int:
    """Device bytes the pinned params tree occupies (arena budgeting).

    Constant for the engine's lifetime: swaps keep shapes/dtypes."""
    return self._state_bytes

  @property
  def released(self) -> bool:
    return self._released

  def release(self) -> None:
    """Retires the engine and drops its pinned device buffers
    (arena eviction path).

    Drops the engine's REFERENCES to the params tree and the
    compiled-executable table rather than hard-deleting the buffers:
    a dispatch already in flight on another thread holds its own
    reference to the published state and completes safely on the old
    params — the buffers free the moment the last reference dies
    (refcounting; in-flight dispatches are milliseconds, so the
    memory deadline is effectively the release). New `predict` calls
    fail fast with a clear error. A reload builds a FRESH engine;
    with the persistent compile cache configured its bucket compiles
    deserialize instead of recompiling (`cache_misses == 0`, the
    arena's reload contract). Idempotent.
    """
    # Under BOTH coordination locks (swap first, then compile — the
    # one place they nest, so no ordering cycle): _compile_bucket
    # checks the released flag under the compile lock (no cold-compile
    # resurrection into the cleared table, no lowering against None
    # avals), and swap_state re-checks it under the swap lock (a swap
    # losing the race to an eviction must not re-pin params into the
    # retired engine). Dict clears and reference drops only — nothing
    # blocking runs under either lock here.
    with self._swap_lock:
      with self._compile_lock:
        if self._released:
          return
        self._released = True
        self._compiled.clear()
        self._published = _Published(None, version=-1, learner_step=-1)
        self._state = None
        self._state_avals = None

  # ---- compilation ----

  def _feature_avals(self, bucket: int):
    return jax.tree_util.tree_map(
        lambda sd: jax.ShapeDtypeStruct((bucket,) + sd.shape, sd.dtype),
        self._row_avals)

  def _compile_bucket(self, bucket: int):
    """Compiles (or finds) the bucket's executable and RETURNS it —
    callers must dispatch the returned handle, not re-read the table:
    a release() racing in clears the table, and the local handle is
    what keeps the dispatch safe."""
    global _COMPILE_COUNT
    import warnings

    with self._compile_lock:
      if self._released:
        # A dispatch racing a release must not resurrect the engine by
        # cold-compiling into the cleared table.
        raise RuntimeError(
            "BucketedServingEngine was released (arena eviction); "
            "reload the tenant through the arena instead.")
      if bucket in self._compiled:
        return self._compiled[bucket]  # benign race to the warmup thread
      args = [self._state_avals, self._feature_avals(bucket)]
      if self._takes_rng:
        args.append(jax.ShapeDtypeStruct((2,), np.uint32))
      with warnings.catch_warnings():
        # Donation is best-effort: when no output matches a donated
        # input's shape/dtype XLA simply doesn't alias, which is fine —
        # the advisory warning would spam every warmup.
        warnings.filterwarnings(
            "ignore", message=".*donated buffers were not usable.*")
        # Compiling under the lock is the POINT of this lock: it
        # serializes an async warmup against a cold predict so the
        # same bucket never compiles twice; only compilers contend.
        # t2rcheck: disable=CON301
        executable = self._jitted.lower(*args).compile()
        self._compiled[bucket] = executable
      _COMPILE_COUNT += 1
      return executable

  def warmup(self) -> float:
    """AOT-compiles every bucket; returns wall seconds spent.

    Run at startup, BEFORE traffic: after it returns, every request
    size ≤ max_batch hits a finished executable and the control loop
    never absorbs a compile stall.
    """
    t0 = time.perf_counter()
    for bucket in self._table:
      if bucket not in self._compiled:
        self._compile_bucket(bucket)
    self.warmup_seconds = time.perf_counter() - t0
    return self.warmup_seconds

  def warmup_async(self) -> threading.Thread:
    """Starts `warmup()` on a background thread (compile-ahead).

    The cold-start overlap: callers kick this off, run their own
    startup work (typically the checkpoint restore), then
    `wait_warmup()`. Requests arriving mid-warmup are safe — the
    compile lock serializes them with the warmup thread, and an
    already-compiled bucket dispatches without waiting for the rest
    of the table. Idempotent: a second call returns the live thread.
    """
    if self._warmup_thread is None:
      def _run():
        try:
          self.warmup()
        except BaseException as e:  # surfaced by wait_warmup()
          self._warmup_error = e

      self._warmup_thread = threading.Thread(
          target=_run, name="engine-warmup", daemon=True)
      self._warmup_thread.start()
    return self._warmup_thread

  def wait_warmup(self) -> float:
    """Joins an async warmup; returns its wall seconds.

    Re-raises whatever the warmup thread raised — on EVERY join, not
    just the first: a failed warmup means uncompiled buckets, and a
    later caller (a retried restore(), a warmup_seconds read) must
    not be told the hot path is ready when it is not. No-op (0.0) if
    `warmup_async` was never called.
    """
    if self._warmup_thread is None:
      return 0.0
    self._warmup_thread.join()
    if self._warmup_error is not None:
      raise self._warmup_error
    return self.warmup_seconds

  # ---- params hot-swap ----

  @property
  def publication(self) -> _Published:
    """The current (state, version, learner_step) publication as ONE
    atomic read — callers that need version AND learner_step paired
    (the fleet's per-episode lag stamp) must use this, not the two
    scalar properties back to back (a swap between the reads would
    tear the pair)."""
    return self._published

  @property
  def params_version(self) -> int:
    """Monotonic publication counter: 0 = construction-time params,
    +1 per successful `swap_state`. The per-episode policy-version
    stamp actor fleets log (the `param_refresh_lag` measurement seam)."""
    return self._published.version

  @property
  def params_learner_step(self) -> int:
    """Learner step stamped on the currently-published params."""
    return self._published.learner_step

  def swap_state(self, new_state: Any,
                 learner_step: Optional[int] = None) -> None:
    """Publishes a fully-materialized new params tree (lock-free reads).

    The swap lock only serializes concurrent SWAPPERS (checkpoint
    poller vs. manual refresh); readers never take it — they grab the
    current reference once per dispatch. Each swap bumps the monotonic
    `params_version`; `learner_step` stamps the publication with the
    publisher's training progress (kept from the previous publication
    when omitted, so non-learner swappers don't reset the lag clock).
    """
    if self._released:
      raise RuntimeError(
          "BucketedServingEngine was released (arena eviction); "
          "swap through the arena, which reloads evicted tenants "
          "from their loader instead.")
    with self._swap_lock:
      # Re-check under the lock release() also takes: a swap that
      # lost the race to an eviction must not re-pin a fresh params
      # tree into the retired engine (a transient over-budget window
      # on a tight arena) — it fails here and the arena reports the
      # publication as not-landed.
      if self._released:
        raise RuntimeError(
            "BucketedServingEngine was released (arena eviction); "
            "swap through the arena, which reloads evicted tenants "
            "from their loader instead.")
      # Holding the lock across the transfer is intentional: only
      # SWAPPERS contend here (the hot path reads the published tuple
      # lock-free), and overlapping transfers of two checkpoint trees
      # would waste device memory for no ordering benefit.
      # t2rcheck: disable=CON301
      placed = jax.device_put(new_state)
      # Block BEFORE publishing: a dispatch must never race ahead of
      # a half-transferred restore.
      # t2rcheck: disable=CON301
      jax.block_until_ready(placed)
      previous = self._published
      self._published = _Published(
          placed,
          version=previous.version + 1,
          learner_step=(previous.learner_step if learner_step is None
                        else int(learner_step)))
      self._state = placed
      self.swap_count += 1
    telemetry.event("serving.swap_state",
                    version=self._published.version,
                    learner_step=self._published.learner_step)
    self._tm_swaps.inc()

  # ---- the hot path ----

  def predict(self, features: Any,
              rng: Optional[jax.Array] = None) -> Any:
    """One bucketed dispatch; returns host numpy outputs, unpadded."""
    if self._released:
      raise RuntimeError(
          "BucketedServingEngine was released (arena eviction); "
          "reload the tenant through the arena instead.")
    leaves = jax.tree_util.tree_leaves(features)
    n = int(np.asarray(leaves[0]).shape[0])
    bucket = bucketing.bucket_for(n, self._table)
    executable = self._compiled.get(bucket)
    if executable is None:
      # Cold bucket (warmup skipped): compile once, counted. Never
      # taken after warmup() — the table is fully populated there.
      executable = self._compile_bucket(bucket)
    padded = bucketing.pad_batch(features, bucket)
    # LOCAL references to both the executable (above) and the state
    # (one atomic publication read — old or new, never mixed): a
    # release racing in can clear the table and publish the None
    # sentinel, but this dispatch completes safely on what it already
    # holds; only a state read AFTER the release fails, clearly.
    state = self._published.state
    if state is None:
      raise RuntimeError(
          "BucketedServingEngine was released (arena eviction); "
          "reload the tenant through the arena instead.")
    t0 = time.perf_counter()
    with telemetry.span("serving.dispatch", bucket=bucket, rows=n):
      if self._takes_rng:
        outputs = executable(state, padded, rng)
      else:
        outputs = executable(state, padded)
      outputs = jax.tree_util.tree_map(
          lambda a: np.asarray(jax.device_get(a)), outputs)
    # Registry publication: per-bucket latency (the serving p50/p95
    # the telemetry RPC serves) next to the existing counters.
    hist = self._tm_bucket_ms.get(bucket)
    if hist is None:
      hist = self._tm_bucket_ms[bucket] = tmetrics.histogram(
          f"{self._metric_prefix}bucket_{bucket}_ms")
    hist.observe((time.perf_counter() - t0) * 1e3)
    self.dispatch_count += 1
    self.dispatches_per_bucket[bucket] = (
        self.dispatches_per_bucket.get(bucket, 0) + 1)
    self._tm_dispatches.inc()
    return bucketing.unpad_batch(outputs, n)
