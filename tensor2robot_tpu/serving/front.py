"""ServingFront: continuous batching across tenants, one dispatcher.

The single-model `MicroBatcher` parks a dispatcher thread per model;
with N tenants that is N threads each waiting its own deadline while
the device idles between their dispatches. The front replaces that
with ONE continuous-batching loop over every tenant's queue:

        tenant queues (bounded, admission-gated)
  a ──► [r r r]   ╲
  b ──► [r]        ──► round-robin pick ──► coalesce ≤ max_batch rows
  c ──► [r r]     ╱         │                of ONE tenant
                            ▼
                  arena.engine_async(tenant)   ◄─ LRU touch; a COLD
                            │                     tenant's load runs on
                            ▼                     an arena thread while
                  engine.predict(...)             the loop serves others
                            │
                            ▼
                  per-request slices → futures, latency stamped

A cold/evicted tenant never parks the dispatcher (ISSUE 14 satellite):
its load runs on an arena background thread, the round-robin skips the
tenant until the load's done-callback wakes the loop, and its queued
requests then dispatch against the warm engine (or fail with the
loader's error — the next submit retries the load).

Requests of DIFFERENT tenants never co-batch (different programs);
continuous batching means the dispatcher never waits between tenants —
as long as ANY tenant has queued work the device gets back-to-back
dispatches, and each tenant's batch forms naturally from what queued
while the device was busy (`max_wait_us=0`, the default, holds nothing;
a nonzero deadline trades a little latency for fuller batches exactly
like the micro-batcher).

FAIR SHARE is round-robin with a per-turn cap: each turn serves at
most one dispatch (≤ the tenant's `max_batch` rows) before the
pointer advances, so a deep queue cannot starve a shallow one — an
abusive tenant is first clipped by admission (its own drops), then
bounded to its 1/N turn share here.

The submit path is the admission pipeline (serving/admission.py):
token-bucket rate gate → bounded tenant queue with the replay
service's overflow contract ("drop" counted, "block" with deadline).
`submit()` after `close()` fails fast — same contract as the
micro-batcher, pinned by tests.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import telemetry
from tensor2robot_tpu.serving.admission import (
    AdmissionController,
    RequestRejected,
    TenantPolicy,
    deadline_slices,
)
from tensor2robot_tpu.serving.arena import ModelArena
from tensor2robot_tpu.serving import coalesce
from tensor2robot_tpu.telemetry import metrics as tmetrics


class _Request:

  __slots__ = ("features", "n", "future", "t_submit")

  def __init__(self, features: Any, n: int):
    self.features = features
    self.n = n
    self.future: Future = Future()
    self.t_submit = time.perf_counter()


class _Tenant:
  """Per-tenant front state: bounded queue + carry + metric handles."""

  __slots__ = ("tenant", "queue", "carry", "loading", "rng",
               "tm_request_ms", "tm_completions", "tm_slo_ok",
               "tm_queue_depth", "tm_goodput", "goodput_rows",
               "goodput_t0")

  def __init__(self, tenant: str, max_queue: int, seed: int,
               takes_rng: bool):
    self.tenant = tenant
    self.queue: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
    self.carry: Optional[_Request] = None
    # The tenant's arena load in flight (dispatcher-observed): while
    # set and unresolved, the round-robin SKIPS this tenant — its
    # requests wait in the queue, every other tenant keeps dispatching
    # (cold loads never block the loop; ISSUE 14 satellite).
    self.loading: Optional[Future] = None
    self.rng = jax.random.PRNGKey(seed) if takes_rng else None
    self.tm_request_ms = tmetrics.histogram(
        f"serving.{tenant}.request_ms")
    self.tm_completions = tmetrics.counter(
        f"serving.{tenant}.completions")
    self.tm_slo_ok = tmetrics.counter(f"serving.{tenant}.slo_ok")
    self.tm_queue_depth = tmetrics.gauge(
        f"serving.{tenant}.queue_depth")
    # Live goodput (ISSUE 15): in-SLO completed ROWS per second over a
    # rolling window, derived from the same completion accounting the
    # slo_ok counter rides — renders with a tenant= label like every
    # serving.<tenant>.* name.
    self.tm_goodput = tmetrics.gauge(
        f"serving.{tenant}.goodput_rows_per_sec")
    self.goodput_rows = 0.0
    self.goodput_t0 = time.perf_counter()

  def pending(self) -> bool:
    return self.carry is not None or not self.queue.empty()


@gin.configurable
class ServingFront:
  """Multi-tenant serving entry: admission → queues → one dispatcher."""

  def __init__(self,
               arena: ModelArena,
               admission: Optional[AdmissionController] = None,
               max_wait_us: int = 0,
               seed: int = 0):
    """Args:
      arena: the pinned-param pool (tenants register through the
        front so arena, admission, and queues stay in step).
      admission: the per-tenant gate; None constructs one with
        defaults (gin-configured `AdmissionController`).
      max_wait_us: batch-forming hold per dispatch, like the
        micro-batcher's. 0 (default) = pure continuous batching —
        dispatch whatever is queued, never hold the device.
      seed: base PRNG seed for rng-taking tenants (CEM policies);
        per-tenant keys fold per dispatch.
    """
    self._arena = arena
    self._admission = admission or AdmissionController()
    self._max_wait = max_wait_us / 1e6
    self._seed = int(seed)
    self._tenants: Dict[str, _Tenant] = {}
    self._order: List[str] = []
    self._rr = 0
    self._dispatch_index = 0
    self._stop = threading.Event()
    # Serializes submit()'s closed-check+enqueue against close(), the
    # micro-batcher's fail-fast contract: a request must never land on
    # a queue after the dispatcher decided to exit.
    self._submit_lock = threading.Lock()
    # Wakeup FLAG, not a token per request: a maxsize-1 queue set by
    # every submit (put_nowait, Full ignored) and consumed only when
    # the dispatcher goes idle. One token per request would never be
    # drained under sustained load (rounds keep finding work) and
    # grow without bound — the eventfd-style coalesced flag carries
    # the same no-lost-wakeup guarantee: a submit enqueues its request
    # BEFORE setting the flag, so after the dispatcher consumes a flag
    # its next scan sees the request, or a newer flag is already set.
    self._work: "queue.Queue[bool]" = queue.Queue(maxsize=1)
    self.dispatches = 0
    self.requests = 0
    self.dispatches_per_tenant: Dict[str, int] = {}
    # Front-wide live goodput window (in-SLO rows/s across tenants);
    # per-tenant windows live on each _Tenant entry. Dispatcher-thread
    # state only — no lock.
    self._goodput_rows = 0.0
    self._goodput_t0 = time.perf_counter()
    self._thread = threading.Thread(
        target=self._run, name="serving-front", daemon=True)
    self._thread.start()

  @property
  def arena(self) -> ModelArena:
    return self._arena

  @property
  def admission(self) -> AdmissionController:
    return self._admission

  # ---- registration ----

  def register_tenant(self,
                      tenant: str,
                      loader,
                      policy: Optional[TenantPolicy] = None,
                      max_batch: int = 8,
                      takes_rng: bool = False,
                      warmup: bool = True,
                      preload: bool = False) -> None:
    """One call wires a tenant end to end: arena residency spec,
    admission policy, and the front queue. `preload=True` loads (and
    AOT-warms) the engine now instead of on first request."""
    # Validate the policy the tenant will actually get — the explicit
    # one OR the controller's (gin-configured) default: a bucket of
    # depth `burst` can NEVER grant `max_batch` tokens, so every
    # full-size request would shed at any load ("drop") or spin to its
    # deadline ("block"). Loud at registration, not a 100%-shed
    # mystery in production. Checked BEFORE any registration so a
    # rejection leaves no half-registered tenant behind.
    effective = (policy if policy is not None
                 else self._admission.policy(tenant))
    if (effective.rate_rps is not None
        and effective.burst < max_batch):
      raise ValueError(
          f"tenant {tenant!r}: burst={effective.burst} < "
          f"max_batch={max_batch} — a max-size request could never be "
          "admitted; raise burst to at least max_batch.")
    self._arena.register(tenant, loader, max_batch=max_batch,
                         takes_rng=takes_rng, warmup=warmup)
    policy = self._admission.register(tenant, policy)
    entry = _Tenant(tenant, policy.max_queue,
                    seed=self._seed + len(self._order),
                    takes_rng=takes_rng)
    with self._submit_lock:
      self._tenants[tenant] = entry
      self._order.append(tenant)
    if preload:
      self._arena.engine(tenant)

  # ---- caller side ----

  def submit(self, tenant: str, features: Any) -> Future:
    """Admission-gated enqueue; returns the request's Future.

    Raises `RequestRejected` when the tenant's token bucket or queue
    bound sheds it (policy "drop", or "block" past its deadline), and
    `RuntimeError` after `close()` — fail fast, never enqueue into a
    dead dispatcher.
    """
    entry = self._tenants.get(tenant)
    if entry is None:
      raise KeyError(f"tenant {tenant!r} is not registered")
    leaves = jax.tree_util.tree_leaves(features)
    n = int(np.asarray(leaves[0]).shape[0])
    max_batch = self._arena.spec(tenant).max_batch
    if n > max_batch:
      raise ValueError(
          f"request of {n} rows exceeds tenant {tenant!r} max_batch "
          f"{max_batch}; split it or raise max_batch.")
    if self._stop.is_set():
      raise RuntimeError(
          "ServingFront is closed; submit() after close() would "
          "enqueue into a dead dispatcher.")
    if not self._admission.admit(tenant, n, stop=self._stop):
      raise RequestRejected(
          tenant, "rate",
          f"tenant {tenant!r}: over admitted rate "
          f"(rate_rps={self._admission.policy(tenant).rate_rps}); "
          "request shed")
    request = _Request(features, n)
    policy = self._admission.policy(tenant)
    if self._try_enqueue(tenant, entry, request):
      return request.future
    # Queue full. "drop": count + reject. "block": backpressure in
    # timed SLEEP slices, each retrying `_try_enqueue` — every attempt
    # re-checks the closed flag under the submit lock, so a close()
    # can never be outrun by a late enqueue onto a freed slot
    # (sleeping happens outside the lock, the replay producers'
    # timed-put shape). Either shed path refunds the rate tokens the
    # request spent — unserved rows must not charge the tenant's
    # future budget. The request keeps its original submit stamp:
    # time spent blocked here is real latency the SLO accounting
    # must see.
    if policy.overflow == "drop":
      self._admission.queue_full(tenant, n)
      raise RequestRejected(
          tenant, "queue_full",
          f"tenant {tenant!r}: queue full "
          f"(max_queue={policy.max_queue}); request shed")
    for slice_secs in deadline_slices(policy.block_timeout_secs):
      # No stop event here: _try_enqueue re-checks the closed flag
      # under the submit lock every slice and raises the fail-fast
      # error itself — a close() mid-wait is noticed within a slice.
      time.sleep(slice_secs)
      if self._try_enqueue(tenant, entry, request):
        return request.future
    self._admission.queue_full(tenant, n)
    raise RequestRejected(
        tenant, "queue_full",
        f"tenant {tenant!r}: queue full past "
        f"block_timeout_secs={policy.block_timeout_secs}; "
        "request shed")

  def _try_enqueue(self, tenant: str, entry: _Tenant,
                   request: _Request) -> bool:
    """ONE enqueue attempt; the fail-fast contract lives here, once.

    Closed-check + bounded put + request accounting all happen under
    the submit lock (close() sets the stop flag under the same lock,
    so a request can never land on a queue after close() decided to
    drain); returns False on a full queue. A successful enqueue is
    what `admitted` MEANS: the request cleared both gates, so the
    admitted/dropped counters partition offered load with no overlap —
    including on the closed path: every caller sits past the rate gate
    (tokens charged), so a close() racing the enqueue refunds and
    counts the shed before failing fast.
    """
    closed = False
    with self._submit_lock:
      if self._stop.is_set():
        closed = True
      else:
        try:
          entry.queue.put_nowait(request)
        except queue.Full:
          return False
        self.requests += 1
    if closed:
      # Outside the submit lock: queue_full takes the admission locks.
      self._admission.queue_full(tenant, request.n)
      raise RuntimeError(
          "ServingFront is closed; submit() after close() would "
          "enqueue into a dead dispatcher.")
    self._wake()
    self._admission.count_admitted(tenant, request.n)
    return True

  def _wake(self, _done_future: Any = None) -> None:
    """Sets the coalesced wakeup flag (submit path AND arena-load
    done-callbacks — the signature tolerates the Future argument)."""
    try:
      self._work.put_nowait(True)
    except queue.Full:
      pass  # a wakeup is already pending — the scan will see us

  def predict(self, tenant: str, features: Any) -> Any:
    """Blocking predict — submit + wait (a control loop's tick)."""
    return self.submit(tenant, features).result()

  # ---- dispatcher thread ----

  @staticmethod
  def _load_in_flight(entry: _Tenant) -> bool:
    return entry.loading is not None and not entry.loading.done()

  def _next_tenant(self) -> Optional[_Tenant]:
    """Round-robin over tenants with pending work (fair share).
    Tenants whose arena load is still in flight are skipped — their
    turn comes when the load's done-callback wakes the dispatcher."""
    with self._submit_lock:
      order = list(self._order)
      start = self._rr
    count = len(order)
    for offset in range(count):
      tenant_id = order[(start + offset) % count]
      entry = self._tenants[tenant_id]
      if entry.pending() and not self._load_in_flight(entry):
        with self._submit_lock:
          self._rr = (start + offset + 1) % count
        return entry
    return None

  def _run(self) -> None:
    while True:
      served = self._serve_round()
      if served:
        continue
      if self._stop.is_set():
        # Drained: every queue and carry is empty.
        if all(not t.pending() for t in self._tenants.values()):
          return
        # Pending work behind an in-flight load: park on the wakeup
        # flag (the load's done-callback sets it) instead of spinning
        # the drain scan hot.
        if any(self._load_in_flight(t) for t in self._tenants.values()):
          try:
            self._work.get(timeout=0.05)
          except queue.Empty:
            pass
        continue
      try:
        # Idle: park on the wakeup flag. A stale flag costs one empty
        # scan — never a lost request, never a busy spin. The idle
        # tick also rolls the goodput windows so gauges decay honestly
        # through quiet stretches.
        self._work.get(timeout=0.05)
      except queue.Empty:
        self._roll_goodput_windows()
        continue

  def _serve_round(self) -> bool:
    entry = self._next_tenant()
    if entry is None:
      return False
    # A load that just resolved: surface its outcome before dispatch.
    load, entry.loading = entry.loading, None
    if load is not None and load.exception() is not None:
      # The load failed — its queued requests get the loader's error
      # (claim-first, so a cancelled future can't poison delivery);
      # the NEXT submit triggers a fresh load attempt.
      max_batch = self._arena.spec(entry.tenant).max_batch
      batch, entry.carry = coalesce.take_batch(
          entry.queue, entry.carry, max_batch, 0.0)
      failed = coalesce.claim_batch(batch)
      if failed:
        coalesce.fail_batch(failed, load.exception())
      return bool(batch)
    # Async arena touch (LRU bump; load-on-miss runs on an arena
    # thread): a cold tenant never parks this dispatcher — mark it
    # loading, wake on completion, serve everyone else meanwhile.
    engine, pending = self._arena.engine_async(entry.tenant)
    if pending is not None:
      entry.loading = pending
      pending.add_done_callback(self._wake)
      return True  # turn consumed; the tenant waits on its load
    max_batch = self._arena.spec(entry.tenant).max_batch
    batch, entry.carry = coalesce.take_batch(
        entry.queue, entry.carry, max_batch, self._max_wait)
    if not batch:
      return False
    self._dispatch(entry, batch, engine)
    return True  # queue entries were consumed either way

  _GOODPUT_WINDOW_SECS = 1.0

  def _roll_goodput_windows(self, now: Optional[float] = None) -> None:
    """Closes every goodput window that has run ≥1 s — per tenant and
    front-wide — publishing rows/window (0 when nothing completed).
    Called after each completion batch AND from the dispatcher's idle
    tick, so windows keep rolling through quiet stretches: an idle
    tenant's gauge decays to 0 within ~a window instead of freezing at
    its last burst, and a burst after a long gap is denominated over
    ~one window, not the whole gap. Dispatcher-thread only."""
    if now is None:
      now = time.perf_counter()
    for entry in list(self._tenants.values()):
      window = now - entry.goodput_t0
      if window >= self._GOODPUT_WINDOW_SECS:
        entry.tm_goodput.set(entry.goodput_rows / window)
        entry.goodput_rows = 0.0
        entry.goodput_t0 = now
    window = now - self._goodput_t0
    if window >= self._GOODPUT_WINDOW_SECS:
      tmetrics.gauge("perf.goodput_rows_per_sec").set(
          self._goodput_rows / window)
      self._goodput_rows = 0.0
      self._goodput_t0 = now

  def _dispatch(self, entry: _Tenant, batch: List[_Request],
                engine: Any) -> None:
    # Claim first (shared coalesce contract): requests cancelled while
    # queued drop out here, survivors can't be cancelled — delivery
    # can never hit a poisoned future.
    batch = coalesce.claim_batch(batch)
    if not batch:
      return
    try:
      rows = sum(r.n for r in batch)
      entry.tm_queue_depth.set(entry.queue.qsize())
      features = coalesce.concat_features(batch)
      with telemetry.span("serving.front_dispatch",
                          tenant=entry.tenant,
                          requests=len(batch), rows=rows):
        if entry.rng is not None:
          key = jax.random.fold_in(entry.rng, self._dispatch_index)
          outputs = engine.predict(features, rng=key)
        else:
          outputs = engine.predict(features)
      self._dispatch_index += 1
      self.dispatches += 1
      self.dispatches_per_tenant[entry.tenant] = (
          self.dispatches_per_tenant.get(entry.tenant, 0) + 1)
      slo_ms = self._admission.policy(entry.tenant).slo_ms
      done = time.perf_counter()
      for request in batch:
        latency_ms = (done - request.t_submit) * 1e3
        entry.tm_request_ms.observe(latency_ms)
        entry.tm_completions.inc()
        if latency_ms <= slo_ms:
          entry.tm_slo_ok.inc()
          entry.goodput_rows += request.n
          self._goodput_rows += request.n
      self._roll_goodput_windows(done)
      coalesce.deliver(batch, outputs)
    except Exception as exc:  # noqa: BLE001 — deliver to every caller
      coalesce.fail_batch(batch, exc)

  # ---- lifecycle ----

  def close(self, timeout: float = 30.0) -> None:
    """Drains queued requests, then stops the dispatcher thread."""
    with self._submit_lock:
      self._stop.set()
    self._thread.join(timeout=timeout)
    for entry in self._tenants.values():
      stranded = [entry.carry] if entry.carry is not None else []
      entry.carry = None
      while True:
        try:
          stranded.append(entry.queue.get_nowait())
        except queue.Empty:
          break
      for request in stranded:
        if not request.future.done():
          request.future.set_exception(
              RuntimeError("ServingFront closed before dispatch."))

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False
