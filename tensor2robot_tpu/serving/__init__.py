"""Low-latency serving: bucketed AOT compilation + dynamic micro-batching.

The on-robot deployment metric is per-call `predict()` latency — the
control loop blocks on it every action (SURVEY.md §4.4) — and the
north-star deployment serves many concurrent control loops from one
chip. This package turns the predictors' one-request-per-dispatch path
into a serving engine:

  * `bucketing` — powers-of-two batch buckets and batch-dim padding,
    so every request shape maps onto a finite, pre-compilable set of
    device programs.
  * `engine.BucketedServingEngine` — per-bucket AOT-compiled programs
    (zero retraces/recompiles on the hot path after `warmup()`),
    donated input buffers, and a pinned device-resident params tree
    shared across buckets with lock-free hot-swap on refresh.
  * `microbatcher.MicroBatcher` — a thread-safe queue that coalesces
    concurrent `predict()` calls into one device dispatch under a
    max-batch / max-wait-µs deadline (the Podracer batched-inference
    idiom), with graceful single-request fallback.
  * `cem_policy.CEMPolicyServer` — the QT-Opt action-selection entry:
    batched on-device CEM behind the engine + micro-batcher.

The MULTI-TENANT front (docs/SERVING.md "Multi-tenant front") stacks
three more layers over the same engine:

  * `arena.ModelArena` — many models over one device: a budgeted
    pinned-param pool with LRU eviction and compile-cache-warm
    reloads (`cache_misses == 0` on an evicted tenant's reload).
  * `admission.AdmissionController` — per-tenant token-bucket rate +
    bounded queues with the replay service's overflow contract
    ("drop" counted / "block" with deadline), and SLO scorecards read
    off the `serving.<tenant>.bucket_<n>_ms` histograms.
  * `front.ServingFront` — ONE continuous-batching dispatcher over
    every tenant's queue with round-robin fair share, replacing
    per-model micro-batcher loops.

The REPLICATED tier (docs/SERVING.md "Replicated tier", ISSUE 17)
puts that front on the wire behind `fleet.front.front_main` host
processes and adds the caller-side composition layers:

  * `router.ServingRouter` — rendezvous-hash tenant placement over N
    front hosts (the replay plane's HRW rule, shared via
    `replay.sampler`), hot-tenant spread, and data-path failover:
    a replica death sheds its tenants to HRW survivors within one
    client deadline.
  * `speculative.SpeculativeCEM` — serve the 1-iteration CEM elite
    inline while the full program refines in the background; refined
    actions are version-stamped and never cross a param hot-swap.
  * `dedup.ObservationDedupCache` — quantized-observation hash +
    param version → cached action; identical frames from robot
    fleets short-circuit without touching a replica.
"""

from tensor2robot_tpu.serving.bucketing import (
    bucket_for,
    bucket_table,
    pad_batch,
    unpad_batch,
)
from tensor2robot_tpu.serving.engine import BucketedServingEngine
from tensor2robot_tpu.serving.microbatcher import MicroBatcher
from tensor2robot_tpu.serving.cem_policy import CEMPolicyServer
from tensor2robot_tpu.serving.admission import (
    AdmissionController,
    RequestRejected,
    TenantPolicy,
)
from tensor2robot_tpu.serving.arena import ModelArena
from tensor2robot_tpu.serving.front import ServingFront
from tensor2robot_tpu.serving.dedup import (
    ObservationDedupCache,
    observation_key,
)
from tensor2robot_tpu.serving.speculative import SpeculativeCEM
from tensor2robot_tpu.serving.router import (
    NoReplicasError,
    ServingRouter,
)
