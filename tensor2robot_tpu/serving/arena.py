"""ModelArena: many models multiplexed over one device's memory.

The single-model engine pins ONE params tree for its lifetime; the
north-star service multiplexes MANY models/checkpoint versions over
one chip (the same resource-multiplexing argument the Podracer
architectures make for training hardware — one device stays saturated
by many workloads, none owns it). The arena is that multiplexer:

  * a BUDGETED pool of pinned-param `BucketedServingEngine`s, one per
    resident tenant, accounted in device bytes (`engine.state_bytes`);
  * LRU EVICTION when a load would exceed the budget: the
    least-recently-dispatched tenant's engine releases its device
    buffers (params only — compiled code was never the budget);
  * COMPILE-CACHE-WARM RELOADS: engines lower their buckets from
    avals (stable cache keys, ISSUE 2), so with the persistent XLA
    compilation cache configured (`startup/compile_cache.py`) an
    evicted tenant's reload DESERIALIZES every bucket instead of
    recompiling — `cache_misses == 0` on reload is the contract,
    counted per load via `CompileWatch` and pinned by tests and the
    bench's eviction leg.

Loads use a placeholder-future protocol so the structural lock never
covers a blocking operation (the CON301 contract): a miss installs a
Future under the lock, builds the engine OUTSIDE it, then publishes.
Concurrent callers of the SAME tenant wait on the future; callers of
OTHER resident tenants are never blocked by a load in flight.

Eviction vs. dispatch: `release()` RETIRES the engine by dropping its
references (buffers free when the last holder lets go) rather than
hard-deleting device buffers — a dispatch already in flight on another
thread completes safely on the params it holds, and new dispatches on
the retired engine fail with a clear error. Concurrent loads and
evictions from any thread are therefore safe; a request racing an
eviction of its own tenant errors cleanly and the next `engine()`
touch reloads.
"""

from __future__ import annotations

import collections
import logging
import re
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Middle segments of `serving.<x>.*` metric names that are NOT tenants
# (the Prometheus adapter renders everything else as a tenant= label);
# tenant ids must avoid them and stay inside the metric-name charset.
RESERVED_TENANT_IDS = frozenset({"arena", "front", "admission"})
_TENANT_RE = re.compile(r"[A-Za-z0-9_\-]+")

# loader() -> (fn, state, example_features): `fn` the pure jittable
# callable, `state` the HOST params tree (the arena device_puts it via
# the engine), `example_features` the per-row wire example. Reloads
# call it again — a production loader re-reads the newest checkpoint.
TenantLoader = Callable[[], Tuple[Callable, Any, Any]]


class _TenantSpec:

  __slots__ = ("tenant", "loader", "max_batch", "takes_rng", "warmup")

  def __init__(self, tenant: str, loader: TenantLoader, max_batch: int,
               takes_rng: bool, warmup: bool):
    self.tenant = tenant
    self.loader = loader
    self.max_batch = max_batch
    self.takes_rng = takes_rng
    self.warmup = warmup


class _Resident:
  """One tenant's residency record: a future that resolves to the
  engine, plus the byte reservation taken while it loads."""

  __slots__ = ("tenant", "future", "bytes", "async_pickup_pending")

  def __init__(self, tenant: str):
    self.tenant = tenant
    self.future: Future = Future()
    self.bytes = 0
    # True between an engine_async() cold MISS and the dispatcher's
    # first post-load re-touch: that re-touch is the tail of the SAME
    # logical dispatch the miss already counted, not a warm hit.
    self.async_pickup_pending = False

  @property
  def loaded(self) -> bool:
    return self.future.done() and self.future.exception() is None


@gin.configurable
class ModelArena:
  """Budgeted pinned-param pool with LRU eviction + warm reloads."""

  def __init__(self,
               budget_bytes: Optional[int] = None,
               cache_dir: Optional[str] = None):
    """Args:
      budget_bytes: device bytes the pool may pin across all resident
        tenants (None = unlimited — no eviction ever). A single tenant
        larger than the whole budget is a configuration error and
        raises at load.
      cache_dir: persistent XLA compilation-cache directory for warm
        reloads (forwarded to `configure_compilation_cache`; None
        keeps the process's current cache config — gin/env). Without
        a cache configured, reloads RECOMPILE and the arena logs a
        warning once: eviction is then a latency cliff, not a shuffle.
    """
    from tensor2robot_tpu.startup import compile_cache
    self._compile_cache = compile_cache
    compile_cache.configure_compilation_cache(cache_dir=cache_dir)
    if compile_cache.cache_dir() is None:
      log.warning(
          "ModelArena without a persistent compilation cache: evicted "
          "tenants will RECOMPILE on reload (set ModelArena.cache_dir "
          "or %s).", compile_cache.ENV_CACHE_DIR)
    self._budget = None if budget_bytes is None else int(budget_bytes)
    self._specs: Dict[str, _TenantSpec] = {}
    # Structural lock: guards the spec/resident tables and the LRU
    # order. Dict/float ops only — loads, releases, and future waits
    # all happen outside it.
    self._lock = threading.Lock()
    self._resident: "collections.OrderedDict[str, _Resident]" = (
        collections.OrderedDict())
    # Tail of the build ticket chain: engine BUILDS serialize by
    # waiting on their predecessor's future (no lock is ever held
    # across the blocking build), so each load's CompileWatch counts
    # exactly its own compiles — a concurrent cold load must never
    # charge its cache misses to another tenant's warm reload (the
    # reload contract's hard gate depends on exact attribution).
    # Dispatches on resident tenants never enter the chain.
    self._build_tail: Optional[Future] = None
    self._reserved_bytes = 0
    self.loads = 0
    self.reloads = 0
    self.evictions = 0
    self.reload_cache_misses = 0
    self.last_load: Optional[Dict[str, Any]] = None
    self._loaded_once: set = set()
    self._tm_hits = tmetrics.counter("serving.arena.hits")
    self._tm_misses = tmetrics.counter("serving.arena.misses")
    self._tm_loads = tmetrics.counter("serving.arena.loads")
    self._tm_evictions = tmetrics.counter("serving.arena.evictions")
    self._tm_resident = tmetrics.gauge("serving.arena.resident_models")
    self._tm_bytes = tmetrics.gauge("serving.arena.resident_bytes")
    self._tm_load_ms = tmetrics.histogram("serving.arena.load_ms")

  # ---- registration ----

  def register(self,
               tenant: str,
               loader: TenantLoader,
               max_batch: int = 8,
               takes_rng: bool = False,
               warmup: bool = True) -> None:
    """Declares a tenant (no load yet — loads are demand-driven).

    `tenant` becomes a metric namespace (`serving.<tenant>.*`) and a
    Prometheus label value, so it must match ``[A-Za-z0-9_-]+`` and
    avoid the reserved segment names.
    """
    if not _TENANT_RE.fullmatch(tenant):
      raise ValueError(
          f"tenant id {tenant!r} must match {_TENANT_RE.pattern} "
          "(it becomes a metric namespace and Prometheus label)")
    if tenant in RESERVED_TENANT_IDS:
      raise ValueError(
          f"tenant id {tenant!r} is a reserved serving metric "
          f"namespace ({sorted(RESERVED_TENANT_IDS)})")
    spec = _TenantSpec(tenant, loader, int(max_batch), bool(takes_rng),
                       bool(warmup))
    with self._lock:
      if tenant in self._specs:
        raise ValueError(f"tenant {tenant!r} already registered")
      self._specs[tenant] = spec

  def spec(self, tenant: str) -> _TenantSpec:
    with self._lock:
      found = self._specs.get(tenant)
    if found is None:
      raise KeyError(f"tenant {tenant!r} is not registered")
    return found

  @property
  def tenants(self) -> Tuple[str, ...]:
    with self._lock:
      return tuple(self._specs)

  @property
  def budget_bytes(self) -> Optional[int]:
    return self._budget

  def resident_tenants(self) -> Tuple[str, ...]:
    """LRU→MRU order, loads in flight included."""
    with self._lock:
      return tuple(self._resident)

  def resident_bytes(self) -> int:
    with self._lock:
      return self._reserved_bytes

  # ---- the load path ----

  def engine(self, tenant: str):
    """Get-or-load: the tenant's live engine, LRU-touched.

    A hit returns immediately (dict ops only). A miss runs the loader
    and AOT warmup on THIS thread; concurrent callers of the same
    tenant block on the load's future instead of loading twice, and
    other residents keep dispatching throughout.
    """
    spec = self.spec(tenant)
    with self._lock:
      record = self._resident.get(tenant)
      if record is not None:
        self._resident.move_to_end(tenant)
        loading = not record.future.done()
      else:
        record = _Resident(tenant)
        self._resident[tenant] = record
        loading = None  # this thread owns the load
    if loading is None:
      self._tm_misses.inc()
      return self._load(spec, record)
    self._tm_hits.inc()
    # Done: returns immediately. Mid-load on another thread: waiting
    # on its future is the "never load the same tenant twice" seam.
    return record.future.result()

  def engine_async(self, tenant: str):
    """Non-blocking get-or-load: `(engine, None)` on a resident hit
    (LRU-touched, dict ops only), `(None, future)` when the tenant is
    cold or mid-load — a cold touch starts the load on a BACKGROUND
    thread and returns immediately, so a single-threaded caller (the
    ServingFront dispatcher) is never parked behind a loader while
    other tenants have dispatchable work (ISSUE 14 satellite). The
    future resolves to the engine, or to the load's exception."""
    spec = self.spec(tenant)
    with self._lock:
      record = self._resident.get(tenant)
      if record is not None:
        # Same ownership rule as engine(): whoever INSTALLS the record
        # owns its load; everyone else rides the future.
        self._resident.move_to_end(tenant)
        hit = record.future.result() if record.loaded else None
        # The first post-load touch completes the cold dispatch whose
        # miss was already counted — don't double it as a warm hit
        # (the sync engine() path counts that dispatch once).
        count_hit = hit is not None and not record.async_pickup_pending
        if hit is not None:
          record.async_pickup_pending = False
        owner = False
      else:
        record = _Resident(tenant)
        record.async_pickup_pending = True
        self._resident[tenant] = record
        hit = None
        count_hit = False
        owner = True
    if owner:
      self._tm_misses.inc()
      threading.Thread(
          target=self._load_quietly, args=(spec, record),
          name=f"arena-load-{tenant}", daemon=True).start()
      return None, record.future
    if hit is not None:
      if count_hit:
        self._tm_hits.inc()
      return hit, None
    return None, record.future

  def _load_quietly(self, spec: _TenantSpec, record: _Resident) -> None:
    """Background-thread wrapper: failures land on the record future
    (every waiter sees them); nothing to re-raise into."""
    try:
      self._load(spec, record)
    except BaseException:  # noqa: BLE001 — surfaced via the future
      log.exception("async load of tenant %r failed", spec.tenant)

  def _load(self, spec: _TenantSpec, record: _Resident):
    from tensor2robot_tpu.serving.engine import BucketedServingEngine
    tenant = spec.tenant
    t0 = time.perf_counter()
    # Join the build chain: wait for the previous build to finish so
    # the CompileWatch below observes ONLY this build's compiles.
    with self._lock:
      predecessor, self._build_tail = self._build_tail, Future()
      ticket = self._build_tail
    try:
      if predecessor is not None:
        # Predecessor failures are its loader's problem, not ours —
        # the chain only sequences, never propagates.
        predecessor.exception()
      fn, state, example = spec.loader()
      import jax
      host_bytes = sum(
          leaf.nbytes for leaf in jax.tree_util.tree_leaves(state)
          if hasattr(leaf, "nbytes"))
      victims = self._reserve_or_evict(tenant, record, host_bytes)
      for victim in victims:
        victim.release()
      reload_ = tenant in self._loaded_once
      with self._compile_cache.CompileWatch() as watch:
        engine = BucketedServingEngine(
            fn, state, example,
            max_batch=spec.max_batch,
            takes_rng=spec.takes_rng,
            metric_prefix=f"serving.{tenant}.")
        if spec.warmup:
          engine.warmup()
      seconds = time.perf_counter() - t0
      with self._lock:
        # device bytes may differ from the host estimate (padding,
        # dtypes); settle the reservation to the real figure.
        self._reserved_bytes += engine.state_bytes - record.bytes
        record.bytes = engine.state_bytes
        self.loads += 1
        if reload_:
          self.reloads += 1
          self.reload_cache_misses += watch.cache_misses
        self._loaded_once.add(tenant)
        self.last_load = {
            "tenant": tenant,
            "seconds": round(seconds, 4),
            "reload": reload_,
            "cache_misses": watch.cache_misses,
            "cache_hits": watch.cache_hits,
        }
      self._tm_loads.inc()
      self._tm_load_ms.observe(seconds * 1e3)
      record.future.set_result(engine)
      self._publish_gauges()  # after set_result: the gauge counts it
      return engine
    except BaseException as e:
      with self._lock:
        self._resident.pop(tenant, None)
        self._reserved_bytes -= record.bytes
      self._publish_gauges()
      record.future.set_exception(e)
      raise
    finally:
      ticket.set_result(None)  # hand the build chain to the next load

  def _reserve_or_evict(self, tenant: str, record: _Resident,
                        need_bytes: int) -> List[Any]:
    """Books `need_bytes` for `tenant`, choosing LRU victims to make
    room. Structural work only — returns the victims' engines for the
    CALLER to release outside the lock."""
    victims: List[Any] = []
    with self._lock:
      if self._budget is not None and need_bytes > self._budget:
        raise ValueError(
            f"tenant {tenant!r} needs {need_bytes} bytes, over the "
            f"whole arena budget {self._budget}; raise budget_bytes")
      while (self._budget is not None
             and self._reserved_bytes + need_bytes > self._budget):
        victim_id = next(
            (tid for tid, rec in self._resident.items()
             if tid != tenant and rec.loaded), None)
        if victim_id is None:
          # Everything else is mid-load (can't evict a load in
          # flight); over-budget transiently rather than deadlock.
          break
        rec = self._resident.pop(victim_id)
        self._reserved_bytes -= rec.bytes
        self.evictions += 1
        victims.append(rec.future.result())
      record.bytes = need_bytes
      self._reserved_bytes += need_bytes
    for _ in victims:
      self._tm_evictions.inc()
    return victims

  def _publish_gauges(self) -> None:
    with self._lock:
      models = sum(1 for rec in self._resident.values() if rec.loaded)
      total = self._reserved_bytes
    self._tm_resident.set(models)
    self._tm_bytes.set(total)

  # ---- refresh / eviction entry points ----

  def swap_state(self, tenant: str, state: Any,
                 learner_step: Optional[int] = None) -> bool:
    """Hot-swaps a RESIDENT tenant's params (lock-free readers, the
    engine's swap contract). Returns False when the tenant is not
    resident — an evicted tenant picks its new checkpoint up from the
    loader at the next reload, so there is nothing to swap. Never
    blocks other tenants: the swap runs on the caller's thread against
    one engine; every other engine keeps dispatching (pinned by
    tests/test_serving_front.py with a zero-recompile check)."""
    self.spec(tenant)  # raises on unknown tenant
    with self._lock:
      record = self._resident.get(tenant)
    if record is None or not record.loaded:
      return False
    engine = record.future.result()
    try:
      engine.swap_state(state, learner_step=learner_step)
    except RuntimeError:
      if engine.released:
        return False  # evicted mid-swap: the publication didn't land
      raise
    # Re-check residency AFTER the swap: an LRU eviction racing in
    # would retire the engine and discard the new params — returning
    # True would tell a checkpoint poller its publication landed when
    # the next reload will serve whatever the loader reads instead.
    with self._lock:
      still_resident = self._resident.get(tenant) is record
    return still_resident and not engine.released

  def evict(self, tenant: str) -> bool:
    """Explicit eviction (tests, manual shedding); False if absent."""
    with self._lock:
      record = self._resident.get(tenant)
      if record is None or not record.loaded:
        return False
      self._resident.pop(tenant)
      self._reserved_bytes -= record.bytes
      self.evictions += 1
    self._tm_evictions.inc()
    record.future.result().release()
    self._publish_gauges()
    return True

  def stats(self) -> Dict[str, Any]:
    with self._lock:
      return {
          "budget_bytes": self._budget,
          "resident_bytes": self._reserved_bytes,
          "resident": [tid for tid, rec in self._resident.items()
                       if rec.loaded],
          "loads": self.loads,
          "reloads": self.reloads,
          "evictions": self.evictions,
          "reload_cache_misses": self.reload_cache_misses,
          "last_load": dict(self.last_load) if self.last_load else None,
      }
