"""Batch-bucket math: powers-of-two buckets + batch-dim padding.

XLA programs are shape-specialized: serving a request of every batch
size 1..N would compile N programs (and recompile on the first sight of
each size — a multi-second stall inside a robot's control tick). The
standard fix is a finite bucket table: requests pad up to the next
power-of-two bucket, so the engine pre-compiles log2(max_batch)+1
programs once at startup and the hot path never traces again.

Padding rows replicate the request's LAST real row rather than zeros:
replicated rows are guaranteed in-distribution for any per-row network
(no NaN/inf hazards from all-zero images through normalization layers),
and per-row inference is row-independent — inference-mode batch norm
uses stored statistics — so pad rows cannot change real rows' outputs
(pinned by tests/test_serving.py).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import numpy as np


def bucket_table(max_batch: int) -> Tuple[int, ...]:
  """Powers of two 1, 2, 4, ... covering `max_batch` (last ≥ max_batch)."""
  if max_batch < 1:
    raise ValueError(f"max_batch must be >= 1, got {max_batch}")
  table = []
  b = 1
  while b < max_batch:
    table.append(b)
    b *= 2
  table.append(b)
  return tuple(table)


def bucket_for(n: int, table: Sequence[int]) -> int:
  """Smallest bucket holding n rows; raises when n exceeds the table."""
  if n < 1:
    raise ValueError(f"batch size must be >= 1, got {n}")
  for b in table:
    if n <= b:
      return b
  raise ValueError(
      f"batch size {n} exceeds the largest bucket {table[-1]}; raise "
      f"max_batch or split the request.")


def _pad_rows(array: np.ndarray, bucket: int) -> np.ndarray:
  n = array.shape[0]
  if n == bucket:
    return array
  pad = np.repeat(array[-1:], bucket - n, axis=0)
  return np.concatenate([array, pad], axis=0)


def pad_batch(tree: Any, bucket: int) -> Any:
  """Pads every leaf's leading dim up to `bucket` (last-row replication)."""
  import jax

  return jax.tree_util.tree_map(
      lambda a: _pad_rows(np.asarray(a), bucket), tree)


def unpad_batch(tree: Any, n: int) -> Any:
  """Slices every leaf back to the request's true n rows."""
  import jax

  return jax.tree_util.tree_map(lambda a: a[:n], tree)
