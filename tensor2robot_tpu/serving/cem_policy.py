"""CEM action-selection service: the QT-Opt policy behind the batcher.

The reference's robots each called `predict()` per control tick and ran
the CEM refinement host-side; `QTOptLearner.build_policy` already moved
the whole CEM loop on-device as one XLA program. This module is the
deployment wrapper around that program: bucketed AOT compilation (a
robot fleet's request sizes all hit pre-compiled code), a pinned
device-resident params tree that checkpoint refreshes hot-swap, and a
micro-batcher so N concurrent robots cost ~one CEM program launch
instead of N.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.serving.engine import BucketedServingEngine
from tensor2robot_tpu.serving.microbatcher import MicroBatcher
from tensor2robot_tpu.specs import TensorSpecStruct, make_random_tensors


@gin.configurable
class CEMPolicyServer:
  """Serves batched CEM action selection for a QTOptLearner."""

  def __init__(self,
               learner,
               state: Any,
               max_batch: int = 8,
               max_wait_us: int = 200,
               cem_population: Optional[int] = None,
               cem_iterations: Optional[int] = None,
               seed: int = 0,
               warmup: bool = True):
    """Args:
      learner: a `QTOptLearner` (provides the jittable CEM policy).
      state: acting params — a critic `TrainState` (opt_state=None, the
        checkpoint-hook handoff form) or a full `QTOptState`.
      max_batch: largest coalesced dispatch; buckets cover 1..max_batch.
      max_wait_us: micro-batch deadline (0 = never hold a request).
      cem_population / cem_iterations: serving-side CEM overrides
        (robots often run a cheaper CEM than the Bellman backup).
      seed: base PRNG for CEM sampling; folded per dispatch.
      warmup: AOT-compile every bucket now (recommended — first-tick
        compiles inside a control loop are exactly what this exists to
        prevent). `warmup_seconds` records the cost.
    """
    self._learner = learner
    # The serving CEM rides the learner's gin-selected perf levers
    # (int8 tower / fused select — docs/PERF.md); an int8 learner that
    # was never calibrated on real data gets spec-random calibration
    # here, BEFORE the engine AOT-compiles the policy.
    ensure = getattr(learner, "ensure_calibrated", None)
    if ensure is not None:
      ensure(state)
    policy = learner.build_policy(cem_population=cem_population,
                                  cem_iterations=cem_iterations)
    example = make_random_tensors(
        learner.observation_specification(), batch_size=1, seed=0)
    self._engine = BucketedServingEngine(
        policy, state, example, max_batch=max_batch, takes_rng=True)
    self.warmup_seconds = self._engine.warmup() if warmup else 0.0
    self._batcher = MicroBatcher(self._engine,
                                 max_wait_us=max_wait_us,
                                 rng=jax.random.PRNGKey(seed))

  @property
  def engine(self) -> BucketedServingEngine:
    return self._engine

  @property
  def batcher(self) -> MicroBatcher:
    return self._batcher

  @property
  def params_version(self) -> int:
    """Monotonic params-publication counter (engine hot-swap count):
    the policy-version stamp actors log per episode."""
    return self._engine.params_version

  @property
  def params_learner_step(self) -> int:
    """Learner step stamped on the currently-served params — the
    `param_refresh_lag` reference point (docs/FLEET.md)."""
    return self._engine.params_learner_step

  def update_state(self, state: Any,
                   learner_step: Optional[int] = None) -> None:
    """Hot-swaps the acting params (checkpoint-refresh entry point).

    `learner_step` stamps the refresh with the publisher's training
    progress; fleets thread it through so every served action can be
    attributed to the learner step its params came from.
    """
    self._engine.swap_state(state, learner_step=learner_step)

  def select_actions(self,
                     observations: Dict[str, np.ndarray]) -> np.ndarray:
    """Blocking batched action selection — one call per control tick.

    `observations`: flat numpy dict conforming to the learner's
    observation spec, with a leading batch dim (a single robot passes
    batch 1). Thread-safe: concurrent callers coalesce into shared
    dispatches.
    """
    struct = (observations
              if isinstance(observations, TensorSpecStruct)
              else TensorSpecStruct.from_flat_dict(dict(observations)))
    return np.asarray(self._batcher.predict(struct))

  def select_actions_direct(self, observations, rng) -> np.ndarray:
    """Engine-direct selection (no batcher): latency benches use this
    to measure the device program without queueing."""
    struct = (observations
              if isinstance(observations, TensorSpecStruct)
              else TensorSpecStruct.from_flat_dict(dict(observations)))
    return np.asarray(self._engine.predict(struct, rng=rng))

  def close(self) -> None:
    self._batcher.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False
