"""Dynamic micro-batching: coalesce concurrent predicts into one dispatch.

Many robots (or sim actors, or RPC handlers) each want ONE action per
control tick; the chip wants one big batch per program launch. The
micro-batcher sits between them: callers block on `predict()`, a single
dispatcher thread drains the request queue into the largest batch the
deadline allows (≤ the engine's max_batch, ≤ max_wait_µs of queueing),
pads it onto a bucket via the engine, and scatters per-caller slices
back. Under load, N concurrent callers cost ~one dispatch instead of N
(the Podracer batched-inference idiom, PAPERS.md); a lone caller waits
at most the deadline — and with `max_wait_us=0` not at all (graceful
single-request fallback: an empty queue dispatches the first request
immediately).

Correctness contract (pinned by tests/test_serving.py): per-caller
results are exactly the rows an unbatched `engine.predict` would have
produced — coalescing and padding are invisible to callers.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.serving import coalesce
from tensor2robot_tpu.telemetry import metrics as tmetrics


class _Request:

  __slots__ = ("features", "n", "future")

  def __init__(self, features: Any, n: int):
    self.features = features
    self.n = n
    self.future: Future = Future()


class MicroBatcher:
  """Coalesces concurrent requests onto a `BucketedServingEngine`."""

  def __init__(self, engine, max_wait_us: int = 200,
               rng: Optional[jax.Array] = None):
    """Args:
      engine: a `BucketedServingEngine` (owns buckets + compiled code).
      max_wait_us: how long a dispatch may hold its FIRST request while
        waiting for more to coalesce. 0 = never wait (single-request
        fallback only coalesces what is already queued).
      rng: base PRNG key for rng-taking engines (CEM policies); folded
        per dispatch so coalesced callers draw distinct action noise.
    """
    self._engine = engine
    self._max_wait = max_wait_us / 1e6
    self._rng = rng
    self._dispatch_index = 0
    self._carry: Optional[_Request] = None
    self._queue: "queue.Queue[_Request]" = queue.Queue()
    self._stop = threading.Event()
    # Serializes submit()'s closed-check+enqueue against close()'s
    # stop: without it a request could land on the queue after the
    # dispatcher decided to exit and block its caller forever.
    self._submit_lock = threading.Lock()
    self.dispatches = 0
    self.requests = 0
    self.batch_sizes: List[int] = []
    self._tm_queue_depth = tmetrics.gauge(
        "serving.microbatch_queue_depth")
    self._tm_rows = tmetrics.histogram(
        "serving.microbatch_rows",
        bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256))
    self._thread = threading.Thread(target=self._run, daemon=True)
    self._thread.start()

  # ---- caller side ----

  def submit(self, features: Dict[str, np.ndarray]) -> Future:
    """Enqueues one request (1..max_batch rows); returns its Future."""
    leaves = jax.tree_util.tree_leaves(features)
    n = int(np.asarray(leaves[0]).shape[0])
    if n > self._engine.max_batch:
      raise ValueError(
          f"request of {n} rows exceeds the engine's max_batch "
          f"{self._engine.max_batch}; split it or raise max_batch.")
    request = _Request(features, n)
    with self._submit_lock:
      if self._stop.is_set():
        # Fail fast: the dispatcher thread is (being) stopped, so an
        # enqueued request would never dispatch and its caller would
        # block forever on the future (pinned by tests/test_serving.py).
        raise RuntimeError(
            "MicroBatcher is closed; submit() after close() would "
            "enqueue into a dead dispatcher. Create a new MicroBatcher "
            "(or the multi-tenant ServingFront) instead.")
      self.requests += 1
      self._queue.put(request)
    return request.future

  def predict(self, features: Dict[str, np.ndarray]) -> Any:
    """Blocking predict — what a control loop calls each tick."""
    return self.submit(features).result()

  # ---- dispatcher thread ----

  def _run(self) -> None:
    while (not self._stop.is_set() or not self._queue.empty()
           or self._carry is not None):
      batch, self._carry = coalesce.take_batch(
          self._queue, self._carry, self._engine.max_batch,
          self._max_wait, first_timeout_secs=0.05)
      if not batch:
        continue
      self._dispatch(batch)

  def _dispatch(self, batch: List[_Request]) -> None:
    # Claim first: a request cancelled while queued is dropped here,
    # and the survivors can no longer be cancelled — delivery is
    # race-free (the shared coalesce contract).
    batch = coalesce.claim_batch(batch)
    if not batch:
      return
    try:
      rows = sum(r.n for r in batch)
      # Registry publication: queue depth at dispatch time (requests
      # still waiting behind this batch) + coalesced batch size — the
      # micro-batcher's two load signals.
      self._tm_queue_depth.set(self._queue.qsize())
      self._tm_rows.observe(rows)
      features = coalesce.concat_features(batch)
      with telemetry.span("serving.microbatch_dispatch",
                          requests=len(batch), rows=rows):
        if self._rng is not None:
          key = jax.random.fold_in(self._rng, self._dispatch_index)
          outputs = self._engine.predict(features, rng=key)
        else:
          outputs = self._engine.predict(features)
      self._dispatch_index += 1
      self.dispatches += 1
      self.batch_sizes.append(rows)
      coalesce.deliver(batch, outputs)
    except Exception as exc:  # noqa: BLE001 — deliver to every caller
      coalesce.fail_batch(batch, exc)

  # ---- lifecycle ----

  def close(self, timeout: float = 30.0) -> None:
    """Drains queued requests, then stops the dispatcher thread."""
    with self._submit_lock:
      self._stop.set()
    self._thread.join(timeout=timeout)
    # Defensive: if the dispatcher thread died or timed out, fail any
    # stranded requests instead of hanging their callers.
    while True:
      try:
        request = self._queue.get_nowait()
      except queue.Empty:
        break
      if not request.future.done():
        request.future.set_exception(
            RuntimeError("MicroBatcher closed before dispatch."))

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False
