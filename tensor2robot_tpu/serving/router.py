"""The replicated-tier router: consistent-hash tenant placement over
N serving-front hosts, with caller-side failover and dedup.

One front host caps goodput at one process's dispatch loop no matter
how many engines the broadcast tree can feed; the replicated tier
(docs/SERVING.md "Replicated tier") scales it by PLACEMENT instead of
proxying: this router is a thin CLIENT-side library — requests go
straight from the caller to the owning front replica, so the router
adds a hash and a dict lookup to the data path, never a network hop.

Placement is rendezvous hashing over the live replica set — the SAME
rule (`replay.sampler.rendezvous_*`, byte-compatible with
`fleet.actor.home_shard`) that homes actors on replay shards:

  * each tenant homes on its HRW winner, so arena budgets shard
    across hosts with no coordination and no placement table;
  * a HOT tenant spreads over its top-`spread` replicas (requests
    round-robin across them), trading per-replica batch coalescing
    for parallel dispatch loops;
  * on a replica death ONLY the dead replica's tenants remap (the
    HRW membership property, pinned by tests/test_serving_router.py)
    — every other tenant keeps its warm arena residency.

Failover is part of the data path, not a control plane: a call that
dies with `TimeoutError`/`ConnectionError` (the rpc.py envelope's
terminal errors) marks the replica dead, remaps over the survivors,
and retries — so tenants shed to survivors within one client deadline
of a crash, before the orchestrator's heartbeat poll even notices.
`RpcError` (a server-side application error — most commonly an
admission `RequestRejected`) is NEVER failover: the replica is
healthy and sheds by policy; the error propagates to the caller.

The observation-dedup cache (`serving.dedup`) rides here because the
router sees every tenant's traffic before placement: identical
(quantized) frames under an unchanged param version short-circuit to
the cached action without touching any replica. Version tracking is
piggybacked on predict replies (every front reply carries its
`params_version`); a version advance invalidates stale entries, and
`notify_published()` lets a publish-aware driver invalidate eagerly.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.replay.sampler import rendezvous_spread
from tensor2robot_tpu.serving.dedup import ObservationDedupCache
from tensor2robot_tpu.telemetry import metrics as tmetrics


class NoReplicasError(ConnectionError):
  """Every replica in the tenant's failover order is dead."""


class ServingRouter:
  """Caller-side placement + failover over a front-replica set."""

  def __init__(self,
               replicas: Dict[int, Tuple[str, int]],
               authkey: bytes = rpc_lib.DEFAULT_AUTHKEY,
               transport: str = "loopback",
               spread: int = 1,
               dedup_capacity: int = 0,
               quantize_scale: float = 256.0,
               connect_timeout_secs: float = 20.0,
               call_timeout_secs: float = rpc_lib.DEFAULT_CALL_TIMEOUT_SECS,
               max_retries: int = 0,
               sndbuf: int = 0,
               rcvbuf: int = 0):
    """Args:
      replicas: front_index → RPC address of every front host.
      spread: a tenant's requests round-robin over its top-`spread`
        HRW replicas (1 = classic single-home placement).
      dedup_capacity: > 0 enables the observation-dedup cache.
      max_retries: per-call retries INSIDE one replica (0 default —
        the router's cross-replica failover IS the retry story; inner
        retries multiply the shed time by (retries+1)).
    """
    if not replicas:
      raise ValueError("ServingRouter needs at least one replica")
    if spread < 1:
      raise ValueError(f"spread must be >= 1, got {spread}")
    self._addresses = {int(i): tuple(a) for i, a in replicas.items()}
    self._spread = int(spread)
    self._client_kwargs = dict(
        authkey=authkey, transport=transport,
        connect_timeout_secs=connect_timeout_secs,
        call_timeout_secs=call_timeout_secs,
        max_retries=max_retries, sndbuf=sndbuf, rcvbuf=rcvbuf)
    self._lock = threading.Lock()
    self._alive = set(self._addresses)
    # Per-replica client POOLS: RpcClient serializes concurrent
    # callers on its connection, so each caller thread checks a
    # client out and returns it — N threads get N connections, and a
    # front's per-connection handler threads give them real
    # concurrency server-side.
    self._pool: Dict[int, List[rpc_lib.RpcClient]] = {}
    self._rr: Dict[str, int] = {}
    self._version = 0
    self._dedup: Optional[ObservationDedupCache] = None
    if dedup_capacity > 0:
      self._dedup = ObservationDedupCache(
          capacity=dedup_capacity, quantize_scale=quantize_scale)
    self._tm_requests = tmetrics.counter("serving.router.requests")
    self._tm_failovers = tmetrics.counter("serving.router.failovers")
    self._tm_shed = tmetrics.counter("serving.router.shed")
    self._tm_alive = tmetrics.gauge("serving.router.replicas_alive")
    self._tm_alive.set(len(self._alive))
    # Telemetry counters are process-global (shared across routers);
    # stats() must describe THIS router, so keep local tallies too.
    self._n = {"requests": 0, "failovers": 0, "shed": 0}
    self._closed = False

  # ---- membership ----

  def alive(self) -> List[int]:
    with self._lock:
      return sorted(self._alive)

  def placement(self, tenant: str) -> List[int]:
    """The tenant's failover-ordered replica list (HRW top-spread
    first, then the remaining survivors in rank order)."""
    with self._lock:
      members = sorted(self._alive)
    if not members:
      raise NoReplicasError("no live front replicas")
    ranked = rendezvous_spread(tenant, members, k=len(members))
    return ranked

  def mark_dead(self, index: int) -> None:
    with self._lock:
      if index not in self._alive:
        return
      self._alive.discard(index)
      stale = self._pool.pop(index, [])
      self._tm_alive.set(len(self._alive))
    for client in stale:
      try:
        client.close()
      except Exception:  # noqa: BLE001 — teardown of a dead peer
        pass

  def mark_alive(self, index: int,
                 address: Optional[Tuple[str, int]] = None) -> None:
    """Re-adds a replica (a respawned front) to the placement set.

    Any pooled clients for the index are stale by definition — they
    hold sockets to the PREVIOUS incarnation (a respawn binds a fresh
    port), and checking one out would fail the first call and demote
    the replica straight back to dead (fatal when it is the only
    one). Flush them here so the next predict dials the new address.
    """
    with self._lock:
      if address is not None:
        self._addresses[int(index)] = tuple(address)
      if index not in self._addresses:
        raise KeyError(f"unknown replica {index}")
      self._alive.add(int(index))
      stale = self._pool.pop(int(index), [])
      self._tm_alive.set(len(self._alive))
    for client in stale:
      try:
        client.close()
      except Exception:  # noqa: BLE001 — teardown of a dead peer
        pass

  # ---- version / dedup plumbing ----

  @property
  def params_version(self) -> int:
    with self._lock:
      return self._version

  def notify_published(self, version: int) -> None:
    """Publish-aware drivers call this after a param fan-out: the
    dedup cache drops every entry from older versions eagerly."""
    self._observe_version(int(version))

  def _observe_version(self, version: int) -> None:
    with self._lock:
      if version <= self._version:
        return
      self._version = version
    if self._dedup is not None:
      self._dedup.invalidate(version)

  # ---- client pool ----

  def _checkout(self, index: int) -> rpc_lib.RpcClient:
    with self._lock:
      if index not in self._alive:
        raise ConnectionError(f"replica {index} is marked dead")
      pool = self._pool.setdefault(index, [])
      if pool:
        return pool.pop()
      address = self._addresses[index]
    return rpc_lib.RpcClient(address, **self._client_kwargs)

  def _checkin(self, index: int, client: rpc_lib.RpcClient) -> None:
    with self._lock:
      if index in self._alive and not self._closed:
        self._pool.setdefault(index, []).append(client)
        return
    client.close()

  # ---- the data path ----

  def predict(self, tenant: str, features: Any) -> Any:
    """One routed action request: dedup short-circuit → the tenant's
    replica (round-robin over its spread set) → failover across
    survivors on replica death."""
    self._tm_requests.inc()
    with self._lock:
      self._n["requests"] += 1
    key = None
    if self._dedup is not None:
      # Tenant-scoped: two tenants streaming the SAME frame must not
      # share cached actions — they can be entirely different models.
      key = f"{tenant}|{self._dedup.key(features)}"
      cached = self._dedup.get(key, self.params_version)
      if cached is not None:
        return cached
    ranked = self.placement(tenant)
    spread = ranked[:self._spread]
    with self._lock:
      offset = self._rr[tenant] = self._rr.get(tenant, -1) + 1
    # The candidate order: start inside the spread set at the
    # round-robin position, then the remaining survivors as failover.
    candidates = (spread[offset % len(spread):]
                  + spread[:offset % len(spread)]
                  + ranked[len(spread):])
    last_error: Optional[BaseException] = None
    for index in candidates:
      try:
        client = self._checkout(index)
      except ConnectionError as e:
        last_error = e
        continue
      try:
        reply = client.call(
            "predict", {"tenant": tenant, "features": features})
      except (TimeoutError, ConnectionError) as e:
        # A dead/wedged replica: poisoned client stays closed, the
        # replica leaves the placement set, the next candidate gets
        # the request. This IS the shed path — no orchestrator in
        # the loop.
        last_error = e
        client.close()
        self.mark_dead(index)
        self._tm_failovers.inc()
        with self._lock:
          self._n["failovers"] += 1
        continue
      except rpc_lib.RpcError:
        # Server-side application error (admission shed, unknown
        # tenant): the replica is healthy — never failover.
        self._checkin(index, client)
        self._tm_shed.inc()
        with self._lock:
          self._n["shed"] += 1
        raise
      self._checkin(index, client)
      version = int(reply.get("params_version", 0))
      self._observe_version(version)
      action = reply["action"]
      if self._dedup is not None and key is not None:
        self._dedup.put(key, version, action)
      return action
    raise NoReplicasError(
        f"no live replica could serve tenant {tenant!r}: "
        f"{last_error!r}")

  # ---- observability / lifecycle ----

  def dedup_stats(self) -> Optional[Dict[str, int]]:
    return None if self._dedup is None else self._dedup.stats()

  def stats(self) -> Dict[str, Any]:
    with self._lock:
      alive = sorted(self._alive)
      counts = dict(self._n)
    counts.update({
        "alive": alive,
        "params_version": self.params_version,
        "dedup": self.dedup_stats(),
    })
    return counts

  def close(self) -> None:
    with self._lock:
      self._closed = True
      pools = list(self._pool.values())
      self._pool.clear()
    for pool in pools:
      for client in pool:
        try:
          client.close()
        except Exception:  # noqa: BLE001
          pass

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False
