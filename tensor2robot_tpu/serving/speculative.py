"""Speculative CEM: serve the iteration-1 elite NOW, refine behind it.

A converged QT-Opt policy's CEM distribution barely moves between
iterations — iteration 1's elite mean is already within the action
noise floor of iteration N's (the annealed-population observation
from round 4). The serving consequence: for latency-critical callers
the tier can answer with the ONE-iteration program (≈1/N the device
time of the full loop) and run the full program in the background,
publishing its refined action to a cache so a repeated observation
(robot fleets park; frames duplicate) gets the exact full-CEM answer
at cache-lookup cost. Targets ~2× p50 for 2-iteration configs.

Both programs come from the same seam: `learner.build_policy(
cem_iterations=1)` vs `build_policy(cem_iterations=N)` — each a
single fused XLA program over the SAME params.

Correctness contract (pinned by tests/test_serving_router.py):

  * A refined action NEVER crosses a param hot-swap. The version is
    read BEFORE the fast dispatch; the refined result is stamped with
    that version and inserted only if the current version still
    matches when the refinement lands; `get` additionally requires a
    stamp match at serve time. A publish therefore invalidates every
    in-flight and cached refinement atomically (version mismatch),
    and `on_publish()` clears the cache eagerly.
  * The fast path is always a REAL engine answer for the caller's
    exact observation under the current params — speculation degrades
    refinement freshness, never action validity.

Refinement runs on one daemon worker with a bounded queue: serving
latency must never block on speculation, so an over-full refine queue
DROPS work (counted) rather than backpressuring the hot path.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional

from tensor2robot_tpu.serving.dedup import ObservationDedupCache
from tensor2robot_tpu.telemetry import metrics as tmetrics


class SpeculativeCEM:
  """Wraps a (fast, full) policy pair behind one `predict`."""

  def __init__(self,
               fast_predict: Callable[[Any], Any],
               full_predict: Callable[[Any], Any],
               version_fn: Callable[[], int],
               capacity: int = 256,
               refine_queue: int = 32,
               quantize_scale: float = 256.0):
    """Args:
      fast_predict: the 1-iteration policy — called inline.
      full_predict: the full-CEM policy — called on the refine worker.
      version_fn: returns the CURRENT param version (monotonic; the
        front bumps it on every publish/hot-swap).
      capacity: refined-action cache entries (LRU).
      refine_queue: bounded refine backlog; overflow drops (counted).
      quantize_scale: observation-key quantization (see dedup module).
    """
    self._fast = fast_predict
    self._full = full_predict
    self._version = version_fn
    self._cache = ObservationDedupCache(
        capacity=capacity, quantize_scale=quantize_scale,
        metric_prefix="serving.speculative.cache.")
    self._queue: "queue.Queue" = queue.Queue(maxsize=refine_queue)
    self._fast_served = tmetrics.counter(
        "serving.speculative.fast_served")
    self._refined_served = tmetrics.counter(
        "serving.speculative.refined_served")
    self._refines = tmetrics.counter("serving.speculative.refines")
    self._discarded = tmetrics.counter(
        "serving.speculative.refine_discarded")
    self._dropped = tmetrics.counter(
        "serving.speculative.refine_dropped")
    # Telemetry counters are process-global (every SpeculativeCEM in
    # the process shares them); stats() must describe THIS instance,
    # so keep local tallies beside them (lock: predict thread + refine
    # worker both bump).
    self._n_lock = threading.Lock()
    self._n = {"fast_served": 0, "refined_served": 0, "refines": 0,
               "refine_discarded": 0, "refine_dropped": 0}
    # Queued + IN-FLIGHT refinements: queue emptiness alone cannot
    # tell flush() the backlog drained — the worker dequeues before
    # it computes, so the last refinement is invisible to the queue
    # while still pending.
    self._outstanding = 0
    self._closed = False
    self._worker = threading.Thread(
        target=self._refine_loop, name="speculative-refine",
        daemon=True)
    self._worker.start()

  # ---- the serving path ----

  def predict(self, features: Any) -> Any:
    """The speculative serve: refined-cache hit under the CURRENT
    version, else the fast program inline + a queued refinement."""
    if self._closed:
      raise RuntimeError("SpeculativeCEM is closed")
    version = self._version()
    key = self._cache.key(features)
    refined = self._cache.get(key, version)
    if refined is not None:
      self._refined_served.inc()
      self._bump("refined_served")
      return refined
    action = self._fast(features)
    self._fast_served.inc()
    self._bump("fast_served")
    try:
      self._queue.put_nowait((key, version, features))
    except queue.Full:
      self._dropped.inc()
      self._bump("refine_dropped")
    else:
      with self._n_lock:
        self._outstanding += 1
    return action

  def _bump(self, name: str) -> None:
    with self._n_lock:
      self._n[name] += 1

  # ---- the refine worker ----

  def _refine_loop(self) -> None:
    while True:
      try:
        item = self._queue.get(timeout=0.2)
      except queue.Empty:
        if self._closed:
          return
        continue
      if item is None:
        return
      try:
        key, version, features = item
        if self._version() != version:
          # The params moved while this refinement waited; its result
          # would be stamped with a dead version — skip the dispatch.
          self._discarded.inc()
          self._bump("refine_discarded")
          continue
        try:
          refined = self._full(features)
        except Exception:  # engine closing mid-shutdown; never crash
          self._discarded.inc()
          self._bump("refine_discarded")
          continue
        if self._version() == version:
          self._cache.put(key, version, refined)
          self._refines.inc()
          self._bump("refines")
        else:
          self._discarded.inc()
          self._bump("refine_discarded")
      finally:
        with self._n_lock:
          self._outstanding -= 1

  # ---- lifecycle ----

  def on_publish(self, new_version: Optional[int] = None) -> None:
    """Hot-swap notification: eagerly drop refinements for dead
    versions (the stamp check already guarantees they cannot serve)."""
    self._cache.invalidate(new_version)

  def flush(self, timeout_secs: float = 5.0) -> bool:
    """Waits until every queued AND in-flight refinement has landed
    or been discarded (tests/bench only)."""
    import time
    deadline = time.monotonic() + timeout_secs
    while True:
      with self._n_lock:
        idle = self._outstanding == 0
      if idle:
        return True
      if time.monotonic() >= deadline:
        return False
      time.sleep(0.005)

  def stats(self) -> Dict[str, int]:
    out = self._cache.stats()
    with self._n_lock:
      out.update(self._n)
    return out

  def close(self) -> None:
    if self._closed:
      return
    self._closed = True
    try:
      self._queue.put_nowait(None)  # wake the worker promptly; a
    except queue.Full:              # full queue falls back to the
      pass                          # timed-get closed check
    self._worker.join(timeout=5.0)
