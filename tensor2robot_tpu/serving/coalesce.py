"""Shared request-coalescing machinery: micro-batcher ∩ serving front.

Both dispatchers speak the same request protocol — objects with
``features`` (a pytree with a leading batch dim), ``n`` (rows), and
``future`` (a `concurrent.futures.Future`) — and share four steps
whose contracts must never drift between the single-model and
multi-tenant paths (the reason this module exists, once):

  * `take_batch` — first request (carry leads: a request that
    overflowed the previous dispatch heads this one, so FIFO-re-put
    line-jumping can't starve it) plus whatever coalesces within the
    deadline, ≤ max_batch rows;
  * `claim_batch` — marks every taken request RUNNING via
    ``set_running_or_notify_cancel()``. A future cancelled while
    queued is DROPPED here (its caller already sees CancelledError);
    after a successful claim ``cancel()`` can no longer win, so
    result delivery can never hit `InvalidStateError` — one poisoned
    future must never cost its co-batched neighbors their results;
  * `concat_features` / `deliver` — one concatenated dispatch in,
    per-caller slices out, each ``.copy()``-ed so a caller's in-place
    post-processing cannot corrupt its co-batched neighbors' rows;
  * `fail_batch` — an error inside the dispatch reaches every
    still-pending caller instead of hanging them.
"""

from __future__ import annotations

import queue
import time
from concurrent import futures
from typing import Any, List, Optional, Tuple

import jax
import numpy as np


def take_batch(source: "queue.Queue",
               carry,
               max_batch: int,
               max_wait_secs: float,
               first_timeout_secs: Optional[float] = None
               ) -> Tuple[List[Any], Any]:
  """Coalesces one dispatch's requests; returns ``(batch, carry')``.

  The first request comes from ``carry`` (it leads, see module doc) or
  from the queue — blocking up to ``first_timeout_secs`` (None =
  non-blocking; the front's continuous loop has its own wakeup
  channel, the micro-batcher parks here). Further requests coalesce
  until ``max_batch`` rows or the ``max_wait_secs`` deadline; with a
  zero deadline, already-queued requests still coalesce but nothing is
  held waiting for arrivals. A request that would overflow becomes the
  new carry.
  """
  if carry is not None:
    first, carry = carry, None
  else:
    try:
      first = (source.get(timeout=first_timeout_secs)
               if first_timeout_secs else source.get_nowait())
    except queue.Empty:
      return [], None
  batch = [first]
  rows = first.n
  deadline = time.perf_counter() + max_wait_secs
  while rows < max_batch:
    remaining = deadline - time.perf_counter()
    try:
      nxt = (source.get(timeout=remaining) if remaining > 0
             else source.get_nowait())
    except queue.Empty:
      break
    if rows + nxt.n > max_batch:
      carry = nxt
      break
    batch.append(nxt)
    rows += nxt.n
  return batch, carry


def claim_batch(batch: List[Any]) -> List[Any]:
  """RUNNING-marks the batch; returns the requests still live.

  Dropped entries were cancelled while queued (their callers hold a
  CANCELLED future) or already FINISHED by a racing ``close()``'s
  stranded-request drain — ``set_running_or_notify_cancel`` raises
  `InvalidStateError` on those, which must not kill the dispatcher
  mid-batch and strand the neighbors. Everything returned is
  un-cancellable and un-finished, so `deliver` cannot race.
  """
  claimed = []
  for request in batch:
    try:
      if request.future.set_running_or_notify_cancel():
        claimed.append(request)
    except (futures.InvalidStateError, RuntimeError):
      # Stdlib raises bare RuntimeError for a FINISHED future here
      # (InvalidStateError is only its set_result/set_exception
      # sibling): a racing close() already failed it; not ours.
      pass
  return claimed


def concat_features(batch: List[Any]) -> Any:
  """One dispatch-ready features tree from the batch's requests."""
  return jax.tree_util.tree_map(
      lambda *leaves: np.concatenate(
          [np.asarray(a) for a in leaves], axis=0),
      *[request.features for request in batch])


def deliver(batch: List[Any], outputs: Any) -> None:
  """Scatters per-caller slices of ``outputs`` back to the futures.

  Requires a CLAIMED batch (`claim_batch`): every future is RUNNING,
  so ``set_result`` cannot raise. Slices are copied — callers own
  their rows.
  """
  offset = 0
  for request in batch:
    lo, hi = offset, offset + request.n
    request.future.set_result(jax.tree_util.tree_map(
        lambda a: a[lo:hi].copy(), outputs))
    offset = hi


def fail_batch(batch: List[Any], exc: BaseException) -> None:
  """Delivers ``exc`` to every caller still waiting."""
  for request in batch:
    if not request.future.done():
      request.future.set_exception(exc)
