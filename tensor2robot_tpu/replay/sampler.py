"""Streaming sampler: store → fixed-wire-spec batches → prefetcher.

The learner-facing edge of the data plane. `ReplayBatchSampler` is an
infinite iterator of `TensorSpecStruct` batches in the store's wire
spec — exactly what `data.prefetch.ShardedPrefetcher` consumes — and it
is where sampling STALENESS becomes a measured quantity: every batch's
per-row age (learner step at sample minus learner step at add, via the
store's `set_learner_step` tag) lands in a fixed-bucket histogram the
trainer logs alongside `stall_fraction`.

Round-5 context: the K>1 online caveat in `train_qtopt` said the last
step of a dispatch can train on samples up to ~3K parameter updates
old, and could only say it in prose. With the trainer tagging the store
each iteration, `staleness_snapshot()` reports the real distribution —
and the dispatch-depth / K trade-off becomes tunable against data
instead of a docstring.

The sampler can also record a SCHEDULE DIGEST — a running SHA-256 over
the exact global row ids drawn — which is what the seeded
success-protocol reproducibility check compares across runs (two runs
with the same seeds must produce identical digests).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.replay.store import ReplayStore
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.telemetry import metrics as tmetrics

# Fixed bucket EDGES (upper bounds, in learner steps) so histograms are
# comparable across runs and JSON-stable; the last bucket is open.
STALENESS_BUCKETS: Tuple[int, ...] = (
    0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)


@gin.configurable
class ReplayBatchSampler:
  """Infinite fixed-batch sampling stream with staleness accounting."""

  def __init__(self,
               store: ReplayStore,
               batch_size: int,
               record_schedule: bool = False):
    self._store = store
    self._batch_size = int(batch_size)
    self._record_schedule = record_schedule
    self._digest = hashlib.sha256()
    self._lock = threading.Lock()
    self._counts = np.zeros(len(STALENESS_BUCKETS) + 1, np.int64)
    self._age_sum = 0
    self._age_max = 0
    self._rows = 0
    self._batches = 0
    # Per-batch mean ages in a fixed RING (not an append-capped list:
    # that would freeze the "recent" p95 on the run's first window
    # forever) — 65536 batches of history bounds memory while the p95
    # tracks the live distribution on long runs.
    self._recent_means = np.zeros(65536, np.float64)
    self._recent_count = 0
    self._tm_staleness = tmetrics.histogram(
        "replay.staleness_steps", tmetrics.DEFAULT_STEP_BOUNDS)

  @property
  def batch_size(self) -> int:
    return self._batch_size

  @property
  def store(self) -> ReplayStore:
    return self._store

  @property
  def wire_spec(self) -> TensorSpecStruct:
    """The fixed wire spec every emitted batch conforms to."""
    return self._store.transition_spec

  def sample(self) -> TensorSpecStruct:
    """One batch; staleness and (optionally) the schedule recorded."""
    batch, ages, row_ids = self._store.sample_with_ages(self._batch_size)
    with self._lock:
      self._counts += np.bincount(
          np.searchsorted(STALENESS_BUCKETS, ages, side="left"),
          minlength=len(self._counts))[:len(self._counts)]
      self._age_sum += int(ages.sum())
      self._age_max = max(self._age_max, int(ages.max()))
      self._rows += ages.size
      self._batches += 1
      self._recent_means[
          self._recent_count % self._recent_means.size] = ages.mean()
      self._recent_count += 1
      if self._record_schedule:
        self._digest.update(row_ids.tobytes())
    # Registry publication: per-batch mean age into the step-bucket
    # histogram (the telemetry-plane view of the same distribution).
    self._tm_staleness.observe(float(ages.mean()))
    return batch

  def __iter__(self) -> Iterator[TensorSpecStruct]:
    while True:
      yield self.sample()

  # Alias so the adapter's legacy `as_stream` shape reads naturally.
  def as_stream(self) -> Iterator[TensorSpecStruct]:
    return iter(self)

  # ---- reproducibility ----

  def schedule_digest(self) -> str:
    """SHA-256 over every (shard, slot) drawn so far, in order."""
    if not self._record_schedule:
      raise RuntimeError(
          "schedule recording is off; construct with "
          "record_schedule=True")
    with self._lock:
      return self._digest.hexdigest()

  # ---- staleness reporting ----

  def staleness_snapshot(self) -> Dict[str, object]:
    """The measured staleness distribution since construction.

    `histogram` maps bucket upper-bound labels ("<=8", ..., ">16384")
    to sampled-row counts; ages are in LEARNER STEPS (sample-time step
    minus add-time step), so an offline buffer reads as all-zero ages
    until training begins and grows linearly after — the online regime
    is the signal this exists for.
    """
    with self._lock:
      labels = [f"<={b}" for b in STALENESS_BUCKETS] + [
          f">{STALENESS_BUCKETS[-1]}"]
      hist = {label: int(c) for label, c in zip(labels, self._counts)}
      mean = self._age_sum / self._rows if self._rows else 0.0
      live = self._recent_means[
          :min(self._recent_count, self._recent_means.size)]
      p95 = float(np.percentile(live, 95)) if live.size else 0.0
      return {
          "histogram": hist,
          "mean_age_steps": mean,
          "max_age_steps": self._age_max,
          "batch_mean_age_p95_steps": p95,
          "rows": self._rows,
          "batches": self._batches,
      }

  def metrics_scalars(self, prefix: str = "replay_") -> Dict[str, float]:
    """The scalar cut of the snapshot, shaped for the train log."""
    snap = self.staleness_snapshot()
    return {
        f"{prefix}staleness_mean_steps": float(snap["mean_age_steps"]),
        f"{prefix}staleness_max_steps": float(snap["max_age_steps"]),
        f"{prefix}staleness_batch_p95_steps": float(
            snap["batch_mean_age_p95_steps"]),
        f"{prefix}sampled_batches": float(snap["batches"]),
    }


def make_stream(store: ReplayStore, batch_size: int,
                record_schedule: bool = False
                ) -> Tuple[Iterator[TensorSpecStruct],
                           ReplayBatchSampler]:
  """(iterator, sampler) — the iterator feeds `ShardedPrefetcher`, the
  sampler handle stays with the trainer for staleness/metrics reads."""
  sampler = ReplayBatchSampler(store, batch_size,
                               record_schedule=record_schedule)
  return iter(sampler), sampler


# ---- cross-shard fan-out (ISSUE 16: the sharded replay plane) ----
#
# With one shard per replay HOST, a learner batch is assembled from
# per-shard sample RPCs instead of one store gather. These two pure
# helpers define that assembly; `fleet.learner.RemoteReplay` applies
# them over its shard clients. The result obeys the PR-3 gather
# contract — rows grouped by shard, shards in index order (SHARD-MAJOR)
# — so a cross-host batch has the same layout an in-process
# multi-shard `sample_with_ages` gather produces.


def shard_fanout_counts(batch_size: int,
                        shard_sizes: Tuple[int, ...]) -> Tuple[int, ...]:
  """Per-shard sample counts, proportional to shard fill.

  Mirrors the in-store multi-shard draw (uniform over the TOTAL
  population → expected counts proportional to shard sizes) with a
  deterministic largest-remainder rounding: quotas floor, and the
  leftover rows go to the largest fractional remainders (ties to the
  lower shard index). Empty shards draw zero — a fleet whose actors
  all hash to one shard still samples correctly.
  """
  sizes = [max(0, int(s)) for s in shard_sizes]
  total = sum(sizes)
  if batch_size < 0:
    raise ValueError(f"batch_size must be >= 0, got {batch_size}")
  if total == 0:
    raise ValueError("cannot allocate a sample batch: every shard "
                     "is empty")
  quotas = [batch_size * s / total for s in sizes]
  counts = [int(q) for q in quotas]
  remainders = sorted(
      range(len(sizes)), key=lambda i: (counts[i] - quotas[i], i))
  for i in remainders[:batch_size - sum(counts)]:
    counts[i] += 1
  return tuple(counts)


def concat_shard_major(
    parts: "list[Dict[str, np.ndarray]]") -> Dict[str, np.ndarray]:
  """Concatenates per-shard flat sample dicts in shard-index order."""
  if not parts:
    raise ValueError("no shard produced rows for this batch")
  if len(parts) == 1:
    return dict(parts[0])
  return {key: np.concatenate([part[key] for part in parts], axis=0)
          for key in parts[0]}


# ---- rendezvous hashing (ISSUE 17: the shared HRW seam) ----
#
# The replay plane homes actors on shards with highest-random-weight
# hashing (`fleet.actor.home_shard`); the replicated serving tier
# places tenants on front replicas with the SAME rule. These helpers
# are the canonical form, generalized to an arbitrary bucket-id set so
# a router can rank over the SURVIVORS after a replica death. The salt
# is byte-identical to `home_shard`'s (`"{key}|shard-{i}"`), and
# tests/test_serving_router.py pins `rendezvous_choose(k, range(n)) ==
# home_shard(k, n)` so the two modules (actor.py must stay jax-free
# and cannot import this one) can never drift.


def rendezvous_weight(key: str, bucket: int) -> int:
  """The deterministic pseudo-random weight of (key, bucket)."""
  digest = hashlib.sha256(f"{key}|shard-{bucket}".encode()).digest()
  return int.from_bytes(digest[:8], "big")


def rendezvous_rank(key: str,
                    buckets: "Iterable[int]") -> "list[int]":
  """Buckets sorted by descending HRW weight for `key`.

  The operational property (pinned): removing a bucket deletes its
  entry from every key's ranking and changes NOTHING else — so only
  keys whose top choice was the removed bucket remap, and each key's
  fallback order is stable under further membership changes.
  """
  members = sorted(set(int(b) for b in buckets))
  if not members:
    raise ValueError("rendezvous_rank needs at least one bucket")
  return sorted(members,
                key=lambda b: rendezvous_weight(key, b),
                reverse=True)


def rendezvous_choose(key: str, buckets: "Iterable[int]") -> int:
  """The HRW winner — `home_shard` over an arbitrary bucket set."""
  return rendezvous_rank(key, buckets)[0]


def rendezvous_spread(key: str, buckets: "Iterable[int]",
                      k: int) -> "list[int]":
  """The top-`k` buckets for `key` — a hot tenant spread over k
  replicas. `k` is clamped to the membership size; order is the
  failover order (index 0 is the HRW home)."""
  if k < 1:
    raise ValueError(f"k must be >= 1, got {k}")
  return rendezvous_rank(key, buckets)[:k]
