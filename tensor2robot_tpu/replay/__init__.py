"""Distributed replay data plane (actors → service → store → learner).

The QT-Opt workload is online RL: a fleet of actors streams transitions
into replay while the learner samples from it (SURVEY.md §3; Podracer,
arXiv:2104.06272). This package is that layer, host-side and
production-shaped:

  * `store`    — sharded ring-buffer memory tier (per-shard locks,
                 uniform/FIFO/prioritized seeded sampling, bounded
                 eviction with optional disk spill, per-row add-step
                 tags for staleness).
  * `service`  — multi-producer ingestion front (bounded queue with
                 explicit backpressure or drop-and-count overflow,
                 per-actor sessions whose episodes commit atomically,
                 crash/restart survival).
  * `sampler`  — fixed-wire-spec streaming sampler feeding
                 `data.prefetch.ShardedPrefetcher`, with the measured
                 per-batch staleness histogram and a schedule digest
                 for reproducibility checks.

`research/qtopt/replay_buffer.ReplayBuffer` remains the thin
API-compatible adapter over a 1-shard store; `bench.py --replay`
measures the plane (shard scaling, actor-fleet ingestion, staleness).
See docs/REPLAY.md.
"""

from tensor2robot_tpu.replay.sampler import (
    STALENESS_BUCKETS,
    ReplayBatchSampler,
    make_stream,
)
from tensor2robot_tpu.replay.service import (
    ActorIngestSession,
    ReplayWriteService,
)
from tensor2robot_tpu.replay.store import ReplayStore
