"""Multi-producer ingestion front: actors → bounded queue → store.

The reference's actor fleet streamed episodes into the replay service
over RPC; the failure modes that design has to survive are the ones
this module makes explicit:

  * BACKPRESSURE — producers go through a bounded queue. Policy
    ``"block"`` applies classic backpressure (an actor's `put` waits
    for the writer to drain — collection slows to match ingestion);
    policy ``"drop"`` never blocks a producer: an overflowing batch is
    counted and discarded (`dropped_batches`/`dropped_transitions`),
    which is the right trade when fresh on-policy data supersedes stale
    queued data anyway. The LEARNER is on neither path: sampling reads
    the store directly and cannot block on ingestion under either
    policy (pinned by tests/test_replay.py).
  * ACTOR CRASH — producers write through per-actor SESSIONS that stage
    an episode locally and commit it atomically at `end_episode`. A
    crash mid-episode abandons the staged rows; the store never sees a
    partial episode.
  * RESTART — re-opening a session under the same `actor_id` aborts
    whatever the dead incarnation staged (counted in
    `aborted_episodes`/`restarts`) and resumes ingestion cleanly.

One writer thread drains the queue into `ReplayStore.add` (whole
batches — one shard lock apiece). A writer-thread error is latched and
re-raised on `flush()`/`close()` rather than silently killing intake.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.replay.sampler import ReplayBatchSampler
from tensor2robot_tpu.replay.store import (
    ReplayStore,
    _record_event,
    to_flat_arrays,
)
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Lag histogram bucket upper bounds, in learner steps (same labelling
# scheme as the staleness histogram). ONE source of truth with the
# telemetry registry's step-bucket family so the authoritative
# snapshot and its registry twin can never desynchronize.
LAG_BUCKETS = tuple(int(b) for b in tmetrics.DEFAULT_STEP_BOUNDS)

OVERFLOW_POLICIES = ("drop", "block")


class _Enqueued:
  __slots__ = ("flat", "n", "priority")

  def __init__(self, flat: Dict[str, np.ndarray], n: int,
               priority: Optional[float]):
    self.flat = flat
    self.n = n
    self.priority = priority


class ActorIngestSession:
  """One actor's write handle: episodes stage locally, commit atomically.

  Not thread-safe across actors by design — each actor owns its session
  (the service hands out one per `actor_id`). `add` is the
  single-commit convenience for bandit-style envs whose "episode" is
  one batched step.
  """

  def __init__(self, service: "ReplayWriteService", actor_id: str):
    self._service = service
    self.actor_id = actor_id
    self._staged: List[Dict[str, np.ndarray]] = []
    self._in_episode = False
    self.closed = False
    self.episodes_committed = 0
    self.transitions_committed = 0

  def begin_episode(self) -> None:
    if self._in_episode:
      # A begin without an end is the crash shape: discard the partial.
      self.abort()
    self._in_episode = True
    self._staged = []

  def append(self, transitions: Any) -> None:
    """Stages a [N, ...] chunk of the current episode (local only)."""
    if self.closed:
      raise RuntimeError(
          f"session {self.actor_id!r} is closed (actor restarted?)")
    if not self._in_episode:
      self.begin_episode()
    self._staged.append(to_flat_arrays(transitions))

  def end_episode(self, priority: Optional[float] = None) -> bool:
    """Commits the staged episode through the bounded queue.

    Returns False when the drop policy discarded it (queue full).
    """
    if not self._in_episode:
      return False
    staged, self._staged = self._staged, []
    self._in_episode = False
    if not staged:
      return False
    if len(staged) == 1:
      flat = staged[0]
    else:
      flat = {k: np.concatenate([c[k] for c in staged], axis=0)
              for k in staged[0]}
    accepted = self._service._enqueue(flat, priority)
    if accepted:
      n = int(next(iter(flat.values())).shape[0])
      self.episodes_committed += 1
      self.transitions_committed += n
    return accepted

  def add(self, transitions: Any,
          priority: Optional[float] = None) -> bool:
    """begin → append → end in one call (single-step episode batches)."""
    self.begin_episode()
    self.append(transitions)
    return self.end_episode(priority)

  def abort(self) -> None:
    """Discards any staged partial episode (crash / restart path)."""
    if self._in_episode or self._staged:
      self._service._count_abort(self.actor_id)
    self._staged = []
    self._in_episode = False


@gin.configurable
class ReplayWriteService:
  """Bounded-queue ingestion front over a `ReplayStore`."""

  def __init__(self,
               store: ReplayStore,
               queue_batches: int = 16,
               overflow: str = "drop",
               block_timeout_secs: Optional[float] = None):
    """Args:
      store: the sharded store batches drain into.
      queue_batches: bounded queue depth, in batches.
      overflow: "drop" (count + discard, producer never blocks) or
        "block" (backpressure: producer waits for queue space).
      block_timeout_secs: with "block", an optional cap on the wait —
        on expiry the batch is dropped and counted (an actor must not
        hang forever on a wedged writer).
    """
    if overflow not in OVERFLOW_POLICIES:
      raise ValueError(
          f"overflow must be one of {OVERFLOW_POLICIES}, got {overflow!r}")
    self._store = store
    self._overflow = overflow
    self._block_timeout = block_timeout_secs
    self._queue: "queue.Queue[_Enqueued]" = queue.Queue(
        maxsize=queue_batches)
    self._sessions: Dict[str, ActorIngestSession] = {}
    self._lock = threading.Lock()
    self._stop = threading.Event()
    self._error: Optional[BaseException] = None
    self.enqueued_batches = 0
    self.committed_batches = 0
    self.committed_transitions = 0
    self.dropped_batches = 0
    self.dropped_transitions = 0
    self.aborted_episodes = 0
    self.restarts = 0
    self._tm_drops = tmetrics.counter("replay.dropped_transitions")
    self._tm_aborts = tmetrics.counter("replay.aborted_episodes")
    self._tm_queue_depth = tmetrics.gauge("replay.ingest_queue_depth")
    self._writer = threading.Thread(
        target=self._drain, name="replay-writer", daemon=True)
    self._writer.start()

  @property
  def store(self) -> ReplayStore:
    return self._store

  @property
  def queue_depth(self) -> int:
    return self._queue.qsize()

  # ---- producer side ----

  def session(self, actor_id: str) -> ActorIngestSession:
    """The actor's write handle; reopening an id = crash-restart."""
    with self._lock:
      prior = self._sessions.pop(actor_id, None)
    if prior is not None:
      # Outside the lock: abort() re-enters the service for its
      # counter (the metrics mutex is not reentrant by design).
      prior.abort()
      prior.closed = True
      with self._lock:
        self.restarts += 1
      log.info("replay session %r reopened (actor restart); partial "
               "state discarded", actor_id)
    fresh = ActorIngestSession(self, actor_id)
    with self._lock:
      self._sessions[actor_id] = fresh
    return fresh

  def put(self, transitions: Any,
          priority: Optional[float] = None) -> bool:
    """Sessionless enqueue of one whole batch (dataset readers)."""
    return self._enqueue(to_flat_arrays(transitions), priority)

  def _enqueue(self, flat: Dict[str, np.ndarray],
               priority: Optional[float]) -> bool:
    if self._error is not None:
      raise RuntimeError("replay writer thread died") from self._error
    n = int(next(iter(flat.values())).shape[0])
    item = _Enqueued(flat, n, priority)
    try:
      if self._overflow == "block":
        self._put_blocking(item)
      else:
        self._queue.put_nowait(item)
    except queue.Full:
      with self._lock:
        self.dropped_batches += 1
        self.dropped_transitions += n
      self._tm_drops.inc(n)
      _record_event("/t2r/replay/drop")
      return False
    with self._lock:
      self.enqueued_batches += 1
    self._tm_queue_depth.set(self._queue.qsize())
    return True

  def _put_blocking(self, item: _Enqueued) -> None:
    """Backpressure put that still notices a dead writer.

    A bare ``put(timeout=None)`` would strand the producer FOREVER if
    the writer thread died while the queue was full — the error latch
    is only checked on `_enqueue` entry, and a dead writer never
    drains (found by t2rcheck CON302 triage). Wait in short slices,
    re-checking the latch each slice; `block_timeout_secs` still caps
    the total wait (queue.Full on expiry → counted drop, unchanged).
    """
    deadline = (time.monotonic() + self._block_timeout
                if self._block_timeout is not None else None)
    while True:
      if self._error is not None:
        raise RuntimeError("replay writer thread died") from self._error
      slice_secs = 0.05
      if deadline is not None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          raise queue.Full
        slice_secs = min(slice_secs, remaining)
      try:
        self._queue.put(item, timeout=slice_secs)
        return
      except queue.Full:
        continue

  def _count_abort(self, actor_id: str) -> None:
    with self._lock:
      self.aborted_episodes += 1
    self._tm_aborts.inc()
    _record_event("/t2r/replay/abort")

  # ---- writer thread ----

  def _drain(self) -> None:
    while True:
      try:
        item = self._queue.get(timeout=0.05)
      except queue.Empty:
        if self._stop.is_set():
          return
        continue
      try:
        self._store.add(item.flat, priority=item.priority)
        with self._lock:
          self.committed_batches += 1
          self.committed_transitions += item.n
      except BaseException as e:  # latched; surfaced on flush/close
        self._error = e
        log.exception("replay writer failed; ingestion halted")
        return

  # ---- lifecycle / metrics ----

  def flush(self, timeout_secs: float = 30.0) -> bool:
    """Blocks until everything enqueued so far has been committed."""
    deadline = time.monotonic() + timeout_secs
    while True:
      if self._error is not None:
        raise RuntimeError("replay writer thread died") from self._error
      with self._lock:
        drained = (self.committed_batches >= self.enqueued_batches
                   and self._queue.empty())
      if drained:
        return True
      if time.monotonic() > deadline:
        return False
      time.sleep(0.005)

  def close(self, timeout_secs: float = 10.0) -> None:
    self.flush(timeout_secs)
    self._stop.set()
    self._writer.join(timeout=timeout_secs)
    if self._error is not None:
      raise RuntimeError("replay writer thread died") from self._error

  def metrics_scalars(self, prefix: str = "replay_") -> Dict[str, float]:
    with self._lock:
      return {
          f"{prefix}queue_depth": float(self._queue.qsize()),
          f"{prefix}enqueued_batches": float(self.enqueued_batches),
          f"{prefix}committed_transitions": float(
              self.committed_transitions),
          f"{prefix}dropped_batches": float(self.dropped_batches),
          f"{prefix}dropped_transitions": float(self.dropped_transitions),
          f"{prefix}aborted_episodes": float(self.aborted_episodes),
          f"{prefix}actor_restarts": float(self.restarts),
      }


class LagStats:
  """Thread-safe accumulator for the param-refresh-lag distribution.

  Lives with the replay plane (not the serving host) because the lag
  is MEASURED at commit time, wherever the committed rows land — on
  the single-host fleet that is the host process, on the sharded
  plane it is each shard service (ISSUE 16). `hop` attributes the lag
  to the broadcast-tree depth of the serving host whose params the
  actor acted with: per-hop sub-histograms quantify what each extra
  tree hop costs in publication freshness.
  """

  def __init__(self):
    self._lock = threading.Lock()
    self._counts = np.zeros(len(LAG_BUCKETS) + 1, np.int64)
    self._sum = 0
    self._max = 0
    self._n = 0
    self._by_hop: Dict[int, List[int]] = {}  # hop -> [rows, sum, max]
    self._tm_lag = tmetrics.histogram(
        "fleet.param_refresh_lag_steps", tmetrics.DEFAULT_STEP_BOUNDS)

  def record(self, lag: int, rows: int,
             hop: Optional[int] = None) -> None:
    lag = max(int(lag), 0)
    bucket = int(np.searchsorted(LAG_BUCKETS, lag, side="left"))
    with self._lock:
      self._counts[bucket] += rows
      self._sum += lag * rows
      self._max = max(self._max, lag)
      self._n += rows
      if hop is not None:
        acc = self._by_hop.setdefault(int(hop), [0, 0, 0])
        acc[0] += rows
        acc[1] += lag * rows
        acc[2] = max(acc[2], lag)
    # Twin publication into the process registry (same step-bucket
    # family, same ROW weighting as the accumulator above), so the
    # telemetry RPC serves lag without touching this class and the
    # flight recorder captures it. The per-hop twin rides the same
    # family under a `.hop<k>` suffix (catalogued as a placeholder
    # row in docs/OBSERVABILITY.md).
    self._tm_lag.observe(lag, n=rows)
    if hop is not None:
      tmetrics.histogram(f"fleet.param_refresh_lag_steps.hop{int(hop)}",
                         tmetrics.DEFAULT_STEP_BOUNDS).observe(
                             lag, n=rows)

  def snapshot(self) -> Dict[str, Any]:
    with self._lock:
      labels = [f"<={b}" for b in LAG_BUCKETS] + [f">{LAG_BUCKETS[-1]}"]
      out: Dict[str, Any] = {
          "rows": int(self._n),
          "mean": (self._sum / self._n) if self._n else 0.0,
          "max": int(self._max),
          "histogram": {label: int(count)
                        for label, count in zip(labels, self._counts)},
      }
      if self._by_hop:
        out["by_hop"] = {
            str(hop): {"rows": int(n), "mean": (s / n) if n else 0.0,
                       "max": int(m)}
            for hop, (n, s, m) in sorted(self._by_hop.items())}
      return out


class ReplayFront:
  """The replay plane's RPC-facing surface over ONE store.

  Factored out of the fleet host (ISSUE 16) so the exact same
  session/commit/sample/lag semantics serve two deployments:

    * the single-host fleet — the serving host owns a `ReplayFront`
      next to its engine (replay_hosts=0, unchanged behavior);
    * the sharded plane — each `replay_shard_main` process owns a
      1-shard store behind its own `ReplayFront`, actors commit to
      their rendezvous-hash home shard, and the learner fans samples
      across shards (`fleet.learner.RemoteReplay`), concatenating
      shard-major per the PR-3 gather contract. Staleness and
      param-refresh lag are accounted WHERE EACH SHARD LIVES — the
      same choke-point principle, one process per shard.

  The crash contract is inherited wholesale: sessions are tracked per
  RPC connection (`ctx`) by OBJECT identity and aborted on
  disconnect, so partial episodes never land no matter which process
  the store is in.
  """

  def __init__(self, store: ReplayStore, service: "ReplayWriteService"):
    self.store = store
    self.service = service
    self._samplers: Dict[int, ReplayBatchSampler] = {}
    self._sessions: Dict[str, ActorIngestSession] = {}
    self._lock = threading.Lock()
    self.lag = LagStats()
    self._commit_window: Optional[tuple] = None

  # ---- sessions (the host's restart-with-abort contract) ----

  def session_for(self, actor_id: str, ctx: dict) -> ActorIngestSession:
    with self._lock:
      session = self._sessions.get(actor_id)
    if session is None or session.closed:
      # A fresh claim under an existing actor_id is the restart path:
      # `service.session` counts it and aborts whatever the dead
      # incarnation staged (restart-with-session-abort).
      session = self.service.session(actor_id)
      with self._lock:
        self._sessions[actor_id] = session
    # Track the OBJECT this connection used, not just the id: a
    # hard-killed actor's connection can be detected dead AFTER its
    # replacement re-registered, and the late disconnect must abort
    # the old incarnation's session, never the new one's.
    ctx.setdefault("sessions", {})[actor_id] = session
    return session

  def abort_sessions(self, ctx: dict) -> None:
    """The disconnect path: aborts every session this ctx opened."""
    for actor_id, session in ctx.get("sessions", {}).items():
      if not session.closed:
        session.abort()
      with self._lock:
        if self._sessions.get(actor_id) is session:
          del self._sessions[actor_id]

  # ---- commits ----

  def _record_commit(self, rows: int, policy_learner_step,
                     hop: Optional[int]) -> None:
    now = time.monotonic()
    with self._lock:
      first = self._commit_window[0] if self._commit_window else now
      self._commit_window = (first, now)
    if policy_learner_step is not None:
      self.lag.record(
          self.store.learner_step - int(policy_learner_step), rows,
          hop=hop)

  def commit(self, payload: Dict[str, Any], ctx: dict) -> bool:
    session = self.session_for(payload["actor_id"], ctx)
    accepted = session.add(payload["transitions"])
    if accepted:
      rows = int(next(iter(payload["transitions"].values())).shape[0])
      self._record_commit(rows, payload.get("policy_learner_step"),
                          payload.get("policy_hop"))
    return bool(accepted)

  def begin_episode(self, actor_id: str, ctx: dict) -> bool:
    self.session_for(actor_id, ctx).begin_episode()
    return True

  def append(self, payload: Dict[str, Any], ctx: dict) -> bool:
    self.session_for(payload["actor_id"], ctx).append(
        payload["transitions"])
    return True

  def end_episode(self, payload: Dict[str, Any], ctx: dict) -> bool:
    session = self.session_for(payload["actor_id"], ctx)
    committed_before = session.transitions_committed
    accepted = session.end_episode()
    if accepted:
      self._record_commit(
          session.transitions_committed - committed_before,
          payload.get("policy_learner_step"),
          payload.get("policy_hop"))
    return bool(accepted)

  # ---- sampling / learner tag ----

  def sampler(self, batch_size: int) -> ReplayBatchSampler:
    with self._lock:
      sampler = self._samplers.get(batch_size)
      if sampler is None:
        sampler = ReplayBatchSampler(self.store, batch_size)
        self._samplers[batch_size] = sampler
    return sampler

  def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
    batch = self.sampler(int(batch_size)).sample()
    return {k: np.asarray(v) for k, v in batch.to_flat_dict().items()}

  def size(self) -> int:
    return len(self.store)

  def set_learner_step(self, step: int) -> None:
    self.store.set_learner_step(int(step))

  # ---- reporting ----

  def staleness(self) -> Dict[str, Any]:
    with self._lock:
      samplers = list(self._samplers.items())
    return {str(batch_size): sampler.staleness_snapshot()
            for batch_size, sampler in samplers}

  def metrics(self) -> Dict[str, Any]:
    with self._lock:
      commit_window = self._commit_window
    return {
        "store": self.store.metrics_snapshot(),
        "service": self.service.metrics_scalars(),
        "staleness": self.staleness(),
        "param_refresh_lag": self.lag.snapshot(),
        "commit_window": (None if commit_window is None else {
            "first_time": commit_window[0],
            "last_time": commit_window[1],
        }),
    }

  def metrics_scalars(self) -> Dict[str, float]:
    out = self.store.metrics_scalars()
    with self._lock:
      samplers = list(self._samplers.values())
    for sampler in samplers:
      out.update(sampler.metrics_scalars())
    out["fleet_param_refresh_lag_mean"] = self.lag.snapshot()["mean"]
    return out

  def close(self) -> None:
    self.service.close()
