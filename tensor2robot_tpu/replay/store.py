"""Sharded replay store: the data-plane's memory tier.

The reference's QT-Opt replay was an external Google-infra service a
fleet of actors streamed grasp episodes into while Bellman updaters
sampled (SURVEY.md §3 — never open-sourced). The single-process
`research/qtopt/replay_buffer.py` ring buffer stood in for it through
round 5; this module is the production-shaped replacement underneath
it: N independent ring-buffer SHARDS, each with its own mutex, so
concurrent actor adds and learner sampling contend on different locks
(adds route whole batches round-robin across shards; a sample gathers
each shard's slice as one contiguous block under that shard's lock
only, so writers on other shards never wait on the sampler — and
concurrent samplers overlap their gathers. Within one gather the row
memcpys are already striped across cores by `native/gather.cc`).

Sampling modes (one seeded `numpy` Generator, deterministic given the
call sequence):

  * ``uniform`` — one `rng.integers` over the LIVE total, split to
    shards by cumulative size. With `num_shards=1` this performs the
    exact rng call and row gather the legacy `ReplayBuffer` performed,
    which is what keeps the thin adapter bit-identical to the old
    in-process path (pinned by tests/test_replay.py).
  * ``fifo`` — globally oldest-first by add sequence (offline replay of
    logged episodes in order); the read cursor wraps when it catches
    the writer, so the stream is infinite like the others.
  * ``prioritized`` — proportional to per-row priority (set at add
    time, e.g. per-episode TD error or success weight).

Eviction is capacity-bounded ring overwrite per shard; evicted rows can
optionally SPILL to disk as `.npz` chunks (`spill_dir`) so an online
run's overwritten history remains auditable/re-trainable instead of
vanishing. Every row carries the learner step at which it was added
(`set_learner_step`), which is what turns sampling staleness from a
docstring caveat into the measured per-batch age the sampler reports.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.utils import native

SAMPLING_MODES = ("uniform", "fifo", "prioritized")


def to_flat_arrays(transitions: Any) -> Dict[str, np.ndarray]:
  """Transition batch (TensorSpecStruct or mapping) → flat numpy dict.

  The one normalization every ingestion path shares (direct store.add,
  service.put, session staging), so the coercion semantics cannot
  drift between them.
  """
  if isinstance(transitions, TensorSpecStruct):
    flat = transitions.to_flat_dict()
  else:
    flat = dict(transitions)
  return {k: np.asarray(v) for k, v in flat.items()}


def _record_event(name: str) -> None:
  """Best-effort jax.monitoring tap (same channel as CompileWatch)."""
  try:
    import jax.monitoring as monitoring
    monitoring.record_event(name)
  except Exception:  # noqa: BLE001 — instrumentation must never raise
    pass


class _Shard:
  """One ring buffer: storage + per-row metadata under one mutex."""

  __slots__ = ("storage", "add_step", "add_seq", "priority", "lock",
               "insert", "size", "cursor")

  def __init__(self, flat_spec: Dict[str, Any], capacity: int):
    self.storage: Dict[str, np.ndarray] = {}
    for key, spec in flat_spec.items():
      self.storage[key] = np.zeros(
          (capacity,) + tuple(spec.shape), dtype=spec.dtype)
    self.add_step = np.zeros((capacity,), np.int64)   # learner step at add
    self.add_seq = np.zeros((capacity,), np.int64)    # global add order
    self.priority = np.zeros((capacity,), np.float64)
    self.lock = threading.Lock()
    self.insert = 0
    self.size = 0
    self.cursor = 0  # FIFO read position (rows consumed mod size)


@gin.configurable
class ReplayStore:
  """Sharded, capacity-bounded transition store with seeded sampling."""

  def __init__(self,
               transition_spec: TensorSpecStruct,
               capacity: int = 100_000,
               num_shards: int = 1,
               seed: int = 0,
               sampling: str = "uniform",
               spill_dir: Optional[str] = None):
    """Args:
      transition_spec: flat(-tenable) spec of one transition row.
      capacity: TOTAL row capacity; each shard holds capacity//num_shards
        (the remainder is dropped — capacity must be >= num_shards).
      num_shards: independent ring buffers (per-shard locks).
      seed: sampler determinism (one Generator for the whole store).
      sampling: "uniform" | "fifo" | "prioritized".
      spill_dir: when set, rows evicted by ring overwrite are saved as
        npz chunks here instead of being silently lost.
    """
    if sampling not in SAMPLING_MODES:
      raise ValueError(
          f"sampling must be one of {SAMPLING_MODES}, got {sampling!r}")
    if num_shards < 1:
      raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if capacity < num_shards:
      raise ValueError(
          f"capacity {capacity} < num_shards {num_shards}: every shard "
          "needs at least one row.")
    self._spec = specs_lib.flatten_spec_structure(transition_spec)
    self._flat_spec = dict(self._spec.to_flat_dict())
    self._num_shards = int(num_shards)
    self._shard_capacity = int(capacity) // self._num_shards
    self._capacity = self._shard_capacity * self._num_shards
    self._sampling = sampling
    self._spill_dir = spill_dir
    self._shards = [_Shard(self._flat_spec, self._shard_capacity)
                    for _ in range(self._num_shards)]
    self._rng = np.random.default_rng(seed)
    # One lock for the sampler state (rng + cross-shard bookkeeping);
    # it is never held while a shard gather runs, so adds into other
    # shards proceed concurrently with sampling.
    self._sample_lock = threading.Lock()
    self._route = 0          # round-robin add target
    self._add_seq = 0        # global monotonically increasing add order
    self._learner_step = 0
    self._spill_chunks = 0
    # Counter increments happen from many threads (actors on different
    # shards); a dedicated stats mutex keeps them exact — `+=` on an
    # int is a read-modify-write that drops updates under contention.
    self._stats_lock = threading.Lock()
    # ---- instrumentation (read via metrics_snapshot) ----
    self.adds_total = 0          # transitions
    self.add_calls = 0
    self.samples_total = 0       # transitions
    self.sample_calls = 0
    self.evictions_total = 0
    self.spilled_total = 0
    self._created = time.monotonic()
    self._last_snapshot = (time.monotonic(), 0, 0)
    # Telemetry handles cached once: the add/sample hot paths call
    # .inc()/.set() directly instead of re-resolving names through
    # the registry lock per call.
    self._tm_adds = tmetrics.counter("replay.adds")
    self._tm_samples = tmetrics.counter("replay.samples")
    self._tm_evictions = tmetrics.counter("replay.evictions")
    self._tm_fill = tmetrics.gauge("replay.fill")
    self._tm_learner_step = tmetrics.gauge("replay.learner_step")

  # ---- shape / introspection ----

  @property
  def capacity(self) -> int:
    return self._capacity

  @property
  def num_shards(self) -> int:
    return self._num_shards

  @property
  def shard_capacity(self) -> int:
    return self._shard_capacity

  @property
  def transition_spec(self) -> TensorSpecStruct:
    return self._spec

  @property
  def sampling(self) -> str:
    return self._sampling

  def __len__(self) -> int:
    return sum(s.size for s in self._shards)

  def shard_sizes(self) -> Tuple[int, ...]:
    return tuple(s.size for s in self._shards)

  # ---- learner-step plumbing (staleness source) ----

  def set_learner_step(self, step: int) -> None:
    """Tags subsequent adds with the learner's current step (an int
    assignment — safe to call every loop iteration from the trainer
    while actor threads add concurrently)."""
    self._learner_step = int(step)
    self._tm_learner_step.set(self._learner_step)

  @property
  def learner_step(self) -> int:
    return self._learner_step

  # ---- add path ----

  def add(self, transitions: Any,
          priority: Optional[float] = None) -> int:
    """Appends a BATCH of transitions ([N, ...] per key); returns N.

    The whole batch lands on ONE shard (round-robin per call), so an
    add takes exactly one shard lock — concurrent actors adding and the
    learner sampling other shards never serialize on it.
    """
    flat = to_flat_arrays(transitions)
    for key in self._flat_spec:
      if key not in flat:
        raise KeyError(f"Transition batch missing key {key!r}.")
    if priority is not None and priority < 0:
      raise ValueError(
          f"priority must be >= 0 (got {priority}): negative weights "
          "break the prioritized sampler's cumulative draw.")
    n = int(next(iter(flat.values())).shape[0])
    if n == 0:
      return 0
    if n > self._capacity:
      # Legacy total-capacity semantics: only the last `capacity` rows
      # can survive anyway.
      flat = {k: v[-self._capacity:] for k, v in flat.items()}
      n = self._capacity
    if n > self._shard_capacity and self._num_shards > 1:
      # A batch bigger than one shard SPLITS across shards instead of
      # silently truncating rows the total capacity could hold.
      for lo in range(0, n, self._shard_capacity):
        self.add({k: v[lo:lo + self._shard_capacity]
                  for k, v in flat.items()}, priority=priority)
      return n
    if n > self._shard_capacity:
      flat = {k: v[-self._shard_capacity:] for k, v in flat.items()}
      n = self._shard_capacity
    with self._sample_lock:
      shard = self._shards[self._route]
      self._route = (self._route + 1) % self._num_shards
      seq0 = self._add_seq
      self._add_seq += n
    step = self._learner_step
    prio = 1.0 if priority is None else float(priority)
    spill_payload = None
    with shard.lock:
      start = shard.insert
      idx = (start + np.arange(n)) % self._shard_capacity
      evicted = max(0, n - (self._shard_capacity - shard.size))
      if evicted and self._spill_dir:
        # Copy the doomed rows under the lock; the disk write happens
        # AFTER release — a multi-MB np.savez under the shard mutex
        # would stall every sampler/writer on this shard behind
        # filesystem latency.
        spill_idx = idx[n - evicted:]
        spill_payload = {key: native.gather_rows(store, spill_idx)
                         for key, store in shard.storage.items()}
        spill_payload["__add_step"] = shard.add_step[spill_idx].copy()
      for key, store in shard.storage.items():
        native.scatter_rows(store, idx, np.ascontiguousarray(flat[key]))
      shard.add_step[idx] = step
      shard.add_seq[idx] = seq0 + np.arange(n)
      shard.priority[idx] = prio
      shard.insert = int((start + n) % self._shard_capacity)
      shard.size = int(min(shard.size + n, self._shard_capacity))
    if spill_payload is not None:
      self._write_spill(spill_payload)
    with self._stats_lock:
      self.adds_total += n
      self.add_calls += 1
      self.evictions_total += evicted
    # Registry publication (telemetry plane): the same counters the
    # snapshot reports, visible process-wide without a store handle.
    self._tm_adds.inc(n)
    self._tm_fill.set(len(self) / max(self._capacity, 1))
    if evicted:
      self._tm_evictions.inc(evicted)
      _record_event("/t2r/replay/evict")
    return n

  def _write_spill(self, arrays: Dict[str, np.ndarray]) -> None:
    """Persists one batch of evicted rows (no locks held)."""
    os.makedirs(self._spill_dir, exist_ok=True)
    with self._stats_lock:
      chunk = self._spill_chunks
      self._spill_chunks += 1
    path = os.path.join(self._spill_dir, f"spill-{chunk:08d}.npz")
    np.savez(path + ".tmp", **arrays)
    os.replace(path + ".tmp.npz", path)
    with self._stats_lock:
      self.spilled_total += int(arrays["__add_step"].size)

  # ---- sample path ----

  def sample(self, batch_size: int) -> TensorSpecStruct:
    """A batch in the wire spec (metadata dropped)."""
    batch, _, _ = self.sample_with_ages(batch_size)
    return batch

  def sample_with_ages(self, batch_size: int
                       ) -> Tuple[TensorSpecStruct, np.ndarray,
                                  np.ndarray]:
    """(batch, ages_in_learner_steps [B], global_row_ids [B]).

    `ages` is the staleness measurement: learner step NOW minus the
    learner step each sampled row was added at. `global_row_ids`
    (shard * shard_capacity + slot) exist so reproducibility tests can
    digest the exact sample schedule.

    Multi-shard batches are emitted SHARD-MAJOR (rows grouped by
    shard, deterministic given the draw): each shard's slice is one
    contiguous gather under that shard's lock only, so concurrent
    adds/samples on other shards never wait — the whole point of
    sharding. Row order within a uniform/prioritized batch is
    statistically irrelevant; FIFO mode restores global oldest-first
    order (its contract) at the cost of one permutation. The gather
    itself is already striped across cores inside `native.gather_rows`,
    which is why there is no per-shard thread fan-out here.
    """
    with self._sample_lock:
      sizes = [s.size for s in self._shards]
      total = sum(sizes)
      if total == 0:
        raise ValueError("Cannot sample from an empty replay store.")
      if self._sampling == "uniform":
        shard_ids, local = self._draw_uniform(batch_size, sizes, total)
      elif self._sampling == "prioritized":
        shard_ids, local = self._draw_prioritized(batch_size, sizes)
      else:
        # FIFO's oldest-first contract needs a CONSISTENT view of every
        # shard's insert/add_seq while the draw walks them: take all
        # shard locks in index order (no other path holds one shard
        # lock while acquiring another, so the order cannot deadlock)
        # and re-snapshot sizes under them. FIFO is the offline-replay
        # mode; this is not the online hot path.
        for sh in self._shards:
          sh.lock.acquire()
        try:
          sizes = [s.size for s in self._shards]
          shard_ids, local = self._draw_fifo(batch_size, sizes)
        finally:
          for sh in self._shards:
            sh.lock.release()
    now = self._learner_step
    if self._num_shards == 1:
      # The legacy-exact path: one gather, draw order preserved.
      shard = self._shards[0]
      with shard.lock:
        out = {key: native.gather_rows(store, local)
               for key, store in shard.storage.items()}
        ages = now - shard.add_step[local]
        row_ids = local.copy()
    else:
      order = np.argsort(shard_ids, kind="stable")
      sorted_local = local[order]
      out = {key: np.empty((batch_size,) + store.shape[1:],
                           dtype=store.dtype)
             for key, store in self._shards[0].storage.items()}
      ages = np.empty((batch_size,), np.int64)
      row_ids = np.empty((batch_size,), np.int64)
      counts = np.bincount(shard_ids, minlength=self._num_shards)
      lo = 0
      for s in range(self._num_shards):
        hi = lo + int(counts[s])
        if hi == lo:
          continue
        idx = sorted_local[lo:hi]
        shard = self._shards[s]
        with shard.lock:
          for key, store in shard.storage.items():
            # Slice gathers run single-threaded BY DESIGN: a sharded
            # store's parallelism comes from concurrent callers and
            # writers on other shards (that is why you shard) — letting
            # every slice also fan out native threads oversubscribes
            # the cores the concurrent callers are using (measured
            # slower under load). The 1-shard path above keeps the
            # intra-gather striping.
            native.gather_rows(store, idx, out=out[key][lo:hi],
                               num_threads=1)
          ages[lo:hi] = now - shard.add_step[idx]
        row_ids[lo:hi] = s * self._shard_capacity + idx
        lo = hi
      if self._sampling == "fifo":
        # FIFO's contract is global oldest-first: undo the shard-major
        # grouping back to the draw order.
        inverse = np.empty_like(order)
        inverse[order] = np.arange(batch_size)
        out = {key: arr[inverse] for key, arr in out.items()}
        ages = ages[inverse]
        row_ids = row_ids[inverse]
    with self._stats_lock:
      self.samples_total += batch_size
      self.sample_calls += 1
    self._tm_samples.inc(batch_size)
    np.maximum(ages, 0, out=ages)  # adds race the step tag by design
    return TensorSpecStruct.from_flat_dict(out), ages, row_ids

  def _draw_uniform(self, batch: int, sizes: List[int], total: int):
    """One rng call over the live total (the legacy-exact draw)."""
    flat = self._rng.integers(0, total, size=batch)
    if self._num_shards == 1:
      return np.zeros(batch, np.int64), flat
    cum = np.cumsum(sizes)
    shard_ids = np.searchsorted(cum, flat, side="right")
    offsets = cum - np.asarray(sizes)
    return shard_ids, flat - offsets[shard_ids]

  def _draw_prioritized(self, batch: int, sizes: List[int]):
    """Proportional to per-row priority across every live row."""
    parts = []
    for s, shard in enumerate(self._shards):
      if sizes[s]:
        parts.append(shard.priority[:sizes[s]])
    weights = np.concatenate(parts) if parts else np.zeros(0)
    cum = np.cumsum(weights)
    if cum[-1] <= 0:
      flat = self._rng.integers(0, int(sum(sizes)), size=batch)
    else:
      flat = np.searchsorted(cum,
                             self._rng.random(batch) * cum[-1],
                             side="right")
      flat = np.minimum(flat, len(weights) - 1)
    cumsize = np.cumsum(sizes)
    shard_ids = np.searchsorted(cumsize, flat, side="right")
    offsets = cumsize - np.asarray(sizes)
    return shard_ids, flat - offsets[shard_ids]

  def _draw_fifo(self, batch: int, sizes: List[int]):
    """Globally oldest-first by add sequence; wraps when exhausted.

    Per-shard: the oldest live row sits at insert-size (mod cap);
    `cursor` counts rows consumed since then. Each draw takes the
    smallest next add_seq among shards with UNREAD rows; only when
    every live shard is fully read do all cursors reset together — a
    per-shard reset would let a wrapped shard's old rows jump ahead
    of another shard's unread ones.
    """
    shard_ids = np.empty(batch, np.int64)
    local = np.empty(batch, np.int64)
    for i in range(batch):
      if all(self._shards[s].cursor >= sizes[s]
             for s in range(self._num_shards) if sizes[s]):
        for shard in self._shards:
          shard.cursor = 0  # full pass done: restart from the oldest
      best, best_seq = -1, None
      for s, shard in enumerate(self._shards):
        if sizes[s] == 0 or shard.cursor >= sizes[s]:
          continue
        pos = (shard.insert - sizes[s] + shard.cursor) \
            % self._shard_capacity
        seq = shard.add_seq[pos]
        if best_seq is None or seq < best_seq:
          best, best_seq = s, seq
      shard = self._shards[best]
      pos = (shard.insert - sizes[best] + shard.cursor) \
          % self._shard_capacity
      shard_ids[i] = best
      local[i] = pos
      shard.cursor += 1
    return shard_ids, local

  # ---- warmup / metrics ----

  def wait_until_size(self, min_size: int,
                      timeout_secs: Optional[float] = None) -> bool:
    """Blocks until `min_size` transitions are live (actor warmup)."""
    deadline = (time.monotonic() + timeout_secs
                if timeout_secs is not None else None)
    while len(self) < min_size:
      if deadline is not None and time.monotonic() > deadline:
        return False
      time.sleep(0.01)
    return True

  def metrics_snapshot(self) -> Dict[str, float]:
    """Cumulative counters + instantaneous fill; cheap, lock-free."""
    size = len(self)
    return {
        "size": float(size),
        "capacity": float(self._capacity),
        "fill": size / max(self._capacity, 1),
        "num_shards": float(self._num_shards),
        "adds_total": float(self.adds_total),
        "samples_total": float(self.samples_total),
        "evictions_total": float(self.evictions_total),
        "spilled_total": float(self.spilled_total),
        "learner_step": float(self._learner_step),
    }

  def metrics_scalars(self, prefix: str = "replay_") -> Dict[str, float]:
    """Windowed rates since the previous call (the train-log shape:
    one call per log interval alongside `stall_fraction`)."""
    now = time.monotonic()
    t0, adds0, samples0 = self._last_snapshot
    dt = max(now - t0, 1e-9)
    adds, samples = self.adds_total, self.samples_total
    self._last_snapshot = (now, adds, samples)
    size = len(self)
    return {
        f"{prefix}fill": size / max(self._capacity, 1),
        f"{prefix}size": float(size),
        f"{prefix}adds_per_sec": (adds - adds0) / dt,
        f"{prefix}samples_per_sec": (samples - samples0) / dt,
        f"{prefix}evictions_total": float(self.evictions_total),
        f"{prefix}spilled_total": float(self.spilled_total),
    }
