"""ResNet with optional FiLM conditioning.

Reference parity: tensor2robot `layers/resnet.py` (+ film_resnet
variant) — the backbone for grasp2vec embeddings and the larger
grasping models (SURVEY.md §3 "Network layers" row).

TPU-first: NHWC, bfloat16 activations / float32 params, static shapes.
Standard pre-act-free torchvision-style v1 blocks; stage widths are
multiples of 64 so every conv tiles the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.vision_layers import FiLM


class ResNetBlock(nn.Module):
  """Basic 3x3+3x3 residual block (resnet-18/34 style)."""

  filters: int
  strides: Tuple[int, int] = (1, 1)
  use_film: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array,
               conditioning: Optional[jax.Array] = None,
               train: bool = False) -> jax.Array:
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, dtype=self.dtype)
    residual = x
    y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                use_bias=False, dtype=self.dtype, name="conv1")(x)
    y = norm(name="bn1")(y)
    y = nn.relu(y)
    y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                dtype=self.dtype, name="conv2")(y)
    y = norm(scale_init=nn.initializers.zeros, name="bn2")(y)
    if self.use_film and conditioning is not None:
      y = FiLM(dtype=self.dtype, name="film")(y, conditioning)
    if residual.shape != y.shape:
      residual = nn.Conv(self.filters, (1, 1), self.strides,
                         use_bias=False, dtype=self.dtype,
                         name="proj")(residual)
      residual = norm(name="bn_proj")(residual)
    return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
  """1x1-3x3-1x1 bottleneck block (resnet-50 style)."""

  filters: int
  strides: Tuple[int, int] = (1, 1)
  use_film: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array,
               conditioning: Optional[jax.Array] = None,
               train: bool = False) -> jax.Array:
    norm = partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, dtype=self.dtype)
    residual = x
    y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                name="conv1")(x)
    y = nn.relu(norm(name="bn1")(y))
    y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                use_bias=False, dtype=self.dtype, name="conv2")(y)
    y = nn.relu(norm(name="bn2")(y))
    y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                dtype=self.dtype, name="conv3")(y)
    y = norm(scale_init=nn.initializers.zeros, name="bn3")(y)
    if self.use_film and conditioning is not None:
      y = FiLM(dtype=self.dtype, name="film")(y, conditioning)
    if residual.shape != y.shape:
      residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                         use_bias=False, dtype=self.dtype,
                         name="proj")(residual)
      residual = norm(name="bn_proj")(residual)
    return nn.relu(residual + y)


class ResNet(nn.Module):
  """Configurable ResNet; `num_classes=None` returns pooled features.

  `film_conditioning` (a (B, D) vector passed at call time) modulates
  every block when `use_film=True` — the film_resnet variant used by
  conditioned policies.

  `return_spatial=True` additionally returns the final pre-pool feature
  map `(B, H, W, C)` — grasp2vec's localization heatmaps correlate goal
  embeddings against it (reference `research/grasp2vec/` visualization;
  SURVEY.md §3).
  """

  stage_sizes: Sequence[int] = (2, 2, 2, 2)
  num_filters: int = 64
  block_cls: Any = ResNetBlock
  num_classes: Optional[int] = None
  use_film: bool = False
  return_spatial: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, images: jax.Array,
               conditioning: Optional[jax.Array] = None,
               train: bool = False) -> Any:
    x = images.astype(self.dtype)
    x = nn.Conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                use_bias=False, dtype=self.dtype, name="conv_init")(x)
    x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                     dtype=self.dtype, name="bn_init")(x)
    x = nn.relu(x)
    x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
    for i, block_count in enumerate(self.stage_sizes):
      for j in range(block_count):
        strides = (2, 2) if i > 0 and j == 0 else (1, 1)
        x = self.block_cls(
            filters=self.num_filters * 2 ** i,
            strides=strides,
            use_film=self.use_film,
            dtype=self.dtype,
            name=f"stage{i}_block{j}",
        )(x, conditioning=conditioning, train=train)
    spatial = x
    x = jnp.mean(x, axis=(1, 2))
    if self.num_classes is not None:
      x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
    if self.return_spatial:
      return x.astype(jnp.float32), spatial.astype(jnp.float32)
    return x.astype(jnp.float32)


def resnet18(**kwargs) -> ResNet:
  return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=ResNetBlock, **kwargs)


def resnet34(**kwargs) -> ResNet:
  return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=ResNetBlock, **kwargs)


def resnet50(**kwargs) -> ResNet:
  return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock,
                **kwargs)
