"""SNAIL building blocks: causal temporal convolutions + attention.

Reference parity: tensor2robot `layers/snail.py` — the SNAIL
(Mishra et al. 2017) temporal-convolution/attention blocks used by the
meta-learning vrgripper policies (SURVEY.md §3 "Network layers" row).

TPU-first: causal masking is a static lower-triangular mask (no dynamic
shapes), dense blocks use dilated 1D convs which XLA lowers to MXU
matmuls, attention is one fused softmax(QKᵀ)V — all static-shaped so a
single compilation serves every step.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class CausalConv1D(nn.Module):
  """Dilated causal 1D conv over (B, T, C) via left-padding."""

  features: int
  kernel_size: int = 2
  dilation: int = 1
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    pad = self.dilation * (self.kernel_size - 1)
    x = jnp.pad(x, ((0, 0), (pad, 0), (0, 0)))
    return nn.Conv(self.features, (self.kernel_size,),
                   kernel_dilation=(self.dilation,), padding="VALID",
                   dtype=self.dtype)(x)


class DenseBlock(nn.Module):
  """Gated activation causal conv whose output concats onto the input."""

  filters: int
  dilation: int
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    xf = CausalConv1D(self.filters, dilation=self.dilation,
                      dtype=self.dtype, name="filter")(x)
    xg = CausalConv1D(self.filters, dilation=self.dilation,
                      dtype=self.dtype, name="gate")(x)
    activations = jnp.tanh(xf) * nn.sigmoid(xg)
    return jnp.concatenate([x, activations], axis=-1)


class TCBlock(nn.Module):
  """Stack of DenseBlocks with dilations 1, 2, 4, ... covering seq_len."""

  seq_len: int
  filters: int
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    num_layers = max(1, int(math.ceil(math.log2(max(self.seq_len, 2)))))
    for i in range(num_layers):
      x = DenseBlock(self.filters, dilation=2 ** i, dtype=self.dtype,
                     name=f"dense_{i}")(x)
    return x


class AttentionBlock(nn.Module):
  """Single-head causal attention whose output concats onto the input."""

  key_size: int
  value_size: int
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    t = x.shape[1]
    q = nn.Dense(self.key_size, dtype=self.dtype, name="query")(
        x.astype(self.dtype))
    k = nn.Dense(self.key_size, dtype=self.dtype, name="key")(
        x.astype(self.dtype))
    v = nn.Dense(self.value_size, dtype=self.dtype, name="value")(
        x.astype(self.dtype))
    logits = jnp.einsum("btk,bsk->bts", q, k) / math.sqrt(self.key_size)
    mask = jnp.tril(jnp.ones((t, t), dtype=bool))
    logits = jnp.where(mask[None], logits.astype(jnp.float32), -1e30)
    weights = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
    out = jnp.einsum("bts,bsv->btv", weights, v)
    return jnp.concatenate([x, out.astype(x.dtype)], axis=-1)


class SNAIL(nn.Module):
  """The canonical SNAIL trunk: attn -> TC -> attn -> TC -> attn -> proj."""

  seq_len: int
  filters: int = 32
  key_size: int = 64
  value_size: int = 32
  output_size: Optional[int] = None
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    x = AttentionBlock(self.key_size, self.value_size, dtype=self.dtype,
                       name="attn_0")(x)
    x = TCBlock(self.seq_len, self.filters, dtype=self.dtype,
                name="tc_0")(x)
    x = AttentionBlock(self.key_size, self.value_size, dtype=self.dtype,
                       name="attn_1")(x)
    x = TCBlock(self.seq_len, self.filters, dtype=self.dtype,
                name="tc_1")(x)
    x = AttentionBlock(self.key_size, self.value_size, dtype=self.dtype,
                       name="attn_2")(x)
    if self.output_size is not None:
      x = nn.Dense(self.output_size, dtype=self.dtype, name="proj")(
          x.astype(self.dtype))
    return x.astype(jnp.float32)
