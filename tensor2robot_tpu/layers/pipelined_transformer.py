"""Causal transformer trunk whose blocks run as pipeline stages.

The reference has no pipeline parallelism (SURVEY.md §3 marks PP "not
needed for these CNN-scale models"); `parallel/pipeline.py` provides
the GPipe schedule as a library primitive. This module makes it a
FRAMEWORK capability: a drop-in trunk whose depth is split into
`num_stages` equal stages, with the stage weights stacked under one
``stages`` param subtree (the name `pipeline_sharding` keys on) and
the schedule driven by `pipeline_apply`. A gin config can therefore
select a pipelined model + ``sharding_strategy="pipeline"`` and train
through `train_eval_model` with no hand-wiring — the contract the MoE
trunk already has for expert parallelism.

Checkpoint portability: without a mesh (or without a `stage` axis)
`pipeline_apply` falls back to a sequential scan over the SAME stacked
params — identical math, so a pod-trained pipelined checkpoint serves
on one chip unchanged (tests pin the pipelined and sequential outputs
equal to f32 tolerance).

Embedding, learned positions, and the final LayerNorm mirror
`transformer.CausalTransformer`; only the block stack differs (every
stage must be shape-preserving, which pre-LN blocks are).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.layers.transformer import TransformerBlock
from tensor2robot_tpu.parallel.pipeline import (
    init_stage_params,
    pipeline_apply,
)

# Param-name contract `pipeline_sharding` keys on: every leaf under a
# path segment with this name carries a leading [num_stages] dim.
STAGE_PARAMS_NAME = "stages"


class _StageBlocks(nn.Module):
  """One pipeline stage: `blocks_per_stage` pre-LN transformer blocks."""

  blocks_per_stage: int
  num_heads: int
  head_dim: int
  attention_impl: str
  causal: bool
  dtype: Any

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    for i in range(self.blocks_per_stage):
      x = TransformerBlock(
          num_heads=self.num_heads, head_dim=self.head_dim,
          attention_impl=self.attention_impl, causal=self.causal,
          dtype=self.dtype, name=f"block{i}")(x)
    return x


class PipelinedCausalTransformer(nn.Module):
  """Embedding + positions + (depth/num_stages blocks) × num_stages.

  Matches `CausalTransformer`'s input/output contract ([B, T, F] →
  [B, T, width]) so model families can swap trunks by config. The
  stage weights live stacked as one ``stages`` param (leading
  [num_stages] dim on every leaf); with `mesh` carrying a `stage`
  axis of exactly `num_stages` devices, `pipeline_apply` runs the
  GPipe microbatch schedule over it, each device materializing one
  stage. B must divide into `num_microbatches` × the mesh's data-axis
  size (static shapes — the batch comes from specs).
  """

  width: int
  depth: int
  num_heads: int
  max_len: int
  num_stages: int
  num_microbatches: int = 2
  remat: bool = False
  attention_impl: str = "reference"
  causal: bool = True
  mesh: Optional[Any] = None
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
    b, t, _ = x.shape
    if isinstance(t, int) and t > self.max_len:
      raise ValueError(f"sequence length {t} > max_len {self.max_len}")
    if self.width % self.num_heads:
      raise ValueError(
          f"width {self.width} must divide evenly into "
          f"{self.num_heads} heads.")
    if self.num_stages < 1 or self.depth % self.num_stages:
      raise ValueError(
          f"depth {self.depth} must split into num_stages="
          f"{self.num_stages} equal shape-preserving stages.")
    if self.attention_impl in ("ring", "ring_flash"):
      # The stage blocks run INSIDE the stage shard_map; composing a
      # second (sequence-axis) shard_map per stage is not supported —
      # without this guard the mesh silently isn't forwarded and
      # _attend raises a misleading "pass mesh=" error.
      raise ValueError(
          "attention_impl='ring'/'ring_flash' (sequence parallelism) "
          "cannot run inside pipeline stages; use 'flash', "
          "'reference', or 'auto' for the pipelined trunk.")
    head_dim = self.width // self.num_heads

    x = nn.Dense(self.width, dtype=self.dtype, name="embed")(
        x.astype(self.dtype))
    positions = self.param(
        "positions", nn.initializers.normal(0.02),
        (self.max_len, self.width))
    pos_t = jnp.take(positions, jnp.arange(t), axis=0, mode="clip")
    x = x + pos_t[None].astype(self.dtype)

    stage = _StageBlocks(
        blocks_per_stage=self.depth // self.num_stages,
        num_heads=self.num_heads, head_dim=head_dim,
        attention_impl=self.attention_impl, causal=self.causal,
        dtype=self.dtype)
    # One pytree-valued param: every leaf gains a leading [S] dim,
    # nested under the `stages` name — the contract state_sharding's
    # "pipeline" strategy keys on. Init shapes are T-independent
    # (blocks have no positional state), so a minimal sample batch
    # keeps init cheap at any context length.
    sample = jnp.zeros((1, min(8, t), self.width), self.dtype)
    stage_params = self.param(
        STAGE_PARAMS_NAME,
        lambda rng: init_stage_params(
            lambda r: stage.init(r, sample)["params"],
            rng, self.num_stages))
    x = pipeline_apply(
        lambda p, h: stage.apply({"params": p}, h),
        stage_params, x, mesh=self.mesh,
        num_microbatches=self.num_microbatches, remat=self.remat)
    return nn.LayerNorm(dtype=self.dtype, name="ln_out")(
        x).astype(jnp.float32)
