"""Vision building blocks: conv towers, spatial softmax, FiLM.

Reference parity: tensor2robot `layers/vision_layers.py` — the
`BuildImagesToFeaturesModel`-style conv stacks used by the grasping /
pose models, plus spatial-softmax keypoint pooling (SURVEY.md §3
"Network layers" row; exact reference symbols tagged [U] there).

TPU-first design notes:
  * NHWC layout throughout — XLA's TPU conv emitter tiles NHWC convs
    onto the MXU directly.
  * `dtype` parameter everywhere: activations in bfloat16 on TPU while
    params stay float32 (flax default behavior when dtype != param_dtype).
  * Channel counts default to multiples of 8/128 so tensors tile the
    8×128 VPU lanes and 128×128 MXU without padding waste.
  * No python control flow on traced values; everything static-shaped.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class ConvTower(nn.Module):
  """A VGG-ish stack of conv+norm+relu blocks with optional pooling.

  The workhorse image encoder for grasping/pose models (reference's
  images-to-features conv stacks).
  """

  filters: Sequence[int] = (32, 64, 128)
  kernel_sizes: Optional[Sequence[int]] = None  # default 3 everywhere
  strides: Optional[Sequence[int]] = None       # default 2 everywhere
  use_batch_norm: bool = True
  activation: Callable = nn.relu
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, images: jax.Array, train: bool = False) -> jax.Array:
    x = images.astype(self.dtype)
    kernels = self.kernel_sizes or (3,) * len(self.filters)
    strides = self.strides or (2,) * len(self.filters)
    for i, (f, k, s) in enumerate(zip(self.filters, kernels, strides)):
      x = nn.Conv(f, (k, k), strides=(s, s), padding="SAME",
                  use_bias=not self.use_batch_norm, dtype=self.dtype,
                  name=f"conv_{i}")(x)
      if self.use_batch_norm:
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name=f"bn_{i}")(x)
      x = self.activation(x)
    return x


def spatial_softmax(features: jax.Array,
                    temperature: Optional[jax.Array] = None
                    ) -> jax.Array:
  """Soft-argmax keypoints: (B, H, W, C) -> (B, C*2) expected (x, y).

  Reference parity: the spatial-softmax pooling used by the pose /
  vrgripper encoders. Coordinates are in [-1, 1].
  """
  b, h, w, c = features.shape
  # (B, H*W, C): softmax over spatial positions per channel.
  logits = features.reshape(b, h * w, c).astype(jnp.float32)
  if temperature is not None:
    logits = logits / temperature
  probs = jax.nn.softmax(logits, axis=1)
  xs = jnp.linspace(-1.0, 1.0, w)
  ys = jnp.linspace(-1.0, 1.0, h)
  grid_x = jnp.tile(xs[None, :], (h, 1)).reshape(h * w)
  grid_y = jnp.tile(ys[:, None], (1, w)).reshape(h * w)
  exp_x = jnp.einsum("bpc,p->bc", probs, grid_x)
  exp_y = jnp.einsum("bpc,p->bc", probs, grid_y)
  return jnp.concatenate([exp_x, exp_y], axis=-1)


class SpatialSoftmax(nn.Module):
  """Module wrapper around `spatial_softmax` with a learnable temperature."""

  learnable_temperature: bool = True

  @nn.compact
  def __call__(self, features: jax.Array) -> jax.Array:
    if self.learnable_temperature:
      log_temp = self.param("log_temperature", nn.initializers.zeros, ())
      temperature = jnp.exp(log_temp)
    else:
      temperature = None
    return spatial_softmax(features, temperature)


class FiLM(nn.Module):
  """Feature-wise linear modulation: x * (1 + gamma) + beta.

  gamma/beta are projected from a conditioning vector; the (1 + gamma)
  parameterization keeps the identity transform at init.
  """

  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array, conditioning: jax.Array) -> jax.Array:
    channels = x.shape[-1]
    gb = nn.Dense(2 * channels, dtype=self.dtype, name="film_proj")(
        conditioning.astype(self.dtype))
    gamma, beta = jnp.split(gb, 2, axis=-1)
    # Broadcast (B, C) over spatial dims of (B, H, W, C) / (B, T, C).
    while gamma.ndim < x.ndim:
      gamma = gamma[:, None]
      beta = beta[:, None]
    return x * (1.0 + gamma) + beta


class ImageEncoder(nn.Module):
  """ConvTower -> {spatial_softmax | global pool | flatten} -> embedding.

  One-stop image-to-vector encoder matching the common reference pattern
  of conv stack + pooling + dense projection.
  """

  filters: Sequence[int] = (32, 64, 128)
  embedding_size: int = 128
  pooling: str = "spatial_softmax"  # | "mean" | "flatten"
  use_batch_norm: bool = True
  film: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, images: jax.Array,
               conditioning: Optional[jax.Array] = None,
               train: bool = False) -> jax.Array:
    x = ConvTower(filters=self.filters, use_batch_norm=self.use_batch_norm,
                  dtype=self.dtype, name="tower")(images, train=train)
    if self.film and conditioning is not None:
      x = FiLM(dtype=self.dtype, name="film")(x, conditioning)
    if self.pooling == "spatial_softmax":
      x = SpatialSoftmax(name="ssoftmax")(x)
    elif self.pooling == "mean":
      x = jnp.mean(x, axis=(1, 2))
    elif self.pooling == "flatten":
      x = x.reshape(x.shape[0], -1)
    else:
      raise ValueError(f"Unknown pooling: {self.pooling}")
    x = nn.Dense(self.embedding_size, dtype=self.dtype,
                 name="proj")(x.astype(self.dtype))
    return x.astype(jnp.float32)
