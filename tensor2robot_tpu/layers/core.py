"""Core network building blocks shared by the canonical models.

TPU-first conventions: bfloat16-friendly (dtype parameter everywhere,
params stay float32), channel counts that tile the 128×128 MXU, and no
python control flow on traced values.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu.specs import TensorSpecStruct


def flatten_and_concat(features: Any,
                       keys: Optional[Sequence[str]] = None) -> jax.Array:
  """Flattens selected (or all floating) leaves and concats on last axis."""
  if isinstance(features, (dict, TensorSpecStruct)):
    flat = features.to_flat_dict() if isinstance(features, TensorSpecStruct) \
        else dict(features)
    if keys is not None:
      leaves = [flat[k] for k in keys]
    else:
      leaves = [v for v in flat.values()
                if jnp.issubdtype(v.dtype, jnp.floating)]
  else:
    leaves = [features]
  batch = leaves[0].shape[0]
  return jnp.concatenate(
      [leaf.reshape(batch, -1) for leaf in leaves], axis=-1)


class MLP(nn.Module):
  """Plain MLP; optionally applies to a feature struct via key selection."""

  hidden_sizes: Sequence[int]
  output_size: Optional[int] = None
  activation: Callable = nn.relu
  dropout_rate: float = 0.0
  activate_final: bool = False
  feature_keys: Optional[Sequence[str]] = None
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, features, train: bool = False):
    x = flatten_and_concat(features, self.feature_keys)
    x = x.astype(self.dtype)
    sizes = list(self.hidden_sizes)
    if self.output_size is not None:
      sizes.append(self.output_size)
    for i, size in enumerate(sizes):
      x = nn.Dense(size, dtype=self.dtype, name=f"dense_{i}")(x)
      is_last = i == len(sizes) - 1
      if not is_last or self.activate_final:
        x = self.activation(x)
        if self.dropout_rate > 0:
          x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
    return x.astype(jnp.float32)
