"""Network building blocks (reference: tensor2robot layers/)."""

from tensor2robot_tpu.layers.core import MLP, flatten_and_concat
from tensor2robot_tpu.layers.vision_layers import (
    ConvTower,
    FiLM,
    ImageEncoder,
    SpatialSoftmax,
    spatial_softmax,
)
from tensor2robot_tpu.layers.resnet import (
    BottleneckBlock,
    ResNet,
    ResNetBlock,
    resnet18,
    resnet34,
    resnet50,
)
from tensor2robot_tpu.layers.mdn import (
    MDNHead,
    MDNParams,
    mdn_log_prob,
    mdn_loss,
    mdn_mean,
    mdn_mode,
    mdn_sample,
)
from tensor2robot_tpu.layers.snail import (
    AttentionBlock,
    CausalConv1D,
    DenseBlock,
    SNAIL,
    TCBlock,
)
from tensor2robot_tpu.layers.transformer import (
    CausalTransformer,
    MultiHeadAttention,
    TransformerBlock,
)
from tensor2robot_tpu.layers.pipelined_transformer import (
    PipelinedCausalTransformer,
)
