"""Mixture-density-network head for continuous action policies.

Reference parity: tensor2robot `layers/mdn.py` — the MDN output head
used by vrgripper behavioral-cloning policies (SURVEY.md §3 "Network
layers" row). The reference leaned on tensorflow_probability; here the
diagonal-Gaussian mixture math is written directly in jnp (logsumexp),
which XLA fuses into the surrounding network — no tfp dependency.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

_LOG_2PI = math.log(2.0 * math.pi)


class MDNParams(NamedTuple):
  """Mixture parameters: shapes (..., K), (..., K, D), (..., K, D)."""

  logits: jax.Array
  means: jax.Array
  log_scales: jax.Array


class MDNHead(nn.Module):
  """Projects features to mixture params over `output_size` dims."""

  num_components: int
  output_size: int
  min_log_scale: float = -5.0
  max_log_scale: float = 2.0
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, features: jax.Array) -> MDNParams:
    k, d = self.num_components, self.output_size
    raw = nn.Dense(k * (1 + 2 * d), dtype=self.dtype,
                   name="mdn_proj")(features.astype(self.dtype))
    raw = raw.astype(jnp.float32)
    logits = raw[..., :k]
    means = raw[..., k:k + k * d].reshape(*raw.shape[:-1], k, d)
    log_scales = raw[..., k + k * d:].reshape(*raw.shape[:-1], k, d)
    log_scales = jnp.clip(log_scales, self.min_log_scale,
                          self.max_log_scale)
    return MDNParams(logits, means, log_scales)


def mdn_log_prob(params: MDNParams, targets: jax.Array) -> jax.Array:
  """log p(targets) under the mixture; targets (..., D) -> (...)."""
  t = targets[..., None, :]  # broadcast over components
  inv_scales = jnp.exp(-params.log_scales)
  z = (t - params.means) * inv_scales
  comp_lp = -0.5 * jnp.sum(z * z + _LOG_2PI, axis=-1) - jnp.sum(
      params.log_scales, axis=-1)
  mix_lp = jax.nn.log_softmax(params.logits, axis=-1)
  return jax.nn.logsumexp(mix_lp + comp_lp, axis=-1)


def mdn_loss(params: MDNParams, targets: jax.Array) -> jax.Array:
  """Mean negative log likelihood."""
  return -jnp.mean(mdn_log_prob(params, targets))


def mdn_mode(params: MDNParams) -> jax.Array:
  """Mean of the most likely component — the standard greedy action."""
  best = jnp.argmax(params.logits, axis=-1)
  return jnp.take_along_axis(
      params.means, best[..., None, None], axis=-2).squeeze(-2)


def mdn_mean(params: MDNParams) -> jax.Array:
  """Full mixture mean."""
  weights = jax.nn.softmax(params.logits, axis=-1)
  return jnp.sum(weights[..., None] * params.means, axis=-2)


def mdn_sample(params: MDNParams, rng: jax.Array) -> jax.Array:
  """Draws one sample per leading batch element."""
  rng_k, rng_eps = jax.random.split(rng)
  comp = jax.random.categorical(rng_k, params.logits, axis=-1)
  means = jnp.take_along_axis(params.means, comp[..., None, None],
                              axis=-2).squeeze(-2)
  log_scales = jnp.take_along_axis(params.log_scales,
                                   comp[..., None, None],
                                   axis=-2).squeeze(-2)
  eps = jax.random.normal(rng_eps, means.shape)
  return means + jnp.exp(log_scales) * eps
