"""Causal transformer trunk with pluggable attention backends.

The reference's sequence models were SNAIL-style causal convs +
single-head attention over short episodes (`layers/snail.py` parity
module); this trunk is the long-context counterpart the TPU stack
makes first-class: the same module scales from short demo episodes to
32k-step contexts by swapping the attention implementation —

  * "reference": materialized softmax attention (CPU tests, short T),
  * "flash": the Pallas O(T)-memory kernel (`ops/flash_attention.py`),
  * "ring": sequence-parallel across chips
    (`parallel/ring_attention.py`; requires `mesh`). On TPU the
    per-device blocks run the flash kernel, whose lse output is
    differentiable — training through the ring works,
  * "ring_flash": the ring with flash blocks forced on (interpret
    mode off-TPU) — the CPU-testable spelling of the TPU ring path,
  * "auto": flash on TPU, reference elsewhere.

All backends compute EXACT attention in forward AND backward, so
checkpoints are portable across them (train with ring on a pod, serve
with flash on one chip).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _attend(q, k, v, *, impl: str, causal: bool, mesh) -> jax.Array:
  """Dispatches [B, T, H, D] attention to the chosen backend."""
  from tensor2robot_tpu.ops import flash_attention
  from tensor2robot_tpu.parallel import (
      attention_reference,
      ring_attention,
  )

  on_tpu = jax.devices()[0].platform == "tpu"
  if impl == "auto":
    impl = "flash" if on_tpu else "reference"
  if impl == "flash":
    return flash_attention(q, k, v, causal=causal)
  if impl in ("ring", "ring_flash"):
    if mesh is None:
      raise ValueError(
          f"attention_impl={impl!r} needs a device mesh with a "
          "'seq' axis; pass mesh= (models: the mesh constructor "
          "argument) or use 'flash'/'reference' single-device.")
    # On TPU the ring runs the flash kernel within each chip
    # (partials combined by logsumexp over the ICI ring);
    # "ring_flash" forces that composition off-TPU too, via the
    # pallas interpreter — how CPU tests cover the production path.
    use_flash = on_tpu or impl == "ring_flash"
    return ring_attention(q, k, v, mesh=mesh, causal=causal,
                          block_impl="flash" if use_flash
                          else "reference",
                          flash_interpret=use_flash and not on_tpu)
  if impl == "reference":
    return attention_reference(q, k, v, causal=causal)
  raise ValueError(f"Unknown attention impl: {impl!r}")


class MultiHeadAttention(nn.Module):
  """QKV projections around a pluggable exact-attention backend."""

  num_heads: int
  head_dim: int
  attention_impl: str = "reference"
  causal: bool = True
  mesh: Optional[Any] = None
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
    b, t, _ = x.shape
    h, d = self.num_heads, self.head_dim
    x = x.astype(self.dtype)
    qkv = nn.Dense(3 * h * d, use_bias=False, dtype=self.dtype,
                   name="qkv")(x)
    q, k, v = jnp.split(qkv.reshape(b, t, 3 * h, d), 3, axis=2)
    out = _attend(q, k, v, impl=self.attention_impl,
                  causal=self.causal, mesh=self.mesh)
    out = out.reshape(b, t, h * d)
    return nn.Dense(x.shape[-1], dtype=self.dtype, name="proj")(out)


class TransformerBlock(nn.Module):
  """Pre-LN block: x + MHA(LN(x)); x + MLP(LN(x)).

  With `moe_experts > 0` the dense MLP becomes a MoE layer
  (`parallel/moe.py`): routed capacity scales with expert count, not
  per-token FLOPs, and with a mesh `expert` axis the experts run
  expert-parallel. Dropped-token rows pass through on the residual —
  the Switch-transformer semantics.
  """

  num_heads: int
  head_dim: int
  mlp_ratio: int = 4
  attention_impl: str = "reference"
  causal: bool = True
  mesh: Optional[Any] = None
  dtype: Any = jnp.bfloat16
  moe_experts: int = 0
  moe_k: int = 2
  moe_capacity_factor: float = 2.0

  @nn.compact
  def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
    width = x.shape[-1]
    y = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
    x = x + MultiHeadAttention(
        num_heads=self.num_heads, head_dim=self.head_dim,
        attention_impl=self.attention_impl, causal=self.causal,
        mesh=self.mesh, dtype=self.dtype, name="attn")(y, train=train)
    y = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x)
    if self.moe_experts:
      from tensor2robot_tpu.parallel.moe import MoEMLP
      y = MoEMLP(
          num_experts=self.moe_experts,
          hidden_dim=width * self.mlp_ratio, k=self.moe_k,
          capacity_factor=self.moe_capacity_factor, mesh=self.mesh,
          dtype=self.dtype, name="moe")(y)
    else:
      y = nn.Dense(width * self.mlp_ratio, dtype=self.dtype,
                   name="mlp_in")(y)
      y = nn.gelu(y)
      y = nn.Dense(width, dtype=self.dtype, name="mlp_out")(y)
    return x + y


class CausalTransformer(nn.Module):
  """Embedding + learned positions + N blocks + final LN.

  Input: per-step feature vectors [B, T, F]; output [B, T, width].
  `max_len` bounds the learned positional table (positions are static
  in this framework — episode/context lengths come from specs).
  """

  width: int
  depth: int
  num_heads: int
  max_len: int
  attention_impl: str = "reference"
  causal: bool = True
  mesh: Optional[Any] = None
  dtype: Any = jnp.bfloat16
  # MoE: every `moe_every`-th block (1-indexed from the top of each
  # group) swaps its dense MLP for `moe_experts` routed experts; 0
  # disables. The GShard convention is every-other-block (moe_every=2).
  moe_experts: int = 0
  moe_every: int = 2
  moe_k: int = 2
  moe_capacity_factor: float = 2.0

  @nn.compact
  def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
    b, t, _ = x.shape
    # isinstance guard: under jax2tf shape polymorphism (the export
    # path) t is a symbolic dimension and the comparison would be
    # inconclusive. There is NO loud serving-side length check — an
    # exported graph fed t > max_len silently clips to the last
    # learned position (see the mode="clip" note below); in-process
    # callers get this ValueError.
    if isinstance(t, int) and t > self.max_len:
      raise ValueError(f"sequence length {t} > max_len {self.max_len}")
    if self.width % self.num_heads:
      raise ValueError(
          f"width {self.width} must divide evenly into "
          f"{self.num_heads} heads (got remainder "
          f"{self.width % self.num_heads}); attention would silently "
          "run at reduced capacity otherwise.")
    head_dim = self.width // self.num_heads
    x = nn.Dense(self.width, dtype=self.dtype, name="embed")(
        x.astype(self.dtype))
    positions = self.param(
        "positions", nn.initializers.normal(0.02),
        (self.max_len, self.width))
    # iota-gather instead of positions[:t]: basic slicing rejects the
    # symbolic t of the jax2tf-polymorphic export path, while a
    # dimension-sized arange is supported. mode="clip": in an exported
    # graph a t > max_len request repeats the last learned position
    # (predictable degradation) rather than jnp.take's default
    # fill-with-NaN; in-process callers still get the loud ValueError
    # from the isinstance guard above.
    pos_t = jnp.take(positions, jnp.arange(t), axis=0, mode="clip")
    x = x + pos_t[None].astype(self.dtype)
    for i in range(self.depth):
      is_moe = (self.moe_experts > 0
                and (i + 1) % max(self.moe_every, 1) == 0)
      x = TransformerBlock(
          num_heads=self.num_heads, head_dim=head_dim,
          attention_impl=self.attention_impl, causal=self.causal,
          mesh=self.mesh, dtype=self.dtype, name=f"block{i}",
          moe_experts=self.moe_experts if is_moe else 0,
          moe_k=self.moe_k,
          moe_capacity_factor=self.moe_capacity_factor,
      )(x, train=train)
    return nn.LayerNorm(dtype=self.dtype, name="ln_out")(
        x).astype(jnp.float32)
