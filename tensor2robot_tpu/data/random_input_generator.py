"""Random spec-conforming input generator — the framework test backbone.

Reference parity: tensor2robot `input_generators/default_input_generator.py`
`DefaultRandomInputGenerator` (SURVEY.md §3, §5): generates random batches
that conform to the model's declared specs, so the entire
train/eval/export path can run without any dataset on disk.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.specs import TensorSpecStruct


@gin.configurable
class RandomInputGenerator(AbstractInputGenerator):
  """Yields random batches conforming to the bound specs, forever."""

  def __init__(self, batch_size: int = 32, sequence_length: int = 3,
               seed: int = 0):
    super().__init__(batch_size=batch_size)
    self._sequence_length = sequence_length
    self._seed = seed

  def _create_dataset(
      self, mode: Mode, batch_size: int,
  ) -> Iterator[Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]]:
    feature_spec = self.feature_spec
    label_spec = self.label_spec
    seed = self._seed
    step = 0
    while True:
      features = specs.make_random_tensors(
          feature_spec, batch_size=batch_size,
          sequence_length=self._sequence_length,
          seed=seed + step, include_optional=False)
      labels = None
      if label_spec is not None:
        labels = specs.make_random_tensors(
            label_spec, batch_size=batch_size,
            sequence_length=self._sequence_length,
            seed=seed + step + 7919, include_optional=False)
      yield features, labels
      step += 1


# Reference-compatible alias.
DefaultRandomInputGenerator = RandomInputGenerator
