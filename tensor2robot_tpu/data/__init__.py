"""Spec-driven input pipelines (reference: tensor2robot input_generators/)."""

from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.data.random_input_generator import (
    DefaultRandomInputGenerator,
    RandomInputGenerator,
)
from tensor2robot_tpu.data.tfrecord_input_generator import (
    DefaultRecordInputGenerator,
    TFRecordEpisodeInputGenerator,
    TFRecordInputGenerator,
    write_episode_tfrecord,
    write_tfrecord,
)
from tensor2robot_tpu.data.prefetch import (
    ShardedPrefetcher,
    device_put_batch,
    make_data_sharding,
    prefetch_to_mesh,
)
