"""Spec-driven input pipelines (reference: tensor2robot input_generators/).

Exports resolve LAZILY (PEP 562): data-plane worker processes import
`tensor2robot_tpu.data.plane` at spawn, and an eager package init would
drag `prefetch`'s jax import (seconds of spin-up per worker) into
processes that only parse and memcpy. Consumers see the same names;
only the import moment moves.

Gin registration must NOT move with it: `run_t2r_trainer` parses
shipped configs right after `importlib.import_module
("tensor2robot_tpu.data")`, before any attribute access, so the
`@gin.configurable` names are declared below via
`register_lazy_configurables` — the first config reference imports the
defining submodule (registering it) instead of failing unregistered.
"""

from tensor2robot_tpu import config as _gin

_EXPORTS = {
    "AbstractInputGenerator": "abstract_input_generator",
    "Mode": "abstract_input_generator",
    "DefaultRandomInputGenerator": "random_input_generator",
    "RandomInputGenerator": "random_input_generator",
    "DefaultRecordInputGenerator": "tfrecord_input_generator",
    "TFRecordEpisodeInputGenerator": "tfrecord_input_generator",
    "TFRecordInputGenerator": "tfrecord_input_generator",
    "write_episode_tfrecord": "tfrecord_input_generator",
    "write_tfrecord": "tfrecord_input_generator",
    "ShardedPrefetcher": "prefetch",
    "TimedIterator": "prefetch",
    "device_put_batch": "prefetch",
    "make_data_sharding": "prefetch",
    "prefetch_to_mesh": "prefetch",
    "stack_batches": "prefetch",
    "HostDataPlane": "plane",
    "ShmRing": "shm_ring",
    "WireLayout": "shm_ring",
}

__all__ = sorted(_EXPORTS)

for _name, _mod in (("RandomInputGenerator", "random_input_generator"),
                    ("TFRecordInputGenerator", "tfrecord_input_generator"),
                    ("TFRecordEpisodeInputGenerator",
                     "tfrecord_input_generator"),
                    ("prefetch_buffer_size", "prefetch"),
                    ("HostDataPlane", "plane")):
  _gin.register_lazy_configurables(f"{__name__}.{_mod}", (_name,))
del _name, _mod


def __getattr__(name):
  module_name = _EXPORTS.get(name)
  if module_name is None:
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
  import importlib

  module = importlib.import_module(f"{__name__}.{module_name}")
  value = getattr(module, name)
  globals()[name] = value  # cache: next access skips __getattr__
  return value


def __dir__():
  return __all__
