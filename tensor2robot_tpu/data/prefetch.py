"""Host→device pipelining: sharded, double-buffered batch placement.

This replaces the reference's TPUEstimator infeed queue (SURVEY.md §4.1
"host↔device boundary is the infeed queue fed by tf.data"). TPU-native
version: each host batch is placed onto the mesh as a global `jax.Array`
sharded along the data axis via `jax.make_array_from_process_local_data`
(multi-host correct: each process contributes its local shard), with a
lookahead buffer so device compute of step N overlaps host prep + H2D
transfer of step N+1.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Iterator, Optional

import jax
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.telemetry import metrics as tmetrics


@gin.configurable
def prefetch_buffer_size(buffer_size: Optional[int] = None,
                         online: bool = False,
                         offline_default: int = 2,
                         online_default: int = 1) -> int:
  """Resolves the `ShardedPrefetcher` lookahead depth (gin tunable).

  Depth trades throughput for sampling lead: each buffered dispatch is
  a batch sampled BEFORE the steps ahead of it ran, so an ONLINE run
  (actors feeding replay while the learner trains) pays `depth × K`
  extra steps of staleness per buffered dispatch. The online default is
  therefore 1 — the K>1 online sampling-lead finding from round 5 —
  while offline streams (logged episodes, prefill_random), where sample
  timing is irrelevant, keep double-buffering. An explicit
  `buffer_size` (arg or gin) always wins.
  """
  if buffer_size is not None:
    if buffer_size < 1:
      raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
    return int(buffer_size)
  return int(online_default if online else offline_default)


def make_data_sharding(mesh: jax.sharding.Mesh,
                       data_axes=("data",)) -> jax.sharding.NamedSharding:
  """Batch-dim sharding over the mesh's data axes, replicated elsewhere."""
  axes = tuple(a for a in data_axes if a in mesh.axis_names)
  spec = jax.sharding.PartitionSpec(axes if axes else None)
  return jax.sharding.NamedSharding(mesh, spec)


def validate_steps_per_dispatch(k: int, **cadences: Optional[int]
                                ) -> int:
  """Checks the iterations_per_loop quantization contract.

  Every named cadence (log/checkpoint/eval/max-steps) must be a
  multiple of K — boundaries are only observable between dispatches.
  Shared by both trainers so the contract cannot silently diverge.
  Returns k. None-valued cadences are skipped.
  """
  k = int(k)
  if k < 1:
    raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
  if k > 1:
    for name, value in cadences.items():
      if value and value % k:
        raise ValueError(
            f"{name}={value} must be a multiple of "
            f"steps_per_dispatch={k} (the iterations_per_loop "
            "quantization: boundaries are only observable between "
            "dispatches).")
  return k


class StackedBatchStream:
  """Groups K consecutive batches into one [K, B, ...]-stacked pytree.

  The host side of `steps_per_dispatch`: the trainer's scan consumes
  one stacked block per device program. A finite stream that runs dry
  mid-stack ends the output stream cleanly (the partial stack is
  dropped) and the drop is LOGGED: a dataset whose length isn't a
  multiple of K trains up to K-1 fewer steps than K=1 would, and that
  must not be silent.

  A class rather than a generator so `close()` works CROSS-THREAD: the
  inner stream may own real resources — a data-plane stream owns worker
  PROCESSES — and `ShardedPrefetcher.close` must be able to reach them
  from the consumer thread while the prefetch thread is still blocked
  inside `__next__` (a generator would refuse with "generator already
  executing"; closing the plane instead UNBLOCKS that thread).
  """

  def __init__(self, stream: Iterator[Any], k: int):
    self._it = iter(stream)
    self._k = int(k)
    self._exhausted = False

  def __iter__(self):
    return self

  def __next__(self):
    if self._exhausted:
      raise StopIteration
    batches = []
    for _ in range(self._k):
      try:
        batches.append(next(self._it))
      except StopIteration:
        self._exhausted = True
        if batches:
          import logging

          logging.getLogger(__name__).warning(
              "steps_per_dispatch=%d dropped a partial tail of %d "
              "batch(es): the finite input stream's length is not a "
              "multiple of K, so this run trains %d fewer step(s) "
              "than K=1 would.", self._k, len(batches), len(batches))
        self.close()  # the inner stream is done: release it now
        raise
    return jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *batches)

  def close(self) -> None:
    closer = getattr(self._it, "close", None)
    if callable(closer):
      closer()


def stack_batches(stream: Iterator[Any], k: int) -> StackedBatchStream:
  return StackedBatchStream(stream, k)


def scan_k_steps(step_fn, state, stacked_batches, rng, step0):
  """K train steps as one traced program (the dispatch body both
  trainers jit — shared so the iterations_per_loop semantics cannot
  diverge between them, the same reason `validate_steps_per_dispatch`
  is shared).

  Args:
    step_fn: (state, *batch_parts, rng) → (state, metrics) — the
      per-step train function.
    state: the carried TrainState (donated by the caller's jit).
    stacked_batches: TUPLE of [K, B, ...]-stacked pytrees; scanned
      together, so each scan step sees the tuple's per-step slices.
    rng: the per-run step PRNG base key.
    step0: absolute step of the dispatch's first step; each scanned
      step folds `rng` by `step0 + i` — the per-step PRNG stream is
      IDENTICAL to K=1 (the equivalence both trainers' tests pin).

  Returns (state, last step's metrics) — hooks/logging observe only
  each dispatch's final step, the TPUEstimator quantization contract.
  """
  from jax import numpy as jnp

  def body(carry, xs):
    st, i = carry
    st, metrics = step_fn(*((st,) + xs),
                          jax.random.fold_in(rng, step0 + i))
    return (st, i + 1), metrics

  (state, _), metrics_seq = jax.lax.scan(
      body, (state, jnp.zeros((), jnp.int32)), stacked_batches)
  return state, jax.tree_util.tree_map(lambda m: m[-1], metrics_seq)


def stacked_sharding(sharding: jax.sharding.NamedSharding
                     ) -> jax.sharding.NamedSharding:
  """The [K, B, ...]-stacked twin of a batch sharding: the batch dim's
  spec shifts right one position (K is never sharded)."""
  return jax.sharding.NamedSharding(
      sharding.mesh, jax.sharding.PartitionSpec(None, *sharding.spec))


def device_put_batch(batch: Any, sharding: jax.sharding.Sharding) -> Any:
  """Places a pytree of host numpy arrays as global sharded jax.Arrays."""

  def put(x):
    x = np.asarray(x)
    # Batch-axis sharding only applies to arrays with a batch dim; scalars
    # replicate.
    if x.ndim == 0:
      return jax.device_put(x)
    return jax.make_array_from_process_local_data(sharding, x)

  return jax.tree_util.tree_map(put, batch)


class ShardedPrefetcher:
  """Iterator wrapper: host batches → mesh-sharded arrays, N steps ahead.

  A background thread pulls from the (possibly slow: TFRecord parse,
  image decode) host iterator and performs the H2D transfer, keeping up
  to `buffer_size` global batches resident ahead of compute. This is the
  framework's single host↔device seam; everything downstream is jitted.
  """

  def __init__(self,
               iterator: Iterator[Any],
               sharding: jax.sharding.Sharding,
               buffer_size: int = 2):
    self._iterator = iterator
    self._sharding = sharding
    self._buffer_size = buffer_size
    self._queue: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    self._done = object()
    self._error: Optional[BaseException] = None
    self._stop = threading.Event()
    self._thread = threading.Thread(target=self._worker, daemon=True)
    self._thread.start()

  def _worker(self):
    # Zero-copy source protocol (data-plane streams): batches are
    # views into a shared-memory ring; the slot may only recycle once
    # the device owns the bytes, so block on the transfer, then
    # release. Sources without the protocol are unaffected.
    release = None
    if getattr(self._iterator, "release_after_transfer", False):
      release = getattr(self._iterator, "release_consumed", None)
    try:
      for batch in self._iterator:
        placed = device_put_batch(batch, self._sharding)
        if release is not None:
          jax.block_until_ready(placed)
          release()
        # Bounded put that notices close(): don't block forever holding
        # device buffers once the consumer abandoned the stream.
        while not self._stop.is_set():
          try:
            self._queue.put(placed, timeout=0.1)
            break
          except queue.Full:
            continue
        if self._stop.is_set():
          return
    except BaseException as e:  # surfaced on the consumer thread
      self._error = e
    finally:
      # The sentinel must reach the consumer (or close() must have been
      # called) or __next__ would block forever; bounded-put like above.
      while not self._stop.is_set():
        try:
          self._queue.put(self._done, timeout=0.1)
          break
        except queue.Full:
          continue

  def _close_source(self) -> bool:
    """Closes the input stream; True unless it must be retried.

    A plain generator refuses a cross-thread close while the prefetch
    thread is executing it (ValueError: generator already executing) —
    that is the one retryable outcome. Data-plane chains
    (`HostDataPlane` / `_PlaneStream` / `StackedBatchStream`) close
    from any thread.
    """
    closer = getattr(self._iterator, "close", None)
    if not callable(closer):
      return True
    try:
      closer()
      return True
    except ValueError:  # generator running in the prefetch thread
      return False
    except Exception:  # pragma: no cover - teardown must not raise
      import logging
      logging.getLogger(__name__).warning(
          "input stream close() failed", exc_info=True)
      return True

  def close(self, timeout_secs: float = 5.0) -> None:
    """Stops the worker and releases buffered device batches.

    Call when abandoning the stream early (e.g. bounded eval over an
    infinite generator); otherwise the worker thread would sit blocked
    holding `buffer_size` device-resident batches. Closes the source
    too: data-plane streams own worker PROCESSES and a shared-memory
    segment — abandoning the prefetcher must not leak them (pinned by
    tests/test_data_plane.py).
    """
    self._stop.set()
    while True:
      try:
        self._queue.get_nowait()
      except queue.Empty:
        break
    self._thread.join(timeout=timeout_secs)
    if self._thread.is_alive():
      # The thread is stuck inside next(source) — e.g. a starved
      # HostDataPlane polling its full queue, which no stop flag of
      # OURS interrupts. Closing the source from here UNBLOCKS it
      # (plane close terminates workers; the blocked __next__ raises),
      # so the join below reclaims the thread instead of leaking the
      # whole chain behind a 5s shrug.
      closed = self._close_source()
      self._thread.join(timeout=timeout_secs)
      if not closed and not self._thread.is_alive():
        closed = self._close_source()  # generator now suspended: retry
      if not closed:
        import logging
        logging.getLogger(__name__).warning(
            "input stream close() could not run: the prefetch thread "
            "is still executing the source generator; its resources "
            "may leak until process exit")
    else:
      self._close_source()

  def __iter__(self):
    return self

  def __next__(self):
    # Timed-slice get: a bare `get()` would strand this consumer
    # forever if `close()` ran between the empty-queue check and the
    # block — close() drains the queue and the worker's bounded
    # sentinel-put gives up once `_stop` is set, so nothing would ever
    # arrive to wake a blocked consumer (found by t2rcheck CON302).
    while True:
      if self._stop.is_set():
        raise StopIteration
      try:
        item = self._queue.get(timeout=0.1)
        break
      except queue.Empty:
        continue
    if item is self._done:
      if self._error is not None:
        raise self._error
      raise StopIteration
    return item


class TimedIterator:
  """Iterator wrapper accumulating wall time spent blocked in `next()`.

  The `input_wait_fraction` measurement both trainers log: near 0 the
  feed keeps up (the device is the bottleneck); toward 1 the chip
  starves — the continuously-measured form of the bench's `feeds_chip`
  verdict. Shared here so the two train loops' feed-boundness metric
  cannot drift apart. Raise `TFRecordInputGenerator.num_workers` (the
  process-parallel data plane, docs/DATA.md) when it climbs.
  """

  def __init__(self, iterator: Iterator[Any]):
    self._it = iter(iterator)
    self.wait_secs = 0.0

  def __iter__(self):
    return self

  def __next__(self):
    t0 = time.perf_counter()
    try:
      return next(self._it)
    finally:
      self.wait_secs += time.perf_counter() - t0

  def wait_fraction(self, interval_secs: float) -> float:
    """Clamped share of `interval_secs` spent blocked; resets the
    accumulator (one call per log interval)."""
    fraction = min(max(self.wait_secs / max(interval_secs, 1e-9), 0.0),
                   1.0)
    self.wait_secs = 0.0
    # Registry publication: the telemetry-plane twin of the train
    # log's input_wait_fraction (one gauge set per log interval).
    tmetrics.gauge("input.wait_fraction").set(fraction)
    return fraction


def prefetch_to_mesh(iterator: Iterator[Any],
                     mesh: jax.sharding.Mesh,
                     data_axes=("data",),
                     buffer_size: int = 2) -> ShardedPrefetcher:
  return ShardedPrefetcher(
      iterator, make_data_sharding(mesh, data_axes), buffer_size)
