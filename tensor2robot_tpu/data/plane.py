"""Process-parallel host data plane: N decode workers → shm ring → one
consumer stream.

WHY: the committed input benches (BENCH_DETAIL.json `input_pipeline*`)
show the tf.data pipeline capping out around one core's worth of
decode — and `decode_scaling` shows threads can't fix it (2-process
aggregate ≈ 1-process in-process: the GIL plus TF intra-op contention).
The Podracer lesson (arXiv:2104.06272) is that TPU utilization is a
host-side data-plane problem: decouple a scalable host plane from
device compute. This module is that plane's local form — the same
fan-in shape the replay service uses for actors, applied to file-backed
input:

    worker 0 ─┐ (own process: parse+decode its file shard)
    worker 1 ─┼─ shm ring (finished batches, zero-copy) ─→ assembler
    worker N ─┘                                            (consumer)

Each worker owns a DETERMINISTIC shard of the file list (files[i::N]),
runs the ordinary graph-parse tf.data pipeline over it, and memcpys
each finished batch into a free ring slot. The consumer's `__next__`
pops finished slots and returns numpy views INTO the ring — no copy on
the hot path (`copy=True` trades one memcpy for an unconditional
lifetime: see `h2d_aliases_host_memory` for when that trade is
mandatory).

Failure semantics mirror `replay.service` (same latch-and-re-raise
discipline):
  * a worker EXCEPTION ships its traceback through the full queue, is
    latched, and re-raises in the consumer on this and every later
    `__next__`;
  * a worker DEATH without a message (segfault, kill) is detected by
    exit-code polling and latched the same way;
  * `close()` always terminates workers — including workers blocked
    waiting for a free slot (they poll a stop event) — and unlinks the
    shared segment. Close is idempotent and safe to call with the
    stream mid-flight.

Ordering: batches arrive in ring-completion order. With ONE worker that
order is the worker's own pipeline order, which is why
`num_workers ∈ {0, 1}` can promise a bitwise-identical stream under a
fixed seed (pinned in tests/test_data_plane.py); with N > 1 workers
arrival order is load-dependent and only the per-worker suborder is
deterministic.
"""

from __future__ import annotations

import logging
import multiprocessing
import queue as queue_lib
import time
import traceback
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.shm_ring import ShmRing, WireLayout
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Queue message tags (worker → consumer).
_BATCH, _DONE, _ERROR = "batch", "done", "error"


def h2d_aliases_host_memory() -> bool:
  """Does `jax.device_put` of page-aligned host memory ALIAS it?

  On the CPU backend XLA zero-copies suitably aligned numpy buffers —
  measured here: a device_put of a shared-memory-backed array tracks
  later writes to the segment. Recycling a ring slot would then mutate
  a "device" batch in flight, so consumers feeding jax on CPU must
  copy out of the ring. On TPU/GPU the H2D DMA lands in device memory;
  once the transfer completes the host view is dead weight and the
  slot can be recycled (the `release_after_transfer` protocol in
  `ShardedPrefetcher`).
  """
  try:
    import jax
    return jax.default_backend() == "cpu"
  except Exception:  # pragma: no cover - no jax in a pure host tool
    return True  # be safe: copy


def _worker_main(source: Callable[[int, int], Iterator[Dict[str, np.ndarray]]],
                 worker_index: int, num_workers: int, ring_name: str,
                 layout: WireLayout, num_slots: int, free_q, full_q,
                 stop) -> None:
  """Worker process body: stream batches from `source` into the ring.

  `source(worker_index, num_workers)` must yield flat dicts conforming
  to `layout`. Every blocking acquire polls `stop` so `close()` can
  always reclaim a worker stuck on a full ring.
  """
  ring = None
  try:
    ring = ShmRing.attach(ring_name, layout, num_slots)
    for flat in source(worker_index, num_workers):
      while True:
        if stop.is_set():
          return
        try:
          slot = free_q.get(timeout=0.1)
          break
        except queue_lib.Empty:
          continue
      ring.write(slot, flat)
      full_q.put((_BATCH, worker_index, slot))
    full_q.put((_DONE, worker_index, -1))
  except BaseException:  # latched and re-raised consumer-side
    try:
      full_q.put((_ERROR, worker_index, traceback.format_exc()))
    except Exception:  # pragma: no cover - queue already torn down
      pass
  finally:
    if ring is not None:
      ring.close()
    # Flush this process's queue feeder threads so an exit never
    # strands a message half-written into the pipe.
    for q in (free_q, full_q):
      try:
        q.close()
        q.join_thread()
      except Exception:  # pragma: no cover
        pass


@gin.configurable
class HostDataPlane:
  """N worker processes fanned into one shm-ring batch stream.

  Args:
    source: picklable callable `(worker_index, num_workers) → iterator
      of flat dict batches` conforming to `layout`. Runs INSIDE each
      worker process (spawn context: it must import everything it
      needs).
    layout: the ring's `WireLayout` (full batched shapes).
    num_workers: worker process count (>= 1; `num_workers=0` callers
      should not construct a plane at all — that's the in-process
      path).
    slots_per_worker: ring depth per worker, FLOORED AT 2 (values
      below are promoted: a worker must be able to decode one batch
      while its last waits for the consumer, or the plane serializes).
      The ring's memory footprint is `num_slots × layout.slot_bytes`
      with `num_slots = max(2, slots_per_worker) × num_workers` —
      size against the floor, not the requested value.
    copy: `views()` batches are copied out of the ring before being
      returned. `False` returns zero-copy views valid until the NEXT
      `__next__`/`close` (the consumer owns exactly one slot at a
      time). None resolves to `h2d_aliases_host_memory()` — copy
      whenever a downstream jax.device_put could alias ring memory.
    mp_context: multiprocessing start method. "spawn" (default) keeps
      workers clear of the parent's TF/JAX runtime state — forking a
      process with live TF threadpools deadlocks.
  """

  def __init__(self,
               source: Callable[[int, int],
                                Iterator[Dict[str, np.ndarray]]],
               layout: WireLayout,
               num_workers: int,
               slots_per_worker: int = 2,
               copy: Optional[bool] = None,
               mp_context: str = "spawn"):
    if num_workers < 1:
      raise ValueError(
          f"HostDataPlane needs num_workers >= 1, got {num_workers}")
    self._layout = layout
    self._copy = h2d_aliases_host_memory() if copy is None else bool(copy)
    self.num_slots = max(2, slots_per_worker) * num_workers
    self._ring = ShmRing(layout, self.num_slots)
    ctx = multiprocessing.get_context(mp_context)
    self._free_q = ctx.Queue()
    self._full_q = ctx.Queue()
    self._stop = ctx.Event()
    for slot in range(self.num_slots):
      self._free_q.put(slot)
    self._pending_slot: Optional[int] = None
    self._done: List[bool] = [False] * num_workers
    self._suspect: List[bool] = [False] * num_workers
    self._error: Optional[BaseException] = None
    self._closed = False
    self._last_death_poll = time.monotonic()
    self.batches_out = 0
    self._workers = [
        ctx.Process(
            target=_worker_main,
            args=(source, i, num_workers, self._ring.name, layout,
                  self.num_slots, self._free_q, self._full_q,
                  self._stop),
            name=f"t2r-data-plane-{i}", daemon=True)
        for i in range(num_workers)]
    for p in self._workers:
      p.start()

  # ---- consumer protocol ----

  def __iter__(self) -> "HostDataPlane":
    return self

  def release(self) -> None:
    """Returns the slot backing the last-yielded views to the free
    pool. Idempotent; called automatically on the next `__next__`
    (zero-copy mode) or immediately (copy mode)."""
    if self._pending_slot is not None and not self._closed:
      self._free_q.put(self._pending_slot)
    self._pending_slot = None

  def _latch(self, err: BaseException) -> BaseException:
    self._error = err
    tmetrics.counter("data_plane.worker_failures").inc()
    return err

  def _check_workers(self) -> None:
    """Exit-code poll: a worker that died without a message (segfault,
    external kill, silent os._exit) latches a crash error."""
    for i, p in enumerate(self._workers):
      if self._done[i] or p.is_alive():
        continue
      if p.exitcode != 0:
        raise self._latch(RuntimeError(
            f"data-plane worker {i} died (exit code {p.exitcode}) "
            "without reporting; its batch (if mid-write) is "
            "discarded"))
      # Dead with exit code 0 but no DONE marker read yet. A NORMAL
      # finisher flushes its marker into the pipe before exiting
      # (join_thread in the worker's finally), but that flush can land
      # in the instant between this poll window expiring and the
      # is_alive check — so give it exactly one more full get() window
      # to surface before declaring the death silent (e.g. a source
      # that os._exit(0)s mid-stream), which would otherwise hang the
      # consumer forever.
      if self._suspect[i]:
        raise self._latch(RuntimeError(
            f"data-plane worker {i} exited (code 0) without sending "
            "its done marker; treating as a silent death so the "
            "consumer never hangs"))
      self._suspect[i] = True

  def _poll_crashed_workers(self) -> None:
    """Nonzero-exit deaths latch even while the queue stays BUSY.

    `_check_workers` only runs on an empty-queue window, so with N > 1
    workers a crashed (OOM-killed, segfaulted) worker would otherwise
    go undetected as long as its siblings keep batches flowing — the
    stream silently drops that worker's file shard. Clean (code 0)
    exits are NOT judged here: a legitimate finisher's done marker may
    lawfully sit queued behind other workers' batches, and declaring
    it a silent death early would be a false positive; those resolve
    on the empty-queue path, where the queue has provably drained.
    """
    now = time.monotonic()
    if now - self._last_death_poll < 0.5:
      return
    self._last_death_poll = now
    for i, p in enumerate(self._workers):
      if not self._done[i] and not p.is_alive() and p.exitcode != 0:
        raise self._latch(RuntimeError(
            f"data-plane worker {i} died (exit code {p.exitcode}) "
            "without reporting; its file shard is no longer being "
            "produced"))

  def __next__(self) -> Dict[str, np.ndarray]:
    if self._error is not None:
      raise RuntimeError("data-plane worker failed") from self._error
    if self._closed:
      raise StopIteration
    self.release()
    while True:
      if all(self._done):
        # Per-producer FIFO: every worker's batches precede its done
        # marker, so once all markers are in the queue holds nothing.
        raise StopIteration
      self._poll_crashed_workers()
      try:
        tag, widx, payload = self._full_q.get(timeout=0.2)
      except queue_lib.Empty:
        self._check_workers()
        continue
      if tag == _BATCH:
        self.batches_out += 1
        tmetrics.counter("data_plane.batches").inc()
        if self._copy:
          batch = {k: np.array(v)
                   for k, v in self._ring.views(payload).items()}
          self._free_q.put(payload)
          return batch
        self._pending_slot = payload
        return self._ring.views(payload)
      if tag == _DONE:
        self._done[widx] = True
        continue
      assert tag == _ERROR
      raise self._latch(RuntimeError(
          f"data-plane worker {widx} raised:\n{payload}"))

  # ---- introspection / lifecycle ----

  @property
  def copies_batches(self) -> bool:
    return self._copy

  def require_copies(self) -> None:
    """Switches to copy-out mode (callers that retain batches past the
    next `__next__`, e.g. K-step stacking)."""
    self._copy = True

  def workers_alive(self) -> int:
    return sum(p.is_alive() for p in self._workers)

  def close(self, timeout_secs: float = 5.0) -> None:
    """Stops workers (even mid-block), reclaims the shared segment."""
    if self._closed:
      return
    self._closed = True
    self._stop.set()
    # Drain the full queue so worker feeder threads can flush and the
    # workers' final puts never wedge their interpreter shutdown.
    deadline = time.monotonic() + timeout_secs
    for p in self._workers:
      p.join(timeout=max(0.0, deadline - time.monotonic()) + 0.1)
    for p in self._workers:
      if p.is_alive():  # blocked past the grace period: force it
        p.terminate()
        p.join(timeout=1.0)
      if p.is_alive():  # pragma: no cover - terminate() ignored
        p.kill()
        p.join(timeout=1.0)
    for q in (self._full_q, self._free_q):
      try:
        while True:
          q.get_nowait()
      except queue_lib.Empty:
        pass
      q.close()
      q.join_thread()
    self._pending_slot = None
    self._ring.close()

  def __del__(self):  # best-effort: never leak processes/shm segments
    try:
      self.close(timeout_secs=1.0)
    except Exception:  # pragma: no cover
      pass
